"""Continuous-batching serving tests (ISSUE 7 acceptance criteria).

The contracts under test:

* the paged KV pool: free-list allocator invariants (dead block
  reserved, exhaustion is loud, double-free is loud, free restores);
* the scheduler: optimistic FCFS admission against live-token demand,
  prefix sharing copy-on-write (one physical copy, refcount-exact),
  preemption (youngest victim, evict-and-recompute, token-identical
  resume), SLO-aware dispatch knobs, chunked-prefill progression,
  eviction returns every reference (no leak across N churn cycles —
  warm prefix residents are capacity, not leaks);
* paged ``decode_attention`` == contiguous (bitwise on the XLA gather
  path, tolerance on the interpret-mode kernel), with and without the
  bucketed relative bias;
* the fused sampling tail: greedy == argmax, kernel == XLA fallback
  token-for-token on shared noise, top-k/top-p kept sets match the
  standalone sort/cumsum sampler's sets;
* the ServingEngine: greedy decode under paging/chunking is
  TOKEN-IDENTICAL to the single-request ``DecodeEngine``, and
  ``prefill_chunk._cache_size() == 1`` / ``decode_step._cache_size()
  == 1`` across a scripted admit/evict/length-mix churn schedule
  (recompile-freedom — the stable-aval contract);
* ``serve`` monitor records validate through the schema, the report,
  and the ``tools/validate_metrics.py --serve`` forced dispatch.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.inference import DecodeEngine, sample_logits
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.ops import decode_attention, fused_sample
from apex_tpu.serving import (
    DEAD_BLOCK,
    BlockAllocator,
    PrefixCache,
    Request,
    Scheduler,
    ServingEngine,
    SLOPolicy,
    blocks_needed,
)

K = jr.PRNGKey(11)


@pytest.fixture(scope="module")
def tiny():
    cfg = GPTConfig(vocab_size=97, max_seq_len=128, hidden_size=32,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    attention_impl="flash", remat=False, dropout=0.0)
    model = GPTModel(cfg)
    return model, model.init(K)


@pytest.fixture(scope="module")
def reference_engine(tiny):
    model, _ = tiny
    return DecodeEngine(model)


def _req(rng, rid, max_prompt=30, max_new=12):
    return Request(
        rid=rid,
        prompt=np.asarray(rng.integers(0, 97, rng.integers(1, max_prompt)),
                          np.int32),
        max_new_tokens=int(rng.integers(1, max_new)))


class TestBlockAllocator:
    def test_dead_block_never_allocated(self):
        a = BlockAllocator(5)
        ids = a.allocate(4)
        assert sorted(ids) == [1, 2, 3, 4] and DEAD_BLOCK not in ids

    def test_exhaustion_and_restore(self):
        a = BlockAllocator(4)
        ids = a.allocate(3)
        with pytest.raises(RuntimeError, match="exhausted"):
            a.allocate(1)
        a.free(ids)
        assert a.num_free == 3 and a.num_live == 0
        assert len(a.allocate(3)) == 3

    def test_double_free_and_dead_free_are_loud(self):
        a = BlockAllocator(4)
        (bid,) = a.allocate(1)
        a.free([bid])
        with pytest.raises(ValueError, match="double free"):
            a.free([bid])
        with pytest.raises(ValueError, match="dead block"):
            a.free([DEAD_BLOCK])

    def test_needs_two_blocks_minimum(self):
        with pytest.raises(ValueError, match="dead block"):
            BlockAllocator(1)

    def test_blocks_needed(self):
        assert [blocks_needed(n, 8) for n in (1, 8, 9, 16, 17)] \
            == [1, 1, 2, 2, 3]

    # --- ISSUE 10 accounting: leak counter, high-water, fragmentation -----

    def test_leak_counter_zero_across_churn_cycles(self):
        """N scripted admit/evict cycles of mixed sizes: the leak
        counter is EXACTLY zero throughout and at the end, and the
        lifetime alloc/free totals balance."""
        import numpy as np
        a = BlockAllocator(16)
        rng = np.random.default_rng(7)
        for _ in range(50):
            n = int(rng.integers(1, 6))
            ids = a.allocate(n)
            assert a.leaked == 0
            a.check_accounting()
            a.free(ids)
            assert a.leaked == 0
        assert a.alloc_total == a.free_total > 0
        assert a.num_live == 0 and a.num_free == 15
        a.check_accounting()

    def test_high_water_is_monotone(self):
        import numpy as np
        a = BlockAllocator(20)
        rng = np.random.default_rng(8)
        held, seen = [], []
        for _ in range(40):
            if held and rng.random() < 0.5:
                a.free([held.pop()])
            else:
                if a.num_free:
                    held.extend(a.allocate(1))
            seen.append(a.high_water)
            assert a.high_water >= a.num_live
        assert seen == sorted(seen), "high_water regressed"
        assert a.high_water == max(
            seen), "high_water is not the running max"

    def test_double_free_still_loud_with_counters(self):
        """The new counters must not swallow the loud failure modes —
        and a refused free must not corrupt the ledger."""
        a = BlockAllocator(6)
        ids = a.allocate(2)
        a.free(ids)
        with pytest.raises(ValueError, match="double free"):
            a.free([ids[0]])
        with pytest.raises(ValueError, match="dead block"):
            a.free([DEAD_BLOCK])
        assert a.alloc_total == 2 and a.free_total == 2
        assert a.leaked == 0
        a.check_accounting()

    def test_accounting_check_is_loud_on_corruption(self):
        a = BlockAllocator(6)
        ids = a.allocate(3)
        a.check_accounting()
        a._live.discard(ids[0])  # cross-wire behind the API
        assert a.leaked == 1
        with pytest.raises(RuntimeError, match="accounting broken"):
            a.check_accounting()

    def test_fragmentation_accounting(self):
        a = BlockAllocator(9)
        assert a.fragmentation_pct() == 0.0  # fresh pool: one run
        ids = a.allocate(8)
        assert a.fragmentation_pct() == 0.0  # empty free list
        a.free([ids[1], ids[4], ids[6]])     # 3 scattered singletons
        assert a.fragmentation_pct() == pytest.approx(100 * (1 - 1 / 3))
        a.free([i for i in ids if i not in (ids[1], ids[4], ids[6])])
        assert a.fragmentation_pct() == 0.0  # whole pool back: one run

    # --- serving tier 2: refcounts, COW sharing, residency ----------------

    def test_refcount_exact_across_shared_prefix(self):
        """Three holders of one block (owner + two sharers): the block
        only physically frees on the LAST release, the physical
        counters never drift, and leaked stays exactly zero the whole
        way — refcount churn is invisible to the leak identity."""
        a = BlockAllocator(6)
        (bid,) = a.allocate(1)
        a.retain([bid])
        a.retain([bid])
        assert a.refcount(bid) == 3 and a.is_shared(bid)
        assert a.alloc_total == 1  # retains are not allocations
        a.free([bid])
        a.free([bid])
        assert a.num_live == 1 and a.free_total == 0  # still held
        assert a.leaked == 0
        a.check_accounting()
        a.free([bid])  # last reference: physical free
        assert a.num_live == 0 and a.free_total == 1
        assert a.leaked == 0
        a.check_accounting()

    def test_shared_block_over_free_is_still_loud(self):
        a = BlockAllocator(6)
        (bid,) = a.allocate(1)
        a.retain([bid])
        a.free([bid])
        a.free([bid])  # refcount hits 0: physically freed
        with pytest.raises(ValueError, match="double free"):
            a.free([bid])  # one more than the references ever held
        with pytest.raises(ValueError, match="cannot retain"):
            a.retain([bid])  # sharing freed memory would cross-wire
        a.check_accounting()

    def test_check_accounting_covers_refcounts(self):
        a = BlockAllocator(6)
        ids = a.allocate(2)
        a.check_accounting()
        a._ref[ids[0]] = 0  # live block with no reference: corrupt
        with pytest.raises(RuntimeError, match="refcounts corrupt"):
            a.check_accounting()
        a._ref[ids[0]] = 1
        a.check_accounting()
        del a._ref[ids[1]]  # live block missing from the ref ledger
        with pytest.raises(RuntimeError, match="refcounts corrupt"):
            a.check_accounting()

    def test_resident_marking(self):
        """Cache-resident blocks are live-but-not-demand: num_resident
        tracks them, physical free clears the flag, and marking a
        non-live block is loud."""
        a = BlockAllocator(6)
        ids = a.allocate(3)
        a.mark_resident(ids[0])
        a.mark_resident(ids[1])
        assert a.num_resident == 2
        a.unmark_resident(ids[1])
        assert a.num_resident == 1
        a.free([ids[0]])  # physical free clears residency
        assert a.num_resident == 0
        with pytest.raises(ValueError, match="resident"):
            a.mark_resident(ids[0])  # no longer live
        a._resident.add(99)  # stray resident id: corrupt
        with pytest.raises(RuntimeError, match="resident-but-not-live"):
            a.check_accounting()


class TestPrefixCache:
    def _cache(self, num_blocks=20, block=4, capacity=None):
        a = BlockAllocator(num_blocks)
        return a, PrefixCache(a, block, capacity_blocks=capacity)

    def _index_chain(self, a, c, tokens):
        """Allocate + insert every full block of ``tokens``; returns
        the block ids (simulating a request registering its prefill)."""
        B = c.block_size
        eids, bids = [0], []
        for i in range(len(tokens) // B):
            (bid,) = a.allocate(1)
            eids.append(c.insert(eids[-1], tokens[i * B:(i + 1) * B],
                                 bid))
            bids.append(bid)
        return bids

    def test_match_walks_the_chain(self):
        a, c = self._cache()
        prompt = np.arange(13, dtype=np.int32)
        bids = self._index_chain(a, c, prompt)
        assert len(bids) == 3  # 13 tokens / 4 = 3 full blocks
        chain = c.match(prompt)
        assert [e.block_id for e in chain] == bids
        # a prompt diverging inside block 2 matches only blocks 0-1
        other = prompt.copy()
        other[6] = 99
        assert [e.block_id for e in c.match(other)] == bids[:1]
        # block-level stats counted on counting lookups only
        assert c.block_queries == 6 and c.block_hits == 4
        assert c.match(prompt, count=False) and c.block_queries == 6

    def test_same_tokens_different_parent_are_distinct(self):
        """The chain key: an identical token block under a DIFFERENT
        prefix is a different entry — content equality of one block
        never aliases two prefixes."""
        a, c = self._cache()
        blk = np.asarray([5, 6, 7, 8], np.int32)
        p1 = np.concatenate([np.zeros(4, np.int32), blk])
        p2 = np.concatenate([np.ones(4, np.int32), blk])
        b1 = self._index_chain(a, c, p1)
        b2 = self._index_chain(a, c, p2)
        assert c.num_entries == 4  # two roots, two distinct children
        assert [e.block_id for e in c.match(p1)] == b1
        assert [e.block_id for e in c.match(p2)] == b2
        assert b1[1] != b2[1]

    def test_hash_collisions_can_never_alias(self):
        """Force EVERY key into one bucket: lookups still resolve by
        full ``(parent, tokens)`` comparison, so two different prefixes
        keep distinct entries and hits return the right blocks."""
        class Colliding(PrefixCache):
            def _hash(self, parent_eid, tokens):
                return 0  # worst-case hash: everything collides

        a = BlockAllocator(20)
        c = Colliding(a, 4)
        p1 = np.arange(8, dtype=np.int32)
        p2 = np.arange(8, dtype=np.int32) + 50
        b1 = self._index_chain(a, c, p1)
        b2 = self._index_chain(a, c, p2)
        assert len(c._buckets) == 1  # truly all in one bucket
        assert [e.block_id for e in c.match(p1)] == b1
        assert [e.block_id for e in c.match(p2)] == b2
        assert c.match(np.arange(8, dtype=np.int32) + 99) == []

    def test_gate_precheck_is_side_effect_free(self):
        """match(count=False) — the admission gate's pre-check — must
        neither bump LRU stamps (a held-back request would pin its
        chain MRU against reclaim without using it) nor count stats;
        commit_match does both when the admission really happens."""
        a, c = self._cache()
        p = np.arange(8, dtype=np.int32)
        self._index_chain(a, c, p)
        stamps = {e.eid: e.stamp for e in c._by_eid.values()}
        q, h = c.block_queries, c.block_hits
        chain = c.match(p, count=False)
        assert len(chain) == 2
        assert {e.eid: e.stamp for e in c._by_eid.values()} == stamps
        assert (c.block_queries, c.block_hits) == (q, h)
        c.commit_match(p, chain)
        assert c.block_queries == q + 2 and c.block_hits == h + 2
        after = {e.eid: e.stamp for e in c._by_eid.values()}
        assert all(after[eid] > stamps[eid] for eid in stamps)

    def test_insert_retains_and_marks_resident(self):
        a, c = self._cache()
        prompt = np.arange(8, dtype=np.int32)
        bids = self._index_chain(a, c, prompt)
        for bid in bids:
            assert a.refcount(bid) == 2  # owner + cache
        assert a.num_resident == 2
        a.free(bids)  # the owner finishes: cache keeps them warm
        assert a.num_live == 2 == a.num_resident
        assert a.leaked == 0
        a.check_accounting()

    def test_reclaim_is_lru_leaf_first_and_skips_pinned(self):
        a, c = self._cache()
        p1 = np.arange(8, dtype=np.int32)        # chain of 2
        p2 = np.arange(4, dtype=np.int32) + 40   # chain of 1
        b1 = self._index_chain(a, c, p1)
        b2 = self._index_chain(a, c, p2)
        a.free(b1)  # owner 1 done: chain 1 reclaimable
        # owner 2 still holds b2 (refcount 2): pinned, never reclaimed
        assert c.reclaimable() == 2
        # p2 was touched more recently; p1's LEAF (child) must go first
        assert c.reclaim(1) == 1
        assert [e.block_id for e in c.match(p1, count=False)] == b1[:1]
        assert c.reclaim(10) == 1  # then p1's root; b2 stays pinned
        assert c.num_entries == 1
        assert [e.block_id for e in c.match(p2, count=False)] == b2
        assert a.refcount(b2[0]) == 2
        a.check_accounting()

    def test_capacity_bound_reclaims_or_skips(self):
        a, c = self._cache(capacity=2)
        p1 = np.arange(8, dtype=np.int32)
        b1 = self._index_chain(a, c, p1)
        a.free(b1)  # unpinned: evictable
        assert c.num_entries == 2
        # a third block forces the LRU leaf out (capacity holds)
        (bid,) = a.allocate(1)
        c.insert(0, np.asarray([70, 71, 72, 73], np.int32), bid)
        assert c.num_entries == 2
        assert c.evictions == 1
        # with every entry pinned, insert SKIPS indexing instead of
        # growing: the new block is simply not findable, and the
        # returned eid is DANGLING (never the still-valid parent — the
        # chain must stay skipped, see the aliasing test below)
        a2, c2 = self._cache(capacity=1)
        (pinned,) = a2.allocate(1)
        c2.insert(0, np.asarray([1, 2, 3, 4], np.int32), pinned)
        (extra,) = a2.allocate(1)
        eid = c2.insert(0, np.asarray([9, 9, 9, 9], np.int32), extra)
        assert eid != 0 and eid not in c2._by_eid
        assert c2.num_entries == 1
        assert a2.refcount(extra) == 1  # not retained by the cache

    def test_capacity_skip_cannot_miskey_the_next_block(self):
        """Review-confirmed hazard: if block A's insert is skipped at
        capacity but capacity frees before block B of the SAME chain
        inserts, B must NOT land under A's parent — a prompt's second
        block findable as a first block would alias mid-prompt KV onto
        a future prompt's position 0. The skip returns a dangling eid,
        so the whole rest of the chain stays unindexed."""
        a, c = self._cache(capacity=1)
        (pinned,) = a.allocate(1)
        c.insert(0, np.asarray([7, 7, 7, 7], np.int32), pinned)  # pinned
        blk_a = np.asarray([1, 2, 3, 4], np.int32)
        blk_b = np.asarray([5, 6, 7, 8], np.int32)
        (ba,) = a.allocate(1)
        eid_a = c.insert(0, blk_a, ba)      # skipped: capacity + pinned
        assert eid_a not in c._by_eid
        a.free([pinned])                    # capacity frees in between
        (bb,) = a.allocate(1)
        eid_b = c.insert(eid_a, blk_b, bb)  # chain STAYS skipped
        assert eid_b == eid_a and c.num_entries == 1
        # the mid-prompt block is NOT findable as a prompt start
        assert c.match(np.concatenate([blk_b, blk_b]),
                       count=False) == []
        a.check_accounting()

    def test_reclaimed_parent_breaks_the_chain_quietly(self):
        """Capacity pressure can evict the parent an in-progress chain
        was building on (another slot's entries may be fresher): the
        next insert must skip indexing — never wire an unreachable
        child or KeyError — and keep skipping for the rest of that
        chain."""
        a, c = self._cache(capacity=2)
        (b0,) = a.allocate(1)
        e0 = c.insert(0, np.asarray([1, 2, 3, 4], np.int32), b0)
        a.free([b0])  # only the cache holds it: reclaimable
        # other traffic fills capacity with a FRESHER unpinned root,
        # then a third insert reclaims LRU = e0 (our parent-to-be)
        (b1,) = a.allocate(1)
        c.insert(0, np.asarray([9, 9, 9, 9], np.int32), b1)
        a.free([b1])
        (b2,) = a.allocate(1)
        c.insert(0, np.asarray([8, 8, 8, 8], np.int32), b2)
        assert e0 not in c._by_eid  # the parent is gone
        # chaining on the evicted parent: quiet skip, stable return
        (b3,) = a.allocate(1)
        got = c.insert(e0, np.asarray([5, 6, 7, 8], np.int32), b3)
        assert got == e0
        assert a.refcount(b3) == 1  # not retained by the cache
        (b4,) = a.allocate(1)
        assert c.insert(got, np.asarray([4, 3, 2, 1], np.int32),
                        b4) == e0
        a.check_accounting()

    def test_insert_race_keeps_existing_entry(self):
        """Two requests prefill the same prefix concurrently: the
        second insert finds the first entry and does NOT retain its
        own private block — both copies live, one findable."""
        a, c = self._cache()
        blk = np.asarray([3, 1, 4, 1], np.int32)
        (b1,) = a.allocate(1)
        e1 = c.insert(0, blk, b1)
        (b2,) = a.allocate(1)
        e2 = c.insert(0, blk, b2)
        assert e1 == e2 and c.num_entries == 1
        assert a.refcount(b1) == 2 and a.refcount(b2) == 1

    def test_full_block_keys_only(self):
        a, c = self._cache()
        (bid,) = a.allocate(1)
        with pytest.raises(ValueError, match="FULL blocks"):
            c.insert(0, np.asarray([1, 2], np.int32), bid)

    def test_mismatched_allocator_refused(self):
        a, c = self._cache()
        with pytest.raises(ValueError, match="own allocator"):
            Scheduler(num_slots=1, block_size=4, max_blocks_per_slot=8,
                      allocator=BlockAllocator(8), prefill_chunk=4,
                      prefix_cache=c)


class TestScheduler:
    def _sched(self, num_blocks=20, num_slots=2, block=4, chunk=8):
        return Scheduler(num_slots=num_slots, block_size=block,
                         max_blocks_per_slot=16,
                         allocator=BlockAllocator(num_blocks),
                         prefill_chunk=chunk)

    def test_chunked_prefill_progression(self):
        s = self._sched()
        prompt = np.arange(19, dtype=np.int32)
        s.submit(Request(rid=0, prompt=prompt, max_new_tokens=3))
        s.admit(now=0.0)
        works = []
        while True:
            w = s.next_prefill()
            if w is None:
                break
            works.append((w.start, w.live, w.is_last))
            np.testing.assert_array_equal(
                w.tokens[:w.live], prompt[w.start:w.start + w.live])
            s.note_prefill(w, sampled_token=42, now=1.0)
        # 19 tokens in chunks of 8: (0,8) (8,8) (16,3 last)
        assert works == [(0, 8, False), (8, 8, False), (16, 3, True)]
        # blocks cover exactly the live frontier: ceil(19/4) = 5
        assert s.allocator.num_live == 5
        assert s.decoding_slots() == [0]

    def test_optimistic_admission_beats_worst_case_gate(self):
        # pool of 5 allocatable blocks; each request worst-cases at
        # ceil((8 + 4 - 1)/4) = 3 blocks. The PR-7 worst-case gate
        # admitted ONE at a time; optimistic admission gates on the
        # FIRST CHUNK's live demand (2 blocks each) and fills both
        # slots at once — the whole point of serving tier 2.
        s = self._sched(num_blocks=6)
        for i in range(3):
            s.submit(Request(rid=i, prompt=np.zeros(8, np.int32),
                             max_new_tokens=4))
        assert s.admit(now=0.0) == [0, 1]  # both slots, FCFS order
        assert s.num_waiting == 1          # rid 2: no free slot
        # drive rid 0+1 to completion; rid 2 takes the freed slot
        while not s.idle():
            w = s.next_prefill(0.0)
            if w is not None:
                s.note_prefill(w, sampled_token=1, now=0.0)
            batch = s.decode_batch(0.0)
            if batch is not None:
                s.note_decode(np.full(2, 7), now=0.0)
            s.admit(now=0.0)
        assert [r.rid for r in s.completed] == [0, 1, 2]

    def test_preemption_on_pool_pressure(self):
        """Mid-flight shortfall evicts the YOUNGEST request (never the
        oldest — the head of the line must progress): its blocks
        release, the request re-queues at the FRONT with generated
        tokens intact, and it finishes after re-admission."""
        # 7 allocatable; worst case each: ceil((8 + 17 - 1)/4) = 6
        s = self._sched(num_blocks=8)
        for i in range(2):
            s.submit(Request(rid=i, prompt=np.zeros(8, np.int32),
                             max_new_tokens=17))
        assert s.admit(now=0.0) == [0, 1]  # optimistic: both in
        tok = 0
        while not s.idle():
            w = s.next_prefill(0.0)
            if w is not None:
                s.note_prefill(w, sampled_token=tok, now=0.0)
                tok += 1
            batch = s.decode_batch(0.0)
            if batch is not None:
                s.note_decode(np.arange(2) + tok, now=0.0)
                tok += 2
            s.admit(now=0.0)
        assert s.preemptions >= 1
        done = {r.rid: r for r in s.completed}
        # the victim is the YOUNGER request (never rid 0 — the oldest
        # always progresses); its stream survived eviction intact
        assert done[0].evictions == 0
        assert done[1].evictions >= 1
        assert s.recompute_tokens > 0
        assert len(s.completed) == 2
        assert all(len(r.tokens) == 17 for r in s.completed)
        # every reference returned: refcount-exact, leak-free
        s.allocator.check_accounting()
        assert s.allocator.num_live == 0
        assert s.allocator.leaked == 0

    def _cached_sched(self, num_blocks=40, num_slots=2, block=4,
                      chunk=8):
        a = BlockAllocator(num_blocks)
        return Scheduler(num_slots=num_slots, block_size=block,
                         max_blocks_per_slot=16, allocator=a,
                         prefill_chunk=chunk,
                         prefix_cache=PrefixCache(a, block))

    def _run_prefill(self, s, upto_rid=None):
        tok = 7
        while True:
            w = s.next_prefill(0.0)
            if w is None or (upto_rid is not None and w.rid != upto_rid):
                return
            s.note_prefill(w, sampled_token=tok, now=0.0)
            tok += 1

    def test_shared_prefix_maps_one_physical_copy(self):
        """Two requests with a common 2-block system prompt: the second
        admission maps its leading table entries onto the FIRST
        request's physical blocks (refcount 3: owner + cache + sharer),
        skips those chunks, and prefill resumes at the frontier."""
        s = self._cached_sched()
        sysp = np.arange(8, dtype=np.int32)
        s.submit(Request(rid=0, prompt=np.concatenate(
            [sysp, np.full(3, 60, np.int32)]), max_new_tokens=4))
        s.admit(now=0.0)
        self._run_prefill(s)  # rid 0 fully prefilled + registered
        s.submit(Request(rid=1, prompt=np.concatenate(
            [sysp, np.full(5, 61, np.int32)]), max_new_tokens=4))
        (i1,) = s.admit(now=0.0)
        slot = s._slots[i1]
        assert slot.shared_blocks == 2
        assert slot.prefilled == 8  # resumes past the shared prefix
        assert not s._waiting
        row0, row1 = s.tables.row(0), s.tables.row(i1)
        np.testing.assert_array_equal(row0[:2], row1[:2])  # ONE copy
        for bid in row1[:2]:
            assert s.allocator.refcount(int(bid)) == 3
        req1 = slot.request
        assert req1.prefix_hit_blocks == 2
        w = s.next_prefill(0.0)
        assert w.rid == 1 and w.start == 8 and w.live == 5
        # the prefix covering the LAST prompt token is never shared
        # outright: a request whose whole prompt is cached still
        # recomputes the final block privately (the COW discipline)
        s.note_prefill(w, sampled_token=9, now=0.0)
        s.submit(Request(rid=2, prompt=sysp.copy(), max_new_tokens=2))
        finished = []
        while len(s.completed) < 2:  # drain rid 0+1
            batch = s.decode_batch(0.0)
            s.note_decode(np.full(2, 5), now=0.0)
            finished = s.completed
        (i2,) = s.admit(now=0.0)
        slot2 = s._slots[i2]
        assert slot2.shared_blocks == 1  # NOT 2: last block recomputed
        assert slot2.prefilled == 4
        w = s.next_prefill(0.0)
        assert w.start == 4 and w.live == 4
        assert len(finished) == 2

    def test_gate_excludes_chain_blocks_the_admission_would_pin(self):
        """Reclaimable headroom must not count the request's OWN
        matched chain: retaining it at admission makes it unreclaimable
        instantly, so the old gate admitted straight into guaranteed
        self-preemption (admit→evict thrash). The request must be HELD
        instead, with zero preemptions."""
        a = BlockAllocator(6)  # 5 allocatable
        s = Scheduler(num_slots=2, block_size=4, max_blocks_per_slot=16,
                      allocator=a, prefill_chunk=4,
                      prefix_cache=PrefixCache(a, 4))
        sysp = np.arange(8, dtype=np.int32)
        # A registers the 2-block system prompt, finishes at prefill
        s.submit(Request(rid=0, prompt=sysp.copy(), max_new_tokens=1))
        s.admit(now=0.0)
        self._run_prefill(s)
        assert s.completed and a.num_resident == 2
        # C fills the rest of the pool and keeps decoding
        s.submit(Request(rid=1, prompt=np.full(12, 9, np.int32),
                         max_new_tokens=4))
        s.admit(now=0.0)
        self._run_prefill(s)
        assert a.num_free == 0
        # B shares the cached chain and needs 1 block BEYOND it: the
        # only "reclaimable" blocks are the 2 B itself would pin
        s.submit(Request(rid=2, prompt=np.concatenate(
            [sysp, np.asarray([5], np.int32)]), max_new_tokens=2))
        assert s.admit(now=0.0) == []  # held, not thrash-admitted
        assert s.preemptions == 0
        # once C finishes, B admits and completes normally
        while len(s.completed) < 2:
            s.decode_batch(0.0)
            s.note_decode(np.full(2, 3), now=0.0)
            s.admit(now=0.0)
        self._run_prefill(s)
        while len(s.completed) < 3:
            s.decode_batch(0.0)
            s.note_decode(np.full(2, 4), now=0.0)
        assert s.preemptions == 0
        a.check_accounting()

    def test_resumed_request_discards_refill_sample(self):
        """Evict-and-recompute: the re-prefill's sampled token is
        discarded and the decode state (generated count, last token)
        restored — the stream continues where it left off."""
        s = self._cached_sched(num_blocks=40)
        s.submit(Request(rid=0, prompt=np.arange(6, dtype=np.int32),
                         max_new_tokens=6))
        s.admit(now=0.0)
        self._run_prefill(s)
        for _ in range(2):  # two decode steps: tokens [7, 20, 21]
            s.decode_batch(0.0)
            s.note_decode(np.full(2, 20 + _), now=0.0)
        req = s._slots[0].request
        before = list(req.tokens)
        s._preempt(0, now=0.0)
        assert req.evictions == 1 and req.tokens == before
        s.admit(now=0.0)
        slot = s._slots[0]
        assert slot.resumed and slot.generated == 3
        assert slot.last_token == before[-1]
        assert len(slot.eprompt) == 6 + 2  # prompt + all but last token
        self._run_prefill(s)
        assert not s._slots[0].resumed
        assert req.tokens == before  # the re-prefill sample DISCARDED
        s.decode_batch(0.0)
        s.note_decode(np.full(2, 33), now=0.0)
        assert req.tokens == before + [33]

    def test_slo_policy_prefers_short_prompts_under_burn(self):
        """TTFT burn flips admission from FCFS to shortest-arrived
        first; clearing the burn restores FCFS."""
        pol = SLOPolicy()
        a = BlockAllocator(60)
        s = Scheduler(num_slots=1, block_size=4, max_blocks_per_slot=16,
                      allocator=a, prefill_chunk=4, policy=pol)
        s.submit(Request(rid=0, prompt=np.zeros(40, np.int32),
                         max_new_tokens=2))
        s.submit(Request(rid=1, prompt=np.zeros(4, np.int32),
                         max_new_tokens=2))
        pol.prefer_short_prompts = True
        (i,) = s.admit(now=0.0)
        assert s._slots[i].request.rid == 1  # short prompt jumped
        pol.prefer_short_prompts = False
        s.submit(Request(rid=2, prompt=np.zeros(4, np.int32),
                         max_new_tokens=2))
        self._drain_one(s)
        (i,) = s.admit(now=0.0)
        assert s._slots[i].request.rid == 0  # FCFS restored

    def _drain_one(self, s):
        while not s.completed:
            w = s.next_prefill(0.0)
            if w is not None:
                s.note_prefill(w, sampled_token=1, now=0.0)
            batch = s.decode_batch(0.0)
            if batch is not None:
                s.note_decode(np.full(s.num_slots, 2), now=0.0)

    def test_slo_policy_update_from_signals(self):
        class _Tel:
            slo_burning = False
            queue_buildup = False

        pol = SLOPolicy(max_prefill_share=3)
        tel = _Tel()
        pol.update(tel)
        assert pol.prefill_share == 1 and not pol.prefer_short_prompts
        tel.queue_buildup = True
        tel.slo_burning = True
        pol.update(tel)
        assert pol.prefill_share == 2 and pol.prefer_short_prompts
        pol.update(tel)
        pol.update(tel)
        assert pol.prefill_share == 3  # capped at max_prefill_share
        tel.queue_buildup = False
        tel.slo_burning = False
        pol.update(tel)  # one step back per clean window
        assert pol.prefill_share == 2 and not pol.prefer_short_prompts
        assert pol.adjustments >= 3

    def test_eviction_returns_every_block(self):
        """No leak across N churn cycles: after every request completes
        the free list is exactly the fresh pool."""
        s = self._sched(num_blocks=12)
        rng = np.random.default_rng(3)
        for cycle in range(6):
            s.submit(_req(rng, cycle, max_prompt=20, max_new=6))
        while not s.idle():
            s.admit(now=0.0)
            w = s.next_prefill()
            if w is not None:
                s.note_prefill(w, sampled_token=5, now=0.0)
            batch = s.decode_batch()
            if batch is not None:
                s.note_decode(np.full(2, 9), now=0.0)
        assert len(s.completed) == 6
        assert s.allocator.num_live == 0
        assert s.allocator.num_free == 11
        np.testing.assert_array_equal(
            s.tables.asarray(), np.full((2, 16), DEAD_BLOCK))

    def test_submit_validation(self):
        s = self._sched()
        with pytest.raises(ValueError, match="cache rows"):
            s.submit(Request(rid=0, prompt=np.zeros(60, np.int32),
                             max_new_tokens=10))  # 69 > 16*4
        # fits a slot but can NEVER fit the pool: refusing eagerly beats
        # the permanent admission stall it would otherwise become
        tight = Scheduler(num_slots=2, block_size=8,
                          max_blocks_per_slot=8,
                          allocator=BlockAllocator(4), prefill_chunk=8)
        with pytest.raises(ValueError, match="never be admitted"):
            tight.submit(Request(rid=0, prompt=np.zeros(33, np.int32),
                                 max_new_tokens=8))  # 5 blocks > 3
        # the error names the knob AND the rounding recipe (ISSUE 10):
        # ceil((prompt + max_new - 1)/block_size) and the num_blocks
        # floor that would make the request admissible
        with pytest.raises(ValueError) as ei:
            tight.submit(Request(rid=3, prompt=np.zeros(33, np.int32),
                                 max_new_tokens=8))
        msg = str(ei.value)
        for needle in ("num_blocks=4", "ceil((prompt 33 + max_new_tokens "
                       "8 - 1) / block_size 8)", "needs 5 blocks",
                       "Raise num_blocks to >= 6"):
            assert needle in msg, f"submit error dropped {needle!r}: {msg}"
        with pytest.raises(ValueError, match=">= 1"):
            s.submit(Request(rid=0, prompt=np.zeros(4, np.int32),
                             max_new_tokens=0))
        with pytest.raises(ValueError, match="prefill_chunk"):
            Scheduler(num_slots=1, block_size=4, max_blocks_per_slot=4,
                      allocator=BlockAllocator(4), prefill_chunk=6)

    def test_future_arrivals_wait(self):
        s = self._sched()
        s.submit(Request(rid=0, prompt=np.zeros(4, np.int32),
                         max_new_tokens=2, arrival_s=5.0))
        assert s.admit(now=1.0) == []
        assert s.next_arrival() == 5.0
        assert s.admit(now=6.0) == [0]


class TestPagedDecodeAttention:
    def _scatter(self, kc, vc, nb_max, bs):
        """Scatter a contiguous (b, h_kv, nb_max*bs, d) cache into a
        shuffled pool + tables."""
        b, h_kv, _, d = kc.shape
        num_blocks = b * nb_max + 1
        rng = np.random.default_rng(0)
        ids = rng.permutation(np.arange(1, num_blocks))
        tables = np.zeros((b, nb_max), np.int32)
        pk = np.zeros((num_blocks, h_kv, bs, d), np.float32)
        pv = np.zeros((num_blocks, h_kv, bs, d), np.float32)
        n = 0
        for bi in range(b):
            for j in range(nb_max):
                tables[bi, j] = ids[n]
                pk[ids[n]] = np.asarray(kc[bi, :, j * bs:(j + 1) * bs])
                pv[ids[n]] = np.asarray(vc[bi, :, j * bs:(j + 1) * bs])
                n += 1
        return jnp.asarray(pk), jnp.asarray(pv), jnp.asarray(tables)

    def test_paged_matches_contiguous(self):
        b, h, h_kv, d, bs, nb_max = 3, 8, 2, 64, 128, 4
        q = jr.normal(K, (b, h, d))
        kc = jr.normal(jr.fold_in(K, 1), (b, h_kv, bs * nb_max, d))
        vc = jr.normal(jr.fold_in(K, 2), (b, h_kv, bs * nb_max, d))
        lens = jnp.array([5, 300, 0], jnp.int32)  # ragged + dead row
        pk, pv, tables = self._scatter(kc, vc, nb_max, bs)
        want = decode_attention(q, kc, vc, lens, impl="xla")
        got = decode_attention(q, pk, pv, lens, impl="xla",
                               block_tables=tables)
        # the gather fallback runs the EXACT contiguous math
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
        got_pl = decode_attention(q, pk, pv, lens, impl="pallas",
                                  block_tables=tables)
        np.testing.assert_allclose(np.asarray(got_pl), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_paged_with_bucketed_bias(self):
        from apex_tpu.ops.attention import BucketedBias
        b, h, h_kv, d, bs, nb_max = 2, 4, 2, 64, 128, 2
        bb = BucketedBias(jr.normal(jr.fold_in(K, 9), (16, h)) * 0.4,
                          bidirectional=False, max_distance=64)
        q = jr.normal(K, (b, h, d))
        kc = jr.normal(jr.fold_in(K, 1), (b, h_kv, bs * nb_max, d))
        vc = jr.normal(jr.fold_in(K, 2), (b, h_kv, bs * nb_max, d))
        lens = jnp.array([200, 77], jnp.int32)
        pk, pv, tables = self._scatter(kc, vc, nb_max, bs)
        want = decode_attention(q, kc, vc, lens, impl="xla", bias=bb)
        got = decode_attention(q, pk, pv, lens, impl="xla", bias=bb,
                               block_tables=tables)
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
        got_pl = decode_attention(q, pk, pv, lens, impl="pallas", bias=bb,
                                  block_tables=tables)
        np.testing.assert_allclose(np.asarray(got_pl), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_validation(self):
        q = jnp.zeros((2, 4, 64))
        pool = jnp.zeros((5, 2, 16, 64))
        lens = jnp.zeros((2,), jnp.int32)
        with pytest.raises(ValueError, match="block_tables"):
            decode_attention(q, pool, pool, lens,
                             block_tables=jnp.zeros((3, 4), jnp.int32))
        with pytest.raises(ValueError, match="integer"):
            decode_attention(q, pool, pool, lens,
                             block_tables=jnp.zeros((2, 4)))
        with pytest.raises(ValueError, match="h_kv"):
            decode_attention(q, jnp.zeros((5, 3, 16, 64)),
                             jnp.zeros((5, 3, 16, 64)), lens,
                             block_tables=jnp.zeros((2, 4), jnp.int32))


class TestFusedSample:
    def test_greedy_is_argmax(self):
        logits = jr.normal(K, (3, 17))
        np.testing.assert_array_equal(
            np.asarray(fused_sample(logits)),
            np.asarray(jnp.argmax(logits, -1)))

    def test_validation(self):
        logits = jnp.zeros((1, 8))
        with pytest.raises(ValueError, match="requires a PRNG key"):
            fused_sample(logits, None, temperature=1.0)
        with pytest.raises(ValueError, match="temperature"):
            fused_sample(logits, K, temperature=-1.0)
        with pytest.raises(ValueError, match="top_p"):
            fused_sample(logits, K, temperature=1.0, top_p=0.0)
        with pytest.raises(ValueError, match="top_k"):
            fused_sample(logits, K, temperature=1.0, top_k=-1)
        with pytest.raises(ValueError, match="\\(b, V\\)"):
            fused_sample(jnp.zeros((8,)))

    def test_kernel_matches_xla_fallback_token_for_token(self):
        """Shared noise -> the kernel's bisection thresholds select the
        SAME kept set as the fallback (they run the same helpers), so
        the sampled token agrees exactly, across knob combinations."""
        logits = jr.normal(jr.fold_in(K, 1), (4, 256)) * 2.0
        for tk, tp in [(0, 1.0), (7, 1.0), (0, 0.8), (11, 0.6)]:
            draw = jax.jit(lambda key, impl, tk=tk, tp=tp: fused_sample(
                logits, key, temperature=0.9, top_k=tk, top_p=tp,
                impl=impl), static_argnames=("impl",))
            for i in range(15):
                k = jr.fold_in(K, 1000 + i)
                np.testing.assert_array_equal(
                    np.asarray(draw(k, "xla")), np.asarray(draw(k, "pallas")),
                    err_msg=f"top_k={tk} top_p={tp} draw {i}")

    def test_topk_support(self):
        logits = jr.normal(jr.fold_in(K, 2), (4, 256))
        top = np.asarray(jax.lax.top_k(logits, 5)[1])
        draw = jax.jit(lambda key: fused_sample(
            logits, key, temperature=1.3, top_k=5, impl="pallas"))
        for i in range(40):
            toks = np.asarray(draw(jr.fold_in(K, 50 + i)))
            for bi in range(4):
                assert toks[bi] in top[bi]

    def test_topp_kept_set_matches_standalone_sampler(self):
        """The fused tail's bisection nucleus == the standalone
        sort/cumsum nucleus: over many draws both samplers' supports
        equal the numpy oracle set."""
        logits = jr.normal(jr.fold_in(K, 3), (3, 256)) * 2.0
        fused_draw = jax.jit(lambda key: fused_sample(
            logits, key, temperature=0.9, top_p=0.6, impl="pallas"))
        ref_draw = jax.jit(lambda key: sample_logits(
            logits, key, temperature=0.9, top_p=0.6))
        seen_f = [set() for _ in range(3)]
        seen_r = [set() for _ in range(3)]
        for i in range(300):
            tf = np.asarray(fused_draw(jr.fold_in(K, 5000 + i)))
            tr = np.asarray(ref_draw(jr.fold_in(K, 7000 + i)))
            for bi in range(3):
                seen_f[bi].add(int(tf[bi]))
                seen_r[bi].add(int(tr[bi]))
        s = np.asarray(logits, np.float64) / 0.9
        for bi in range(3):
            order = np.argsort(-s[bi])
            probs = np.exp(s[bi] - s[bi].max())
            probs /= probs.sum()
            csum = np.cumsum(probs[order])
            ncut = int(np.searchsorted(csum, 0.6) + 1)
            oracle = set(order[:ncut].tolist())
            assert seen_f[bi] == oracle, (bi, seen_f[bi], oracle)
            assert seen_r[bi] == oracle, (bi, seen_r[bi], oracle)

    def test_topp_composed_with_topk_filters(self):
        """Regression: top-p must still bite AFTER a top-k pass. The
        top-k filter pins the row min at the FILTERED sentinel; a
        bisection starting there never collapses, silently disabling
        top-p (caught in review). Same oracle as the standalone
        sampler's composition test: top_k=2 keeps {0, 1}; over that
        renormalized pair, top_p=0.5 keeps ONLY the head. (Vocab padded
        to the kernel's 128-lane grid with negligible-mass entries.)"""
        row = np.full(128, -20.0, np.float32)
        row[:6] = [3.0, 2.9, 2.8, 0.0, -1.0, -2.0]
        logits = jnp.asarray(row)[None]
        for impl in ("xla", "pallas"):
            draw = jax.jit(lambda key, impl=impl: fused_sample(
                logits, key, temperature=1.0, top_k=2, top_p=0.5,
                impl=impl))
            for i in range(30):
                assert int(draw(jr.fold_in(K, 900 + i))[0]) == 0, impl
        # and with top_p=0.6 the crossing token joins: both appear
        seen = set()
        draw = jax.jit(lambda key: fused_sample(
            logits, key, temperature=1.0, top_k=2, top_p=0.6,
            impl="pallas"))
        for i in range(200):
            seen.add(int(draw(jr.fold_in(K, 1200 + i))[0]))
        assert seen == {0, 1}


class TestServingEngine:
    def test_greedy_single_request_matches_decode_engine(
            self, tiny, reference_engine):
        """The acceptance anchor: a no-churn single-request workload
        through the paged, chunked engine decodes the IDENTICAL token
        sequence as DecodeEngine — and both serving programs compiled
        exactly once."""
        model, params = tiny
        prompt = np.asarray(jr.randint(jr.fold_in(K, 3), (7,), 0, 97),
                            np.int32)
        n = 8
        want = np.asarray(reference_engine.generate(
            params, jnp.asarray(prompt)[None], n))[0]
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64)
        done = eng.serve(params, [Request(rid=0, prompt=prompt,
                                          max_new_tokens=n)])
        np.testing.assert_array_equal(np.asarray(done[0].tokens), want)
        assert eng.prefill_chunk._cache_size() == 1
        assert eng.decode_step._cache_size() == 1
        assert done[0].first_token_s is not None
        assert done[0].finish_s >= done[0].first_token_s

    def test_churn_schedule_recompile_free_and_leak_free(
            self, tiny, reference_engine):
        """The scripted churn schedule: more requests than slots, mixed
        prompt/output lengths, a pool SMALLER than worst-case-everything
        — across every admit/evict the jit caches stay at 1, every
        request still matches the single-request engine token-for-token,
        and after N cycles every block is back on the free list."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=16, max_seq_len=64,
                            num_blocks=13)
        rng = np.random.default_rng(0)
        reqs = [_req(rng, i) for i in range(7)]
        sched = eng.make_scheduler()
        done = eng.serve(params, reqs, scheduler=sched)
        assert len(done) == 7
        assert eng.prefill_chunk._cache_size() == 1, "prefill re-traced"
        assert eng.decode_step._cache_size() == 1, "decode re-traced"
        for r in done:
            assert len(r.tokens) == r.max_new_tokens
            want = np.asarray(reference_engine.generate(
                params, jnp.asarray(r.prompt)[None], r.max_new_tokens))[0]
            np.testing.assert_array_equal(np.asarray(r.tokens), want,
                                          err_msg=f"rid {r.rid}")
        # no leak: with the prefix cache on, the only live blocks left
        # are the cache's refcounted residents (warm capacity, not
        # demand) and the accounting is refcount-exact
        alloc = sched.allocator
        alloc.check_accounting()
        assert alloc.leaked == 0
        assert alloc.num_live == alloc.num_resident
        assert alloc.num_live == sched.prefix_cache.num_resident_blocks
        # reclaiming the warm set restores the fresh pool exactly
        sched.prefix_cache.clear()
        assert alloc.num_live == 0
        assert alloc.num_free == eng.num_blocks - 1
        # and paging did its job: the high-water stayed under the pool
        assert 0 < eng.last_stats.blocks_high_water <= eng.num_blocks - 1

    def test_arrival_replay_and_ttft_stamps(self, tiny):
        """Requests with future arrivals are held; TTFT/finish stamps
        are ordered and on the serve clock."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64)
        reqs = [Request(rid=0, prompt=np.zeros(4, np.int32),
                        max_new_tokens=3, arrival_s=0.0),
                Request(rid=1, prompt=np.zeros(6, np.int32),
                        max_new_tokens=2, arrival_s=0.05)]
        done = eng.serve(params, reqs)
        assert {r.rid for r in done} == {0, 1}
        for r in done:
            assert r.admit_s >= r.arrival_s
            assert r.first_token_s >= r.admit_s
            assert r.finish_s >= r.first_token_s
            assert len(r.token_s) == len(r.tokens)

    def test_sampled_serving_uses_fused_tail_support(self, tiny):
        """top-k serving: every generated token of every request lies in
        the top-k of the teacher-forced logits on its own prefix."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64,
                            temperature=0.7, top_k=3)
        prompt = np.asarray(jr.randint(jr.fold_in(K, 5), (4,), 0, 97),
                            np.int32)
        done = eng.serve(params, [Request(rid=0, prompt=prompt,
                                          max_new_tokens=5)],
                         key=jr.fold_in(K, 60))
        toks = done[0].tokens
        seq = jnp.asarray(prompt)[None]
        for t in range(5):
            logits = model.logits(params, seq)[:, -1]
            top3 = np.asarray(jax.lax.top_k(logits, 3)[1])[0]
            assert toks[t] in top3
            seq = jnp.concatenate(
                [seq, jnp.asarray([[toks[t]]], jnp.int32)], axis=1)

    def test_validation(self, tiny):
        model, _ = tiny
        with pytest.raises(ValueError, match="multiple of.*block_size"):
            ServingEngine(model, num_slots=2, block_size=8, max_seq_len=60)
        with pytest.raises(ValueError, match="position table"):
            ServingEngine(model, num_slots=2, block_size=8,
                          max_seq_len=256)
        with pytest.raises(ValueError, match="prefill_chunk"):
            ServingEngine(model, num_slots=2, block_size=8,
                          max_seq_len=64, prefill_chunk=12)
        with pytest.raises(ValueError, match="num_slots"):
            ServingEngine(model, num_slots=0, block_size=8, max_seq_len=64)
        eng = ServingEngine(model, num_slots=1, block_size=8,
                            max_seq_len=64, temperature=1.0)
        with pytest.raises(ValueError, match="requires a key"):
            eng.serve({}, [])


class TestServingTier2:
    """Prefix caching + preemption through the REAL engine: greedy
    parity across hit/miss/evict/readmit churn, both jit caches pinned
    at 1, allocator accounting refcount-exact, prefill work actually
    skipped on a hit."""

    def test_prefix_hit_parity_and_skipped_chunks(
            self, tiny, reference_engine):
        """Requests sharing a system prompt: every token stream is
        IDENTICAL to the single-request engine, later requests hit the
        cache (fewer prefill chunks ran than a cold engine needs), and
        the shared blocks are one physical copy."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64)
        sysp = np.asarray(jr.randint(jr.fold_in(K, 21), (24,), 0, 97),
                          np.int32)
        reqs = [Request(
            rid=i,
            prompt=np.concatenate([sysp, np.full(3 + i, 10 + i,
                                                 np.int32)]),
            max_new_tokens=4, arrival_s=0.0) for i in range(4)]
        sched = eng.make_scheduler()
        done = eng.serve(params, reqs, scheduler=sched)
        assert len(done) == 4
        for r in done:
            want = np.asarray(reference_engine.generate(
                params, jnp.asarray(r.prompt)[None],
                r.max_new_tokens))[0]
            np.testing.assert_array_equal(np.asarray(r.tokens), want,
                                          err_msg=f"rid {r.rid}")
        hits = [r for r in done if r.prefix_hit_blocks > 0]
        assert hits, "no request hit the warm prefix cache"
        assert max(r.prefix_hit_blocks for r in hits) == 3  # 24/8 sysp
        # chunks actually skipped: a cold engine runs ceil(len/8) per
        # prompt; the sweep must have run strictly fewer
        cold = sum(-(-len(r.prompt) // 8) for r in done)
        assert eng.last_stats.prefill_chunks < cold
        assert eng.prefill_chunk._cache_size() == 1
        assert eng.decode_step._cache_size() == 1
        sched.allocator.check_accounting()
        assert sched.allocator.num_live == sched.allocator.num_resident

    def test_whole_prompt_cached_recomputes_last_block(
            self, tiny, reference_engine):
        """The COW edge: a prompt that is EXACTLY its cached blocks
        still recomputes the final block privately (its last-row
        logits seed the first token; shared blocks are never write
        targets) — and the tokens still match the baseline."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64)
        prompt = np.asarray(jr.randint(jr.fold_in(K, 22), (16,), 0, 97),
                            np.int32)  # exactly 2 blocks
        sched = eng.make_scheduler()
        done = eng.serve(
            params,
            [Request(rid=0, prompt=prompt.copy(), max_new_tokens=3),
             Request(rid=1, prompt=prompt.copy(), max_new_tokens=5)],
            scheduler=sched)
        want0 = np.asarray(reference_engine.generate(
            params, jnp.asarray(prompt)[None], 3))[0]
        want1 = np.asarray(reference_engine.generate(
            params, jnp.asarray(prompt)[None], 5))[0]
        by_rid = {r.rid: r for r in done}
        np.testing.assert_array_equal(np.asarray(by_rid[0].tokens), want0)
        np.testing.assert_array_equal(np.asarray(by_rid[1].tokens), want1)
        # whichever request came second shared only block 0 — never the
        # block holding the prompt's last token
        assert {r.prefix_hit_blocks for r in done} <= {0, 1}
        sched.allocator.check_accounting()

    def test_preemption_roundtrip_token_identical(
            self, tiny, reference_engine):
        """A pool sized below worst-case-everything under concurrent
        load: preemption engages, evicted-and-recomputed requests are
        TOKEN-IDENTICAL to the unpreempted baseline, both jit caches
        stay at 1 across the evict/readmit churn, and the pool drains
        refcount-exact."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64,
                            num_blocks=7)
        rng = np.random.default_rng(0)
        reqs = [Request(
            rid=i, prompt=np.asarray(rng.integers(0, 97, 12), np.int32),
            max_new_tokens=14) for i in range(4)]
        sched = eng.make_scheduler()
        done = eng.serve(params, reqs, scheduler=sched)
        assert len(done) == 4
        assert sched.preemptions > 0, "pool pressure never preempted"
        assert any(r.evictions > 0 for r in done)
        assert sched.recompute_tokens > 0
        for r in done:
            want = np.asarray(reference_engine.generate(
                params, jnp.asarray(r.prompt)[None],
                r.max_new_tokens))[0]
            np.testing.assert_array_equal(
                np.asarray(r.tokens), want,
                err_msg=f"rid {r.rid} (evictions={r.evictions})")
        assert eng.prefill_chunk._cache_size() == 1
        assert eng.decode_step._cache_size() == 1
        sched.allocator.check_accounting()
        assert sched.allocator.leaked == 0
        assert sched.allocator.num_live == sched.allocator.num_resident

    def test_trace_builder_is_deterministic(self):
        """bench.py's Poisson serve trace: same seed → token-identical
        requests and arrival times (replayable sweeps); a different
        seed actually varies."""
        import importlib.util
        root = os.path.join(os.path.dirname(__file__), "..")
        spec = importlib.util.spec_from_file_location(
            "bench_for_trace", os.path.join(root, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        kw = dict(n_req=6, offered_rps=100.0, vocab=97,
                  prompt_rng=(4, 20), newtok_rng=(2, 6),
                  sys_prompt_len=8)
        t1 = bench.build_serve_trace(3, **kw)
        t2 = bench.build_serve_trace(3, **kw)
        t3 = bench.build_serve_trace(4, **kw)
        assert len(t1) == len(t2) == 6
        for a, b in zip(t1, t2):
            np.testing.assert_array_equal(a.prompt, b.prompt)
            assert a.max_new_tokens == b.max_new_tokens
            assert a.arrival_s == b.arrival_s
        assert any(
            len(a.prompt) != len(c.prompt)
            or (a.prompt.shape == c.prompt.shape
                and (a.prompt != c.prompt).any())
            for a, c in zip(t1, t3))
        # the shared-prefix population really shares: at least two
        # requests of the seeded trace carry an identical first block
        big = bench.build_serve_trace(0, n_req=16, offered_rps=100.0,
                                      vocab=97, prompt_rng=(4, 20),
                                      newtok_rng=(2, 6),
                                      sys_prompt_len=8)
        heads = [tuple(r.prompt[:8]) for r in big]
        assert any(heads.count(h) >= 2 for h in set(heads))


class TestHotSwap:
    """Serving weight hot-swap (ISSUE 14): a new checkpoint's params
    load into a live engine BETWEEN dispatch steps as a contents-only
    mutation — stable avals, both jit caches pinned at 1, in-flight
    requests finish token-identically to a no-swap baseline when the
    weights are equal, and the ``swap`` lifecycle event rides
    ``ServeTelemetry``."""

    def _reqs(self, n=3, max_new=10):
        rng = np.random.default_rng(5)
        return [_req(rng, i, max_prompt=20, max_new=max_new)
                for i in range(n)]

    def _serve(self, tiny, swap_params=None, at_step=None, reqs=None,
               telemetry=None):
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64)
        if swap_params is not None:
            eng.request_swap(swap_params, at_step=at_step,
                             source="test-ckpt")
        done = eng.serve(params, reqs if reqs is not None
                         else self._reqs(), telemetry=telemetry)
        return eng, {r.rid: list(r.tokens) for r in done}

    def test_equal_weights_swap_is_token_identical_and_pinned(self, tiny):
        """THE acceptance witness: mid-flight swap of EQUAL weights —
        streams token-identical to the no-swap run, caches at 1."""
        _, params = tiny
        reqs_a, reqs_b = self._reqs(), self._reqs()
        eng0, base = self._serve(tiny, reqs=reqs_a)
        clone = jax.tree.map(lambda x: jnp.array(x), params)
        eng1, swapped = self._serve(tiny, swap_params=clone, at_step=5,
                                    reqs=reqs_b)
        assert base == swapped
        assert eng1.last_stats.swaps == 1
        for eng in (eng0, eng1):
            assert eng.prefill_chunk._cache_size() == 1
            assert eng.decode_step._cache_size() == 1

    def test_different_weights_actually_apply(self, tiny):
        """The swap is not a no-op: perturbed weights change the tokens
        generated AFTER the swap point (deterministic greedy decode —
        no flake surface)."""
        model, params = tiny
        jolted = jax.tree.map(lambda x: x + 0.5, params)
        reqs_a = [Request(rid=0, prompt=np.zeros(4, np.int32),
                          max_new_tokens=12)]
        reqs_b = [Request(rid=0, prompt=np.zeros(4, np.int32),
                          max_new_tokens=12)]
        _, base = self._serve(tiny, reqs=reqs_a)
        eng, swapped = self._serve(tiny, swap_params=jolted, at_step=4,
                                   reqs=reqs_b)
        assert eng.last_stats.swaps == 1
        assert base != swapped  # the new weights really serve
        assert eng.decode_step._cache_size() == 1  # still no retrace

    def test_unreached_swap_is_dropped_not_leaked(self, tiny):
        """A deferred swap whose at_step the run never reaches must NOT
        survive into a later serve() call on the same engine — dropped
        at drain, with stats.swaps == 0 as the tell."""
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64)
        jolted = jax.tree.map(lambda x: x + 1.0, params)
        eng.request_swap(jolted, at_step=10_000)
        done = eng.serve(params, [Request(
            rid=0, prompt=np.zeros(4, np.int32), max_new_tokens=3)])
        assert eng.last_stats.swaps == 0
        assert eng._pending_swap is None  # dropped, not deferred
        # a later run on the same engine serves the ORIGINAL weights
        want = [list(r.tokens) for r in done]
        done2 = eng.serve(params, [Request(
            rid=0, prompt=np.zeros(4, np.int32), max_new_tokens=3)])
        assert eng.last_stats.swaps == 0
        assert [list(r.tokens) for r in done2] == want
        # the drop survives a MID-RUN exception too (exception-safety
        # of the documented contract): a crashed serve must not leave
        # the stale swap armed for the next call
        eng.request_swap(jolted, at_step=10_000)
        real_decode = eng.decode_step

        def boom(*a, **k):
            raise RuntimeError("injected mid-serve failure")

        eng.decode_step = boom
        try:
            with pytest.raises(RuntimeError, match="injected"):
                eng.serve(params, [Request(
                    rid=0, prompt=np.zeros(4, np.int32),
                    max_new_tokens=3)])
        finally:
            eng.decode_step = real_decode
        assert eng._pending_swap is None

    def test_swap_event_rides_telemetry_and_record(self, tiny, tmp_path):
        import io as _io

        from apex_tpu.monitor.report import (format_serve_timeline,
                                             serve_timeline)
        from apex_tpu.serving.telemetry import ServeTelemetry

        _, params = tiny
        stream = _io.StringIO()
        monitor.enable(stream=stream)
        try:
            tel = ServeTelemetry(slots=2, status="SKIP",
                                 reason="cpu smoke")
            clone = jax.tree.map(lambda x: jnp.array(x), params)
            self._serve(tiny, swap_params=clone, at_step=3,
                        telemetry=tel)
        finally:
            monitor.disable()
        lines = stream.getvalue().splitlines()
        assert monitor.validate_jsonl(lines) == []
        recs = [json.loads(l) for l in lines]
        swaps = [r for r in recs if r.get("phase") == "swap"]
        assert len(swaps) == 1
        assert swaps[0]["rid"] == -1
        assert swaps[0]["swap_source"] == "test-ckpt"
        assert swaps[0]["step"] >= 3
        assert tel.swaps == 1
        assert tel.final_fields()["swaps"] == 1
        # the timeline renders the swap instead of dropping it
        tl = serve_timeline(recs)
        assert len(tl["swaps"]) == 1
        assert "hot-swapped" in format_serve_timeline(tl)

    def test_aval_mismatch_is_eager_and_leaf_named(self, tiny):
        model, params = tiny
        eng = ServingEngine(model, num_slots=2, block_size=8,
                            prefill_chunk=8, max_seq_len=64)
        bad = dict(params)
        bad["lnf_w"] = jnp.zeros((params["lnf_w"].shape[0] + 1,))
        eng.request_swap(bad)
        with pytest.raises(ValueError, match=r"lnf_w"):
            eng.serve(params, [Request(rid=0,
                                       prompt=np.zeros(4, np.int32),
                                       max_new_tokens=2)])
        # dtype drift is named too
        eng2 = ServingEngine(model, num_slots=2, block_size=8,
                             prefill_chunk=8, max_seq_len=64)
        bad2 = dict(params)
        bad2["lnf_w"] = params["lnf_w"].astype(jnp.bfloat16)
        eng2.request_swap(bad2)
        with pytest.raises(ValueError, match="bfloat16"):
            eng2.serve(params, [Request(rid=0,
                                        prompt=np.zeros(4, np.int32),
                                        max_new_tokens=2)])
        # structure drift names the added/missing keys
        eng3 = ServingEngine(model, num_slots=2, block_size=8,
                             prefill_chunk=8, max_seq_len=64)
        bad3 = dict(params, extra_head=jnp.zeros((2,)))
        eng3.request_swap(bad3)
        with pytest.raises(ValueError, match="extra_head"):
            eng3.serve(params, [Request(rid=0,
                                        prompt=np.zeros(4, np.int32),
                                        max_new_tokens=2)])


class TestServeRecord:
    def test_emit_serve_roundtrip_report_and_validator(self, tmp_path):
        path = tmp_path / "events.jsonl"
        monitor.enable(str(path))
        try:
            monitor.emit_meta(device_kind="cpu")
            rec = monitor.emit_serve(
                "OK", tokens_per_s=4321.0, latency_p50_ms=1.2,
                latency_p99_ms=3.4, ttft_p50_ms=20.0, ttft_p99_ms=55.0,
                occupancy_pct=87.5, vs_single_request=1.9,
                greedy_parity=True, jit_cache_ok=True, requests=32,
                slots=8, block_size=128, blocks_high_water=40)
            assert monitor.validate(rec) == []
        finally:
            monitor.disable()
        lines = path.read_text().splitlines()
        assert monitor.validate_jsonl(lines) == []
        from apex_tpu.monitor import report as monitor_report
        summary = monitor_report.aggregate(
            monitor_report.read_records(lines))
        assert summary["serve"]["tokens_per_s"] == 4321.0
        assert summary["serve"]["status"] == "OK"
        rendered = monitor_report.render(summary)
        assert "serve" in rendered and "p50/p99 1.20/3.40" in rendered

    def test_ok_serve_record_with_nan_refused(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="non-finite"):
            reg.emit_serve("OK", tokens_per_s=float("nan"))

    def test_skip_needs_reason(self):
        reg = monitor.MetricsRegistry()
        with pytest.raises(ValueError, match="reason"):
            reg.emit_serve("SKIP")
        rec = reg.emit_serve("SKIP", reason="no TPU",
                             vs_single_request=("skipped", "no TPU"))
        assert rec["vs_single_request"] == {"skipped": True,
                                            "reason": "no TPU"}
        assert monitor.validate(rec) == []
        bare = {k: v for k, v in rec.items() if k != "reason"}
        assert any("reason" in e for e in monitor.validate(bare))

    def test_validator_cli_serve_dispatch(self, tmp_path, capsys):
        """--serve forced dispatch: a valid serve stream passes, a
        stream without a serve record fails, a wrong-kind artifact
        fails — the drift test pinning the CLI contract."""
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        import validate_metrics
        reg = monitor.MetricsRegistry()
        rec = reg.emit_serve("SKIP", reason="no TPU")
        good = tmp_path / "serve.jsonl"
        good.write_text(json.dumps(rec) + "\n")
        assert validate_metrics.main([str(good)]) == 0          # content
        assert validate_metrics.main(["--serve", str(good)]) == 0
        capsys.readouterr()
        # content dispatch catches a malformed serve record
        bad = tmp_path / "bad.jsonl"
        bad_rec = dict(rec, status="OK", tokens_per_s=float("nan"))
        bad.write_text(json.dumps(bad_rec).replace("NaN", '"nan"') + "\n")
        assert validate_metrics.main([str(bad)]) == 1
        # forced dispatch: a stream with no serve record must fail
        other = tmp_path / "other.jsonl"
        other.write_text(json.dumps(
            reg.emit_decode("SKIP", reason="no TPU")) + "\n")
        assert validate_metrics.main(["--serve", str(other)]) == 1
        err = capsys.readouterr().err
        assert "expected a 'serve' artifact" in err
        # a multi-record stream without a serve record also fails
        stream = tmp_path / "stream.jsonl"
        stream.write_text(
            json.dumps(reg.emit_decode("SKIP", reason="no TPU")) + "\n"
            + json.dumps(reg.emit_meta(device_kind="cpu")) + "\n")
        assert validate_metrics.main(["--serve", str(stream)]) == 1
        assert "no 'serve' record" in capsys.readouterr().err


class TestServeBenchLeg:
    def test_bench_serve_emits_valid_skip_record_off_tpu(self, tmp_path):
        """The serving bench leg end-to-end at smoke scale: off-TPU it
        must print/emit an explicit SKIP record — schema-valid, no nan,
        greedy parity + pinned jit caches witnessed — and the stream
        must pass the validator CLI."""
        root = os.path.join(os.path.dirname(__file__), "..")
        path = tmp_path / "serve.jsonl"
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   APEX_TPU_MONITOR=str(path))
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "bench.py"), "--serve"],
            capture_output=True, text=True, env=env, cwd=root, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        record = json.loads(proc.stdout.strip().splitlines()[-1])
        assert record["kind"] == "serve" and record["status"] == "SKIP"
        assert record["greedy_parity"] is True
        assert record["jit_cache_ok"] is True
        assert record["blocks_high_water"] >= 1
        # serving tier 2: the sweep's pool is sized below worst case —
        # preemption must engage, parity must hold ACROSS the churn
        # (incl. evicted and prefix-hit requests), the trace is seeded,
        # and the prefix/preemption fields ride the record
        assert record["churn_parity"] is True
        assert record["churn_parity_checked"] >= 1
        assert record["preemptions"] >= 1
        assert record["trace_seed"] == 0
        assert isinstance(record["prefix_hit_rate"], (int, float, dict))
        assert record["serve_anomaly"]["leaked_blocks"] == 0
        assert record["blocks_resident"] >= 0
        assert monitor.validate(record) == []
        assert monitor.validate_jsonl(
            path.read_text().splitlines()) == []
