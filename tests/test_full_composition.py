"""The 16-device full-composition gate (VERDICT r4 next #2).

The 8-device harness can host at most three parallel axes at extent >= 2
plus dp; 2^4 = 16 means the full dp x tp x pp x (ep|cp) product was
previously *inferred* from 3-axis slices. These tests execute it: each
respawns a subprocess with a 16-device virtual CPU platform (the env vars
must be set before jax initializes, hence the respawn — same recipe as
``__graft_entry__._respawn_on_virtual_mesh``) and runs ONE program binding
all four axes at extent 2 with serial-oracle loss AND gradient parity:

* ``_dryrun_moe_all_axes(16)``   — dp2 x tp2 x pp2 x ep2 (GPT-MoE through
  the pipeline; at n=16 its axis picks hit 2/2/2/2 with dp=2, closing the
  "dp=1 at 8 devices" gap of ``tests/test_moe.py::test_tp2_pp2_ep2_one_mesh``)
* ``_dryrun_tp_cp_pipeline(16)`` — dp2 x tp2 x pp2 x cp2 (dense GPT,
  Megatron-SP on the tp linears, zigzag ring attention inside the ticks)

The same programs run in the driver gate via ``dryrun_multichip(16)``.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_16dev(snippet: str) -> str:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [t for t in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in t]
    flags.append("--xla_force_host_platform_device_count=16")
    env["XLA_FLAGS"] = " ".join(flags)
    child = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import __graft_entry__ as g\n"
        f"{snippet}\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", child], cwd=_REPO, env=env,
        capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, (
        f"16-device composition failed (rc={proc.returncode}):\n"
        f"{proc.stderr[-3000:]}")
    return proc.stdout


@pytest.mark.slow
def test_dp2_tp2_pp2_ep2():
    out = _run_16dev(
        "loss = g._dryrun_moe_all_axes(16)\n"
        "print('MOE16', loss)")
    assert "MOE16" in out


@pytest.mark.slow
def test_dp2_tp2_pp2_cp2():
    out = _run_16dev(
        "loss = g._dryrun_tp_cp_pipeline(16)\n"
        "print('TPCP16', loss)")
    assert "TPCP16" in out
