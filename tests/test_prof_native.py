"""Profiler + native-tier tests (pyprof / apex_C analogs)."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest


class TestNative:
    def test_layout_planner_values(self):
        from apex_tpu import native

        sizes = [100, 2048, 5, 0, 1024, 3000]
        c2t, off = native.plan_layout(sizes, 1024)
        # chunk counts: 1, 2, 1, 1 (zero-size still owns a chunk), 1, 3
        np.testing.assert_array_equal(
            c2t, [0, 1, 1, 2, 3, 4, 5, 5, 5])
        np.testing.assert_array_equal(
            off, np.array([0, 1, 3, 4, 5, 6]) * 1024)

    def test_make_layout_uses_planner(self):
        from apex_tpu.optimizers import multi_tensor as mt

        tree = {"a": jnp.zeros((100,)), "b": jnp.zeros((2048,)), "c": jnp.zeros(())}
        layout = mt.make_layout(tree, 1024)
        np.testing.assert_array_equal(
            np.asarray(layout.chunk_to_tensor), [0, 1, 1, 2])

    def test_trace_aggregator(self):
        from apex_tpu import native

        if not native.available():
            assert native.build(), "native build failed"
        agg = native.aggregate_trace(
            '[{"f":"gemm","flops":1e9,"bytes":1e6,"t":0.001},'
            '{"f":"gemm","flops":2e9,"bytes":2e6,"t":0.002},'
            '{"f":"collective","flops":0,"bytes":5e6,"t":0.004}]'
        )
        assert agg["gemm"]["count"] == 2
        np.testing.assert_allclose(agg["gemm"]["flops"], 3e9)
        assert agg["collective"]["t"] == 0.004


class TestProf:
    def test_annotate_preserves_semantics(self):
        from apex_tpu.prof import annotate

        @annotate("my_op")
        def f(x):
            return x * 2 + 1

        x = jnp.arange(4.0)
        np.testing.assert_array_equal(jax.jit(f)(x), x * 2 + 1)

    def test_cost_analysis_reports_flops(self):
        from apex_tpu.prof import cost_analysis

        def f(a, b):
            return a @ b

        a = jnp.zeros((128, 256))
        b = jnp.zeros((256, 64))
        ca = cost_analysis(f, a, b)
        # 2*M*N*K flops
        assert ca.get("flops", 0) >= 2 * 128 * 256 * 64 * 0.9

    def test_analyze_ops_and_report(self):
        from apex_tpu.prof import analyze_ops
        from apex_tpu.prof.analyzer import report

        ops = [
            {"name": "dot_general.1", "flops": 1e9, "bytes": 1e6, "time_s": 1e-3},
            {"name": "dot_general.2", "flops": 1e9, "bytes": 1e6, "time_s": 1e-3},
            {"name": "all-reduce.0", "flops": 0, "bytes": 4e6, "time_s": 2e-3},
            {"name": "copy.3", "flops": 0, "bytes": 1e7, "time_s": 5e-4},
        ]
        stats = analyze_ops(ops)
        assert stats["gemm"].count == 2
        assert stats["collective"].bytes_accessed == 4e6
        txt = report(stats)
        assert "gemm" in txt and "bound" in txt

    def test_analyze_many_ops_native_path(self):
        from apex_tpu import native
        from apex_tpu.prof import analyze_ops

        if not native.available():
            pytest.skip("native lib not built")
        ops = [{"name": "dot.x", "flops": 1.0, "bytes": 1.0, "time_s": 1e-6}
               for _ in range(2000)]
        stats = analyze_ops(ops)
        assert stats["gemm"].count == 2000
        np.testing.assert_allclose(stats["gemm"].flops, 2000.0)


class TestOpFamilies:
    """The ROADMAP item-5 op-family slice: dynamic-slice/-update-slice,
    real convolutions and embedding-style gathers classify into their
    own rows so every gate workload's profile table attributes them."""

    def test_dynamic_slice_names_classify_memory(self):
        from apex_tpu.prof.analyzer import _family_of

        assert _family_of("dynamic-slice.4") == "memory"
        assert _family_of("dynamic-update-slice.8") == "memory"
        assert _family_of("decode_step/dynamic-update-slice.8") == "memory"
        # category dispatch agrees (XProf traces)
        assert _family_of("fusion.3", "dynamic-slice") == "memory"
        assert _family_of("x.1", "dynamic-update-slice") == "memory"

    def test_conv_splits_from_gemm(self):
        from apex_tpu.prof.analyzer import _family_of

        # a REAL convolution HLO: "convolution" category + conv name
        assert _family_of("resnet/conv.3", "convolution") == "conv"
        assert _family_of("convolution.7", "convolution") == "conv"
        # dot-rooted MXU work stays gemm ("convolution" is also the TPU
        # category label for matmul fusions)
        assert _family_of("gpt/attn/dot.7", "convolution") == "gemm"
        assert _family_of("fusion.276", "convolution fusion") == "gemm"
        # name-only fallback (no category): conv vs convert ordering
        assert _family_of("conv.1") == "conv"
        assert _family_of("convert.2") == "cast"

    def test_embedding_gathers_classify_embedding(self):
        from apex_tpu.prof.analyzer import _family_of

        assert _family_of("gpt/embedding/gather.3") == "embedding"
        assert _family_of("bert/embeddings/fusion.9",
                          "loop fusion") == "embedding"
        assert _family_of("embed_tokens/dynamic-slice.1") == "embedding"
        # MXU work under an embedding scope is NOT reclassified (the
        # tied unembedding matmul must stay gemm)
        assert _family_of("gpt/embedding/dot.2") == "gemm"
        # plain gathers without the scope stay memory
        assert _family_of("scatter/gather.3") == "memory"

    def test_analyze_ops_emits_conv_and_embedding_rows(self):
        from apex_tpu.prof import analyze_ops
        from apex_tpu.prof.analyzer import report

        ops = [
            {"name": "resnet/conv.1", "category": "convolution",
             "flops": 4e9, "bytes": 1e6, "time_s": 2e-3},
            {"name": "gpt/embedding/gather.3", "flops": 0.0,
             "bytes": 2e6, "time_s": 1e-3},
            {"name": "gpt/embedding/gather.3", "flops": 0.0,
             "bytes": 2e6, "time_s": 1e-3},
            {"name": "gpt/attn/dot.7", "flops": 1e9, "bytes": 1e6,
             "time_s": 1e-3},
            {"name": "decode/dynamic-update-slice.2", "flops": 0.0,
             "bytes": 5e5, "time_s": 1e-4},
        ]
        stats = analyze_ops(ops)
        assert stats["conv"].count == 1
        assert stats["conv"].flops == pytest.approx(4e9)
        assert stats["embedding"].count == 2
        assert stats["embedding"].bytes_accessed == pytest.approx(4e6)
        assert stats["gemm"].count == 1
        assert stats["memory"].count == 1
        txt = report(stats)
        assert "conv" in txt and "embedding" in txt


class TestAggregatorParity:
    """ISSUE satellite: the native C++ aggregator
    (csrc/trace_analyzer.cpp) and the numpy fallback must agree on a
    shared trace fixture — asserted against hand-computed ground truth
    whichever is built, and against each other when both are."""

    def _fixture_ops(self):
        # >= 1024 ops so the native path engages; families cover the new
        # conv/embedding rows too
        ops = []
        for i in range(400):
            ops.append({"name": f"gpt/attn/dot.{i}", "flops": 1e9,
                        "bytes": 1e6, "time_s": 1e-4})
        for i in range(300):
            ops.append({"name": f"resnet/conv.{i}",
                        "category": "convolution", "flops": 2e9,
                        "bytes": 2e6, "time_s": 2e-4})
        for i in range(200):
            ops.append({"name": f"gpt/embedding/gather.{i}", "flops": 0.0,
                        "bytes": 3e6, "time_s": 3e-4})
        for i in range(124):
            ops.append({"name": f"tp/all-reduce.{i}", "flops": 0.0,
                        "bytes": 4e6, "time_s": 4e-4})
        return ops

    def _expected(self):
        return {
            "gemm": (400, 400 * 1e9, 400 * 1e6, 400 * 1e-4),
            "conv": (300, 300 * 2e9, 300 * 2e6, 300 * 2e-4),
            "embedding": (200, 0.0, 200 * 3e6, 200 * 3e-4),
            "collective": (124, 0.0, 124 * 4e6, 124 * 4e-4),
        }

    def _check(self, stats):
        for fam, (n, f, b, t) in self._expected().items():
            s = stats[fam]
            assert s.count == n, fam
            np.testing.assert_allclose(s.flops, f, rtol=1e-12)
            np.testing.assert_allclose(s.bytes_accessed, b, rtol=1e-12)
            np.testing.assert_allclose(s.time_s, t, rtol=1e-9)

    def test_native_and_numpy_agree_on_shared_fixture(self):
        from apex_tpu import native
        from apex_tpu.prof import analyze_ops

        ops = self._fixture_ops()
        have_native = native.available() or native.build()

        # forced numpy fallback
        saved = (native._lib, native._tried)
        native._lib, native._tried = None, True
        try:
            stats_py = analyze_ops(ops)
        finally:
            native._lib, native._tried = saved
        self._check(stats_py)  # fallback vs ground truth, always

        if not have_native:
            pytest.skip("native build unavailable; numpy path asserted")
        stats_native = analyze_ops(ops)
        self._check(stats_native)  # native vs ground truth
        assert set(stats_native) == set(stats_py)
        for fam in stats_py:
            a, b = stats_native[fam], stats_py[fam]
            assert a.count == b.count
            np.testing.assert_allclose(a.flops, b.flops, rtol=1e-12)
            np.testing.assert_allclose(a.bytes_accessed, b.bytes_accessed,
                                       rtol=1e-12)
            np.testing.assert_allclose(a.time_s, b.time_s, rtol=1e-9)
