"""Profiler + native-tier tests (pyprof / apex_C analogs)."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest


class TestNative:
    def test_layout_planner_values(self):
        from apex_tpu import native

        sizes = [100, 2048, 5, 0, 1024, 3000]
        c2t, off = native.plan_layout(sizes, 1024)
        # chunk counts: 1, 2, 1, 1 (zero-size still owns a chunk), 1, 3
        np.testing.assert_array_equal(
            c2t, [0, 1, 1, 2, 3, 4, 5, 5, 5])
        np.testing.assert_array_equal(
            off, np.array([0, 1, 3, 4, 5, 6]) * 1024)

    def test_make_layout_uses_planner(self):
        from apex_tpu.optimizers import multi_tensor as mt

        tree = {"a": jnp.zeros((100,)), "b": jnp.zeros((2048,)), "c": jnp.zeros(())}
        layout = mt.make_layout(tree, 1024)
        np.testing.assert_array_equal(
            np.asarray(layout.chunk_to_tensor), [0, 1, 1, 2])

    def test_trace_aggregator(self):
        from apex_tpu import native

        if not native.available():
            assert native.build(), "native build failed"
        agg = native.aggregate_trace(
            '[{"f":"gemm","flops":1e9,"bytes":1e6,"t":0.001},'
            '{"f":"gemm","flops":2e9,"bytes":2e6,"t":0.002},'
            '{"f":"collective","flops":0,"bytes":5e6,"t":0.004}]'
        )
        assert agg["gemm"]["count"] == 2
        np.testing.assert_allclose(agg["gemm"]["flops"], 3e9)
        assert agg["collective"]["t"] == 0.004


class TestProf:
    def test_annotate_preserves_semantics(self):
        from apex_tpu.prof import annotate

        @annotate("my_op")
        def f(x):
            return x * 2 + 1

        x = jnp.arange(4.0)
        np.testing.assert_array_equal(jax.jit(f)(x), x * 2 + 1)

    def test_cost_analysis_reports_flops(self):
        from apex_tpu.prof import cost_analysis

        def f(a, b):
            return a @ b

        a = jnp.zeros((128, 256))
        b = jnp.zeros((256, 64))
        ca = cost_analysis(f, a, b)
        # 2*M*N*K flops
        assert ca.get("flops", 0) >= 2 * 128 * 256 * 64 * 0.9

    def test_analyze_ops_and_report(self):
        from apex_tpu.prof import analyze_ops
        from apex_tpu.prof.analyzer import report

        ops = [
            {"name": "dot_general.1", "flops": 1e9, "bytes": 1e6, "time_s": 1e-3},
            {"name": "dot_general.2", "flops": 1e9, "bytes": 1e6, "time_s": 1e-3},
            {"name": "all-reduce.0", "flops": 0, "bytes": 4e6, "time_s": 2e-3},
            {"name": "copy.3", "flops": 0, "bytes": 1e7, "time_s": 5e-4},
        ]
        stats = analyze_ops(ops)
        assert stats["gemm"].count == 2
        assert stats["collective"].bytes_accessed == 4e6
        txt = report(stats)
        assert "gemm" in txt and "bound" in txt

    def test_analyze_many_ops_native_path(self):
        from apex_tpu import native
        from apex_tpu.prof import analyze_ops

        if not native.available():
            pytest.skip("native lib not built")
        ops = [{"name": "dot.x", "flops": 1.0, "bytes": 1.0, "time_s": 1e-6}
               for _ in range(2000)]
        stats = analyze_ops(ops)
        assert stats["gemm"].count == 2000
        np.testing.assert_allclose(stats["gemm"].flops, 2000.0)
