"""MoE + expert parallelism tests (TPU-first extension; the reference has no
MoE — SURVEY.md §2.3 EP row)."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer.moe import MoEMLP, moe_layer, router_topk

K = jr.PRNGKey(77)


class TestRouter:
    def test_topk_dispatch_shapes_and_onehot(self):
        logits = jr.normal(K, (16, 4))
        dispatch, combine, aux = router_topk(logits, capacity=8, k=2)
        assert dispatch.shape == (16, 4, 8)
        # every token claims at most k slots, one-hot per (expert, slot)
        assert float(jnp.max(dispatch)) == 1.0
        per_token = jnp.sum(dispatch, axis=(1, 2))
        assert float(jnp.max(per_token)) <= 2.0
        # no expert slot double-claimed
        per_slot = jnp.sum(dispatch, axis=0)
        assert float(jnp.max(per_slot)) <= 1.0

    def test_uniform_router_balance_loss_is_one(self):
        logits = jnp.zeros((64, 8))
        _, _, aux = router_topk(logits, capacity=16, k=1)
        np.testing.assert_allclose(float(aux["load_balance_loss"]), 1.0, rtol=1e-5)

    def test_capacity_drops_overflow(self):
        # all tokens want expert 0, capacity 2 -> only 2 slots filled in
        # round 1; round 2 routes to the runner-up expert
        logits = jnp.tile(jnp.array([[5.0, 1.0, 0.0, 0.0]]), (10, 1))
        dispatch, combine, _ = router_topk(logits, capacity=2, k=1)
        assert float(jnp.sum(dispatch[:, 0])) == 2.0
        assert float(jnp.sum(dispatch)) == 2.0  # rest dropped

    def test_identical_experts_reduce_to_dense_mlp(self):
        """With every expert holding the same weights and gates renormalized,
        MoE(x) == MLP(x) for every non-dropped token."""
        T, H, F, E = 32, 16, 32, 4
        bank = MoEMLP(E, H, F)
        params = bank.init(K)
        # make all experts identical
        for n in ("w1", "b1", "w2", "b2"):
            params[n] = jnp.broadcast_to(params[n][:1], params[n].shape)
        x = jr.normal(jr.fold_in(K, 1), (T, H))
        y, _ = moe_layer(params, x, k=2, capacity_factor=4.0)  # ample capacity
        w1, b1 = params["w1"][0], params["b1"][0]
        w2, b2 = params["w2"][0], params["b2"][0]
        ref = jax.nn.gelu(x @ w1 + b1, approximate=True) @ w2 + b2
        # hardware: fp32 matmuls run bf16-rounded at default MXU precision
        tol = (1e-4, 1e-5) if jax.default_backend() != "tpu" else (2e-2, 2e-2)
        np.testing.assert_allclose(y, ref, rtol=tol[0], atol=tol[1])


class TestExpertParallel:
    def test_ep_matches_single_device(self):
        """8-way expert parallelism over the dp axis must reproduce the
        unsharded layer: same params, same tokens, same output."""
        mesh = mesh_lib.make_mesh()  # dp = 8 = expert-parallel degree
        T, H, F, E = 64, 16, 32, 8
        bank = MoEMLP(E, H, F)
        params = bank.init(K)
        x = jr.normal(jr.fold_in(K, 2), (T, H))

        y_ref, aux_ref = moe_layer(params, x, k=2, capacity_factor=4.0)

        def shard(params, x):
            # shard_map's in_specs hand each device its expert slice of
            # w1/b1/w2/b2 and its token slice of x; the router replicates
            y, _ = moe_layer(params, x, k=2, capacity_factor=4.0,
                             axis_name="dp")
            return y

        y = mesh_lib.shard_map(
            shard, mesh=mesh,
            in_specs=({"router": P(), "w1": P("dp"), "b1": P("dp"),
                       "w2": P("dp"), "b2": P("dp")}, P("dp")),
            out_specs=P("dp"),
        )(params, x)
        np.testing.assert_allclose(y, y_ref, rtol=1e-4, atol=1e-5)

    def test_ep_router_shape_mismatch_raises(self):
        mesh = mesh_lib.make_mesh()
        if mesh.shape["dp"] < 2:
            pytest.skip("mismatch needs dp > 1 (4 local experts x dp != 4)")
        bank = MoEMLP(4, 8, 16)  # 4 experts but dp=8 -> E = local*8 != 4
        params = bank.init(K)
        x = jr.normal(K, (16, 8))
        with pytest.raises(ValueError, match="router covers"):
            mesh_lib.shard_map(
                lambda p, x: moe_layer(p, x, axis_name="dp")[0],
                mesh=mesh,
                in_specs=({"router": P(), "w1": P(), "b1": P(),
                           "w2": P(), "b2": P()}, P("dp")),
                out_specs=P("dp"),
            )(params, x)


class TestMoEGrads:
    def test_grads_flow_to_experts_and_router(self):
        T, H, F, E = 32, 16, 32, 4
        bank = MoEMLP(E, H, F)
        params = bank.init(K)
        x = jr.normal(jr.fold_in(K, 3), (T, H))

        def loss(params):
            y, aux = moe_layer(params, x, k=2, capacity_factor=2.0)
            return jnp.sum(y ** 2) + 0.01 * aux["load_balance_loss"]

        g = jax.grad(loss)(params)
        for n in ("router", "w1", "w2"):
            assert float(jnp.sum(jnp.abs(g[n]))) > 0.0, n
