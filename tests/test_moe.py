"""MoE + expert parallelism tests (TPU-first extension; the reference has no
MoE — SURVEY.md §2.3 EP row)."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer.moe import (MoEMLP, moe_layer, router_aux_zeros,
                                      router_topk)

K = jr.PRNGKey(77)


class TestRouter:
    def test_topk_dispatch_shapes_and_onehot(self):
        logits = jr.normal(K, (16, 4))
        dispatch, combine, aux = router_topk(logits, capacity=8, k=2)
        assert dispatch.shape == (16, 4, 8)
        # every token claims at most k slots, one-hot per (expert, slot)
        assert float(jnp.max(dispatch)) == 1.0
        per_token = jnp.sum(dispatch, axis=(1, 2))
        assert float(jnp.max(per_token)) <= 2.0
        # no expert slot double-claimed
        per_slot = jnp.sum(dispatch, axis=0)
        assert float(jnp.max(per_slot)) <= 1.0

    def test_uniform_router_balance_loss_is_one(self):
        logits = jnp.zeros((64, 8))
        _, _, aux = router_topk(logits, capacity=16, k=1)
        np.testing.assert_allclose(float(aux["load_balance_loss"]), 1.0, rtol=1e-5)

    def test_capacity_drops_overflow(self):
        # all tokens want expert 0, capacity 2 -> only 2 slots filled in
        # round 1; round 2 routes to the runner-up expert
        logits = jnp.tile(jnp.array([[5.0, 1.0, 0.0, 0.0]]), (10, 1))
        dispatch, combine, _ = router_topk(logits, capacity=2, k=1)
        assert float(jnp.sum(dispatch[:, 0])) == 2.0
        assert float(jnp.sum(dispatch)) == 2.0  # rest dropped

    def test_identical_experts_reduce_to_dense_mlp(self):
        """With every expert holding the same weights and gates renormalized,
        MoE(x) == MLP(x) for every non-dropped token."""
        T, H, F, E = 32, 16, 32, 4
        bank = MoEMLP(E, H, F)
        params = bank.init(K)
        # make all experts identical
        for n in ("w1", "b1", "w2", "b2"):
            params[n] = jnp.broadcast_to(params[n][:1], params[n].shape)
        x = jr.normal(jr.fold_in(K, 1), (T, H))
        y, _ = moe_layer(params, x, k=2, capacity_factor=4.0)  # ample capacity
        w1, b1 = params["w1"][0], params["b1"][0]
        w2, b2 = params["w2"][0], params["b2"][0]
        ref = jax.nn.gelu(x @ w1 + b1, approximate=True) @ w2 + b2
        # hardware: fp32 matmuls run bf16-rounded at default MXU precision
        tol = (1e-4, 1e-5) if jax.default_backend() != "tpu" else (2e-2, 2e-2)
        np.testing.assert_allclose(y, ref, rtol=tol[0], atol=tol[1])


class TestExpertParallel:
    def test_ep_matches_single_device(self):
        """8-way expert parallelism over the dp axis must reproduce the
        unsharded layer: same params, same tokens, same output."""
        mesh = mesh_lib.make_mesh()  # dp = 8 = expert-parallel degree
        T, H, F, E = 64, 16, 32, 8
        bank = MoEMLP(E, H, F)
        params = bank.init(K)
        x = jr.normal(jr.fold_in(K, 2), (T, H))

        y_ref, aux_ref = moe_layer(params, x, k=2, capacity_factor=4.0)

        def shard(params, x):
            # shard_map's in_specs hand each device its expert slice of
            # w1/b1/w2/b2 and its token slice of x; the router replicates
            y, _ = moe_layer(params, x, k=2, capacity_factor=4.0,
                             axis_name="dp")
            return y

        y = mesh_lib.shard_map(
            shard, mesh=mesh,
            in_specs=({"router": P(), "w1": P("dp"), "b1": P("dp"),
                       "w2": P("dp"), "b2": P("dp")}, P("dp")),
            out_specs=P("dp"),
        )(params, x)
        np.testing.assert_allclose(y, y_ref, rtol=1e-4, atol=1e-5)

    def test_ep_router_shape_mismatch_raises(self):
        mesh = mesh_lib.make_mesh()
        if mesh.shape["dp"] < 2:
            pytest.skip("mismatch needs dp > 1 (4 local experts x dp != 4)")
        bank = MoEMLP(4, 8, 16)  # 4 experts but dp=8 -> E = local*8 != 4
        params = bank.init(K)
        x = jr.normal(K, (16, 8))
        with pytest.raises(ValueError, match="router covers"):
            mesh_lib.shard_map(
                lambda p, x: moe_layer(p, x, axis_name="dp")[0],
                mesh=mesh,
                in_specs=({"router": P(), "w1": P(), "b1": P(),
                           "w2": P(), "b2": P()}, P("dp")),
                out_specs=P("dp"),
            )(params, x)


class TestMoEGrads:
    def test_slot_ids_unique_invariant(self):
        """The invariant the gather dispatch/combine VJPs depend on
        (ADVICE r3 #1): across all k rounds, no real slot id repeats —
        checked over adversarial routings (over-subscribed expert, uniform
        logits, random)."""
        from apex_tpu.transformer.moe import (router_topk_sparse,
                                              slot_ids_are_unique)

        cases = [
            jr.normal(jr.fold_in(K, 40), (64, 4)),
            jnp.zeros((64, 4)),
            jnp.tile(jnp.array([[9.0, 1.0, 0.0, 0.0]]), (64, 1)),
        ]
        for cap in (1, 4, 16):
            for logits in cases:
                for prio in ("gate", "token"):
                    slot_ids, _, _ = router_topk_sparse(
                        logits, cap, k=2, priority=prio)
                    assert bool(slot_ids_are_unique(slot_ids, 4 * cap)), (
                        cap, prio)

    def test_gather_vjps_match_scatter_autodiff(self):
        """Grad-parity regression (ADVICE r3 #2): the hand-written
        _gather_dispatch/_gather_combine VJPs against plain autodiff of the
        scatter/add formulation they replaced."""
        from apex_tpu.transformer.moe import (_gather_combine,
                                              _gather_dispatch,
                                              _slot_inverse,
                                              router_topk_sparse)

        T, H, E, cap = 32, 16, 4, 8
        S = E * cap
        logits = jr.normal(jr.fold_in(K, 41), (T, E))
        slot_ids, gates, _ = router_topk_sparse(logits, cap, k=2)
        inv, valid = _slot_inverse(slot_ids, gates, S)
        xt = jr.normal(jr.fold_in(K, 42), (T, H))
        w = jr.normal(jr.fold_in(K, 43), (H, H)) * 0.3

        def scatter_moe(xt, w):
            # the pre-r3 formulation: row scatter in, gather+weight out
            buf = jnp.zeros((S + 1, H)).at[slot_ids[0]].add(xt)
            buf = buf.at[slot_ids[1]].add(xt)
            op = jnp.tanh(buf[:S] @ w)
            opp = jnp.concatenate([op, jnp.zeros((1, H))], 0)
            y = (gates[0][:, None] * opp[slot_ids[0]]
                 + gates[1][:, None] * opp[slot_ids[1]])
            return jnp.sum(y ** 2)

        def gather_moe(xt, w):
            ein = _gather_dispatch(xt, slot_ids, inv, valid)
            op = jnp.tanh(ein @ w)
            y = _gather_combine(op, gates, slot_ids, inv, valid)
            return jnp.sum(y ** 2)

        # forward parity first (dispatch differs on the dump row only)
        np.testing.assert_allclose(float(gather_moe(xt, w)),
                                   float(scatter_moe(xt, w)),
                                   rtol=1e-5)
        g_ref = jax.grad(scatter_moe, argnums=(0, 1))(xt, w)
        g_got = jax.grad(gather_moe, argnums=(0, 1))(xt, w)
        np.testing.assert_allclose(g_got[0], g_ref[0], rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(g_got[1], g_ref[1], rtol=1e-4, atol=1e-6)

    def test_grads_flow_to_experts_and_router(self):
        T, H, F, E = 32, 16, 32, 4
        bank = MoEMLP(E, H, F)
        params = bank.init(K)
        x = jr.normal(jr.fold_in(K, 3), (T, H))

        def loss(params):
            y, aux = moe_layer(params, x, k=2, capacity_factor=2.0)
            return jnp.sum(y ** 2) + 0.01 * aux["load_balance_loss"]

        g = jax.grad(loss)(params)
        for n in ("router", "w1", "w2"):
            assert float(jnp.sum(jnp.abs(g[n]))) > 0.0, n


class TestRouterPriority:
    def test_gate_priority_keeps_highest_gates(self):
        """Over-subscribed expert, capacity 2: with gate priority the TWO
        most confident tokens keep the slots regardless of batch position;
        with token priority the first two in batch order do (VERDICT r2
        weak #5: position-in-batch bias)."""
        # token confidences for expert 0 rise with position
        conf = jnp.linspace(1.0, 5.0, 8)[:, None]
        logits = jnp.concatenate([conf, jnp.zeros((8, 3))], axis=1)
        d_gate, _, aux_g = router_topk(logits, capacity=2, k=1,
                                       priority="gate")
        kept_g = jnp.sum(d_gate[:, 0], axis=-1)  # (T,) got a slot on e0
        np.testing.assert_array_equal(kept_g, [0, 0, 0, 0, 0, 0, 1, 1])
        d_tok, _, aux_t = router_topk(logits, capacity=2, k=1,
                                      priority="token")
        kept_t = jnp.sum(d_tok[:, 0], axis=-1)
        np.testing.assert_array_equal(kept_t, [1, 1, 0, 0, 0, 0, 0, 0])
        np.testing.assert_allclose(aux_g["drop_fraction"], 6 / 8)
        np.testing.assert_allclose(aux_t["drop_fraction"], 6 / 8)

    def test_drop_fraction_zero_at_ample_capacity(self):
        logits = jr.normal(K, (32, 4))
        _, _, aux = router_topk(logits, capacity=64, k=2)
        assert float(aux["drop_fraction"]) == 0.0

    def test_bad_priority_raises(self):
        with pytest.raises(ValueError, match="priority"):
            router_topk(jnp.zeros((4, 2)), capacity=2, priority="fifo")


class TestDedicatedEpAxis:
    def test_mesh_splits_ep_from_dp(self):
        mesh = mesh_lib.initialize_model_parallel(expert_parallel_size=2)
        assert mesh.axis_names == ("dp", "ep", "pp", "cp", "tp")
        assert mesh.shape["ep"] == 2 and mesh.shape["dp"] == 4
        assert mesh_lib.data_parallel_axis_names() == ("dp", "ep")
        mesh_lib.destroy_model_parallel()

    def test_moe_on_ep_axis_matches_single_device(self):
        """Experts sharded over the dedicated ep axis (replicated over the
        outer dp), tokens sharded over (dp, ep)."""
        mesh = mesh_lib.make_mesh(expert_parallel_size=4)  # dp=2 x ep=4
        T, H, F, E = 64, 16, 32, 8
        bank = MoEMLP(E, H, F)
        params = bank.init(K)
        x = jr.normal(jr.fold_in(K, 5), (T, H))
        y_ref, _ = moe_layer(params, x, k=2, capacity_factor=4.0)

        y = mesh_lib.shard_map(
            lambda p, x: moe_layer(p, x, k=2, capacity_factor=4.0,
                                   axis_name="ep")[0],
            mesh=mesh,
            in_specs=({"router": P(), "w1": P("ep"), "b1": P("ep"),
                       "w2": P("ep"), "b2": P("ep")}, P(("dp", "ep"))),
            out_specs=P(("dp", "ep")),
        )(params, x)
        # each dp group routes over ITS tokens only — capacity is per
        # group, and with ample capacity assignments match the global run
        np.testing.assert_allclose(y, y_ref, rtol=1e-4, atol=1e-5)


class TestGPTMoE:
    """The shippable MoE: experts in GPTConfig's MLP slot."""

    KW = dict(vocab_size=64, max_seq_len=16, hidden_size=32, num_layers=2,
              num_heads=4)

    def test_identical_experts_match_dense_gpt(self):
        """Capacity → ∞ and all experts equal to the dense MLP weights:
        the MoE GPT must reproduce the dense GPT exactly (gates sum to 1
        after normalization; no drops)."""
        from apex_tpu.models import GPTConfig, GPTModel

        dense = GPTModel(GPTConfig(**self.KW))
        pd = dense.init(K)
        moe = GPTModel(GPTConfig(
            **self.KW, moe_num_experts=4, moe_top_k=2,
            moe_capacity_factor=100.0, moe_aux_coeff=0.0, moe_z_coeff=0.0))
        pm = moe.init(K)
        E, L = 4, self.KW["num_layers"]
        # copy the dense mlp into every expert: w1 (L,E,H,F) from dense
        # mlp_up weight (L,F,H); w2 (L,E,F,H) from mlp_down (L,H,F)
        pm = dict(pm)
        lay = dict(pm["layers"])
        lay["moe"] = dict(lay["moe"])
        up_w = pd["layers"]["mlp_up"]["weight"]      # (L, F, H)
        up_b = pd["layers"]["mlp_up"]["bias"]        # (L, F)
        dn_w = pd["layers"]["mlp_down"]["weight"]    # (L, H, F)
        dn_b = pd["layers"]["mlp_down"]["bias"]      # (L, H)
        lay["moe"]["w1"] = jnp.broadcast_to(
            up_w.transpose(0, 2, 1)[:, None], (L, E) + up_w.shape[1:][::-1])
        lay["moe"]["b1"] = jnp.broadcast_to(up_b[:, None], (L, E) + up_b.shape[1:])
        lay["moe"]["w2"] = jnp.broadcast_to(
            dn_w.transpose(0, 2, 1)[:, None], (L, E) + dn_w.shape[1:][::-1])
        lay["moe"]["b2"] = jnp.broadcast_to(dn_b[:, None], (L, E) + dn_b.shape[1:])
        # shared non-mlp params
        for n in ("ln1_w", "ln1_b", "ln2_w", "ln2_b", "qkv", "attn_out"):
            lay[n] = pd["layers"][n]
        pm["layers"] = lay
        for n in ("embedding", "pos_embedding", "lnf_w", "lnf_b"):
            pm[n] = pd[n]

        toks = jr.randint(jr.fold_in(K, 6), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 7), (2, 16), 0, 64)
        with jax.default_matmul_precision("highest"):
            l_moe, aux = moe.loss_fn(pm, toks, tgts, return_aux=True)
            l_dense = dense.loss_fn(pd, toks, tgts)
        assert float(aux["drop_fraction"]) == 0.0
        np.testing.assert_allclose(float(l_moe), float(l_dense),
                                   rtol=2e-5, atol=2e-6)

    def test_gpt_moe_trains_and_surfaces_drops(self):
        from apex_tpu.models import GPTConfig, GPTModel
        import optax

        cfg = GPTConfig(**self.KW, moe_num_experts=4, moe_top_k=2,
                        moe_capacity_factor=1.0)
        m = GPTModel(cfg)
        p = m.init(K)
        toks = jr.randint(jr.fold_in(K, 8), (4, 16), 0, 64)
        tgts = (toks + 1) % 64
        opt = optax.adam(3e-3)
        st = opt.init(p)

        @jax.jit
        def step(p, st):
            (loss, aux), g = jax.value_and_grad(
                lambda p_: m.loss_fn(p_, toks, tgts, return_aux=True),
                has_aux=True)(p)
            u, st = opt.update(g, st, p)
            return optax.apply_updates(p, u), st, loss, aux

        losses = []
        for _ in range(15):
            p, st, loss, aux = step(p, st)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8
        for k_ in ("load_balance_loss", "router_z_loss", "drop_fraction"):
            assert jnp.isfinite(aux[k_]), k_
        assert 0.0 <= float(aux["drop_fraction"]) <= 1.0

    def test_moe_ffn_not_divisible_by_tp_raises(self):
        from apex_tpu.models import GPTConfig

        with pytest.raises(ValueError, match="divisible by tp_size"):
            GPTConfig(**self.KW, ffn_hidden_size=130, moe_num_experts=4,
                      tp_size=4)

    @pytest.mark.parametrize("sp", [False, True])
    def test_gpt_moe_tp2_matches_tp1(self, sp):
        """MoE x tensor parallelism: each expert's ffn dim is tp-sharded
        (MoEMLP tp layout), routing replicated — loss and grads must match
        the unsharded model. With sequence parallelism, _mlp gathers the
        seq-sharded residual stream around the whole MoE block."""
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.models.gpt import shard_params_for_tp

        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=2)
        kw = dict(self.KW, moe_num_experts=4, moe_top_k=2,
                  moe_capacity_factor=2.0)
        cfg1 = GPTConfig(**kw)
        cfg2 = GPTConfig(**kw, tp_size=2, sequence_parallel=sp)
        m1, m2 = GPTModel(cfg1), GPTModel(cfg2)
        params1 = m1.init(K)
        toks = jr.randint(jr.fold_in(K, 70), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 71), (2, 16), 0, 64)

        sharded = shard_params_for_tp(params1, 2, cfg1)
        specs = jax.tree.map(lambda _: P("tp"), sharded)

        def run(p, t, g):
            loss, grads = jax.value_and_grad(m2.loss_fn)(
                jax.tree.map(lambda x: x[0], p), t, g)
            if m2.sp:
                grads = m2.sp_grad_sync(grads)
            return loss, jax.tree.map(lambda x: x[None], grads)

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P()),
                out_specs=(P(), specs),
            ))(sharded, toks, tgts)
            ref_loss, ref = jax.value_and_grad(m1.loss_fn)(
                params1, toks, tgts)

        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-5, atol=1e-6)
        lay, ref_lay = grads["layers"]["moe"], ref["layers"]["moe"]
        # replicated leaves hold the full grad on every shard
        np.testing.assert_allclose(lay["router"][0], ref_lay["router"],
                                   rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(lay["b2"][0], ref_lay["b2"],
                                   rtol=2e-4, atol=1e-5)
        # ffn-sharded leaves: concat tp shards back to the full bank
        np.testing.assert_allclose(
            jnp.concatenate([lay["w1"][0], lay["w1"][1]], axis=3),
            ref_lay["w1"], rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(
            jnp.concatenate([lay["w2"][0], lay["w2"][1]], axis=2),
            ref_lay["w2"], rtol=2e-4, atol=1e-5)

    def test_gpt_moe_through_pipeline_matches_serial(self):
        """MoE + pipeline composition: the schedule's validity-masked aux
        accumulator threads the router losses; loss equals the mean of
        per-microbatch single-device losses (the same per-call aux
        normalization) and drop stats surface."""
        from jax.sharding import PartitionSpec as P

        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.transformer.pipeline_parallel import GPTPipeline

        cfg = GPTConfig(**self.KW, moe_num_experts=4, moe_top_k=2,
                        moe_capacity_factor=2.0)
        m = GPTModel(cfg)
        params = m.init(K)
        pipe = GPTPipeline(m, pp=2)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)
        M, b, s = 4, 2, 16
        toks = jr.randint(jr.fold_in(K, 60), (M, b, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 61), (M, b, s), 0, 64)
        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2)

        def run(p, toks, tgts):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, g, aux = pipe.loss_and_grads(lp, toks, tgts,
                                               return_aux=True)
            g["stages"] = jax.tree.map(lambda x: x[None], g["stages"])
            return loss, g, aux

        with jax.default_matmul_precision("highest"):
            loss, grads, aux = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P()),
                out_specs=(P(), specs,
                           jax.tree.map(lambda _: P(),
                                        router_aux_zeros())),
            ))(part, toks, tgts)

            # oracle: per-microbatch losses averaged (the aux terms are
            # per-call means, so this matches the pipeline normalization)
            ref = jnp.mean(jnp.stack([
                m.loss_fn(params, toks[i], tgts[i]) for i in range(M)]))

        np.testing.assert_allclose(float(loss), float(ref), rtol=2e-5)
        assert 0.0 <= float(aux["drop_fraction"]) <= 1.0
        for g_ in jax.tree.leaves(grads):
            assert bool(jnp.all(jnp.isfinite(g_)))

        # GRADIENT parity against the serial oracle — catches aux-path
        # scaling bugs (e.g. a conservative psum transpose multiplying
        # router grads by pp_size; review r3) that the loss check cannot
        with jax.default_matmul_precision("highest"):
            ref_g = jax.grad(lambda p: jnp.mean(jnp.stack([
                m.loss_fn(p, toks[i], tgts[i])
                for i in range(M)])))(params)
        got = pipe.unpartition(grads)
        np.testing.assert_allclose(
            got["layers"]["moe"]["router"], ref_g["layers"]["moe"]["router"],
            rtol=3e-4, atol=1e-6)
        np.testing.assert_allclose(
            got["layers"]["moe"]["w1"], ref_g["layers"]["moe"]["w1"],
            rtol=3e-4, atol=1e-5)


class TestMoEPipelineEP:
    """Expert parallelism INSIDE the pipeline — the axes compose in one
    program (VERDICT r3 next-round #1): GPTPipeline partitions the expert
    banks over ep via param_specs, the two all_to_alls run stage-local
    inside the scanned tick, and loss_and_grads folds ep into the data
    reduction."""

    KW = dict(vocab_size=64, max_seq_len=16, hidden_size=32, num_layers=2,
              num_heads=4)

    def _oracle(self, cfg1, params, toks, tgts, shards, b):
        """Mean loss/grads over per-(data-shard, microbatch) serial calls —
        routing capacity is per call, matching each device's per-tick
        token count."""
        from apex_tpu.models import GPTModel

        m = GPTModel(cfg1)
        M = toks.shape[0]

        def f(p):
            per = []
            for r in range(shards):
                sl = slice(r * b, (r + 1) * b)
                for i in range(M):
                    per.append(m.loss_fn(p, toks[i, sl], tgts[i, sl]))
            return jnp.mean(jnp.stack(per))

        return jax.value_and_grad(f)(params)

    def test_pp2_ep2_dp2_matches_serial_shards(self):
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.transformer.pipeline_parallel import GPTPipeline

        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2,
                                  expert_parallel_size=2)  # dp2 x ep2 x pp2
        kw = dict(self.KW, moe_num_experts=4, moe_top_k=2,
                  moe_capacity_factor=2.0, attention_impl="flash")
        cfg1 = GPTConfig(**kw)
        cfg = GPTConfig(**kw, ep_axis="ep")
        m = GPTModel(cfg)
        params = GPTModel(cfg1).init(K)
        pipe = GPTPipeline(m, pp=2)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)

        M, b, s = 2, 2, 16
        shards = 4  # dp x ep
        toks = jr.randint(jr.fold_in(K, 80), (M, b * shards, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 81), (M, b * shards, s), 0, 64)

        def run(p, toks, tgts):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, g = pipe.loss_and_grads(lp, toks, tgts, dp_axis="dp")
            g["stages"] = jax.tree.map(lambda x: x[None], g["stages"])
            return loss, g

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, ("dp", "ep")),
                          P(None, ("dp", "ep"))),
                out_specs=(P(), specs),
            ))(part, toks, tgts)
            ref_loss, ref_g = self._oracle(cfg1, params, toks, tgts,
                                           shards, b)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        got = pipe.unpartition(grads)
        np.testing.assert_allclose(
            got["layers"]["moe"]["router"], ref_g["layers"]["moe"]["router"],
            rtol=3e-4, atol=1e-6)
        np.testing.assert_allclose(
            got["layers"]["moe"]["w1"], ref_g["layers"]["moe"]["w1"],
            rtol=3e-4, atol=1e-5)
        np.testing.assert_allclose(
            got["layers"]["moe"]["b2"], ref_g["layers"]["moe"]["b2"],
            rtol=3e-4, atol=1e-5)
        np.testing.assert_allclose(
            got["layers"]["qkv"]["weight"], ref_g["layers"]["qkv"]["weight"],
            rtol=3e-4, atol=1e-5)
        np.testing.assert_allclose(
            got["pos_embedding"], ref_g["pos_embedding"],
            rtol=3e-4, atol=1e-6)

    def test_tp2_pp2_ep2_one_mesh(self):
        """The full 4-axis composition (dp x pp x tp x ep in ONE mesh/one
        shard_map): tp shards each expert's ffn and the attention, pp the
        layers, ep the expert banks."""
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.models.gpt import shard_params_for_tp
        from apex_tpu.transformer.pipeline_parallel import GPTPipeline

        mesh = mesh_lib.make_mesh(
            tensor_model_parallel_size=2, pipeline_model_parallel_size=2,
            expert_parallel_size=2)  # dp1 x ep2 x pp2 x tp2
        kw = dict(self.KW, moe_num_experts=4, moe_top_k=2,
                  moe_capacity_factor=2.0, attention_impl="flash")
        cfg1 = GPTConfig(**kw)
        cfg = GPTConfig(**kw, tp_size=2, sequence_parallel=True,
                        ep_axis="ep")
        m = GPTModel(cfg)
        params1 = GPTModel(cfg1).init(K)
        pipe = GPTPipeline(m, pp=2)
        part = jax.vmap(pipe.partition)(shard_params_for_tp(params1, 2, cfg1))
        specs = pipe.param_specs(part, "tp")

        M, b, s = 2, 2, 16
        shards = 2  # ep (dp extent is 1)
        toks = jr.randint(jr.fold_in(K, 90), (M, b * shards, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 91), (M, b * shards, s), 0, 64)

        def run(p, toks, tgts):
            lp = jax.tree.map(lambda x: x[0], p)  # strip tp
            lp["stages"] = jax.tree.map(lambda x: x[0], lp["stages"])  # pp
            loss, g = pipe.loss_and_grads(lp, toks, tgts, dp_axis="dp")
            g["stages"] = jax.tree.map(lambda x: x[None, None], g["stages"])
            g["embed"] = jax.tree.map(lambda x: x[None], g["embed"])
            g["head"] = jax.tree.map(lambda x: x[None], g["head"])
            return loss, g

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, ("dp", "ep")),
                          P(None, ("dp", "ep"))),
                out_specs=(P(), specs),
            ))(part, toks, tgts)
            ref_loss, ref_g = self._oracle(cfg1, params1, toks, tgts,
                                           shards, b)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        got = jax.vmap(pipe.unpartition)(grads)
        # tp-replicated leaves: rank 0's tree against the oracle
        np.testing.assert_allclose(
            got["layers"]["moe"]["router"][0],
            ref_g["layers"]["moe"]["router"], rtol=3e-4, atol=1e-6)
        np.testing.assert_allclose(
            got["layers"]["moe"]["b2"][0], ref_g["layers"]["moe"]["b2"],
            rtol=3e-4, atol=1e-5)
        np.testing.assert_allclose(
            got["lnf_w"][0], ref_g["lnf_w"], rtol=3e-4, atol=1e-5)
        # ffn-sharded expert banks: concat the tp shards
        np.testing.assert_allclose(
            jnp.concatenate([got["layers"]["moe"]["w1"][0],
                             got["layers"]["moe"]["w1"][1]], axis=-1),
            ref_g["layers"]["moe"]["w1"], rtol=3e-4, atol=1e-5)

    def test_interleaved_v2_pp2_ep2(self):
        """Virtual pipeline chunks compose with ep: v=2 x pp=2 x ep=2 in
        one mesh — expert banks shard over ep inside each chunk slice,
        loss matches the serial oracle."""
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.transformer.pipeline_parallel import GPTPipeline

        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2,
                                  expert_parallel_size=2)  # dp2 x ep2 x pp2
        kw = dict(self.KW, num_layers=4, moe_num_experts=4, moe_top_k=2,
                  moe_capacity_factor=2.0, attention_impl="flash")
        cfg1 = GPTConfig(**kw)
        cfg = GPTConfig(**kw, ep_axis="ep")
        m = GPTModel(cfg)
        params = GPTModel(cfg1).init(K)
        pipe = GPTPipeline(m, pp=2, virtual_chunks=2)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)

        M, b, s = 2, 2, 16
        shards = 4
        toks = jr.randint(jr.fold_in(K, 100), (M, b * shards, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 101), (M, b * shards, s), 0, 64)

        def run(p, toks, tgts):
            lp = dict(p, stages=jax.tree.map(lambda x: x[:, 0],
                                             p["stages"]))
            loss, g = pipe.loss_and_grads(lp, toks, tgts, dp_axis="dp")
            g["stages"] = jax.tree.map(lambda x: x[:, None], g["stages"])
            return loss, g

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, ("dp", "ep")),
                          P(None, ("dp", "ep"))),
                out_specs=(P(), specs),
            ))(part, toks, tgts)
            ref_loss, ref_g = self._oracle(cfg1, params, toks, tgts,
                                           shards, b)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        got = pipe.unpartition(grads)
        np.testing.assert_allclose(
            got["layers"]["moe"]["w1"], ref_g["layers"]["moe"]["w1"],
            rtol=3e-4, atol=1e-5)

    def test_five_axis_ep_pp_cp_one_mesh(self):
        """MoE experts over ep, layers over pp, sequence over cp (ring),
        batch over dp — FIVE mesh axes bound in one shard_map (tp=1 slot
        present in the mesh). The 'axes compose' end state."""
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.ops.attention import zigzag_shard
        from apex_tpu.transformer.pipeline_parallel import GPTPipeline

        mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=2,
                                  context_parallel_size=2,
                                  expert_parallel_size=2)
        assert dict(mesh.shape) == {"dp": 1, "ep": 2, "pp": 2, "cp": 2,
                                    "tp": 1}
        kw = dict(vocab_size=64, max_seq_len=64, hidden_size=32,
                  num_layers=2, num_heads=4, attention_impl="flash",
                  moe_num_experts=4, moe_top_k=2, moe_capacity_factor=2.0)
        cfg1 = GPTConfig(**kw)
        cfg = GPTConfig(**kw, ep_axis="ep", cp_axis="cp")
        m = GPTModel(cfg)
        params = GPTModel(cfg1).init(K)
        pipe = GPTPipeline(m, pp=2)
        part = pipe.partition(params)
        specs = pipe.param_specs(part)

        M, b, s = 2, 2, 64
        shards = 2  # dp*ep data shards (dp extent 1)
        toks = jr.randint(jr.fold_in(K, 110), (M, b * shards, s), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 111), (M, b * shards, s), 0, 64)
        toks_sh = zigzag_shard(toks, 2, 2)
        tgts_sh = zigzag_shard(tgts, 2, 2)

        def run(p, t, g):
            lp = dict(p, stages=jax.tree.map(lambda x: x[0], p["stages"]))
            loss, grads = pipe.loss_and_grads(lp, t, g,
                                              dp_axis=("dp", "cp"))
            grads["stages"] = jax.tree.map(lambda x: x[None],
                                           grads["stages"])
            return loss, grads

        with jax.default_matmul_precision("highest"):
            loss, grads = jax.jit(mesh_lib.shard_map(
                run, mesh=mesh,
                in_specs=(specs, P(None, ("dp", "ep"), "cp"),
                          P(None, ("dp", "ep"), "cp")),
                out_specs=(P(), specs),
            ))(part, toks_sh, tgts_sh)

            # oracle: per-(ep shard, microbatch) serial losses on the FULL
            # sequence (cp only shards the sequence, not the batch)
            m1 = GPTModel(cfg1)
            per = [m1.loss_fn(params, toks[i, r * b:(r + 1) * b],
                              tgts[i, r * b:(r + 1) * b])
                   for r in range(shards) for i in range(M)]
            ref = float(jnp.mean(jnp.stack(per)))

        np.testing.assert_allclose(float(loss), ref, rtol=2e-5)
        got = pipe.unpartition(grads)
        ref_g = jax.grad(lambda p: jnp.mean(jnp.stack([
            m1.loss_fn(p, toks[i, r * b:(r + 1) * b],
                       tgts[i, r * b:(r + 1) * b])
            for r in range(shards) for i in range(M)])))(params)
        # atol 1e-4: the ring fold's exp/log renormalization adds ~5e-5
        # of float noise per backward chain — relative checks on near-zero
        # router-grad entries need the absolute floor (loss parity above
        # pins the semantics; routing decisions are identical at cf=2.0)
        np.testing.assert_allclose(
            got["layers"]["moe"]["router"], ref_g["layers"]["moe"]["router"],
            rtol=5e-4, atol=1e-4)
        np.testing.assert_allclose(
            got["layers"]["moe"]["w1"], ref_g["layers"]["moe"]["w1"],
            rtol=5e-4, atol=1e-4)
