"""Megatron testing-surface parity: global_vars wiring, dynamic batch size,
GPT scaling — equivalents of the reference's
``tests/L0/run_transformer/run_dynamic_batchsize_test.py`` and
``gpt_scaling_test.py`` plus ``testing/global_vars.py`` coverage.
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from apex_tpu.transformer.testing import global_vars

BASE = ["--num-layers", "4", "--hidden-size", "64",
        "--num-attention-heads", "4", "--max-position-embeddings", "128",
        "--seq-length", "128"]


@pytest.fixture(autouse=True)
def _clean_globals():
    yield
    global_vars.destroy_global_vars()


class TestGlobalVars:
    def test_set_global_variables_wires_everything(self):
        args = global_vars.set_global_variables(args_list=BASE + [
            "--micro-batch-size", "2", "--global-batch-size", "16",
            "--world-size", "8",
        ])
        assert global_vars.get_args() is args
        assert global_vars.get_num_microbatches() == 1  # 16/(2*8dp)
        assert global_vars.get_current_global_batch_size() == 16
        timers = global_vars.get_timers()
        timers("tick").start()
        timers("tick").stop()
        assert timers("tick").elapsed() >= 0
        assert global_vars.get_tensorboard_writer() is None
        assert global_vars.get_adlr_autoresume() is None

    def test_accessors_raise_before_init(self):
        with pytest.raises(RuntimeError):
            global_vars.get_timers()

    def test_destroy_resets_microbatch_calculator(self):
        global_vars.set_global_variables(args_list=BASE + [
            "--micro-batch-size", "2", "--global-batch-size", "16",
            "--world-size", "8",
        ])
        assert global_vars.get_num_microbatches() == 1
        global_vars.destroy_global_vars()
        # destroyed state must not answer with a stale calculator
        with pytest.raises(RuntimeError):
            global_vars.get_num_microbatches()


class TestDynamicBatchSize:
    """``run_dynamic_batchsize_test.py``: with --rampup-batch-size the
    number of microbatches grows as samples are consumed, and fwd/bwd runs
    at each microbatch count."""

    def test_rampup_schedule_and_fwd_bwd(self):
        from apex_tpu.transformer.pipeline_parallel import schedules

        global_vars.set_global_variables(args_list=BASE + [
            "--micro-batch-size", "1", "--global-batch-size", "8",
            "--rampup-batch-size", "2", "2", "24",
            "--train-samples", "48", "--world-size", "1",
        ])
        params = {"w": jr.normal(jr.PRNGKey(0), (8, 8)) * 0.3}

        def loss_fn(p, mb):
            return jnp.mean((jnp.tanh(mb @ p["w"]) - mb) ** 2)

        seen = []
        consumed = 0
        while consumed < 48:
            global_vars.update_num_microbatches(consumed,
                                                consistency_check=False)
            m = global_vars.get_num_microbatches()
            seen.append(m)
            mbs = jr.normal(jr.fold_in(jr.PRNGKey(1), consumed), (m, 4, 8))
            loss, grads = schedules.forward_backward_no_pipelining(
                loss_fn, params, mbs)
            assert np.isfinite(float(loss))
            consumed += global_vars.get_current_global_batch_size()
        # batch size ramped 2 -> 8 => microbatches ramped 2 -> 8
        assert seen[0] < seen[-1]
        assert seen == sorted(seen)
        assert seen[-1] == 8


class TestGPTScaling:
    """``gpt_scaling_test.py``: the GPT stack must hold up as width/depth and
    parallelism scale (CI sizes; the real sweep runs on hardware)."""

    @pytest.mark.parametrize("hidden,layers", [(64, 2), (128, 4)])
    def test_width_depth_scaling(self, hidden, layers):
        from apex_tpu.models import GPTConfig, GPTModel

        cfg = GPTConfig(vocab_size=256, max_seq_len=64, hidden_size=hidden,
                        num_layers=layers, num_heads=4)
        model = GPTModel(cfg)
        params = model.init(jr.PRNGKey(0))
        n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        # parameter count tracks 12*L*H^2 + embeddings
        expected = 12 * layers * hidden * hidden
        assert n_params > expected
        toks = jr.randint(jr.PRNGKey(1), (2, 64), 0, 256)
        loss = jax.jit(model.loss_fn)(params, toks, toks)
        assert np.isfinite(float(loss))

    def test_tp4_scaling_runs(self):
        """Parallelism-scaling smoke at tp=4 (bitwise tp-vs-dense parity is
        covered by tests/test_models.py::test_tp2_matches_tp1; the
        reference's scaling test likewise only records that larger configs
        run)."""
        from jax.sharding import PartitionSpec as P

        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.parallel import mesh as mesh_lib

        toks = jr.randint(jr.PRNGKey(1), (2, 32), 0, 256)
        mesh = mesh_lib.initialize_model_parallel(tensor_model_parallel_size=4)
        try:
            tp_model = GPTModel(GPTConfig(
                vocab_size=256, max_seq_len=32, hidden_size=64,
                num_layers=2, num_heads=4, tp_size=4))

            def run(toks):
                p = tp_model.init(jr.PRNGKey(0))
                return tp_model.loss_fn(p, toks, toks)

            loss = mesh_lib.shard_map(
                run, mesh=mesh, in_specs=P(), out_specs=P(),
            )(toks)
            # random-init LM loss must sit near ln(vocab)
            assert float(loss) == pytest.approx(np.log(256), rel=0.25)
        finally:
            mesh_lib.destroy_model_parallel()
