"""Driver-entry coverage: ``__graft_entry__`` must always work.

Round-1 lesson: a crash in the one function the driver actually runs
(``dryrun_multichip`` calling ``jax.devices()`` on a single-chip host)
survived to snapshot because no test imported the module. These tests run
both entry points exactly the way the driver does.
"""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    loss = jax.jit(fn)(*args)
    assert float(loss) > 0


def test_dryrun_multichip_8():
    # Under the test conftest there are 8 virtual CPU devices, so this runs
    # inline; under a real single-chip session it exercises the subprocess
    # respawn path. Both must succeed.
    graft.dryrun_multichip(8)


def test_dryrun_multichip_respawn_path(monkeypatch):
    """Force the subprocess path even when 8 local devices exist."""
    monkeypatch.setattr(jax, "device_count", lambda: 1)
    graft.dryrun_multichip(8)


def test_dryrun_multichip_2():
    graft.dryrun_multichip(2)


@pytest.mark.parametrize("n", [4])
def test_dryrun_multichip_tp_only(n):
    graft.dryrun_multichip(n)
