"""Driver-entry coverage: ``__graft_entry__`` must always work.

Round-1 lesson: a crash in the one function the driver actually runs
(``dryrun_multichip`` calling ``jax.devices()`` on a single-chip host)
survived to snapshot because no test imported the module. These tests run
both entry points exactly the way the driver does.
"""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    loss = jax.jit(fn)(*args)
    assert float(loss) > 0


def test_dryrun_multichip_8(monkeypatch):
    # Under the test conftest there are 8 virtual CPU devices, so this runs
    # inline; under a real single-chip session it exercises the subprocess
    # respawn path. Both must succeed. The 16-wide leg respawn is disabled
    # here (these tests cover the gate's own mechanics; the multi-minute
    # 16-wide child runs once in test_full_composition and on every real
    # driver invocation, which never sets this env).
    monkeypatch.setenv("APEX_TPU_GATE_16WIDE", "0")
    graft.dryrun_multichip(8)


def test_dryrun_multichip_respawn_path(monkeypatch):
    """Force the subprocess path even when 8 local devices exist."""
    monkeypatch.setenv("APEX_TPU_GATE_16WIDE", "0")
    monkeypatch.setattr(jax, "device_count", lambda: 1)
    graft.dryrun_multichip(8)


def test_dryrun_multichip_2(monkeypatch):
    monkeypatch.setenv("APEX_TPU_GATE_16WIDE", "0")
    graft.dryrun_multichip(2)


@pytest.mark.parametrize("n", [4])
def test_dryrun_multichip_tp_only(n, monkeypatch):
    monkeypatch.setenv("APEX_TPU_GATE_16WIDE", "0")
    graft.dryrun_multichip(n)


def test_16wide_respawn_parses_and_skips(monkeypatch, capsys):
    """The 16-wide leg machinery without the 16-wide cost: a faked child
    proves the result-line parse; the env opt-out and a timeout both
    yield explicit skips (never nan); a failed child raises."""
    import subprocess as sp

    monkeypatch.setenv("APEX_TPU_GATE_16WIDE", "0")
    out = graft._respawn_16wide_legs()
    assert out["tpcp_4axis_loss"][0] == "skipped"
    monkeypatch.delenv("APEX_TPU_GATE_16WIDE")

    class FakeProc:
        returncode = 0
        stderr = ""
        stdout = ("noise\nSIXTEEN_WIDE_LEGS "
                  '{"moe_16wide_loss": 4.31, "tpcp_4axis_loss": 4.36}\n')

    monkeypatch.setattr(graft.subprocess, "run",
                        lambda *a, **k: FakeProc())
    out = graft._respawn_16wide_legs()
    assert out == {"moe_16wide_loss": 4.31, "tpcp_4axis_loss": 4.36}

    def timeout(*a, **k):
        raise sp.TimeoutExpired(cmd="x", timeout=900)

    monkeypatch.setattr(graft.subprocess, "run", timeout)
    out = graft._respawn_16wide_legs()
    assert out["moe_16wide_loss"][0] == "skipped"
    assert "900s" in out["moe_16wide_loss"][1]

    class FailProc(FakeProc):
        returncode = 3
        stderr = "boom"

    monkeypatch.setattr(graft.subprocess, "run",
                        lambda *a, **k: FailProc())
    with pytest.raises(RuntimeError, match="rc=3"):
        graft._respawn_16wide_legs()
