"""Docs stay runnable: every ```python block in docs/TRAINING_GUIDE.md
executes, in order, in one namespace on the virtual 8-device mesh — the
"a new user can run DP→TP→PP from docs alone" guarantee (VERDICT r3 next
#10), enforced rather than asserted."""

import os
import re

import pytest


def _guide_blocks():
    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "TRAINING_GUIDE.md")
    text = open(path).read()
    return re.findall(r"```python\n(.*?)```", text, re.DOTALL)


def test_training_guide_blocks_execute_in_order():
    blocks = _guide_blocks()
    assert len(blocks) >= 5, "guide lost its worked examples"
    ns = {}
    for i, block in enumerate(blocks):
        try:
            exec(compile(block, f"TRAINING_GUIDE.md[block {i}]", "exec"),
                 ns)
        except Exception as e:  # pragma: no cover - diagnostic
            pytest.fail(f"guide block {i} failed: {type(e).__name__}: {e}\n"
                        f"---\n{block}")


def test_amp_worked_example_executes():
    path = os.path.join(os.path.dirname(__file__), "..", "docs", "api",
                        "amp.md")
    block = re.findall(r"```python\n(.*?)```", open(path).read(),
                       re.DOTALL)[0]
    ns = {}
    exec(compile(block, "amp.md[worked example]", "exec"), ns)
    import jax.numpy as jnp
    assert jnp.isfinite(ns["loss"])


def _doc_blocks(*relpath):
    path = os.path.join(os.path.dirname(__file__), "..", "docs", *relpath)
    return re.findall(r"```python\n(.*?)```", open(path).read(), re.DOTALL)


def _exec_blocks(blocks, label):
    ns = {}
    for i, block in enumerate(blocks):
        try:
            exec(compile(block, f"{label}[block {i}]", "exec"), ns)
        except Exception as e:  # pragma: no cover - diagnostic
            pytest.fail(f"{label} block {i} failed: "
                        f"{type(e).__name__}: {e}\n---\n{block}")
    return ns


def test_observability_blocks_execute_in_order():
    """The monitor doc's snippets — quickstart, span/anatomy join,
    CostDB calibration — all execute (the monitor/lint docs standard:
    enforced, not asserted)."""
    blocks = _doc_blocks("OBSERVABILITY.md")
    assert len(blocks) >= 3, "OBSERVABILITY.md lost its worked examples"
    _exec_blocks(blocks, "OBSERVABILITY.md")
    # the doc must tear down the process-wide registry it enabled
    from apex_tpu import monitor
    assert not monitor.enabled()


def test_prof_api_blocks_execute_in_order():
    """docs/api/prof.md: capture → report → correlate/anatomy →
    calibrate → cost_analysis, one namespace, runnable on CPU."""
    blocks = _doc_blocks("api", "prof.md")
    assert len(blocks) >= 5, "prof.md lost its worked examples"
    _exec_blocks(blocks, "prof.md")


def test_inference_api_blocks_execute_in_order():
    """docs/api/inference.md: single-batch decode → continuous-batching
    serve → greedy-parity witness, one namespace, runnable on CPU (the
    serving chapter's block math / scheduler contract is enforced, not
    asserted)."""
    blocks = _doc_blocks("api", "inference.md")
    assert len(blocks) >= 3, "inference.md lost its worked examples"
    ns = _exec_blocks(blocks, "inference.md")
    assert ns["srv"].decode_step._cache_size() == 1
    # ISSUE 17: the tp chapter's engine really served sharded
    assert ns["tsrv"].decode_step._cache_size() == 1


def test_inference_doc_covers_serving_contract():
    path = os.path.join(os.path.dirname(__file__), "..", "docs", "api",
                        "inference.md")
    text = open(path).read()
    for needle in ("block table", "free list", "dead block",
                   "Chunked prefill", "fused_sample",
                   "bench.py --serve", "greedy_parity",
                   "_cache_size() == 1", "multiple of 128",
                   # ISSUE 10: request-level telemetry chapter
                   "ServeTelemetry", "serve_event", "serve_window",
                   "--serve-timeline", "telemetry_overhead_pct",
                   "bench_history.py", "rounding recipe",
                   # ISSUE 13: prefix caching + preemption chapter
                   "PrefixCache", "copy-on-write", "refcount",
                   "Optimistic FCFS admission", "evict-and-recompute",
                   "prefix_hit_ttft_p50_ms", "prefix_hit_rate",
                   "preemptions", "churn_parity", "SLOPolicy",
                   "trace_seed", "num_resident",
                   # ISSUE 14: the weight hot-swap contract
                   "request_swap", "contents-only mutation",
                   "restore_params", "swap", "pinned at 1",
                   # ISSUE 15: speculative decoding + quantized KV
                   "fused_verify", "NGramDrafter", "ModelDrafter",
                   "Acceptance math", "rewind contract",
                   "token-identical", "accepted prefix",
                   "rejection sampling", "kv_dtype", "int8",
                   "parity oracle", "kv_quant_logit_err",
                   "bench.py --spec", "acceptance_rate",
                   "spec_verify_step", "lookahead",
                   # ISSUE 17: TP serving + disaggregated handoff
                   "ParallelPlan(tp=2)", "one logical free list",
                   "GLOBAL count", "all_gather_matmul",
                   "matmul_all_reduce", "ppermute_present",
                   "no_full_width_all_gather", "serve_prefill_tp",
                   "serve_decode_tp", "psum", "validate_tp",
                   "pad the vocab to a tp multiple",
                   "collective_bytes_per_step", "export_handoff",
                   "ingest_handoff", "prefill_requests",
                   "read_handoff", "write_handoff", "block_digest",
                   "content-addressed", "handoff_role",
                   "--plan-tp", "TP_SERVE_SCHEMA", "handoff_parity",
                   "handoff_transfer_ms",
                   "validate_metrics.py --tp-serve",
                   # ISSUE 19: tree speculation + fp8 KV
                   "fused_verify_tree", "NGramTreeDrafter",
                   "PagedModelDrafter", "AdaptiveSpecController",
                   "draft_tree", "deepest fully-accepted path",
                   "ancestor mask", "note_spec_tokens",
                   "length masking IS the rewind", "tree_rounds",
                   "spec_degraded", "peak_blocks",
                   "drafter_pool_blocks", "spec_tree_step",
                   "bench.py --spec --tree",
                   "tree_spec_acceptance_rate", "adaptive_beats_fixed",
                   "fp8_e4m3", "spec_verify_tree",
                   # ISSUE 20: self-tuning serving + the SLOPolicy
                   # narrowing contract (backs off on ANY non-buildup
                   # window, not only fully-clean ones)
                   "window without queue buildup", "ReplanPolicy",
                   "ServePlan", "split_knob_changes", "calm_windows",
                   "deferred_knobs", "pop_replan", "replan_parity",
                   "--plan-serve", "searched_beats_hand"):
        assert needle in text, f"inference.md dropped {needle}"


def test_observability_covers_anatomy_and_calibration():
    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "OBSERVABILITY.md")
    text = open(path).read()
    for needle in ("monitor.span", "--anatomy", "step_anatomy",
                   "build_costdb", "--costdb", "host gap",
                   "collective-exposed", "bench.py --profile",
                   # ISSUE 10: serving-telemetry chapter
                   "serve_event", "serve_window", "serve_anomaly",
                   "--serve-timeline", "StreamingHistogram",
                   "straggler", "admission-blocked-by",
                   "bench_history.py",
                   # ISSUE 16: request-scoped tracing chapter
                   "trace_id", "trace_context", "clock_sync",
                   "perf_counter_ns", "monitor trace", "chrome_trace",
                   "Perfetto", "--attribution", "serve_attribution",
                   "spec_rewind_ms", "preempt_wait_ms",
                   "flight recorder", "enable_flight_recorder",
                   "flight_dump", "validate_metrics.py --trace",
                   "SKIP(reason)"):
        assert needle in text, f"OBSERVABILITY.md dropped {needle}"


def test_monitor_doc_covers_serving_telemetry():
    path = os.path.join(os.path.dirname(__file__), "..", "docs", "api",
                        "monitor.md")
    text = open(path).read()
    for needle in ("StreamingHistogram", "one bucket width",
                   "serve_event", "serve_window", "SERVE_ANOMALY_SCHEMA",
                   "emit_serve_window", "--serve-timeline",
                   "serve_timeline", "--serve-window", "buffered",
                   # ISSUE 16: request-scoped tracing section
                   "trace_id", "new_trace_id", "trace_context",
                   "clock_sync", "monitor trace", "chrome_trace",
                   "write_chrome_trace", "--attribution",
                   "serve_attribution", "SERVE_ATTRIBUTION_SCHEMA",
                   "enable_flight_recorder", "flight_dump",
                   "FLIGHT_RECORDER_SCHEMA", "install_signal_handler",
                   "--trace", "telemetry_overhead_pct"):
        assert needle in text, f"monitor.md dropped {needle}"


def test_monitor_doc_trace_block_executes():
    """The tracing worked example in docs/api/monitor.md is
    self-contained and runnable (the other monitor.md snippets are API
    fragments; this one is the executed witness)."""
    blocks = _doc_blocks("api", "monitor.md")
    trace_blocks = [b for b in blocks if "trace_context" in b]
    assert trace_blocks, "monitor.md lost the tracing worked example"
    _exec_blocks(trace_blocks, "monitor.md[tracing]")
    from apex_tpu import monitor
    assert not monitor.enabled()


def test_guide_covers_the_ladder():
    text = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                             "TRAINING_GUIDE.md")).read()
    for needle in ("initialize_model_parallel", "shard_params_for_tp",
                   "build_model", "loss_and_grads", "build_schedule",
                   "zigzag_shard", "distributed_fused_adam",
                   # ISSUE 12: the "choosing a plan" chapter
                   "ParallelPlan", "search_plans", "bench.py --plan",
                   "planned_gpt_step", "predicted_vs_measured_err_pct",
                   # ISSUE 14: the checkpoint/resume chapter
                   "ZeroCheckpointManager", "gather_zero_state",
                   "scatter_zero_state", "restore_params",
                   "bench.py --ckpt", "save_overhead_pct",
                   # ISSUE 15: the §10d drafter recipe
                   "NGramDrafter", "ModelDrafter", "fused_verify",
                   "acceptance_rate", "kv_dtype", "bench.py --spec",
                   "spec_verify_step",
                   # ISSUE 17: the §10e multi-chip serving recipe
                   "ParallelPlan(tp=2)", "export_handoff",
                   "ingest_handoff", "prefill_requests",
                   "bench.py --serve --plan-tp",
                   "serve_decode_tp", "handoff_transfer_ms",
                   # ISSUE 19: the §10f tree-spec recipe
                   "NGramTreeDrafter", "PagedModelDrafter",
                   "AdaptiveSpecController", "fused_verify_tree",
                   "bench.py --spec --tree", "fp8_e4m3",
                   "peak_blocks", "tree_rounds",
                   # ISSUE 18: the §11 apexmem pre-flight
                   "--memory", "memory_budgets.json",
                   "liveness.analyze", "peak_memory_bound",
                   "donation_aliased", "memory_source",
                   "predicted_vs_measured_hbm_err_pct",
                   # ISSUE 20: the §10g self-tuning serving recipe
                   "ServePlan", "price_serve_plan", "search_serve_plans",
                   "ReplanPolicy", "bench.py --serve --plan-serve",
                   "serve_plan_tokens_per_s", "deferred_knobs"):
        assert needle in text, f"guide dropped {needle}"


def test_ckpt_api_blocks_execute_in_order():
    """docs/api/ckpt.md: sharded save → bitwise same-dp restore →
    elastic dp-resize → manager rotation, one namespace, runnable on
    the virtual CPU mesh."""
    blocks = _doc_blocks("api", "ckpt.md")
    assert len(blocks) >= 3, "ckpt.md lost its worked examples"
    ns = _exec_blocks(blocks, "ckpt.md")
    assert ns["restored4"].count == 3


def test_ckpt_doc_covers_the_contract():
    path = os.path.join(os.path.dirname(__file__), "..", "docs", "api",
                        "ckpt.md")
    text = open(path).read()
    for needle in ("save_zero_sharded", "load_zero_state",
                   "gather_zero_state", "scatter_zero_state",
                   "restore_zero_shard", "restore_params",
                   "manifest", "digest", "atomic", "pad", "bitwise",
                   "elastic", "ZeroCheckpointManager", "max_to_keep",
                   "check_and_save_sharded", "bench.py --ckpt",
                   "save_overhead_pct", "SKIP", "hot-swap",
                   "never a deep reshape traceback"):
        assert needle in text, f"ckpt.md dropped {needle}"


def test_plan_api_blocks_execute_in_order():
    """docs/api/plan.md: ParallelPlan round-trip → plan consumption →
    the pricing worked example (shared fixture with tests/test_plan.py)
    → search, one namespace, runnable on the virtual CPU mesh."""
    blocks = _doc_blocks("api", "plan.md")
    assert len(blocks) >= 4, "plan.md lost its worked examples"
    ns = _exec_blocks(blocks, "plan.md")
    assert ns["price"].confidence == "calibrated"
    assert ns["result"].ranked
    # ISSUE 20: the ServePlan chapter's worked pricing fixture
    assert ns["sprice"].confidence == "calibrated"
    assert ns["sprice"].sim_span_ms == 33.0
    assert ns["sresult"].ranked


def test_plan_doc_covers_the_planner_contract():
    path = os.path.join(os.path.dirname(__file__), "..", "docs", "api",
                        "plan.md")
    text = open(path).read()
    for needle in ("ParallelPlan", "validate", "to_json",
                   "static_cost", "nearest", "pipeline_cost_model",
                   "uncalibrated", "--strict", "search_plans",
                   "memory_bound_bytes", "bench.py --plan",
                   "predicted_vs_measured_err_pct", "bench_history",
                   "planned_gpt_step", "deprecated shim",
                   "heterogeneity",
                   # ISSUE 18: the apexmem memory-source chapter
                   "liveness_memory", "memory_source",
                   "memory_disagreement_pct", "closed_form_vs_liveness",
                   "predicted_vs_measured_hbm_err_pct",
                   # ISSUE 20: the ServePlan chapter
                   "ServePlan", "price_serve_plan", "search_serve_plans",
                   "split_knob_changes", "derive_serve_costs",
                   "uncalibrated", "pool_bytes_bound",
                   "bench.py --serve --plan-serve",
                   "searched_beats_hand", "replan_parity",
                   "jit_cache_ok", "serve_plan_tokens_per_s",
                   "serve_plan_predicted_vs_measured_err_pct",
                   "validate_metrics.py --serve-plan"):
        assert needle in text, f"plan.md dropped {needle}"
