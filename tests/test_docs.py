"""Docs stay runnable: every ```python block in docs/TRAINING_GUIDE.md
executes, in order, in one namespace on the virtual 8-device mesh — the
"a new user can run DP→TP→PP from docs alone" guarantee (VERDICT r3 next
#10), enforced rather than asserted."""

import os
import re

import pytest


def _guide_blocks():
    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "TRAINING_GUIDE.md")
    text = open(path).read()
    return re.findall(r"```python\n(.*?)```", text, re.DOTALL)


def test_training_guide_blocks_execute_in_order():
    blocks = _guide_blocks()
    assert len(blocks) >= 5, "guide lost its worked examples"
    ns = {}
    for i, block in enumerate(blocks):
        try:
            exec(compile(block, f"TRAINING_GUIDE.md[block {i}]", "exec"),
                 ns)
        except Exception as e:  # pragma: no cover - diagnostic
            pytest.fail(f"guide block {i} failed: {type(e).__name__}: {e}\n"
                        f"---\n{block}")


def test_amp_worked_example_executes():
    path = os.path.join(os.path.dirname(__file__), "..", "docs", "api",
                        "amp.md")
    block = re.findall(r"```python\n(.*?)```", open(path).read(),
                       re.DOTALL)[0]
    ns = {}
    exec(compile(block, "amp.md[worked example]", "exec"), ns)
    import jax.numpy as jnp
    assert jnp.isfinite(ns["loss"])


def test_guide_covers_the_ladder():
    text = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                             "TRAINING_GUIDE.md")).read()
    for needle in ("initialize_model_parallel", "shard_params_for_tp",
                   "build_model", "loss_and_grads", "build_schedule",
                   "zigzag_shard", "distributed_fused_adam"):
        assert needle in text, f"guide dropped {needle}"
