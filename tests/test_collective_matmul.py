"""Ring-overlapped collective matmuls (``ops.collective_matmul``) on the
virtual 8-device mesh: the overlapped TP/SP linears must reproduce the
blocking oracle's loss and every gradient across the
tp × seq_dim × precision × sequence_parallel matrix, deterministically
(two runs, same bits), with a jaxpr that carries ``ppermute`` and no
full-width ``all_gather`` of the activation."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops import collective_matmul as cm
from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.transformer import tensor_parallel as tp_lib

K = jr.PRNGKey(11)

# S and DHID divisible by every tp in the matrix; DHID is the Column
# output / Row input (the sharded dim)
S, B, DIN, DHID, DOUT = 12, 2, 8, 24, 8

TOL = {
    jnp.dtype(jnp.float32): dict(rtol=2e-5, atol=2e-5),
    # bf16 GEMMs + a chunked (ring-ordered) sum vs one fused reduction:
    # per-dtype tolerance, not bitwise, is the parity contract vs blocking
    jnp.dtype(jnp.bfloat16): dict(rtol=4e-2, atol=4e-2),
}


def _mk_args(seq_dim, dtype):
    shape = (S, B, DIN) if seq_dim == 0 else (B, S, DIN)
    x = jr.normal(K, shape, dtype)
    wc = (jr.normal(jr.fold_in(K, 1), (DHID, DIN)) * 0.3).astype(dtype)
    bc = (jr.normal(jr.fold_in(K, 2), (DHID,)) * 0.1).astype(dtype)
    wr = (jr.normal(jr.fold_in(K, 3), (DOUT, DHID)) * 0.3).astype(dtype)
    br = (jr.normal(jr.fold_in(K, 4), (DOUT,)) * 0.1).astype(dtype)
    return x, wc, bc, wr, br


def _chain(tp_size, sp, seq_dim, overlap):
    """The canonical Megatron pairing: Column(gather=False) → gelu → Row."""
    col = tp_lib.ColumnParallelLinear(
        DIN, DHID, tp_size=tp_size, bias=True, sequence_parallel=sp,
        seq_dim=seq_dim, overlap_comm=overlap)
    row = tp_lib.RowParallelLinear(
        DHID, DOUT, tp_size=tp_size, bias=True, sequence_parallel=sp,
        seq_dim=seq_dim, overlap_comm=overlap)

    def f(x, wc, bc, wr, br):
        h = col({"weight": wc, "bias": bc}, x)
        h = jax.nn.gelu(h, approximate=True)
        return row({"weight": wr, "bias": br}, h)

    return f


def _specs(sp, seq_dim):
    xspec = (P("tp") if seq_dim == 0 else P(None, "tp")) if sp else P()
    in_specs = (xspec, P("tp", None), P("tp"), P(None, "tp"), P())
    return in_specs, xspec


def _loss_and_grads_fn(mesh, tp_size, sp, seq_dim, overlap):
    f = _chain(tp_size, sp, seq_dim, overlap)
    in_specs, out_spec = _specs(sp, seq_dim)

    def inner(x, wc, bc, wr, br):
        sm = mesh_lib.shard_map(f, mesh=mesh, in_specs=in_specs,
                                out_specs=out_spec)
        y = sm(x, wc, bc, wr, br)
        return jnp.sum(jnp.sin(y.astype(jnp.float32)))

    return jax.value_and_grad(inner, argnums=(0, 1, 2, 3, 4))


class TestLayerParityMatrix:
    """The grad-parity matrix of the PR's acceptance: overlapped vs
    blocking Column→Row across tp ∈ {2,3,4}, seq_dim ∈ {0,1},
    fp32/bf16, with and without sequence parallelism — loss and ALL
    grads, per-dtype tolerance, on the virtual mesh."""

    @pytest.mark.parametrize("tp_size", [2, 3, 4])
    @pytest.mark.parametrize("sp", [True, False],
                             ids=["sp", "nosp"])
    def test_overlap_matches_blocking(self, tp_size, sp):
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=tp_size)
        for seq_dim in (0, 1):
            for dtype in (jnp.float32, jnp.bfloat16):
                args = _mk_args(seq_dim, dtype)

                @jax.jit
                def run(*a, seq_dim=seq_dim):
                    lo, go = _loss_and_grads_fn(
                        mesh, tp_size, sp, seq_dim, True)(*a)
                    lb, gb = _loss_and_grads_fn(
                        mesh, tp_size, sp, seq_dim, False)(*a)
                    return lo, go, lb, gb

                lo, go, lb, gb = run(*args)
                tol = TOL[jnp.dtype(dtype)]
                np.testing.assert_allclose(lo, lb, **tol)
                for a, b in zip(go, gb):
                    np.testing.assert_allclose(
                        np.asarray(a, np.float32),
                        np.asarray(b, np.float32), **tol,
                        err_msg=f"tp={tp_size} sp={sp} seq_dim={seq_dim} "
                                f"dtype={jnp.dtype(dtype).name}")


class TestBitwiseDeterminism:
    def test_two_runs_same_bits(self):
        """The rings visit contributions in a fixed order: the overlapped
        path is deterministic — two executions produce identical bytes for
        the loss and every gradient."""
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)
        args = _mk_args(1, jnp.float32)
        run = jax.jit(_loss_and_grads_fn(mesh, 4, True, 1, True))
        l1, g1 = run(*args)
        l2, g2 = run(*args)
        assert np.asarray(l1).tobytes() == np.asarray(l2).tobytes()
        for a, b in zip(g1, g2):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


class TestOverlappedJaxpr:
    """Acceptance: the overlapped linear's program (fwd AND bwd) carries
    ``ppermute`` and no full-width ``all_gather`` of the activation —
    asserted through the shared JXP contract helpers
    (``apex_tpu.lint.contracts``, the one engine that owns every jaxpr
    invariant); the blocking control proves the contract sees the gather
    when it is there."""

    def _jaxpr(self, overlap):
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)
        args = _mk_args(1, jnp.float32)
        fn = _loss_and_grads_fn(mesh, 4, True, 1, overlap)
        return jax.make_jaxpr(fn)(*args)

    def test_overlapped_ppermute_no_all_gather(self):
        from apex_tpu.lint import contracts as jc
        jc.assert_contracts(self._jaxpr(True), [
            jc.ppermute_present("tp"),
            jc.no_full_width_all_gather("tp"),
        ])

    def test_blocking_control_has_all_gather(self):
        from apex_tpu.lint import contracts as jc
        findings = jc.check_jaxpr(self._jaxpr(False),
                                  [jc.no_full_width_all_gather("tp")])
        assert findings and all(f.code == "JXP401" for f in findings)


class TestEagerValidation:
    """The uneven-sequence and misconfiguration errors fire at trace time
    and name the layer and the knob — not a bare XLA shape error."""

    def test_matmul_reduce_scatter_uneven_seq(self):
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)
        x = jr.normal(K, (2, 6, 8))  # 6 % 4 != 0
        w = jr.normal(K, (8, 8))
        sm = mesh_lib.shard_map(
            lambda x, w: cm.matmul_reduce_scatter(
                x, w, axis_name="tp", seq_dim=1),
            mesh=mesh, in_specs=(P(), P()), out_specs=P(None, "tp"))
        with pytest.raises(ValueError, match="divisible.*overlap_comm"):
            sm(x, w)

    def test_sp_reduce_scatter_uneven_seq_names_the_knob(self):
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)
        row = tp_lib.RowParallelLinear(8, 8, tp_size=4, bias=False,
                                       sequence_parallel=True, seq_dim=1)
        w = jr.normal(K, (8, 8))
        x = jr.normal(K, (2, 6, 2))  # 6 % 4 != 0
        sm = mesh_lib.shard_map(
            lambda x, w: row({"weight": w}, x), mesh=mesh,
            in_specs=(P(), P(None, "tp")), out_specs=P(None, "tp"))
        with pytest.raises(ValueError,
                           match="RowParallelLinear.*sequence_parallel"):
            sm(x, w)

    def test_gpt_sp_scatter_uneven_seq(self):
        from apex_tpu.models.gpt import _sp_scatter_seq1
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)
        x = jr.normal(K, (2, 10, 4))  # 10 % 4 != 0: floored before
        sm = mesh_lib.shard_map(
            lambda x: _sp_scatter_seq1(x, "tp"), mesh=mesh,
            in_specs=P(), out_specs=P(None, "tp"))
        with pytest.raises(ValueError, match="sequence_parallel=True"):
            sm(x)

    def test_bad_seq_dim_is_actionable(self):
        x = jr.normal(K, (4, 8))
        w = jr.normal(K, (8, 8))
        with pytest.raises(ValueError, match="seq_dim"):
            cm.all_gather_matmul(x, w, axis_name="tp", seq_dim=1)

    def test_column_overlap_needs_gather_output_false(self):
        with pytest.raises(ValueError, match="gather_output"):
            tp_lib.ColumnParallelLinear(8, 16, tp_size=2,
                                        overlap_comm=True,
                                        gather_output=True)

    def test_gpt_config_validation(self):
        from apex_tpu.models import GPTConfig
        with pytest.raises(ValueError, match="tp_size >= 2"):
            GPTConfig(tp_overlap=True, tp_size=1)
        with pytest.raises(ValueError, match="flash"):
            GPTConfig(tp_overlap=True, tp_size=2)
        with pytest.raises(ValueError, match="tp_axis"):
            # silently measuring the blocking path would be worse than
            # the error: tp_axis=None means no collectives to overlap
            GPTConfig(tp_overlap=True, tp_size=2, tp_axis=None,
                      attention_impl="flash")
        with pytest.raises(ValueError, match="context"):
            GPTConfig(tp_overlap=True, tp_size=2,
                      attention_impl="flash", cp_axis="cp")

    def test_t5_config_rejects_tp_overlap(self):
        from apex_tpu.models import T5Config
        with pytest.raises(ValueError, match="GPTConfig"):
            T5Config(tp_overlap=True)

    def test_tp1_axis_none_degrades_to_plain_matmul(self):
        x = jr.normal(K, (3, 2, 8))
        w = jr.normal(K, (6, 8)) * 0.3
        for fn in (cm.all_gather_matmul, cm.matmul_reduce_scatter,
                   cm.matmul_all_reduce, cm.copy_matmul):
            np.testing.assert_allclose(
                fn(x, w, axis_name=None, seq_dim=0), x @ w.T, rtol=1e-6)


class TestGPTTPOverlap:
    """The flagship model end to end: ``GPTConfig(tp_overlap=True)``
    reproduces the blocking model's loss and grads at tp=4 on the virtual
    mesh — with and without sequence parallelism (all four ring
    primitives on the model's real paths)."""

    @pytest.mark.parametrize("sp", [True, False], ids=["sp", "nosp"])
    def test_loss_and_grads_match_blocking(self, sp):
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.models.gpt import shard_params_for_tp

        kw = dict(vocab_size=64, max_seq_len=32, hidden_size=32,
                  num_layers=2, num_heads=8, attention_impl="flash")
        mesh = mesh_lib.make_mesh(tensor_model_parallel_size=4)
        cfg1 = GPTConfig(**kw, tp_size=1)
        params1 = GPTModel(cfg1).init(K)
        sharded = shard_params_for_tp(params1, 4, cfg1)
        specs = jax.tree.map(lambda _: P("tp"), sharded)
        toks = jr.randint(jr.fold_in(K, 80), (2, 16), 0, 64)
        tgts = jr.randint(jr.fold_in(K, 81), (2, 16), 0, 64)

        def loss_and_grads(overlap):
            model = GPTModel(GPTConfig(**kw, tp_size=4,
                                       sequence_parallel=sp,
                                       tp_overlap=overlap))

            def run(p, t, g):
                loss, grads = jax.value_and_grad(model.loss_fn)(
                    jax.tree.map(lambda x: x[0], p), t, g)
                grads = model.sp_grad_sync(grads)
                return loss, jax.tree.map(lambda x: x[None], grads)

            return jax.jit(mesh_lib.shard_map(
                run, mesh=mesh, in_specs=(specs, P(), P()),
                out_specs=(P(), specs)))(sharded, toks, tgts)

        with jax.default_matmul_precision("highest"):
            loss_o, g_o = loss_and_grads(True)
            loss_b, g_b = loss_and_grads(False)

        np.testing.assert_allclose(loss_o, loss_b, rtol=1e-5, atol=1e-6)
        flat_o, tree_o = jax.tree_util.tree_flatten_with_path(g_o)
        flat_b = jax.tree_util.tree_leaves(g_b)
        assert len(flat_o) == len(flat_b)
        for (path, a), b in zip(flat_o, flat_b):
            np.testing.assert_allclose(
                a, b, rtol=3e-4, atol=1e-5,
                err_msg=f"sp={sp} grad mismatch at "
                        f"{jax.tree_util.keystr(path)}")
