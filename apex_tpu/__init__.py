"""apex_tpu — a TPU-native mixed-precision & distributed-training toolkit.

A from-scratch JAX/XLA/Pallas framework with the capabilities of NVIDIA Apex
(reference: sneaxiy/apex): an automatic-mixed-precision policy engine
(``apex_tpu.amp``), data-parallel gradient synchronization and synchronized
batch-norm (``apex_tpu.parallel``), fused multi-tensor optimizers
(``apex_tpu.optimizers``), fused normalization / softmax / dense / loss ops as
Pallas TPU kernels (``apex_tpu.ops``, re-exported via ``apex_tpu.normalization``,
``apex_tpu.fused_dense``, ``apex_tpu.mlp``), Megatron-style tensor + pipeline
parallelism over a ``jax.sharding.Mesh`` (``apex_tpu.transformer``), ZeRO-style
sharded optimizers and further optional modules (``apex_tpu.contrib``), a
profiler (``apex_tpu.prof``), and runtime telemetry — metrics registry,
step-event JSONL stream, reporting CLI — with no reference analog
(``apex_tpu.monitor``, docs/OBSERVABILITY.md).

Where Apex relies on CUDA streams, NCCL process groups, and monkey-patching,
this framework uses named mesh axes + XLA collectives, functional precision
policies applied to parameter pytrees, and Pallas kernels for the hot ops.

Reference layer map: see SURVEY.md at the repo root. The top-level package
mirrors the reference's public surface (``apex/__init__.py``) without copying
its implementation.
"""

import jax as _jax

# jax-version compatibility: the repo targets current jax names; on older
# releases alias the few renamed/moved APIs once here (every subpackage
# imports apex_tpu first). jax.lax.axis_size(name) is statically
# lax.psum(1, name) — psum of a python scalar constant folds to the axis
# size at trace time, which is exactly axis_size's contract.
if not hasattr(_jax.lax, "axis_size"):  # pragma: no cover - version dep

    def _axis_size(axis_name):
        return _jax.lax.psum(1, axis_name)

    _jax.lax.axis_size = _axis_size

from apex_tpu.utils.logging import get_logger, set_rank_info  # noqa: E402,F401

__version__ = "0.1.0"

# Subpackages are imported lazily by users:
#   from apex_tpu import amp, optimizers, parallel, transformer, ops, contrib
#   from apex_tpu import plan   # ParallelPlan + the CostDB-driven planner
