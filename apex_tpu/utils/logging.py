"""Rank-aware logging.

TPU-native equivalent of the reference's root-logger setup with a
``RankInfoFormatter`` that prints (dp, tp, pp, vpp) ranks on every record
(reference: ``apex/__init__.py:27-38``, rank info from
``apex/transformer/parallel_state.py:250-259``) and the per-module logger
factory (``apex/transformer/log_util.py``).

In a JAX SPMD program there is one Python process per *host*, not per device,
so "rank" here is (process_index, mesh-rank-info-string). The mesh module
registers its rank info via :func:`set_rank_info` when a global mesh is
initialized.
"""

from __future__ import annotations

import logging
import os
import sys

_RANK_INFO: str = ""


def set_rank_info(info: str) -> None:
    """Record a short rank descriptor (e.g. ``"dp0/tp1/pp0"``) shown in logs."""
    global _RANK_INFO
    _RANK_INFO = info


def get_rank_info() -> str:
    return _RANK_INFO


class RankInfoFilter(logging.Filter):
    """Injects ``rank_info`` into every record (cf. RankInfoFormatter)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.rank_info = _RANK_INFO or f"p{os.environ.get('JAX_PROCESS_INDEX', 0)}"
        return True


_FORMAT = "%(asctime)s [%(rank_info)s] %(levelname)s %(name)s: %(message)s"


def get_logger(name: str = "apex_tpu", level: int | None = None) -> logging.Logger:
    """Per-module logger factory (cf. ``apex/transformer/log_util.py``)."""
    logger = logging.getLogger(name)
    if not getattr(logger, "_apex_tpu_configured", False):
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        handler.addFilter(RankInfoFilter())
        logger.addHandler(handler)
        logger.propagate = False
        logger._apex_tpu_configured = True  # type: ignore[attr-defined]
    env_level = os.environ.get("APEX_TPU_LOG_LEVEL")
    if level is not None:
        logger.setLevel(level)
    elif env_level:
        logger.setLevel(env_level.upper())
    elif logger.level == logging.NOTSET:
        logger.setLevel(logging.WARNING)
    return logger


def maybe_print(msg: str, *, rank0_only: bool = True) -> None:
    """Print gated to process 0 (cf. ``apex/amp/_amp_state.py:38-51``)."""
    import jax

    if not rank0_only or jax.process_index() == 0:
        print(msg, flush=True)
