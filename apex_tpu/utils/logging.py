"""Rank-aware logging.

TPU-native equivalent of the reference's root-logger setup with a
``RankInfoFormatter`` that prints (dp, tp, pp, vpp) ranks on every record
(reference: ``apex/__init__.py:27-38``, rank info from
``apex/transformer/parallel_state.py:250-259``) and the per-module logger
factory (``apex/transformer/log_util.py``).

In a JAX SPMD program there is one Python process per *host*, not per device,
so "rank" here is (process_index, mesh-rank-info-string). The mesh module
registers its rank info via :func:`set_rank_info` when a global mesh is
initialized.
"""

from __future__ import annotations

import logging
import os
import sys

_RANK_INFO: str = ""
_PROCESS_INDEX: int | None = None  # cached first successful jax.process_index()


def set_rank_info(info: str) -> None:
    """Record a short rank descriptor (e.g. ``"dp0/tp1/pp0"``) shown in logs."""
    global _RANK_INFO
    _RANK_INFO = info


def get_rank_info() -> str:
    return _RANK_INFO


def process_index() -> int:
    """This host's process index: ``jax.process_index()`` when jax is
    importable and its backend already initialized (the multi-host truth),
    else the ``JAX_PROCESS_INDEX`` env var, else 0.

    The jax path is gated on the backend being up — a log line must never
    be the thing that initializes a TPU backend (import-time records fire
    before ``conftest``/launchers finish selecting the platform)."""
    global _PROCESS_INDEX
    if _PROCESS_INDEX is not None:
        return _PROCESS_INDEX
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            from jax._src import xla_bridge

            if xla_bridge._backends:  # initialized — reading it is free
                _PROCESS_INDEX = int(jax_mod.process_index())
                return _PROCESS_INDEX
        except Exception:  # internals moved / backend mid-init: fall back
            pass
    try:
        return int(os.environ.get("JAX_PROCESS_INDEX", 0))
    except ValueError:
        return 0


class RankInfoFilter(logging.Filter):
    """Injects ``rank_info`` into every record (cf. RankInfoFormatter)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.rank_info = _RANK_INFO or f"p{process_index()}"
        return True


_FORMAT = "%(asctime)s [%(rank_info)s] %(levelname)s %(name)s: %(message)s"


def get_logger(name: str = "apex_tpu", level: int | None = None) -> logging.Logger:
    """Per-module logger factory (cf. ``apex/transformer/log_util.py``).

    Level precedence, re-evaluated on *every* call (not just the first):
    an explicit ``level`` argument wins and sticks; otherwise
    ``APEX_TPU_LOG_LEVEL`` is re-applied — so exporting the env var after a
    module already configured its logger still takes effect on the next
    ``get_logger`` — unless a previous call pinned an explicit level; the
    default is WARNING."""
    logger = logging.getLogger(name)
    if not getattr(logger, "_apex_tpu_configured", False):
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        handler.addFilter(RankInfoFilter())
        logger.addHandler(handler)
        logger.propagate = False
        logger._apex_tpu_configured = True  # type: ignore[attr-defined]
    env_level = os.environ.get("APEX_TPU_LOG_LEVEL")
    if level is not None:
        logger.setLevel(level)
        logger._apex_tpu_explicit_level = True  # type: ignore[attr-defined]
    elif env_level and not getattr(logger, "_apex_tpu_explicit_level", False):
        logger.setLevel(env_level.upper())
    elif logger.level == logging.NOTSET:
        logger.setLevel(logging.WARNING)
    return logger


def maybe_print(msg: str, *, rank0_only: bool = True) -> None:
    """Print gated to process 0 (cf. ``apex/amp/_amp_state.py:38-51``)."""
    import jax

    if not rank0_only or jax.process_index() == 0:
        print(msg, flush=True)
