"""Pytree helpers shared across the framework.

These replace the reference's tensor-list plumbing (``apex_C.flatten`` /
``unflatten``, ``csrc/flatten_unflatten.cpp:16-17``) and the grad inspection
utilities (``apex/transformer/pipeline_parallel/utils.py:265-285``) with
pytree-native equivalents. On TPU, flattening into one contiguous buffer is
also the layout that makes fused-optimizer Pallas kernels efficient, so
:func:`ravel_pytree_fast` is the backbone of ``apex_tpu.optimizers``.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


def tree_cast(tree: PyTree, dtype) -> PyTree:
    """Cast every floating-point leaf to ``dtype``; leave int/bool leaves alone.

    Functional replacement for ``apex/fp16_utils/fp16util.py``'s
    ``network_to_half`` / ``convert_network`` module walkers.
    """
    if dtype is None:
        return tree

    def _cast(x):
        if isinstance(x, (jax.Array, np.ndarray)) and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(_cast, tree)


def tree_size(tree: PyTree) -> int:
    """Total number of elements across all leaves."""
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def tree_norm(tree: PyTree, ord: int = 2) -> jax.Array:
    """Global norm over all leaves (cf. ``amp_C.multi_tensor_l2norm`` —
    ``csrc/multi_tensor_l2norm_kernel.cu`` — which computes per-tensor and
    global L2 norms in one launch; XLA fuses this reduction natively)."""
    leaves = [jnp.asarray(x, jnp.float32) for x in jax.tree.leaves(tree)]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    if ord == 2:
        return jnp.sqrt(sum(jnp.sum(x * x) for x in leaves))
    return sum(jnp.sum(jnp.abs(x) ** ord) for x in leaves) ** (1.0 / ord)


def tree_all_finite(tree: PyTree) -> jax.Array:
    """True iff every element of every leaf is finite.

    The fused inf/nan detection that ``amp_C.multi_tensor_scale`` folds into
    its copy kernel (``csrc/multi_tensor_scale_kernel.cu``); here it is a
    reduction XLA fuses into the surrounding computation, and the result stays
    on device (no D2H sync — cf. the single sync at ``apex/amp/scaler.py:200``).
    """
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]).all()


def ravel_pytree_fast(tree: PyTree) -> Tuple[jax.Array, Callable[[jax.Array], PyTree]]:
    """Flatten a pytree of arrays into one 1-D buffer + an unravel closure.

    Like ``jax.flatten_util.ravel_pytree`` but promotes nothing: all leaves
    must share a dtype (callers group by dtype first, exactly as the reference
    groups tensors with ``split_half_float_double``,
    ``apex/parallel/distributed.py:51-58``).
    """
    leaves, treedef = jax.tree.flatten(tree)
    dtypes = {jnp.asarray(x).dtype for x in leaves}
    if len(dtypes) > 1:
        raise TypeError(
            f"ravel_pytree_fast requires uniform leaf dtype, got {sorted(map(str, dtypes))}; "
            "group leaves by dtype first (cf. split_half_float_double, "
            "apex/parallel/distributed.py:51-58)"
        )
    shapes = [x.shape for x in leaves]
    sizes = [int(np.prod(s)) for s in shapes]
    flat = jnp.concatenate([jnp.reshape(x, (-1,)) for x in leaves]) if leaves else jnp.zeros((0,))

    def unravel(buf: jax.Array) -> PyTree:
        chunks = []
        offset = 0
        for shape, size in zip(shapes, sizes):
            chunks.append(jnp.reshape(buf[offset : offset + size], shape))
            offset += size
        return jax.tree.unflatten(treedef, chunks)

    return flat, unravel
