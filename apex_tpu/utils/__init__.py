"""Shared utilities: logging, pytree helpers, dtype helpers."""

from apex_tpu.utils.logging import get_logger, set_rank_info  # noqa: F401
from apex_tpu.utils.pytree import (  # noqa: F401
    tree_cast,
    tree_size,
    tree_norm,
    tree_all_finite,
    ravel_pytree_fast,
)
