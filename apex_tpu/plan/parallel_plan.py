"""The unified :class:`ParallelPlan`: one frozen object naming every
parallelism decision the repo used to spread across loose kwargs.

Before this module the knobs lived in three places with three error
styles: ``GPTConfig.__post_init__`` validated ``tp_overlap``/
``pp_schedule``, ``parallel.mesh`` validated ep/virtual-chunk
divisibility, and ``build_schedule`` validated microbatch geometry —
the same illegal combination produced a different message depending on
which door it walked through. A plan object is the AMP-style planner's
unit of search (arXiv:2210.07297 searches exactly this space), and
veScale (arXiv:2509.07003) is the argument for keeping the plan's
semantics equal to single-device execution — which our grad-parity
oracles enforce per knob.

Design rules:

* **Frozen + eagerly validated.** Construction runs :meth:`validate`;
  an illegal combination never exists as a live object. Every error
  names the knob and its legal values in one message style.
* **Exact JSON round-trip.** :meth:`to_json` / :meth:`from_json` are
  inverses field-for-field — the ``plan`` monitor record and the
  planner's ranking serialize plans losslessly.
* **The deprecated shim.** :meth:`from_model_kwargs` builds a plan from
  the loose model-config knobs (``tp_size``, ``sequence_parallel``, …)
  with the historical lenient semantics (``sequence_parallel`` at
  ``tp_size=1`` was silently inert, so the shim normalizes it off
  rather than erroring) — no existing caller breaks, while direct
  ``ParallelPlan(...)`` construction stays strict.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Union

#: legal pipeline schedule families (a plan with ``virtual_chunks > 1``
#: under "1f1b" runs the interleaved schedule — interleaving IS the
#: virtual-chunk form of 1f1b, the same convention as ``GPTConfig``)
PP_SCHEDULES = ("1f1b", "zb")

_AXIS_FIELDS = ("dp", "tp", "pp", "cp", "ep")


class PlanError(ValueError):
    """An illegal knob combination, named knob-first."""


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """Mesh axis sizes + the schedule/overlap/ZeRO knobs of one run.

    ``dp``/``tp``/``pp``/``cp``/``ep`` are the mesh axis extents
    (:mod:`apex_tpu.parallel.mesh` layout, ep split out of dp);
    ``virtual_chunks`` is the interleaved/virtual pipeline depth;
    ``zero`` turns on dp-sharded optimizer state
    (:func:`apex_tpu.contrib.optimizers.distributed_fused_adam`).
    """

    dp: int = 1
    tp: int = 1
    pp: int = 1
    cp: int = 1
    ep: int = 1
    sequence_parallel: bool = False
    tp_overlap: bool = False
    pp_schedule: str = "1f1b"
    overlap_p2p: bool = False
    virtual_chunks: int = 1
    zero: bool = False

    def __post_init__(self):
        self.validate()

    # --- validation -----------------------------------------------------------

    def validate(self) -> "ParallelPlan":
        """Cross-field legality, one message style: the knob, its value,
        and the legal values. Raises :class:`PlanError` (a ValueError);
        returns ``self`` so call sites can chain."""
        for name in _AXIS_FIELDS:
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise PlanError(
                    f"{name}={v!r} is not a mesh axis size; legal values "
                    f"are integers >= 1")
        if (not isinstance(self.virtual_chunks, int)
                or isinstance(self.virtual_chunks, bool)
                or self.virtual_chunks < 1):
            raise PlanError(
                f"virtual_chunks={self.virtual_chunks!r} is not a chunk "
                f"count; legal values are integers >= 1")
        if self.pp_schedule not in PP_SCHEDULES:
            raise PlanError(
                f"pp_schedule={self.pp_schedule!r} is not a pipeline "
                f"schedule; legal values are "
                f"{' / '.join(map(repr, PP_SCHEDULES))} (interleaving is "
                f"'1f1b' with virtual_chunks >= 2)")
        if self.virtual_chunks > 1 and self.pp < 2:
            raise PlanError(
                f"virtual_chunks={self.virtual_chunks} requires "
                f"pipeline parallelism: virtual pipeline parallelism "
                f"requires pipeline_model_parallel_size >= 2 (pp="
                f"{self.pp}); legal values at pp=1 are virtual_chunks=1")
        if self.ep > 1 and self.dp % self.ep:
            raise PlanError(
                f"ep={self.ep} with dp={self.dp}: expert_parallel_size "
                f"must divide data_parallel_size (the ep axis splits out "
                f"of dp); legal values are divisors of dp")
        if self.sequence_parallel and self.tp < 2:
            raise PlanError(
                f"sequence_parallel=True with tp={self.tp}: sequence "
                f"parallelism shards the activations the tp boundary "
                f"collectives move; it needs tp_size >= 2 (legal values "
                f"at tp=1 are sequence_parallel=False)")
        if self.tp_overlap:
            if self.tp < 2:
                raise PlanError(
                    f"tp_overlap=True with tp={self.tp}: the overlap "
                    f"hides tp boundary collectives behind the linears' "
                    f"GEMMs; it needs tp_size >= 2 (there is no "
                    f"collective to hide at tp=1)")
            if self.cp > 1:
                raise PlanError(
                    f"tp_overlap=True with cp={self.cp}: tp_overlap does "
                    f"not yet compose with context parallelism (the cp "
                    f"attention branch re-shards the sequence the rings "
                    f"chunk); legal values are cp=1 or tp_overlap=False")
        return self

    def validate_schedule(self) -> "ParallelPlan":
        """The schedule-time strictness :meth:`validate` defers: a plan
        may *carry* ``pp_schedule="zb"`` or ``overlap_p2p`` at ``pp=1``
        (the knobs are inert without a pipeline, the historical
        ``GPTConfig`` semantics), but a schedule *built* from it must
        have a pipeline to schedule."""
        self.validate()
        if self.pp < 2 and (self.pp_schedule != "1f1b"
                            or self.virtual_chunks > 1):
            raise PlanError(
                f"pp_schedule={self.pp_schedule!r} / virtual_chunks="
                f"{self.virtual_chunks} needs "
                f"pipeline_model_parallel_size >= 2 (pp={self.pp}); a "
                f"single stage has no pipeline to schedule")
        return self

    def validate_microbatches(self, num_microbatches: int) -> "ParallelPlan":
        """Microbatch-count geometry (the ``build_schedule`` checks):
        the pipeline must fill, and virtual chunks must divide into the
        schedule's injection groups."""
        m = num_microbatches
        if self.pp > 1 and m < self.pp:
            raise PlanError(
                f"{m} microbatches cannot fill a {self.pp}-stage "
                f"pipeline; lower micro_batch_size or raise "
                f"global_batch_size")
        if self.virtual_chunks > 1 and self.pp > 1:
            group = (2 * self.pp) if self.overlap_p2p else self.pp
            if m % group:
                raise PlanError(
                    f"the interleaved schedule needs every microbatch "
                    f"count divisible by "
                    f"{'2*' if self.overlap_p2p else ''}the pipeline "
                    f"size ({group}); got {m} microbatches"
                    + (" (overlap_p2p=True doubles the injection group "
                       "— each hop spans a full tick)"
                       if self.overlap_p2p else ""))
        return self

    # --- derived facts --------------------------------------------------------

    @property
    def model_parallel_size(self) -> int:
        """Chips one model replica spans (ep rides inside dp)."""
        return self.tp * self.pp * self.cp

    @property
    def world_size(self) -> int:
        return self.dp * self.model_parallel_size

    def describe(self) -> str:
        """Short human tag: ``dp2·tp2·pp2 zb sp overlap[tp,p2p]``."""
        bits = [f"dp{self.dp}", f"tp{self.tp}", f"pp{self.pp}"]
        if self.cp > 1:
            bits.append(f"cp{self.cp}")
        if self.ep > 1:
            bits.append(f"ep{self.ep}")
        out = "·".join(bits)
        if self.pp > 1:
            out += f" {self.pp_schedule}"
            if self.virtual_chunks > 1:
                out += f"v{self.virtual_chunks}"
        if self.sequence_parallel:
            out += " sp"
        overlaps = [n for n, on in (("tp", self.tp_overlap),
                                    ("p2p", self.overlap_p2p)) if on]
        if overlaps:
            out += f" overlap[{','.join(overlaps)}]"
        if self.zero:
            out += " zero"
        return out

    # --- serialization --------------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        """Plain-JSON dict; exact inverse of :meth:`from_json` (pinned by
        ``tests/test_plan.py``)."""
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: Union[str, Dict[str, Any]]) -> "ParallelPlan":
        """Rebuild from :meth:`to_json` output (dict or JSON string).
        Unknown keys are an error — a junk plan must not half-load."""
        if isinstance(obj, str):
            obj = json.loads(obj)
        if not isinstance(obj, dict):
            raise PlanError(f"a plan serializes as a JSON object, got "
                            f"{type(obj).__name__}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(obj) - known)
        if unknown:
            raise PlanError(
                f"unknown plan field(s) {unknown}; legal fields are "
                f"{sorted(known)}")
        return cls(**obj)

    # --- the deprecated loose-kwarg shim --------------------------------------

    @classmethod
    def from_model_kwargs(cls, *, tp_size: int = 1,
                          sequence_parallel: bool = False,
                          tp_overlap: bool = False,
                          pp_schedule: str = "1f1b",
                          overlap_p2p: bool = False,
                          cp: int = 1, ep: int = 1, dp: int = 1,
                          pp: int = 1, virtual_chunks: int = 1,
                          zero: bool = False) -> "ParallelPlan":
        """Build a plan from the historical loose model-config knobs.

        This is the back-compat shim ``GPTConfig``/``T5Config`` route
        through: it preserves the old lenient semantics by *normalizing*
        combinations that used to be silently inert
        (``sequence_parallel``/``tp_overlap`` at ``tp_size=1`` — the
        models treated them as off) instead of raising the strict
        :class:`PlanError` a direct construction would. Knobs that were
        eager errors before (``tp_overlap`` with tp >= 2 but cp set,
        unknown ``pp_schedule``) stay errors, now in the plan's one
        message style.
        """
        if tp_size < 2:
            # historically inert at tp=1 (GPTModel: `sp = c.sequence_
            # parallel and c.tp_size > 1`); tp_overlap at tp<2 was an
            # eager error and stays one — construct strict to raise it
            if not tp_overlap:
                sequence_parallel = False
        return cls(dp=dp, tp=tp_size, pp=pp, cp=cp, ep=ep,
                   sequence_parallel=sequence_parallel,
                   tp_overlap=tp_overlap, pp_schedule=pp_schedule,
                   overlap_p2p=overlap_p2p,
                   virtual_chunks=virtual_chunks, zero=zero)
