"""Analytical plan pricing: StaticCostReport × CostDB × schedule model.

The AMP recipe (arXiv:2210.07297): a candidate plan's step time is
priced, not guessed, from (a) the *traced* per-chip program's static
cost — every collective's payload bytes by ``<kind>[<axis>]`` and every
GEMM's FLOPs by power-of-two class, multiplied through enclosing scans
(:func:`apex_tpu.lint.jaxpr_check.static_cost`, PR 10) — converted
through (b) the *measured* CostDB's achieved bytes/s per size bucket
and FLOP/s per GEMM class (:mod:`apex_tpu.prof.calibrate`, PR 6), with
(c) the pipeline schedule's slot-waste/recompute geometry
(:func:`apex_tpu.monitor.hooks.pipeline_cost_model`, PR 8) as an
explicit multiplier. Heterogeneity needs no special case: CostDB keys
carry the mesh axis, so a topology whose dp hops ride DCN prices
``psum[dp]`` from its own (slower) measured rows — slow-axis entries
reprice dp-vs-tp placement exactly as AMP's heterogeneity term does.

Tracing is abstract: the plan's step is built on the virtual CPU mesh
and walked via ``jax.make_jaxpr`` over ``ShapeDtypeStruct`` operands —
no device buffer is allocated and nothing executes, so pricing a
64-layer plan costs milliseconds regardless of workload size.

Composition (one formula, documented with a worked example in
``docs/api/plan.md``)::

    factor       = (total_units + recompute_units·remat) / ideal_units
    predicted_ms = (gemm_ms + tp_ms + cp_ms) · factor
                   + dp_ms + (0 if overlap_p2p else pp_ms)

where ``*_ms = bytes/rate`` (or ``flops/rate``) summed per axis
family. The schedule factor makes zb-vs-1f1b a priced choice (zb drops
the drain slots but — under remat — pays ``M·v`` extra recompute), and
the ``overlap_p2p`` branch makes overlap-vs-blocking one (overlap
hides the hop bytes but lengthens the drain through the factor's
``L=2`` geometry).

A traced key the CostDB has never measured is a *blind spot*, not a
zero: it is priced at the optional ``default_*`` rate (or omitted) and
always reported in ``uncalibrated`` — the per-plan confidence flag the
``plan`` record carries, the same surface ``prof.calibrate
.diff_static_cost`` exposes for the lint CLI's ``--strict`` gate.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

from apex_tpu.plan.parallel_plan import ParallelPlan, PlanError


@dataclasses.dataclass(frozen=True)
class Workload:
    """The model + batch geometry a plan is priced for (the flagship
    GPT-medium dims by default — ``bench.py``'s train config)."""

    hidden_size: int = 1024
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    num_layers: int = 12
    vocab_size: int = 32768
    seq: int = 1024
    global_batch: int = 16
    micro_batch: int = 2
    dtype_bytes: int = 2          # bf16 activations/params
    remat: bool = False           # per-tick recompute priced when True

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    def layers_per_chunk(self, plan: ParallelPlan) -> int:
        """Layers one pipeline chunk holds; raises (never truncates)
        when the stack does not divide — pricing a 12-layer model as a
        10-layer one would silently compare different models."""
        ways = plan.pp * plan.virtual_chunks
        if self.num_layers % ways:
            raise PlanError(
                f"num_layers={self.num_layers} is not divisible by "
                f"pp*virtual_chunks ({plan.pp}*{plan.virtual_chunks}); "
                f"legal pp/virtual_chunks values divide the layer stack")
        return self.num_layers // ways

    def microbatches(self, plan: ParallelPlan) -> int:
        """Microbatches per dp replica per step; raises when the global
        batch does not divide (same eagerness as ``build_schedule``)."""
        per = self.micro_batch * plan.dp
        if self.global_batch % per:
            raise PlanError(
                f"global_batch={self.global_batch} is not divisible by "
                f"micro_batch*dp ({self.micro_batch}*{plan.dp}); legal "
                f"dp values divide global_batch/micro_batch")
        return self.global_batch // per


# --- the traced per-chip step -------------------------------------------------

#: trace cache: the jaxpr walk depends only on the signature below, not
#: on the schedule/overlap_p2p/zero knobs (those price through the cost
#: model), so a lattice sweep re-traces only distinct programs
_STATIC_CACHE: Dict[Tuple, Dict[str, Any]] = {}


def _trace_signature(plan: ParallelPlan, w: Workload,
                     ticks: int) -> Tuple:
    return (plan.dp, plan.tp, plan.pp, plan.sequence_parallel,
            plan.tp_overlap, ticks, w.hidden_size, w.ffn, w.num_layers,
            plan.virtual_chunks, w.vocab_size, w.seq, w.micro_batch,
            w.dtype_bytes)


def build_plan_step(plan: ParallelPlan, w: Workload):
    """``(fn, args)``: one dp replica's full train step under the plan —
    per-tick stage compute (``layers/(pp·v)`` Column→Row GEMM blocks,
    tp-sharded with the plan's SP/overlap knobs), the pp boundary hop
    per tick, the vocab head GEMM, grads, the dp grad all-reduce, and
    an SGD rebind — as a ``shard_map`` program over the plan's mesh
    axes, with ``ShapeDtypeStruct`` operands ready for
    ``jax.make_jaxpr``. Schedule choice does NOT change this program
    (warmup/drain and recompute price through
    ``pipeline_cost_model``); it is the per-chip *useful work* whose
    collectives and GEMMs the CostDB can rate."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer import tensor_parallel as tp_lib

    world = plan.world_size
    if world > jax.device_count():
        raise PlanError(
            f"plan {plan.describe()} spans {world} chips but this host "
            f"exposes {jax.device_count()} device(s); tracing needs a "
            f"mesh at the plan's extent")
    mesh = mesh_lib.make_mesh(
        tensor_model_parallel_size=plan.tp,
        pipeline_model_parallel_size=plan.pp,
        context_parallel_size=plan.cp,
        devices=jax.devices()[:world])

    tp, pp = plan.tp, plan.pp
    H, ffn, V, s, b = (w.hidden_size, w.ffn, w.vocab_size, w.seq,
                       w.micro_batch)
    lc = w.layers_per_chunk(plan)
    ticks = w.microbatches(plan) * plan.virtual_chunks
    sp = plan.sequence_parallel and tp > 1
    axis = "tp" if tp > 1 else None
    dt = {2: jnp.bfloat16, 4: jnp.float32}[w.dtype_bytes]

    col = tp_lib.ColumnParallelLinear(
        H, ffn, bias=False, tp_size=tp, axis_name=axis,
        sequence_parallel=sp, seq_dim=1, overlap_comm=plan.tp_overlap)
    row = tp_lib.RowParallelLinear(
        ffn, H, bias=False, tp_size=tp, axis_name=axis,
        sequence_parallel=sp, seq_dim=1, overlap_comm=plan.tp_overlap)
    head = tp_lib.ColumnParallelLinear(
        H, V, bias=False, tp_size=tp, axis_name=axis,
        sequence_parallel=sp, seq_dim=1, overlap_comm=plan.tp_overlap)

    def layer(h, wpair):
        w1, w2 = wpair
        up = col({"weight": w1}, h)
        return h + row({"weight": w2}, jax.nn.gelu(up, approximate=True))

    def step(params, x, tgt):
        def tick(loss, xs):
            xt, tt = xs
            h, _ = jax.lax.scan(
                lambda c, wl: (layer(c, wl), None),
                xt, (params["w1"], params["w2"]))
            if pp > 1:
                n = jax.lax.axis_size("pp")
                h = jax.lax.ppermute(
                    h, "pp", [(i, (i + 1) % n) for i in range(n)])
            logits = head({"weight": params["head"]}, h)
            # two terms, not logits-vs-target: logits are vocab-width
            # and (under SP) h is seq-sharded — the GEMMs/collectives
            # are what is being counted, not the loss's value
            err = jnp.mean((h.astype(jnp.float32)
                            - tt.astype(jnp.float32)) ** 2)
            return loss + err + jnp.mean(
                logits.astype(jnp.float32) ** 2), None

        def total(p):
            out, _ = jax.lax.scan(tick, jnp.float32(0.0), (x, tgt))
            return out

        loss, grads = jax.value_and_grad(total)(params)
        if plan.dp > 1:
            grads = jax.tree.map(
                lambda g: jax.lax.psum(g, "dp"), grads)
        new = jax.tree.map(lambda p, g: p - 0.01 * g.astype(p.dtype),
                           params, grads)
        return new, loss

    wspec = {"w1": P(None, "tp", None) if tp > 1 else P(),
             "w2": P(None, None, "tp") if tp > 1 else P(),
             "head": P("tp", None) if tp > 1 else P()}
    xspec = P(None, None, "tp", None) if sp else P()
    fn = mesh_lib.shard_map(step, mesh=mesh,
                            in_specs=(wspec, xspec, xspec),
                            out_specs=(wspec, P()))
    sds = jax.ShapeDtypeStruct
    params = {"w1": sds((lc, ffn, H), dt), "w2": sds((lc, H, ffn), dt),
              "head": sds((V, H), dt)}
    x = sds((ticks, b, s, H), dt)
    return fn, (params, x, x)


#: jaxpr cache shared by static_cost_for_plan and liveness_memory: one
#: abstract trace per distinct program feeds BOTH the time and the
#: memory model. Values are ``(closed_jaxpr, arg_families)``.
_JAXPR_CACHE: Dict[Tuple, Tuple[Any, Tuple[str, ...]]] = {}


def _traced_step(plan: ParallelPlan, w: Workload):
    """``(closed_jaxpr, arg_families)`` of the plan's step, memoized."""
    ticks = w.microbatches(plan) * plan.virtual_chunks
    key = _trace_signature(plan, w, ticks)
    hit = _JAXPR_CACHE.get(key)
    if hit is not None:
        return hit
    import jax

    fn, args = build_plan_step(plan, w)
    params, _x, _tgt = args
    fams = (("params",) * len(jax.tree.leaves(params))
            + ("activations",) * 2)
    closed = jax.make_jaxpr(fn)(*args)
    _JAXPR_CACHE[key] = (closed, fams)
    return closed, fams


def static_cost_for_plan(plan: ParallelPlan, w: Workload
                         ) -> Dict[str, Any]:
    """The plan's per-chip :func:`~apex_tpu.lint.jaxpr_check
    .static_cost` report — traced abstractly (no execution), memoized
    per distinct program."""
    ticks = w.microbatches(plan) * plan.virtual_chunks
    key = _trace_signature(plan, w, ticks)
    hit = _STATIC_CACHE.get(key)
    if hit is not None:
        return hit
    from apex_tpu.lint import jaxpr_check as jx

    closed, _fams = _traced_step(plan, w)
    report = jx.static_cost(
        closed, entrypoint=f"plan_step:{'x'.join(map(str, key[:3]))}")
    _STATIC_CACHE[key] = report
    return report


# --- CostDB conversion --------------------------------------------------------

def _nearest_bucket_rate(rows: List[dict], per_call_bytes: float
                         ) -> Optional[float]:
    """Mean bytes/s of the size bucket nearest the payload — the ONE
    shared rule in :func:`apex_tpu.prof.calibrate.nearest_bucket_rate`
    (also behind ``diff_static_cost``), so the planner's prices and the
    lint CLI's coverage table cannot diverge."""
    from apex_tpu.prof.calibrate import nearest_bucket_rate

    return nearest_bucket_rate(rows, per_call_bytes)


def _nearest_gemm_rate(gemms: Dict[str, dict], cls: str
                       ) -> Tuple[Optional[float], bool]:
    """``(flops/s, exact)`` for a GEMM class: the class's own measured
    mean when present, else the nearest class by log2 FLOPs distance
    (``exact=False`` — a shape class the CostDB never measured is still
    calibrated *compute*, just priced from its nearest neighbor)."""
    ent = gemms.get(cls)
    if ent and ent.get("flops_per_s", {}).get("mean", 0) > 0:
        return ent["flops_per_s"]["mean"], True
    want = math.log2(max(int(cls.rsplit("_", 1)[-1]), 1))
    best, dist = None, None
    for name, e in sorted(gemms.items()):
        rate = e.get("flops_per_s", {}).get("mean", 0)
        if rate <= 0:
            continue
        d = abs(math.log2(max(int(name.rsplit("_", 1)[-1]), 1)) - want)
        if dist is None or d < dist:
            best, dist = rate, d
    return best, False


def _axis_of(key: str) -> str:
    """Mesh axis family of a ``<kind>[<axis>]`` collective key (the
    first axis named — multi-axis keys like ``psum[dp,ep]`` bill to
    their outer family)."""
    inside = key.split("[", 1)[-1].rstrip("]")
    return inside.split(",", 1)[0].strip()


@dataclasses.dataclass(frozen=True)
class PlanMemory:
    """Per-chip HBM estimate (bytes). ``source`` names the model that
    produced it: ``"closed_form"`` (:func:`estimate_memory`'s aval
    arithmetic) or ``"liveness"`` (:func:`liveness_memory`'s
    donation-aware walk of the plan's traced step)."""

    params: int
    optimizer: int
    activations: int
    source: str = "closed_form"

    @property
    def total(self) -> int:
        return self.params + self.optimizer + self.activations

    def to_json(self) -> Dict[str, Any]:
        mb = 1 / 2 ** 20
        return {"params_mb": round(self.params * mb, 2),
                "optimizer_mb": round(self.optimizer * mb, 2),
                "activations_mb": round(self.activations * mb, 2),
                "total_mb": round(self.total * mb, 2),
                "source": self.source}


@dataclasses.dataclass(frozen=True)
class PlanPrice:
    """One plan's predicted step decomposition. ``uncalibrated`` is the
    confidence surface: traced cost keys the CostDB has never measured
    (empty ⇒ ``confidence == "calibrated"``)."""

    plan: ParallelPlan
    predicted_step_ms: float
    gemm_ms: float
    tp_ms: float
    pp_ms: float
    dp_ms: float
    cp_ms: float
    schedule_factor: float
    bubble_fraction: float
    memory: PlanMemory
    uncalibrated: Tuple[str, ...]
    #: closed-form-vs-liveness gap (pct of the closed form), set when
    #: the liveness memory model priced this plan; >10% also lands a
    #: ``memory_model[...]`` honesty flag in ``uncalibrated``
    memory_disagreement_pct: Optional[float] = None

    @property
    def confidence(self) -> str:
        return "calibrated" if not self.uncalibrated else "partial"

    def to_json(self) -> Dict[str, Any]:
        # collective_ms is the EXPOSED, schedule-scaled share (pp hops
        # hidden under overlap_p2p; tp/cp ride every scheduled slot),
        # so gemm_ms·schedule_factor + collective_ms reconciles with
        # predicted_step_ms exactly, for every plan
        hidden = self.plan.overlap_p2p and self.plan.pp > 1
        exposed = ((self.tp_ms + self.cp_ms) * self.schedule_factor
                   + self.dp_ms + (0.0 if hidden else self.pp_ms))
        return {
            "plan": self.plan.to_json(),
            "predicted_step_ms": round(self.predicted_step_ms, 4),
            "confidence": self.confidence,
            "uncalibrated": list(self.uncalibrated),
            "gemm_ms": round(self.gemm_ms, 4),
            "collective_ms": round(exposed, 4),
            "schedule_factor": round(self.schedule_factor, 4),
            "bubble_pct": round(100 * self.bubble_fraction, 2),
            "predicted_memory_mb": self.memory.to_json()["total_mb"],
            "memory_source": self.memory.source,
            **({"memory_disagreement_pct":
                round(self.memory_disagreement_pct, 2)}
               if self.memory_disagreement_pct is not None else {}),
        }


def estimate_memory(plan: ParallelPlan, w: Workload) -> PlanMemory:
    """Per-chip params + optimizer + activations from the plan's
    sharded shapes: ``layers/(pp·v·?)``… params shard over tp (and the
    stage axis), optimizer state is fp32 master+m+v (ZeRO divides it by
    dp), and the activation term counts the schedule's live microbatch
    stash (zb stashes all ``M·v`` tick inputs for the deferred dW
    sweep; 1f1b holds at most ``pp`` in flight)."""
    H, ffn, V = w.hidden_size, w.ffn, w.vocab_size
    lc = w.layers_per_chunk(plan)
    layer_params = 2 * H * ffn  # col + row weights
    per_chip_params = (lc * plan.virtual_chunks * layer_params
                       + V * H) // plan.tp
    param_bytes = per_chip_params * w.dtype_bytes
    # fp32 master + adam m + v = 12 bytes/param, dp-sharded under ZeRO
    opt_bytes = per_chip_params * 12
    if plan.zero:
        opt_bytes //= plan.dp
    b, s = w.micro_batch, w.seq
    act = b * s * H * w.dtype_bytes
    if plan.cp > 1:
        act //= plan.cp
    ticks = w.microbatches(plan) * plan.virtual_chunks
    if plan.pp > 1:
        live = ticks if plan.pp_schedule == "zb" else min(plan.pp, ticks)
    else:
        live = 1
    # stashed tick inputs + in-flight block residuals (H + ffn per
    # layer, tp-sharded with SP/tp on the wide dim) for EVERY chunk
    # this chip hosts — interleaving keeps one microbatch's residuals
    # alive per virtual chunk, a term the liveness cross-check showed
    # this closed form used to drop (ISSUE 18 satellite)
    resid = (b * s * (H + ffn // plan.tp) * w.dtype_bytes * lc
             * max(plan.virtual_chunks, 1))
    if plan.sequence_parallel:
        resid //= plan.tp
    # the vocab head: one microbatch's logits (b, s, V/tp) live at the
    # forward peak in the compute dtype PLUS their fp32 loss cast —
    # another term the liveness cross-check surfaced (at V=32k the
    # logits outweigh the whole layer stash)
    head_act = b * s * (V // plan.tp) * (w.dtype_bytes + 4)
    return PlanMemory(params=param_bytes, optimizer=opt_bytes,
                      activations=live * act + resid + head_act)


def kv_pool_bytes(layers: int, num_blocks: int, kv_heads: int,
                  block_size: int, head_dim: int, *,
                  kv_dtype: str = "bf16") -> int:
    """Closed form for the serving engine's paged KV pool — the k+v
    block stacks plus, under int8, the per-block-row fp32 scale planes
    the quantized pool carries (a term the liveness cross-check showed
    the old sizing arithmetic dropped). Matches
    ``ServingEngine.pool_bytes()`` exactly; linear in ``num_blocks``
    (the knob ServePlan pricing will search), pinned against the
    liveness bound of the serve entrypoints in tests."""
    elem = 1 if kv_dtype == "int8" else 2
    pool = 2 * layers * num_blocks * kv_heads * block_size * head_dim * elem
    if kv_dtype == "int8":
        pool += 2 * layers * num_blocks * block_size * 4
    return pool


def liveness_memory(plan: ParallelPlan, w: Workload) -> PlanMemory:
    """The plan's per-chip memory from the DONATION-AWARE liveness walk
    (:func:`apex_tpu.lint.liveness.analyze`) of the same traced step
    :func:`static_cost_for_plan` prices time from —
    ``source="liveness"``. Family mapping: the analysis's at-peak
    ``params`` bytes stay params; ``activations`` (stashed residuals
    and scan carries) plus ``temps`` (everything the trace holds
    transiently at the peak) land in ``activations``. The traced step
    is an SGD rebind with NO optimizer-state operand, so the optimizer
    term is borrowed from :func:`estimate_memory`'s closed form — the
    one deliberately shared term between the two models.

    The traced program is schedule-AGNOSTIC (one grad over the full
    tick scan stashes every tick's input — zb-like geometry), so for
    1f1b plans the liveness bound is an over-estimate of the windowed
    schedule; :func:`price_plan` surfaces >10% gaps as the
    ``memory_model[...]`` honesty flag rather than silently preferring
    either model."""
    from apex_tpu.lint import liveness

    closed, fams = _traced_step(plan, w)
    rep = liveness.analyze(
        _per_chip_body(closed), arg_families=fams,
        entrypoint=f"plan_step:dp{plan.dp}xtp{plan.tp}xpp{plan.pp}")
    closed_form = estimate_memory(plan, w)
    f = rep.families
    return PlanMemory(
        params=f["params"],
        optimizer=closed_form.optimizer,
        activations=f["activations"] + f["temps"] + f["kv_pool"],
        source="liveness")


def _per_chip_body(closed):
    """The PER-CHIP program of a traced ``shard_map`` step: when the
    top level is a single call-like eqn wrapping the whole program
    (the shard_map/pjit envelope ``build_plan_step`` produces, whose
    body sees the per-shard avals), analyze the body — the outer
    operands are the GLOBAL arrays, which would bill a tp=4 plan 4× its
    per-chip weight bytes. Positional invar correspondence is required;
    anything else analyzes unwrapped."""
    from apex_tpu.lint.jaxpr_check import as_jaxpr, sub_jaxprs

    j = as_jaxpr(closed)
    if len(j.eqns) != 1:
        return closed
    subs = [as_jaxpr(s) for v in j.eqns[0].params.values()
            for s in sub_jaxprs(v)]
    if len(subs) == 1 and len(subs[0].invars) == len(j.invars):
        return subs[0]
    return closed


def conservative_defaults(costdb: Dict[str, Any]) -> Dict[str, float]:
    """Default rates for CostDB blind spots: the SLOWEST measured rate
    of each family (uniform reference floors when a family is empty).
    Pricing an unmeasured key at the worst measured rate *penalizes*
    uncalibrated traffic — without this, ``rate=None`` keys cost 0 ms
    and a plan could win the ranking precisely because its dominant
    traffic was never measured. ``bench.py --plan`` feeds these to
    :func:`price_plan` for every CostDB, measured or not."""
    coll = [r["bytes_per_s"]["mean"]
            for rows in (costdb.get("collectives") or {}).values()
            for r in rows
            if r.get("bytes_per_s", {}).get("mean", 0) > 0]
    gemm = [e["flops_per_s"]["mean"]
            for e in (costdb.get("gemms") or {}).values()
            if e.get("flops_per_s", {}).get("mean", 0) > 0]
    return {"default_bytes_per_s": min(coll) if coll else 1e10,
            "default_flops_per_s": min(gemm) if gemm else 1e14}


def price_plan(plan: ParallelPlan, w: Workload, costdb: Dict[str, Any],
               *, default_bytes_per_s: Optional[float] = None,
               default_flops_per_s: Optional[float] = None,
               memory_source: str = "closed_form") -> PlanPrice:
    """Price one plan against a measured CostDB.

    Deterministic: the same (plan, workload, costdb) prices to the same
    bits — pinned by ``tests/test_plan.py`` — and monotone: raising any
    CostDB rate never makes any plan slower. ``default_*`` rates price
    blind-spot keys so relative ranking survives a sparse CostDB; the
    keys stay listed in ``uncalibrated`` either way (a defaulted price
    is a labeled guess, never silent).

    ``memory_source="liveness"`` prices the memory column from
    :func:`liveness_memory` (the donation-aware walk of the traced
    step) instead of the closed form, and cross-checks the two: a >10%
    total-bytes gap joins ``uncalibrated`` as a ``memory_model[...]``
    honesty flag (confidence drops to "partial"), with the magnitude
    in ``memory_disagreement_pct`` either way."""
    from apex_tpu.monitor.hooks import pipeline_cost_model

    static = static_cost_for_plan(plan, w)
    db_coll = costdb.get("collectives", {}) or {}
    db_gemms = costdb.get("gemms", {}) or {}
    uncal: List[str] = []

    axis_ms = {"tp": 0.0, "pp": 0.0, "dp": 0.0, "cp": 0.0, "ep": 0.0}
    for key, ent in sorted(static.get("collectives", {}).items()):
        calls = max(int(ent.get("calls", 0)), 1)
        total_bytes = float(ent.get("bytes", 0))
        rate = _nearest_bucket_rate(db_coll.get(key) or [],
                                    total_bytes / calls)
        if rate is None:
            uncal.append(key)
            rate = default_bytes_per_s
        if rate:
            axis = _axis_of(key)
            axis_ms[axis if axis in axis_ms else "dp"] += \
                1e3 * total_bytes / rate

    gemm_ms = 0.0
    for cls, ent in sorted(static.get("gemms", {}).items()):
        flops = float(ent.get("flops", 0.0))
        rate, _exact = _nearest_gemm_rate(db_gemms, cls)
        if rate is None:
            uncal.append(cls)
            rate = default_flops_per_s
        if rate:
            gemm_ms += 1e3 * flops / rate

    m = w.microbatches(plan)
    geo = pipeline_cost_model(
        m, plan.pp, plan.virtual_chunks,
        schedule=plan.pp_schedule if plan.pp > 1 else "1f1b",
        overlap_p2p=plan.overlap_p2p and plan.pp > 1)
    units = geo["total_units"] + (geo["recompute_units"] if w.remat
                                  else 0)
    factor = units / geo["ideal_units"]
    pp_exposed = 0.0 if (plan.overlap_p2p and plan.pp > 1) \
        else axis_ms["pp"]
    predicted = ((gemm_ms + axis_ms["tp"] + axis_ms["cp"]) * factor
                 + axis_ms["dp"] + axis_ms["ep"] + pp_exposed)
    if memory_source not in ("closed_form", "liveness"):
        raise PlanError(
            f"unknown memory_source {memory_source!r}; expected "
            f"'closed_form' or 'liveness'")
    memory = estimate_memory(plan, w)
    disagreement = None
    if memory_source == "liveness":
        live_mem = liveness_memory(plan, w)
        disagreement = (100.0 * abs(live_mem.total - memory.total)
                        / max(memory.total, 1))
        if disagreement > 10.0:
            uncal.append(
                f"memory_model[closed_form_vs_liveness:"
                f"{disagreement:.0f}%]")
        memory = live_mem
    return PlanPrice(
        plan=plan, predicted_step_ms=predicted, gemm_ms=gemm_ms,
        tp_ms=axis_ms["tp"], pp_ms=axis_ms["pp"],
        dp_ms=axis_ms["dp"] + axis_ms["ep"], cp_ms=axis_ms["cp"],
        schedule_factor=factor,
        bubble_fraction=geo["bubble_fraction"],
        memory=memory,
        uncalibrated=tuple(sorted(set(uncal))),
        memory_disagreement_pct=disagreement)
