"""Feasible-plan enumeration + ranking: the planner's search loop.

:func:`search_plans` walks the plan lattice for a chip count — every
``dp·tp·pp`` factorization crossed with the schedule/overlap/SP/ZeRO
knobs — filters it through :meth:`ParallelPlan.validate` plus the
workload's divisibility and a per-chip memory bound (the
:func:`~apex_tpu.plan.cost.estimate_memory` aval estimate), prices
every survivor through :func:`~apex_tpu.plan.cost.price_plan`, and
returns plans ranked by predicted step time with a per-plan confidence
flag (``uncalibrated`` CostDB blind spots surfaced, never silently
priced). Infeasible corners are kept with their reasons — a planner
that silently drops half the lattice is indistinguishable from one
that searched it.

:func:`plan_record_fields` turns a search result (plus the optional
measured step time) into the schema-validated ``plan`` monitor record
(:data:`apex_tpu.monitor.schema.PLAN_SCHEMA`) that ``bench.py --plan``
emits and ``tools/bench_history.py`` gates for predicted-vs-measured
error drift.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from apex_tpu.plan.cost import (
    PlanPrice,
    Workload,
    estimate_memory,
    liveness_memory,
    price_plan,
)
from apex_tpu.plan.parallel_plan import ParallelPlan, PlanError


@dataclasses.dataclass(frozen=True)
class PlanCandidate:
    plan: ParallelPlan
    price: PlanPrice

    def to_json(self) -> Dict[str, Any]:
        return self.price.to_json()


@dataclasses.dataclass(frozen=True)
class SearchResult:
    """Ranked feasible plans (best first) + the rejected corners."""

    chips: int
    workload: Workload
    ranked: Tuple[PlanCandidate, ...]
    rejected: Tuple[Tuple[str, str], ...]  # (plan description, reason)

    @property
    def best(self) -> PlanCandidate:
        if not self.ranked:
            raise PlanError(
                f"no feasible plan for {self.chips} chip(s); rejected: "
                + "; ".join(f"{d} ({r})" for d, r in self.rejected[:8]))
        return self.ranked[0]


def _factorizations(chips: int) -> List[Tuple[int, int, int]]:
    """Every (dp, tp, pp) with dp·tp·pp == chips, deterministic order."""
    out = []
    for dp in range(1, chips + 1):
        if chips % dp:
            continue
        rest = chips // dp
        for tp in range(1, rest + 1):
            if rest % tp:
                continue
            out.append((dp, tp, rest // tp))
    return out


def enumerate_plans(chips: int, w: Workload, *,
                    max_virtual_chunks: int = 2,
                    include_zero: bool = True
                    ) -> Tuple[List[ParallelPlan],
                               List[Tuple[str, str]]]:
    """The feasible lattice + rejections. Knob policy: SP is paired on
    whenever tp > 1 (the production pairing every tp bench leg runs);
    ``tp_overlap`` and — at pp > 1 — schedule × ``overlap_p2p`` are
    enumerated both ways (they are exactly the priced choices);
    ``zero`` is enumerated at dp > 1 (it reprices memory, which the
    bound may need). cp/ep stay 1 in this lattice (ring-attention and
    expert placement search are follow-on work — rejecting them here
    would be claiming a search that never ran)."""
    plans: List[ParallelPlan] = []
    rejected: List[Tuple[str, str]] = []
    for dp, tp, pp in _factorizations(chips):
        tag = f"dp{dp}·tp{tp}·pp{pp}"
        if w.global_batch % (w.micro_batch * dp):
            rejected.append((tag, f"global_batch {w.global_batch} not "
                             f"divisible by micro_batch*dp "
                             f"({w.micro_batch}*{dp})"))
            continue
        m = w.global_batch // (w.micro_batch * dp)
        if tp > 1 and (w.ffn % tp or w.vocab_size % tp or w.seq % tp):
            rejected.append((tag, f"tp={tp} does not divide "
                             f"ffn/vocab/seq "
                             f"({w.ffn}/{w.vocab_size}/{w.seq})"))
            continue
        vs = [v for v in range(1, max_virtual_chunks + 1)
              if w.num_layers % (pp * v) == 0 and (v == 1 or pp > 1)]
        if not vs:
            rejected.append((tag, f"num_layers {w.num_layers} not "
                             f"divisible by pp ({pp})"))
            continue
        for v in vs:
            for schedule in (("1f1b", "zb") if pp > 1 else ("1f1b",)):
                for p2p in ((False, True) if pp > 1 else (False,)):
                    # geometry legality does not depend on the
                    # tp_overlap/zero knobs — judge it ONCE per
                    # (schedule, p2p, v) so a rejected corner appears
                    # once in the record, not once per inner flag combo
                    try:
                        probe = ParallelPlan(
                            dp=dp, tp=tp, pp=pp,
                            sequence_parallel=tp > 1,
                            pp_schedule=schedule, overlap_p2p=p2p,
                            virtual_chunks=v)
                        if pp > 1:
                            probe.validate_schedule()
                        probe.validate_microbatches(m)
                    except PlanError as e:
                        rejected.append(
                            (f"{tag} {schedule}v{v}"
                             + ("+p2p" if p2p else ""), str(e)))
                        continue
                    for tov in ((False, True) if tp > 1 else (False,)):
                        for zero in ((False, True)
                                     if (include_zero and dp > 1)
                                     else (False,)):
                            plans.append(dataclasses.replace(
                                probe, tp_overlap=tov, zero=zero))
    return plans, rejected


def search_plans(chips: int, w: Workload, costdb: Dict[str, Any], *,
                 memory_bound_bytes: Optional[int] = None,
                 max_virtual_chunks: int = 2,
                 include_zero: bool = True,
                 default_bytes_per_s: Optional[float] = None,
                 default_flops_per_s: Optional[float] = None,
                 memory_source: str = "closed_form") -> SearchResult:
    """Enumerate → filter (validity, divisibility, memory bound) →
    price → rank. Deterministic: ties break on the plan's describe()
    string, and pricing itself is bit-deterministic.

    ``memory_source="liveness"`` additionally prunes on the
    donation-aware liveness bound of each candidate's TRACED step — a
    plan whose closed-form estimate squeaks under the bound but whose
    real stash geometry (every tick's input held for the deferred
    grad) does not is rejected with a ``liveness``-labeled reason, and
    survivors' memory column (plus the >10% closed-form disagreement
    honesty flag) comes from the same analysis via
    :func:`~apex_tpu.plan.cost.price_plan`."""
    plans, rejected = enumerate_plans(
        chips, w, max_virtual_chunks=max_virtual_chunks,
        include_zero=include_zero)
    ranked: List[PlanCandidate] = []
    for plan in plans:
        try:
            if memory_bound_bytes is not None:
                # the aval memory estimate needs no trace — reject
                # over-bound plans before paying for one
                mem = estimate_memory(plan, w)
                if mem.total > memory_bound_bytes:
                    rejected.append(
                        (plan.describe(),
                         f"predicted per-chip memory "
                         f"{mem.total / 2**20:.0f} MB exceeds the "
                         f"bound {memory_bound_bytes / 2**20:.0f} MB"))
                    continue
                if memory_source == "liveness":
                    lmem = liveness_memory(plan, w)
                    if lmem.total > memory_bound_bytes:
                        rejected.append(
                            (plan.describe(),
                             f"liveness per-chip peak "
                             f"{lmem.total / 2**20:.0f} MB exceeds the "
                             f"bound "
                             f"{memory_bound_bytes / 2**20:.0f} MB "
                             f"(closed form said "
                             f"{mem.total / 2**20:.0f} MB)"))
                        continue
            price = price_plan(plan, w, costdb,
                               default_bytes_per_s=default_bytes_per_s,
                               default_flops_per_s=default_flops_per_s,
                               memory_source=memory_source)
        except PlanError as e:
            rejected.append((plan.describe(), str(e)))
            continue
        ranked.append(PlanCandidate(plan, price))
    ranked.sort(key=lambda c: (c.price.predicted_step_ms,
                               c.plan.describe()))
    return SearchResult(chips=chips, workload=w, ranked=tuple(ranked),
                        rejected=tuple(rejected))


def plan_record_fields(result: SearchResult, *,
                       costdb_source: str,
                       top_n: int = 8,
                       measured_step_ms: Optional[float] = None,
                       skip_reason: Optional[str] = None
                       ) -> Dict[str, Any]:
    """The ``plan`` record's field dict (caller adds status/reason and
    emits through :meth:`MetricsRegistry.emit_plan`). The measured half
    rides as an explicit ``('skipped', reason)`` when no honest
    measurement exists (off-TPU) — never nan."""
    best = result.best
    fields: Dict[str, Any] = {
        "chips": result.chips,
        "searched": len(result.ranked) + len(result.rejected),
        "feasible": len(result.ranked),
        "chosen": best.plan.to_json(),
        "chosen_describe": best.plan.describe(),
        "predicted_step_ms": round(best.price.predicted_step_ms, 4),
        "confidence": best.price.confidence,
        "uncalibrated": list(best.price.uncalibrated),
        "predicted_memory_mb": best.price.memory.to_json()["total_mb"],
        "memory_source": best.price.memory.source,
        "ranking": [c.to_json() for c in result.ranked[:top_n]],
        "rejected": [{"plan": d, "reason": r}
                     for d, r in result.rejected[:top_n]],
        "costdb_source": costdb_source,
    }
    if measured_step_ms is not None:
        err = (100.0 * (best.price.predicted_step_ms - measured_step_ms)
               / measured_step_ms)
        fields["measured_step_ms"] = round(measured_step_ms, 4)
        fields["predicted_vs_measured_err_pct"] = round(abs(err), 3)
    else:
        reason = skip_reason or "no measured step time supplied"
        fields["measured_step_ms"] = ("skipped", reason)
        fields["predicted_vs_measured_err_pct"] = ("skipped", reason)
    return fields
