"""ServePlan: the serving engine's knobs as one priced, searchable object.

PR 11 closed the planner loop for *training* knobs; this module does the
same for serving. The engine grew a dozen hand-tuned knobs (block size,
pool sizing, slot count, prefill chunk/share, spec drafter + tree shape,
kv_dtype, SLO thresholds) while already emitting the telemetry needed to
price them (acceptance rate, prefix hit-rate, occupancy, per-phase
attribution). The AMP recipe (arXiv:2210.07297) applies unchanged:
treat the configuration as a priced choice searched from a cost model,
never a guess — and the veScale discipline (arXiv:2509.07003) governs
the online half: a re-planned engine must stay semantically equal to
the baseline, witnessed by our token-parity machinery.

Three layers, same idiom as ``parallel_plan``/``cost``/``search``:

* :class:`ServePlan` — frozen + eagerly validated (an illegal knob
  combination never exists as a live object; every error names the knob
  and its legal values), exact JSON round-trip, and a content
  :meth:`~ServePlan.digest` so ``replan`` lifecycle events can name the
  from/to configuration in one short token. :func:`split_knob_changes`
  is the online-replan contract: which knob diffs are AVAL-STABLE
  (host-side dispatch only — apply live, jit caches stay at 1) and
  which change compiled shapes (defer to a ``request_swap``-style
  boundary, report, never apply mid-serve).
* :func:`price_serve_plan` — replays a recorded request trace (the
  seeded ``bench.build_serve_trace`` output, or any list of objects
  with ``prompt``/``max_new_tokens``/``arrival_s``) through a
  host-side discrete-event model of the engine loop: worst-case
  admission against the paged pool, chunked prefill with structural
  prefix-cache sharing, batched decode steps whose per-phase costs come
  from :class:`ServeCosts`. Pure host arithmetic over the trace — no
  wall clock, no randomness — so the same (plan, trace, costs) prices
  to the same bits (pinned by ``tests/test_serve_plan.py``), and every
  cost term is monotone: a slower priced phase never predicts higher
  throughput.
* :func:`search_serve_plans` — enumerate the candidate grid around a
  base config, filter feasibility (a pool that cannot hold the trace's
  largest request is a rejection with a reason, not a crash), price
  every survivor, rank by predicted tokens/s then TTFT.
  :func:`serve_plan_record_fields` turns the result into the closed
  ``serve_plan`` monitor record ``bench.py --serve --plan-serve`` emits
  and ``tools/bench_history.py`` gates.

Costs come from the CostDB plus measured serve telemetry via
:func:`derive_serve_costs`. A term neither source measured is a blind
spot: it is priced at a CONSERVATIVE default (the slowest measured rate
of the family, or zero benefit for speculation) and always surfaced in
``uncalibrated`` — never silently defaulted. An unmeasured acceptance
rate prices to 0.0 on purpose: a spec plan can only win the search on
measured evidence.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from apex_tpu.plan.cost import (
    _nearest_gemm_rate,
    conservative_defaults,
    kv_pool_bytes,
)
from apex_tpu.plan.parallel_plan import PlanError

#: legal drafter choices (``"ngram"`` = chain drafts, ``"ngram_tree"``
#: = the PR-19 tree drafter; the paged model drafter prices as a tree)
DRAFTERS = ("none", "ngram", "ngram_tree")

#: legal paged-pool quantizations (None = the cache dtype, bf16-sized)
KV_DTYPES = (None, "int8", "fp8_e4m3")

#: legal admission orders: FCFS, or shortest-arrived-first (the order
#: ``SLOPolicy.prefer_short_prompts`` flips to under a TTFT burn —
#: ``"short_first"`` pins it on)
ADMISSIONS = ("fcfs", "short_first")

#: knob diffs a live engine can apply between dispatch steps: they
#: change host-side dispatch ORDER and REPETITION only, never an aval,
#: so both jit caches stay at one executable across the switch
LIVE_KNOBS = ("max_prefill_share", "slo_ttft_ms", "slo_burn_count",
              "admission")

#: knob diffs that change compiled shapes or pool geometry: a mid-serve
#: apply would re-trace (or corrupt the paged pool), so the online
#: policy DEFERS them to a request_swap-style boundary and reports them
DEFERRED_KNOBS = ("block_size", "num_blocks", "num_slots",
                  "prefill_chunk", "kv_dtype", "drafter", "spec_depth",
                  "spec_branching", "spec_adaptive")


@dataclasses.dataclass(frozen=True)
class ServePlan:
    """Every serving knob of one engine configuration, validated at
    construction (the :class:`~apex_tpu.plan.ParallelPlan` idiom: one
    door, knob-naming errors, exact JSON round-trip).

    ``block_size``/``num_blocks``/``num_slots``/``prefill_chunk``/
    ``kv_dtype`` mirror the :class:`~apex_tpu.serving.ServingEngine`
    constructor; ``max_prefill_share``/``admission``/``slo_*`` drive the
    scheduler policy; the ``drafter``/``spec_*`` block names the
    speculative config (``spec_adaptive`` rides the PR-19
    ``AdaptiveSpecController`` ladder with ``(spec_depth,
    spec_branching)`` as its ceiling).
    """

    num_blocks: int
    block_size: int = 128
    num_slots: int = 8
    prefill_chunk: int = 256
    max_prefill_share: int = 4
    drafter: str = "none"
    spec_depth: int = 0
    spec_branching: int = 1
    spec_adaptive: bool = False
    kv_dtype: Optional[str] = None
    slo_ttft_ms: Optional[float] = None
    slo_burn_count: int = 3
    admission: str = "fcfs"

    def __post_init__(self):
        self.validate()

    # --- validation -----------------------------------------------------------

    def validate(self) -> "ServePlan":
        """Cross-field legality, one message style: the knob, its
        value, and the legal values. Raises :class:`PlanError`; returns
        ``self`` so call sites can chain."""
        for name, floor in (("block_size", 1), ("num_slots", 1),
                            ("max_prefill_share", 1),
                            ("slo_burn_count", 1), ("num_blocks", 2)):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < floor:
                raise PlanError(
                    f"{name}={v!r} is not a serving knob value; legal "
                    f"values are integers >= {floor}"
                    + (" (one block is the reserved dead block)"
                       if name == "num_blocks" else ""))
        if (not isinstance(self.prefill_chunk, int)
                or isinstance(self.prefill_chunk, bool)
                or self.prefill_chunk < self.block_size
                or self.prefill_chunk % self.block_size):
            raise PlanError(
                f"prefill_chunk={self.prefill_chunk!r} is not a chunk "
                f"size; legal values are positive multiples of "
                f"block_size ({self.block_size}) — chunks write whole "
                f"blocks")
        if self.drafter not in DRAFTERS:
            raise PlanError(
                f"drafter={self.drafter!r} is not a drafter; legal "
                f"values are {' / '.join(map(repr, DRAFTERS))}")
        if not isinstance(self.spec_depth, int) \
                or isinstance(self.spec_depth, bool) or self.spec_depth < 0:
            raise PlanError(
                f"spec_depth={self.spec_depth!r} is not a draft depth; "
                f"legal values are integers >= 0")
        if self.drafter == "none":
            if self.spec_depth or self.spec_branching != 1 \
                    or self.spec_adaptive:
                raise PlanError(
                    f"drafter='none' with spec_depth={self.spec_depth} /"
                    f" spec_branching={self.spec_branching} / "
                    f"spec_adaptive={self.spec_adaptive}: a plan without "
                    f"a drafter has no speculative shape; legal values "
                    f"are spec_depth=0, spec_branching=1, "
                    f"spec_adaptive=False")
        elif self.spec_depth < 1:
            raise PlanError(
                f"spec_depth={self.spec_depth} with drafter="
                f"{self.drafter!r}: a drafting plan needs a draft "
                f"depth; legal values are integers >= 1")
        if (not isinstance(self.spec_branching, int)
                or isinstance(self.spec_branching, bool)
                or self.spec_branching < 1):
            raise PlanError(
                f"spec_branching={self.spec_branching!r} is not a tree "
                f"branching; legal values are integers >= 1")
        if self.spec_branching > 1 and self.drafter != "ngram_tree":
            raise PlanError(
                f"spec_branching={self.spec_branching} with drafter="
                f"{self.drafter!r}: only the tree drafter forks; legal "
                f"values are spec_branching=1 or drafter='ngram_tree'")
        if self.spec_adaptive and self.drafter != "ngram_tree":
            raise PlanError(
                f"spec_adaptive=True with drafter={self.drafter!r}: the "
                f"adaptive ladder walks (depth, branching) tree choices;"
                f" legal values are spec_adaptive=False or "
                f"drafter='ngram_tree'")
        if self.kv_dtype not in KV_DTYPES:
            raise PlanError(
                f"kv_dtype={self.kv_dtype!r} is not a pool "
                f"quantization; legal values are "
                f"{' / '.join(map(repr, KV_DTYPES))}")
        if self.slo_ttft_ms is not None:
            v = self.slo_ttft_ms
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or not math.isfinite(v) or v <= 0:
                raise PlanError(
                    f"slo_ttft_ms={v!r} is not an SLO threshold; legal "
                    f"values are finite numbers > 0 (or None to disable "
                    f"burn detection)")
        if self.admission not in ADMISSIONS:
            raise PlanError(
                f"admission={self.admission!r} is not an admission "
                f"order; legal values are "
                f"{' / '.join(map(repr, ADMISSIONS))}")
        return self

    # --- derived facts --------------------------------------------------------

    def describe(self) -> str:
        """Short human tag: ``blk128·pool41·slot8·chunk256 share4
        spec[tree d3b2 adaptive] int8 short_first``."""
        out = (f"blk{self.block_size}·pool{self.num_blocks}"
               f"·slot{self.num_slots}·chunk{self.prefill_chunk}"
               f" share{self.max_prefill_share}")
        if self.drafter != "none":
            kind = "tree" if self.drafter == "ngram_tree" else "chain"
            out += (f" spec[{kind} d{self.spec_depth}"
                    f"b{self.spec_branching}"
                    + (" adaptive" if self.spec_adaptive else "") + "]")
        if self.kv_dtype:
            out += f" {self.kv_dtype}"
        if self.slo_ttft_ms is not None:
            out += f" slo{self.slo_ttft_ms:g}"
        if self.admission != "fcfs":
            out += f" {self.admission}"
        return out

    def digest(self) -> str:
        """Short content hash of the canonical JSON form — the token
        ``replan`` lifecycle events carry as ``plan_from``/``plan_to``
        (stable across processes: same knobs → same digest)."""
        canon = json.dumps(self.to_json(), sort_keys=True)
        return hashlib.sha256(canon.encode()).hexdigest()[:10]

    def engine_kwargs(self) -> Dict[str, Any]:
        """The :class:`~apex_tpu.serving.ServingEngine` constructor
        kwargs this plan pins (all aval-defining — a change here is a
        DEFERRED knob online)."""
        return dict(num_slots=self.num_slots, block_size=self.block_size,
                    num_blocks=self.num_blocks,
                    prefill_chunk=self.prefill_chunk,
                    kv_dtype=self.kv_dtype)

    def telemetry_kwargs(self) -> Dict[str, Any]:
        """The :class:`~apex_tpu.serving.ServeTelemetry` knobs this
        plan pins (host-side — live online)."""
        return dict(slo_ttft_ms=self.slo_ttft_ms,
                    slo_burn_count=self.slo_burn_count)

    # --- serialization --------------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        """Plain-JSON dict; exact inverse of :meth:`from_json` (pinned
        by ``tests/test_serve_plan.py``)."""
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj) -> "ServePlan":
        """Rebuild from :meth:`to_json` output (dict or JSON string).
        Unknown keys are an error — a junk plan must not half-load."""
        if isinstance(obj, str):
            obj = json.loads(obj)
        if not isinstance(obj, dict):
            raise PlanError(f"a serve plan serializes as a JSON object, "
                            f"got {type(obj).__name__}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(obj) - known)
        if unknown:
            raise PlanError(
                f"unknown serve plan field(s) {unknown}; legal fields "
                f"are {sorted(known)}")
        return cls(**obj)


def split_knob_changes(old: ServePlan, new: ServePlan
                       ) -> Tuple[Dict[str, Tuple[Any, Any]],
                                  Dict[str, Tuple[Any, Any]]]:
    """``(live, deferred)`` knob diffs between two plans, each a
    ``{field: (old_value, new_value)}`` dict.

    LIVE diffs are aval-stable: prefill share, SLO thresholds, and
    admission order change only host-side dispatch of the same two
    compiled programs. A spec-SHAPE diff is live exactly when BOTH
    plans run the adaptive tree ladder with the same drafter — the
    ``AdaptiveSpecController`` already walks a static choice set whose
    every (depth, branching) is a pre-compiled program, so moving its
    ceiling re-weights the ladder without a new trace. Everything else
    (pool geometry, chunk size, drafter identity, quantization) changes
    compiled avals or the pool layout and is DEFERRED: reported at the
    re-plan boundary, applied only through an engine rebuild."""
    live: Dict[str, Tuple[Any, Any]] = {}
    deferred: Dict[str, Tuple[Any, Any]] = {}
    for name in LIVE_KNOBS:
        a, b = getattr(old, name), getattr(new, name)
        if a != b:
            live[name] = (a, b)
    shape_live = (old.spec_adaptive and new.spec_adaptive
                  and old.drafter == new.drafter)
    for name in DEFERRED_KNOBS:
        a, b = getattr(old, name), getattr(new, name)
        if a == b:
            continue
        if shape_live and name in ("spec_depth", "spec_branching"):
            live[name] = (a, b)
        else:
            deferred[name] = (a, b)
    return live, deferred


# --- costs --------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ServeCosts:
    """Per-phase rates the trace-replay simulator charges, plus the
    model geometry the KV-byte terms need. ``uncalibrated`` lists the
    terms no source measured (priced conservatively, never silently);
    ``spec_uncalibrated`` holds the spec-only blind spots — they join a
    price's flags only when the priced plan actually drafts."""

    prefill_ms_per_token: float
    decode_ms_per_step: float
    decode_ms_per_row: float
    hbm_bytes_per_s: float
    spec_round_ms: float
    spec_acceptance: float
    num_layers: int
    kv_heads: int
    head_dim: int
    uncalibrated: Tuple[str, ...] = ()
    spec_uncalibrated: Tuple[str, ...] = ()

    def bytes_per_ctx_token(self, kv_dtype: Optional[str]) -> int:
        """KV bytes one decode step streams per context token (k+v
        across the stack; int8/fp8 pools store 1-byte elements, int8
        additionally pays its per-block-row fp32 scale planes — the
        same arithmetic as :func:`~apex_tpu.plan.cost.kv_pool_bytes`,
        per token instead of per pool)."""
        elem = 1 if kv_dtype in ("int8", "fp8_e4m3") else 2
        per = 2 * self.num_layers * self.kv_heads * self.head_dim * elem
        if kv_dtype == "int8":
            per += 2 * self.num_layers * 4
        return per


def derive_serve_costs(costdb: Dict[str, Any], *, hidden_size: int,
                       num_layers: int, num_heads: int, vocab_size: int,
                       head_dim: Optional[int] = None,
                       measured: Optional[Dict[str, float]] = None,
                       default_bytes_per_s: Optional[float] = None,
                       default_flops_per_s: Optional[float] = None
                       ) -> ServeCosts:
    """Per-phase serving costs from the CostDB plus measured serve
    telemetry. ``measured`` carries the terms a real serve run
    produced (keys: ``prefill_ms_per_token``, ``decode_ms_per_step``,
    ``hbm_bytes_per_s``, ``spec_round_ms``, ``spec_acceptance_rate`` —
    the ``bench.py --serve`` attribution/record surface); every term
    NEITHER source measured lands in ``uncalibrated`` and is priced at
    a conservative default (the :func:`~apex_tpu.plan.cost
    .conservative_defaults` family floor, or zero speculative benefit)
    so a blind spot penalizes, never flatters, the plans that lean on
    it."""
    measured = dict(measured or {})
    defaults = conservative_defaults(costdb)
    if default_bytes_per_s is None:
        default_bytes_per_s = defaults["default_bytes_per_s"]
    if default_flops_per_s is None:
        default_flops_per_s = defaults["default_flops_per_s"]
    head_dim = head_dim or hidden_size // num_heads
    uncal: List[str] = []
    spec_uncal: List[str] = []

    # forward FLOPs per token: the 12·H² layer GEMM block + vocab head
    flops_per_token = float(
        2 * (12 * num_layers * hidden_size * hidden_size
             + hidden_size * vocab_size))
    cls = f"gemm_{1 << max(0, round(math.log2(flops_per_token)))}"
    gemm_rate, _exact = _nearest_gemm_rate(
        costdb.get("gemms", {}) or {}, cls)
    if gemm_rate is None:
        uncal.append("serve[gemm_flops_per_s]")
        gemm_rate = default_flops_per_s
    gemm_ms_per_token = 1e3 * flops_per_token / gemm_rate

    if "prefill_ms_per_token" in measured:
        prefill = float(measured["prefill_ms_per_token"])
    else:
        prefill = gemm_ms_per_token
    decode_row = gemm_ms_per_token
    if "decode_ms_per_step" in measured:
        step = float(measured["decode_ms_per_step"])
    else:
        # floor: one dispatch costs at least one row's GEMM work
        uncal.append("serve[decode_step_ms]")
        step = decode_row
    if "hbm_bytes_per_s" in measured:
        hbm = float(measured["hbm_bytes_per_s"])
    else:
        # slowest measured collective rate: a pessimistic stream rate
        # penalizes the plans whose KV traffic was never measured
        uncal.append("serve[hbm_bytes_per_s]")
        hbm = default_bytes_per_s
    if "spec_round_ms" in measured:
        spec_round = float(measured["spec_round_ms"])
    else:
        spec_uncal.append("serve[spec_round_ms]")
        spec_round = step
    if "spec_acceptance_rate" in measured:
        acceptance = float(measured["spec_acceptance_rate"])
    else:
        # zero benefit on purpose: an unmeasured acceptance rate must
        # never let a spec plan win the search
        spec_uncal.append("serve[spec_acceptance_rate]")
        acceptance = 0.0
    return ServeCosts(
        prefill_ms_per_token=prefill, decode_ms_per_step=step,
        decode_ms_per_row=decode_row, hbm_bytes_per_s=hbm,
        spec_round_ms=spec_round, spec_acceptance=acceptance,
        num_layers=num_layers, kv_heads=num_heads, head_dim=head_dim,
        uncalibrated=tuple(sorted(set(uncal))),
        spec_uncalibrated=tuple(sorted(set(spec_uncal))))


# --- the trace-replay discrete-event model ------------------------------------

@dataclasses.dataclass(frozen=True)
class ServePrice:
    """One plan's predicted serving outcome on one trace.
    ``uncalibrated`` is the confidence surface, same contract as
    :class:`~apex_tpu.plan.cost.PlanPrice` (empty ⇒ ``"calibrated"``)."""

    plan: ServePlan
    predicted_tokens_per_s: float
    predicted_ttft_p50_ms: float
    predicted_ttft_p99_ms: float
    predicted_kv_pool_mb: float
    decode_steps: int
    prefill_chunks: int
    sim_span_ms: float
    uncalibrated: Tuple[str, ...]

    @property
    def confidence(self) -> str:
        return "calibrated" if not self.uncalibrated else "partial"

    def to_json(self) -> Dict[str, Any]:
        return {
            "plan": self.plan.to_json(),
            "digest": self.plan.digest(),
            "predicted_tokens_per_s": round(
                self.predicted_tokens_per_s, 3),
            "predicted_ttft_p50_ms": round(self.predicted_ttft_p50_ms, 3),
            "predicted_ttft_p99_ms": round(self.predicted_ttft_p99_ms, 3),
            "predicted_kv_pool_mb": round(self.predicted_kv_pool_mb, 3),
            "confidence": self.confidence,
            "uncalibrated": list(self.uncalibrated),
            "decode_steps": self.decode_steps,
            "prefill_chunks": self.prefill_chunks,
            "sim_span_ms": round(self.sim_span_ms, 3),
        }


class _SimStream:
    __slots__ = ("rid", "prompt_len", "max_new", "arrival_ms", "worst",
                 "blocks", "prefilled", "generated", "first_token_ms",
                 "prompt")

    def __init__(self, req, block_size: int):
        self.rid = int(req.rid)
        self.prompt = req.prompt
        self.prompt_len = int(len(req.prompt))
        self.max_new = int(req.max_new_tokens)
        self.arrival_ms = 1e3 * float(getattr(req, "arrival_s", 0.0))
        rows = self.prompt_len + max(self.max_new - 1, 0)
        self.worst = -(-rows // block_size)
        self.prefilled = 0
        self.generated = 0.0
        self.first_token_ms: Optional[float] = None


def _quantile(xs: Sequence[float], q: float) -> float:
    s = sorted(xs)
    i = min(len(s) - 1, max(0, int(math.ceil(q * len(s))) - 1))
    return s[i]


def price_serve_plan(plan: ServePlan, trace: Sequence[Any],
                     costs: ServeCosts) -> ServePrice:
    """Replay ``trace`` through a host-side discrete-event model of the
    engine loop under ``plan`` and price every phase from ``costs``.

    The model is the engine's dispatch loop with two DOCUMENTED
    conservative simplifications: admission reserves each request's
    WORST-CASE block count (so preemption never has to appear in
    simulated time — the real optimistic gate admits deeper, making
    the prediction a floor, not a flatter), and the prefix cache is
    structural (a set of full-block token runs already prefilled this
    trace — capacity eviction is not modeled). Everything else follows
    the scheduler: FCFS (or shortest-first) admission into free slots,
    up to ``max_prefill_share`` chunks per iteration while a queue is
    pending (one otherwise — the SLOPolicy steady state), the first
    token sampled by the final prefill chunk, one batched decode step
    per iteration charging ``decode_ms_per_step`` plus each live row's
    GEMM and KV-stream bytes, and — under a drafting plan — one spec
    round per row per step emitting ``1 + acceptance·depth`` expected
    tokens against ``spec_round_ms`` overhead.

    Deterministic by construction (no clock, no RNG: same inputs →
    same bits) and monotone in every rate (a slower priced phase never
    predicts higher tokens/s) — both pinned by
    ``tests/test_serve_plan.py``."""
    B = plan.block_size
    pool_cap = plan.num_blocks - 1
    streams = [_SimStream(r, B) for r in trace]
    if not streams:
        raise PlanError("price_serve_plan needs a non-empty trace; an "
                        "empty one prices nothing")
    for s in streams:
        if s.worst > pool_cap:
            raise PlanError(
                f"request {s.rid}: worst case needs {s.worst} blocks "
                f"but num_blocks={plan.num_blocks} leaves {pool_cap} "
                f"allocatable; raise num_blocks to >= {s.worst + 1} or "
                f"drop the request from the trace")
    pending: List[_SimStream] = sorted(
        streams, key=lambda s: (s.arrival_ms, s.rid))
    slots: List[_SimStream] = []
    seen_blocks: set = set()
    t = 0.0
    free_blocks = pool_cap
    ttfts: List[float] = []
    decode_steps = 0
    prefill_chunks = 0
    spec = plan.drafter != "none"
    emit = 1.0 + (costs.spec_acceptance * plan.spec_depth if spec
                  else 0.0)
    ctx_ms = (1e3 * costs.bytes_per_ctx_token(plan.kv_dtype)
              / costs.hbm_bytes_per_s)
    # progress guard: every iteration either admits, prefills a chunk,
    # decodes a step, or jumps the clock to an arrival — bounded by the
    # trace's total work, so exceeding this is a simulator bug
    budget = 1000 + sum(4 + s.prompt_len // max(plan.prefill_chunk, 1)
                        + s.max_new for s in streams)
    while pending or slots:
        budget -= 1
        if budget < 0:
            raise RuntimeError(
                "trace-replay simulator failed to make progress "
                "(model bug — please report the plan + trace)")
        progressed = False
        # --- admission: arrived requests into free slots against the
        # worst-case reservation; order per the plan's admission knob,
        # blocked head holds the line (the scheduler's FCFS rule)
        arrived = [s for s in pending if s.arrival_ms <= t]
        if plan.admission == "short_first":
            arrived.sort(key=lambda s: (s.prompt_len + s.max_new, s.rid))
        for s in arrived:
            if len(slots) >= plan.num_slots:
                break
            if s.worst > free_blocks:
                break
            free_blocks -= s.worst
            shared_cap = (s.prompt_len - 1) // B
            shared = 0
            while (shared < shared_cap and tuple(
                    int(x) for x in s.prompt[shared * B:(shared + 1) * B]
                    ) in seen_blocks):
                shared += 1
            s.prefilled = shared * B
            pending.remove(s)
            slots.append(s)
            progressed = True
        # --- chunked prefill: up to `share` chunks while a queue is
        # pending (the SLOPolicy widened state), one otherwise
        share = (plan.max_prefill_share
                 if any(s.arrival_ms <= t for s in pending) else 1)
        for _ in range(share):
            target = next((s for s in slots
                           if s.prefilled < s.prompt_len), None)
            if target is None:
                break
            live = min(plan.prefill_chunk,
                       target.prompt_len - target.prefilled)
            t += live * costs.prefill_ms_per_token
            prefill_chunks += 1
            target.prefilled += live
            progressed = True
            if target.prefilled >= target.prompt_len:
                # the final chunk's last-row logits sample token #1
                target.generated = 1.0
                target.first_token_ms = t
                ttfts.append(t - target.arrival_ms)
                for k in range((target.prompt_len - 1) // B):
                    seen_blocks.add(tuple(
                        int(x) for x in target.prompt[k * B:(k + 1) * B]))
        # --- one batched decode step over every decoding row
        decoding = [s for s in slots
                    if s.prefilled >= s.prompt_len
                    and s.generated < s.max_new]
        if decoding:
            step_ms = costs.decode_ms_per_step
            for s in decoding:
                ctx = s.prompt_len + s.generated
                step_ms += costs.decode_ms_per_row + ctx * ctx_ms
                if spec:
                    step_ms += costs.spec_round_ms
            t += step_ms
            decode_steps += 1
            for s in decoding:
                s.generated = min(float(s.max_new), s.generated + emit)
            progressed = True
        # --- retire finished streams (free their reservation)
        for s in [s for s in slots if s.generated >= s.max_new]:
            free_blocks += s.worst
            slots.remove(s)
            progressed = True
        if not progressed:
            # idle: jump the clock to the next arrival
            t = max(t, min(s.arrival_ms for s in pending))
    total_tokens = sum(s.max_new for s in streams)
    span_ms = max(t, 1e-9)
    pool_mb = kv_pool_bytes(
        costs.num_layers, plan.num_blocks, costs.kv_heads, B,
        costs.head_dim, kv_dtype=plan.kv_dtype or "bf16") / 2 ** 20
    uncal = costs.uncalibrated + (costs.spec_uncalibrated if spec
                                  else ())
    return ServePrice(
        plan=plan,
        predicted_tokens_per_s=1e3 * total_tokens / span_ms,
        predicted_ttft_p50_ms=_quantile(ttfts, 0.5),
        predicted_ttft_p99_ms=_quantile(ttfts, 0.99),
        predicted_kv_pool_mb=pool_mb,
        decode_steps=decode_steps, prefill_chunks=prefill_chunks,
        sim_span_ms=span_ms,
        uncalibrated=tuple(sorted(set(uncal))))


# --- search -------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ServeCandidate:
    plan: ServePlan
    price: ServePrice

    def to_json(self) -> Dict[str, Any]:
        return self.price.to_json()


@dataclasses.dataclass(frozen=True)
class ServeSearchResult:
    """Ranked feasible serve plans (best first) + rejected corners."""

    requests: int
    ranked: Tuple[ServeCandidate, ...]
    rejected: Tuple[Tuple[str, str], ...]  # (plan description, reason)

    @property
    def best(self) -> ServeCandidate:
        if not self.ranked:
            raise PlanError(
                f"no feasible serve plan for the {self.requests}-request"
                f" trace; rejected: "
                + "; ".join(f"{d} ({r})" for d, r in self.rejected[:8]))
        return self.ranked[0]


def enumerate_serve_plans(base: ServePlan
                          ) -> Tuple[List[ServePlan],
                                     List[Tuple[str, str]]]:
    """The candidate grid around ``base``: slots × pool depth × chunk
    size × prefill share × admission order × spec on/off, with the
    aval-heaviest knobs (block_size, kv_dtype) held at the base's —
    they re-price through the same model but rebuilding the engine for
    them is the deploy-time decision, and the grid stays small enough
    to replay a trace through every cell. Deterministic order; corners
    :class:`ServePlan` itself refuses come back as rejections."""
    plans: List[ServePlan] = []
    rejected: List[Tuple[str, str]] = []
    seen: set = set()
    spec_off = dict(drafter="none", spec_depth=0, spec_branching=1,
                    spec_adaptive=False)
    spec_variants = [spec_off]
    if base.drafter != "none":
        spec_variants.insert(0, dict(
            drafter=base.drafter, spec_depth=base.spec_depth,
            spec_branching=base.spec_branching,
            spec_adaptive=base.spec_adaptive))
    chunks = sorted({base.block_size, base.prefill_chunk,
                     2 * base.prefill_chunk})
    for slots in (base.num_slots, 2 * base.num_slots):
        for blocks in (base.num_blocks, 2 * base.num_blocks):
            for chunk in chunks:
                for share in (1, 2, 4):
                    for admission in ADMISSIONS:
                        for sv in spec_variants:
                            try:
                                p = dataclasses.replace(
                                    base, num_slots=slots,
                                    num_blocks=blocks,
                                    prefill_chunk=chunk,
                                    max_prefill_share=share,
                                    admission=admission, **sv)
                            except PlanError as e:
                                key = (f"slot{slots}·pool{blocks}"
                                       f"·chunk{chunk}")
                                if key not in seen:
                                    seen.add(key)
                                    rejected.append((key, str(e)))
                                continue
                            tag = p.describe()
                            if tag not in seen:
                                seen.add(tag)
                                plans.append(p)
    return plans, rejected


def search_serve_plans(trace: Sequence[Any], costs: ServeCosts, *,
                       base: Optional[ServePlan] = None,
                       candidates: Optional[Sequence[ServePlan]] = None,
                       pool_bytes_bound: Optional[int] = None
                       ) -> ServeSearchResult:
    """Enumerate (around ``base``, or the explicit ``candidates``) →
    filter feasibility → replay-price every survivor → rank by
    predicted tokens/s, ties on TTFT p50 then the describe string.
    Deterministic end to end: the grid order is fixed and pricing is
    bit-deterministic. A pool too small for the trace's largest
    request, or over ``pool_bytes_bound``, is a rejection with a
    reason — never a silently skipped corner."""
    if candidates is None:
        if base is None:
            raise PlanError("search_serve_plans needs a base plan or an "
                            "explicit candidate list")
        plans, rejected = enumerate_serve_plans(base)
    else:
        plans, rejected = list(candidates), []
    if not trace:
        raise PlanError("search_serve_plans needs a non-empty trace; an "
                        "empty one prices nothing")
    rows = max(len(r.prompt) + max(int(r.max_new_tokens) - 1, 0)
               for r in trace)
    ranked: List[ServeCandidate] = []
    for plan in plans:
        need = -(-rows // plan.block_size)
        if need > plan.num_blocks - 1:
            rejected.append(
                (plan.describe(),
                 f"the trace's largest request needs {need} blocks but "
                 f"num_blocks={plan.num_blocks} leaves "
                 f"{plan.num_blocks - 1} allocatable; it could never "
                 f"be admitted"))
            continue
        if pool_bytes_bound is not None:
            pool = kv_pool_bytes(
                costs.num_layers, plan.num_blocks, costs.kv_heads,
                plan.block_size, costs.head_dim,
                kv_dtype=plan.kv_dtype or "bf16")
            if pool > pool_bytes_bound:
                rejected.append(
                    (plan.describe(),
                     f"predicted KV pool {pool / 2**20:.0f} MB exceeds "
                     f"the bound {pool_bytes_bound / 2**20:.0f} MB"))
                continue
        try:
            price = price_serve_plan(plan, trace, costs)
        except PlanError as e:
            rejected.append((plan.describe(), str(e)))
            continue
        ranked.append(ServeCandidate(plan, price))
    ranked.sort(key=lambda c: (-c.price.predicted_tokens_per_s,
                               c.price.predicted_ttft_p50_ms,
                               c.plan.describe()))
    return ServeSearchResult(requests=len(trace), ranked=tuple(ranked),
                             rejected=tuple(rejected))


def serve_plan_record_fields(result: ServeSearchResult, *,
                             costdb_source: str, top_n: int = 8,
                             measured_tokens_per_s: Optional[float] = None,
                             measured_ttft_p50_ms: Optional[float] = None,
                             skip_reason: Optional[str] = None
                             ) -> Dict[str, Any]:
    """The ``serve_plan`` record's field dict (caller adds the hand-
    config comparison, the replan witnesses, and status/reason, then
    emits through ``MetricsRegistry.emit_serve_plan``). The measured
    half rides as an explicit ``('skipped', reason)`` when no honest
    measurement exists (off-TPU) — never nan."""
    best = result.best
    fields: Dict[str, Any] = {
        "searched": len(result.ranked) + len(result.rejected),
        "feasible": len(result.ranked),
        "requests": result.requests,
        "chosen": best.plan.to_json(),
        "chosen_describe": best.plan.describe(),
        "chosen_digest": best.plan.digest(),
        "predicted_tokens_per_s": round(
            best.price.predicted_tokens_per_s, 3),
        "predicted_ttft_p50_ms": round(
            best.price.predicted_ttft_p50_ms, 3),
        "predicted_ttft_p99_ms": round(
            best.price.predicted_ttft_p99_ms, 3),
        "predicted_kv_pool_mb": round(best.price.predicted_kv_pool_mb, 3),
        "confidence": best.price.confidence,
        "uncalibrated": list(best.price.uncalibrated),
        "ranking": [c.to_json() for c in result.ranked[:top_n]],
        "rejected": [{"plan": d, "reason": r}
                     for d, r in result.rejected[:top_n]],
        "costdb_source": costdb_source,
    }
    if measured_tokens_per_s is not None:
        err = (100.0 * (best.price.predicted_tokens_per_s
                        - measured_tokens_per_s) / measured_tokens_per_s)
        fields["measured_tokens_per_s"] = round(measured_tokens_per_s, 3)
        fields["predicted_vs_measured_err_pct"] = round(abs(err), 3)
        if measured_ttft_p50_ms is not None:
            fields["measured_ttft_p50_ms"] = round(
                measured_ttft_p50_ms, 3)
    else:
        reason = skip_reason or "no measured serve run supplied"
        fields["measured_tokens_per_s"] = ("skipped", reason)
        fields["measured_ttft_p50_ms"] = ("skipped", reason)
        fields["predicted_vs_measured_err_pct"] = ("skipped", reason)
    return fields
