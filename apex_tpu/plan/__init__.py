"""Auto-parallelism planning: ``ParallelPlan`` + CostDB-driven search.

The subsystem ROADMAP item 1 names, built on three substrates that
already ship: PR 6's CostDB (measured bytes/s per collective
kind×axis×size bucket, FLOP/s per GEMM class), PR 8's
``pipeline_cost_model`` (schedule slot-waste/recompute geometry), and
PR 10's ``static_cost`` jaxpr walk (per-collective bytes and per-GEMM
FLOPs of a traced program, scan-multiplied).

* :class:`ParallelPlan` — one frozen object for every parallelism knob
  (dp/tp/pp/cp/ep, SP, ``tp_overlap``, ``pp_schedule``,
  ``overlap_p2p``, virtual chunks, ZeRO) with eager cross-field
  validation in one message style; consumed by ``GPTConfig``/
  ``T5Config`` (``plan=``), :func:`apex_tpu.parallel.mesh.make_mesh`
  and ``bench.py`` (the loose kwargs stay as a deprecated shim).
* :mod:`~apex_tpu.plan.cost` — price a candidate plan: trace its
  per-chip step abstractly (``ShapeDtypeStruct`` through
  ``jax.make_jaxpr``, no execution), convert the StaticCostReport's
  bytes/FLOPs through the CostDB's nearest bucket/class rates, apply
  the schedule geometry factor, and estimate per-chip memory from the
  sharded avals — or, with ``memory_source="liveness"``, from the
  donation-aware liveness walk of the SAME trace
  (:func:`~apex_tpu.plan.cost.liveness_memory`, apexmem), with >10%
  closed-form disagreement flagged. Blind-spot keys surface in
  ``uncalibrated``.
* :mod:`~apex_tpu.plan.search` — enumerate the feasible lattice for a
  chip count + memory bound, rank by predicted step time, and build
  the schema-validated ``plan`` record (``bench.py --plan`` emits it;
  ``tools/bench_history.py`` gates its predicted-vs-measured error).

* :mod:`~apex_tpu.plan.serve` — planner tier 2, the SERVING knobs:
  :class:`ServePlan` (frozen, validated, JSON round-trip, content
  digest) covering block/pool/slot/chunk sizing, prefill share, spec
  drafter + tree shape, kv_dtype, SLO thresholds, admission order;
  :func:`price_serve_plan` replays a recorded trace through a
  bit-deterministic host-side discrete-event model with per-phase
  costs from :func:`derive_serve_costs` (CostDB + measured serve
  telemetry, blind spots in ``uncalibrated``);
  :func:`search_serve_plans` ranks the candidate grid and
  :func:`serve_plan_record_fields` builds the closed ``serve_plan``
  record (``bench.py --serve --plan-serve``). The online half —
  ``ReplanPolicy`` swapping priced plans at window edges — lives in
  :mod:`apex_tpu.serving.scheduler` and uses
  :func:`split_knob_changes` to decide live-vs-deferred knobs.

See ``docs/api/plan.md`` for the pricing math and a worked example,
and the TRAINING_GUIDE's "choosing a plan" chapter for the workflow.
"""

from apex_tpu.plan.cost import (  # noqa: F401
    PlanMemory,
    PlanPrice,
    Workload,
    build_plan_step,
    conservative_defaults,
    estimate_memory,
    kv_pool_bytes,
    liveness_memory,
    price_plan,
    static_cost_for_plan,
)
from apex_tpu.plan.parallel_plan import (  # noqa: F401
    PP_SCHEDULES,
    ParallelPlan,
    PlanError,
)
from apex_tpu.plan.search import (  # noqa: F401
    PlanCandidate,
    SearchResult,
    enumerate_plans,
    plan_record_fields,
    search_plans,
)
from apex_tpu.plan.serve import (  # noqa: F401
    ADMISSIONS,
    DRAFTERS,
    KV_DTYPES,
    ServeCandidate,
    ServeCosts,
    ServePlan,
    ServePrice,
    ServeSearchResult,
    derive_serve_costs,
    enumerate_serve_plans,
    price_serve_plan,
    search_serve_plans,
    serve_plan_record_fields,
    split_knob_changes,
)
