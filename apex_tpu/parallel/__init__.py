"""Distributed-training primitives over a JAX device mesh.

TPU-native equivalent of ``apex.parallel`` (reference
``apex/parallel/__init__.py:9-18``): data-parallel gradient synchronization
(:mod:`apex_tpu.parallel.distributed`), synchronized batch-norm
(:mod:`apex_tpu.parallel.sync_batchnorm`), LARC
(:mod:`apex_tpu.parallel.larc`), plus the mesh bookkeeping that replaces the
reference's NCCL process groups (:mod:`apex_tpu.parallel.mesh`).
"""

from apex_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    initialize_model_parallel,
    destroy_model_parallel,
    model_parallel_is_initialized,
    get_mesh,
    get_mesh_spec,
    get_data_parallel_world_size,
    get_tensor_model_parallel_world_size,
    get_pipeline_model_parallel_world_size,
    get_context_parallel_world_size,
    get_expert_parallel_world_size,
    get_virtual_pipeline_model_parallel_world_size,
    get_rank_info,
    DATA_AXIS,
    TENSOR_AXIS,
    PIPELINE_AXIS,
    CONTEXT_AXIS,
    EXPERT_AXIS,
)
from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedGradients,
    cross_replica_gradients,
    all_reduce_gradients,
    data_parallel_sharding,
    replicate,
)
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm, BatchNormState  # noqa: F401
from apex_tpu.parallel.larc import larc  # noqa: F401

LARC = larc  # reference spelling (``apex.parallel.LARC``)
