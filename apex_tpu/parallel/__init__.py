"""Distributed-training primitives over a JAX device mesh.

TPU-native equivalent of ``apex.parallel`` (reference
``apex/parallel/__init__.py:9-18``): data-parallel gradient synchronization
(:mod:`apex_tpu.parallel.distributed`), synchronized batch-norm
(:mod:`apex_tpu.parallel.sync_batchnorm`), LARC
(:mod:`apex_tpu.parallel.larc`), plus the mesh bookkeeping that replaces the
reference's NCCL process groups (:mod:`apex_tpu.parallel.mesh`).
"""

from apex_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    initialize_model_parallel,
    destroy_model_parallel,
    model_parallel_is_initialized,
    get_mesh,
    get_mesh_spec,
    get_data_parallel_world_size,
    get_tensor_model_parallel_world_size,
    get_pipeline_model_parallel_world_size,
    get_context_parallel_world_size,
    get_expert_parallel_world_size,
    get_virtual_pipeline_model_parallel_world_size,
    get_rank_info,
    DATA_AXIS,
    TENSOR_AXIS,
    PIPELINE_AXIS,
    CONTEXT_AXIS,
    EXPERT_AXIS,
)
from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedGradients,
    cross_replica_gradients,
    all_reduce_gradients,
    data_parallel_sharding,
    replicate,
)
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm, BatchNormState  # noqa: F401
from apex_tpu.parallel.larc import larc  # noqa: F401

LARC = larc  # reference spelling (``apex.parallel.LARC``)


def create_syncbn_process_group(group_size: int, mesh=None):
    """BN stats groups of ``group_size`` devices — name-parity port of
    ``apex.parallel.create_syncbn_process_group``
    (``apex/parallel/__init__.py:58-95``). The reference builds NCCL
    subgroups; on a mesh the same partition is an axis split, so this
    returns ``(mesh, axis_name)``: pass the axis name to
    :class:`SyncBatchNorm` / :func:`sync_batch_norm` and run under the
    returned mesh.

    ``group_size == 0`` means the whole dp axis (reference: world size);
    ``group_size == 1`` returns ``(mesh, None)`` — local BN, matching the
    reference's "equivalent to non-sync bn".
    """
    from apex_tpu.contrib.groupbn import split_data_axis_for_bn
    from apex_tpu.parallel import mesh as _mesh_lib

    mesh = mesh if mesh is not None else _mesh_lib.get_mesh()
    if group_size == 0:
        return mesh, DATA_AXIS
    if group_size == 1:
        return mesh, None
    dp = mesh.shape[DATA_AXIS]
    if group_size < 2 or dp % group_size:
        raise ValueError(
            f"group_size ({group_size}) must be a positive divisor of the "
            f"dp axis ({dp})")
    return split_data_axis_for_bn(mesh, group_size), "bn"
