"""Data-parallel gradient synchronization.

TPU-native re-design of ``apex.parallel.DistributedDataParallel``
(``apex/parallel/distributed.py:129``). The reference earns its keep by
overlapping NCCL all-reduces with backward compute: per-param autograd hooks
(``:319-408``), greedy flat-bucket construction (``:164,367-390``), side
streams (``:425-475``). On TPU none of that machinery exists at the user
level: grads of a ``pjit``-ed loss over a batch sharded on the ``dp`` axis are
reduced by XLA-inserted all-reduces, which the latency-hiding scheduler
overlaps with the backward pass automatically. What remains user-visible —
and what this module provides — are the *semantic* knobs the reference
exposes (``distributed.py:162-175``):

* ``gradient_average``            → divide by dp size (pmean vs psum)
* ``gradient_predivide_factor``   → pre-divide locally, post-divide the rest
  (numerics for very large dp counts)
* ``allreduce_always_fp32``       → upcast grads before the reduction

plus sharding helpers that put the batch on the ``dp`` axis in the first
place. The ``Reducer`` manual-call variant (``distributed.py:89``) is
:func:`all_reduce_gradients` used directly inside ``shard_map``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib

PyTree = Any


@dataclasses.dataclass(frozen=True)
class DistributedGradients:
    """Config mirroring apex DDP's reduction options
    (``apex/parallel/distributed.py:162-175``)."""

    axis_name: str = mesh_lib.DATA_AXIS
    gradient_average: bool = True
    gradient_predivide_factor: float = 1.0
    allreduce_always_fp32: bool = False

    def __call__(self, grads: PyTree) -> PyTree:
        return all_reduce_gradients(
            grads,
            axis_name=self.axis_name,
            gradient_average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor,
            allreduce_always_fp32=self.allreduce_always_fp32,
        )


def all_reduce_gradients(
    grads: PyTree,
    *,
    axis_name: str = mesh_lib.DATA_AXIS,
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
    allreduce_always_fp32: bool = False,
) -> PyTree:
    """All-reduce a grad pytree across ``axis_name`` inside ``shard_map``.

    Matches the arithmetic of ``allreduce_bucket``
    (``apex/parallel/distributed.py:425-475``): optional fp32 upcast, divide
    by ``predivide_factor`` before the reduce and by
    ``world_size/predivide_factor`` after (so the full division happens in two
    stages), or plain average / sum.
    """
    from apex_tpu.monitor import hooks as monitor_hooks

    if monitor_hooks.enabled():  # trace-time count, zero run-time cost
        monitor_hooks.count_collective(
            "psum", bytes=monitor_hooks.tree_bytes(grads), axis=axis_name)

    def reduce_one(g: jax.Array) -> jax.Array:
        orig_dtype = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        g = jax.lax.psum(g, axis_name)
        if gradient_average:
            world = jax.lax.axis_size(axis_name)
            if gradient_predivide_factor != 1.0:
                g = g * (gradient_predivide_factor / world)
            else:
                g = g / world
        if allreduce_always_fp32:
            g = g.astype(orig_dtype)
        return g

    return jax.tree.map(reduce_one, grads)


# Alias with the reference's conceptual name.
cross_replica_gradients = all_reduce_gradients


def data_parallel_sharding(
    mesh: Optional[Mesh] = None, *, batch_axis: int = 0
) -> NamedSharding:
    """Sharding that splits the batch dimension over the ``dp`` axis — the
    declaration that replaces wrapping a model in DDP."""
    mesh = mesh or mesh_lib.get_mesh()
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = mesh_lib.DATA_AXIS
    return NamedSharding(mesh, P(*spec))


def replicate(tree: PyTree, mesh: Optional[Mesh] = None) -> PyTree:
    """Replicate a pytree across the whole mesh — the init-time param
    broadcast (``apex/parallel/distributed.py:253``), done once, by XLA."""
    mesh = mesh or mesh_lib.get_mesh()
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
