"""Launcher parity note — the reference's ``apex.parallel.multiproc``
(``apex/parallel/multiproc.py:1-35``) spawns one Python process per local
GPU and sets the ``RANK``/``WORLD_SIZE`` env protocol (the pre-``torchrun``
launcher).

A JAX SPMD program needs no launcher on a single host: one process drives
every local device, and ``jax.sharding.Mesh`` + ``shard_map`` replace the
process-per-device model (SURVEY.md §2.4). On multi-host TPU pods the
runtime itself provides the process group — each host runs the same script
and calls :func:`jax.distributed.initialize`, which is what this module's
:func:`main` does, making ``python -m apex_tpu.parallel.multiproc script.py``
a drop-in spelling for users migrating launch commands.
"""

from __future__ import annotations

import os
import runpy
import sys
import warnings

import jax

# env vars that mean the user explicitly asked for multi-process init — a
# failure then is a real wiring error and must not be swallowed
_EXPLICIT_DIST_ENV = (
    "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
    "JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
)


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        print(f"devices visible to this process: {jax.device_count()}")
        return
    try:
        jax.distributed.initialize()  # auto-detects pod coordinates
    except Exception as e:
        if any(os.environ.get(k) for k in _EXPLICIT_DIST_ENV):
            raise  # requested multi-host init failed: fail loudly, don't
            # run every host as its own single-host world
        if "already" not in str(e).lower():
            warnings.warn(
                f"jax.distributed.initialize() unavailable ({e}); "
                "running single-host")
    script, sys.argv = argv[0], argv
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
