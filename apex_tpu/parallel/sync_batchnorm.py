"""Synchronized batch normalization over the data-parallel axis.

TPU-native re-design of ``apex.parallel.SyncBatchNorm``
(``apex/parallel/optimized_sync_batchnorm.py:9`` +
``optimized_sync_batchnorm_kernel.py:10-119``). The reference's forward runs a
per-GPU Welford kernel, all-gathers (mean, var, count), combines with a
``welford_parallel`` kernel, then normalizes; the backward hand-reduces
(sum_dy, sum_dy_xmu) and all-reduces them (``:74-119``).

Here the cross-replica statistics are two ``pmean``s of per-device moments
(E[x], E[x^2]) — numerically the same combine the Welford kernel performs —
and the backward all-reduce falls out of autodiff: d(pmean)/dx *is* the
reference's hand-written gradient reduction. NHWC (``channel_last=True`` in
the reference) is the native TPU layout. The fused ReLU + residual-add
epilogue (``optimized_sync_batchnorm_kernel.py:33-37``) is an option XLA
fuses into the normalize.

Stats dtype follows the ambient precision policy's ``norm_dtype``
(``keep_batchnorm_fp32``, ``apex/amp/frontend.py:134-144``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import current_policy
from apex_tpu.parallel import mesh as mesh_lib


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchNormState:
    """Running statistics (the module buffers of the reference)."""

    running_mean: jax.Array
    running_var: jax.Array
    num_batches_tracked: jax.Array

    @classmethod
    def create(cls, num_features: int, dtype=jnp.float32) -> "BatchNormState":
        return cls(
            running_mean=jnp.zeros((num_features,), dtype),
            running_var=jnp.ones((num_features,), dtype),
            num_batches_tracked=jnp.zeros((), jnp.int32),
        )


def sync_batch_norm(
    x: jax.Array,
    scale: Optional[jax.Array],
    bias: Optional[jax.Array],
    state: BatchNormState,
    *,
    training: bool = True,
    momentum: float = 0.1,
    eps: float = 1e-5,
    axis_name: Optional[str] = mesh_lib.DATA_AXIS,
    process_group_size: Optional[int] = None,
    fuse_relu: bool = False,
    residual: Optional[jax.Array] = None,
) -> Tuple[jax.Array, BatchNormState]:
    """Apply sync batch-norm to channel-last ``x`` (..., C).

    ``axis_name=None`` degrades to plain (local) batch-norm — the analog of
    running the reference module outside a process group. ``process_group_size``
    documents the reference's BN-group feature
    (``apex/parallel/__init__.py:58-95``): on TPU, reduce over a *sub*-axis by
    splitting the mesh axis instead; pass the sub-axis's name as ``axis_name``.

    Returns ``(y, new_state)``; ``new_state`` tracks running stats with the
    unbiased-variance convention the reference uses for its buffers.
    """
    del process_group_size  # expressed through axis_name; see docstring
    policy = current_policy()
    # Moments are always fp32: E[x^2]-E[x]^2 in half precision cancels
    # catastrophically for large-mean/small-std data (the reference's Welford
    # kernels exist to avoid exactly this). The policy's norm_dtype governs
    # the affine/output math, not the statistics.
    stats_dtype = jnp.float32
    out_dtype = x.dtype if policy.keep_norm_f32 else policy.compute_dtype
    xs = x.astype(stats_dtype)
    reduce_axes = tuple(range(x.ndim - 1))  # all but channels

    if training:
        # Global mean first, then centered second moment: E[(x - mean)^2].
        # Centering before squaring is the numerically stable property the
        # reference's Welford kernels (welford.cu:259+) provide; the naive
        # E[x^2]-E[x]^2 form cancels catastrophically for large-mean data.
        # Costs one extra pmean, same asymptotic cost as the reference's
        # all_gather of (mean, var, count).
        mean = jnp.mean(xs, axis=reduce_axes)
        if axis_name is not None:
            mean = jax.lax.pmean(mean, axis_name)
        centered = xs - mean
        var = jnp.mean(centered * centered, axis=reduce_axes)
        if axis_name is not None:
            var = jax.lax.pmean(var, axis_name)

        # Running stats use unbiased variance over the *global* batch
        # (reference computes count via all_gather'd counts).
        count = jnp.asarray(
            x.size // x.shape[-1], stats_dtype
        ) * (jax.lax.axis_size(axis_name) if axis_name is not None else 1)
        unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
        new_state = BatchNormState(
            running_mean=((1 - momentum) * state.running_mean + momentum * mean).astype(
                state.running_mean.dtype
            ),
            running_var=((1 - momentum) * state.running_var + momentum * unbiased).astype(
                state.running_var.dtype
            ),
            num_batches_tracked=state.num_batches_tracked + 1,
        )
    else:
        mean = state.running_mean.astype(stats_dtype)
        var = state.running_var.astype(stats_dtype)
        new_state = state

    inv = jax.lax.rsqrt(var + eps)
    y = (xs - mean) * inv
    if scale is not None:
        y = y * scale.astype(stats_dtype)
    if bias is not None:
        y = y + bias.astype(stats_dtype)
    if residual is not None:
        y = y + residual.astype(stats_dtype)  # fused add (z argument)
    if fuse_relu:
        y = jnp.maximum(y, 0)
    return y.astype(out_dtype), new_state


class SyncBatchNorm:
    """Thin stateful wrapper with the reference module's constructor surface
    (``apex/parallel/optimized_sync_batchnorm.py:9``): holds (scale, bias,
    running stats); call returns output and mutates nothing — new state is
    returned alongside, functional-style."""

    def __init__(
        self,
        num_features: int,
        eps: float = 1e-5,
        momentum: float = 0.1,
        affine: bool = True,
        axis_name: Optional[str] = mesh_lib.DATA_AXIS,
        fuse_relu: bool = False,
    ):
        self.num_features = num_features
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.axis_name = axis_name
        self.fuse_relu = fuse_relu

    def init(self, dtype=jnp.float32) -> Tuple[dict, BatchNormState]:
        params = (
            {"scale": jnp.ones((self.num_features,), dtype),
             "bias": jnp.zeros((self.num_features,), dtype)}
            if self.affine
            else {}
        )
        return params, BatchNormState.create(self.num_features, dtype)

    def __call__(
        self,
        params: dict,
        state: BatchNormState,
        x: jax.Array,
        *,
        training: bool = True,
        residual: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, BatchNormState]:
        return sync_batch_norm(
            x,
            params.get("scale"),
            params.get("bias"),
            state,
            training=training,
            momentum=self.momentum,
            eps=self.eps,
            axis_name=self.axis_name,
            fuse_relu=self.fuse_relu,
            residual=residual,
        )
