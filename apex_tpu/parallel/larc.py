"""LARC — layer-wise adaptive rate control/clipping.

Re-design of ``apex.parallel.LARC`` (``apex/parallel/LARC.py:5``). The
reference wraps an optimizer and rewrites ``p.grad`` in place before
delegating (``LARC.py:78-107``); here it is an optax gradient transformation
chained *before* the base optimizer, with identical arithmetic:

    adaptive_lr = trust_coefficient * ||p|| / (||g|| + weight_decay * ||p|| + eps)

clip mode:  scale grads by min(adaptive_lr / lr, 1)
scale mode: scale grads by adaptive_lr

Usage::

    tx = optax.chain(apex_tpu.parallel.larc(learning_rate=0.1), optax.sgd(0.1))
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp
import optax


def larc(
    learning_rate: Union[float, Callable[[jax.Array], jax.Array]] = 1.0,
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Per-parameter trust-ratio grad scaling (``apex/parallel/LARC.py:78-107``).

    ``learning_rate`` is needed in clip mode to reproduce
    ``min(adaptive_lr/lr, 1)``; pass the same schedule you give the base
    optimizer. Parameters with zero norm are left untouched, as in the
    reference (``if param_norm != 0 and grad_norm != 0``).
    """

    def init_fn(params):
        del params
        return optax.ScaleByScheduleState(count=jnp.zeros((), jnp.int32))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("larc requires params")
        lr = learning_rate(state.count) if callable(learning_rate) else learning_rate

        def scale_one(g, p):
            p32 = jnp.asarray(p, jnp.float32)
            g32 = jnp.asarray(g, jnp.float32)
            param_norm = jnp.linalg.norm(p32.reshape(-1))
            grad_norm = jnp.linalg.norm(g32.reshape(-1))
            adaptive_lr = (
                trust_coefficient * param_norm / (grad_norm + weight_decay * param_norm + eps)
            )
            if clip:
                factor = jnp.minimum(adaptive_lr / lr, 1.0)
            else:
                factor = adaptive_lr
            # reference applies BOTH decay and scaling only when neither norm
            # is zero (LARC.py:92-102); otherwise the grad passes untouched
            mask = (param_norm > 0) & (grad_norm > 0)
            adapted = (g32 + weight_decay * p32) * factor
            return jnp.where(mask, adapted, g32).astype(g.dtype)

        new_updates = jax.tree.map(scale_one, updates, params)
        return new_updates, optax.ScaleByScheduleState(count=state.count + 1)

    return optax.GradientTransformation(init_fn, update_fn)
