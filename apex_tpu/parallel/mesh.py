"""Global device-mesh bookkeeping for N-D parallelism.

TPU-native replacement for the reference's ``apex/transformer/parallel_state.py``:
where the reference builds a zoo of NCCL process groups for DP x TP x PP (+
virtual PP + embedding groups, ``parallel_state.py:73-247``) and exposes ~40
rank/world-size accessors (``:262-549``), a JAX SPMD program needs exactly one
``jax.sharding.Mesh`` with named axes; collectives reference axes by name and
XLA lowers them to ICI/DCN ring/tree ops.

Axis layout (outer → inner): ``('dp', 'pp', 'cp', 'tp')``. ``tp`` is
innermost so tensor-parallel collectives ride the fastest ICI links; ``dp``
outermost so data-parallel all-reduces tolerate DCN between slices. Context
parallelism (``cp``, for ring attention / long context) and expert parallelism
(``ep``, folded over ``dp``) are first-class here even though the reference
lacks them (SURVEY.md §2.3).

The "rank" accessors come in two flavors:
  * world sizes — module level, from the mesh shape (host-side);
  * ranks — only meaningful per-device, i.e. *inside* ``shard_map``; use
    ``jax.lax.axis_index(axis)``. Host-side code that needs "my rank" the way
    the reference does (e.g. ``get_tensor_model_parallel_rank()``,
    ``parallel_state.py:324``) should restructure to be rank-free — SPMD
    programs are written once for all ranks.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from apex_tpu.utils.logging import get_logger, set_rank_info

logger = get_logger(__name__)

# Canonical axis names. The reference's group getters (e.g.
# get_tensor_model_parallel_group, parallel_state.py:262+) map to these names.
DATA_AXIS = "dp"
PIPELINE_AXIS = "pp"
CONTEXT_AXIS = "cp"
TENSOR_AXIS = "tp"
EXPERT_AXIS = "ep"  # a dedicated sub-axis split out of dp when
# expert_parallel_size > 1 (the mesh becomes 5-D: dp, ep, pp, cp, tp with
# ep just inside dp so expert all_to_alls ride closer links); data-parallel
# collectives then span BOTH axes — use data_parallel_axis_names()

_MESH: Optional[Mesh] = None
_SPEC: Optional["MeshSpec"] = None


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Static description of the parallel decomposition.

    Mirrors the arguments of the reference's ``initialize_model_parallel``
    (``apex/transformer/parallel_state.py:73-110``) plus the TPU-first
    extensions (context/expert parallelism).
    """

    data_parallel_size: int = 1
    tensor_model_parallel_size: int = 1
    pipeline_model_parallel_size: int = 1
    context_parallel_size: int = 1
    expert_parallel_size: int = 1
    virtual_pipeline_model_parallel_size: Optional[int] = None
    # Encoder-decoder (T5-class) two-segment pipelines: stages
    # [0, split) run the encoder, [split, pp) the decoder (reference
    # ``parallel_state.py:147-149``; consumed by
    # ``pipeline_parallel.encoder_decoder``).
    pipeline_model_parallel_split_rank: Optional[int] = None

    def __post_init__(self):
        # divisibility/axis legality is ParallelPlan.validate()'s job —
        # ONE validator, one message style, whichever door (GPTConfig,
        # make_mesh, build_schedule) an illegal combo walks through
        from apex_tpu.plan.parallel_plan import ParallelPlan, PlanError

        v = self.virtual_pipeline_model_parallel_size
        if v is not None and self.pipeline_model_parallel_size < 2:
            # stricter than the plan's lenient v=1: ASKING for virtual
            # pipelining without a pipeline is a config error here
            raise ValueError(
                f"virtual_pipeline_model_parallel_size={v}: virtual "
                "pipeline parallelism requires "
                "pipeline_model_parallel_size >= 2")
        try:
            ParallelPlan(
                dp=self.data_parallel_size,
                tp=self.tensor_model_parallel_size,
                pp=self.pipeline_model_parallel_size,
                cp=self.context_parallel_size,
                ep=self.expert_parallel_size,
                virtual_chunks=v if v is not None else 1)
        except PlanError as e:
            raise ValueError(str(e)) from None
        split = self.pipeline_model_parallel_split_rank
        if split is not None and not (
                0 < split < self.pipeline_model_parallel_size):
            raise ValueError(
                f"pipeline_model_parallel_split_rank ({split}) must lie "
                f"strictly inside [1, pp) — both segments need at least one "
                f"stage (pp={self.pipeline_model_parallel_size})")

    @property
    def model_parallel_size(self) -> int:
        return (
            self.tensor_model_parallel_size
            * self.pipeline_model_parallel_size
            * self.context_parallel_size
        )

    @property
    def world_size(self) -> int:
        return self.data_parallel_size * self.model_parallel_size


def _apply_plan(plan: "ParallelPlan", devices, loose):
    """Unpack a ParallelPlan into the loose axis sizes + the sliced
    device list (dp is authoritative — a host exposing more devices
    must not silently widen it). One helper for both mesh doors so a
    new plan field cannot be threaded through one and not the other.
    ``loose`` carries the door's positional (tp, pp, cp, ep) kwargs: a
    non-default loose size that disagrees with the plan is an eager
    error (the GPTConfig rule) — never a silent merge."""
    for name, got, want in (
            ("tensor_model_parallel_size", loose[0], plan.tp),
            ("pipeline_model_parallel_size", loose[1], plan.pp),
            ("context_parallel_size", loose[2], plan.cp),
            ("expert_parallel_size", loose[3], plan.ep)):
        if got != 1 and got != want:
            raise ValueError(
                f"{name}={got} contradicts plan={plan.describe()} "
                f"(which implies {name}={want}); pass the knob through "
                f"the plan, not alongside it")
    if plan.world_size > len(devices):
        raise RuntimeError(
            f"plan {plan.describe()} spans {plan.world_size} "
            f"device(s); only {len(devices)} available")
    return (plan.tp, plan.pp, plan.cp, plan.ep,
            devices[: plan.world_size])


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    *,
    context_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    expert_parallel_size: int = 1,
    pipeline_model_parallel_split_rank: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    plan: Optional["ParallelPlan"] = None,
) -> Mesh:
    """Build and install the global mesh.

    Equivalent of ``parallel_state.initialize_model_parallel``
    (``apex/transformer/parallel_state.py:73-247``): validates divisibility,
    computes the data-parallel size from the device count, and constructs the
    decomposition — but as ONE mesh rather than O(world_size) process groups.
    The reference's rank-ordering convention (tp fastest-varying, then pp,
    then dp) is preserved so the same global batch maps to the same devices.
    """
    global _MESH, _SPEC
    if devices is None:
        devices = jax.devices()
    if plan is not None:
        (tensor_model_parallel_size, pipeline_model_parallel_size,
         context_parallel_size, expert_parallel_size,
         devices) = _apply_plan(plan, devices, (
             tensor_model_parallel_size, pipeline_model_parallel_size,
             context_parallel_size, expert_parallel_size))
        v = virtual_pipeline_model_parallel_size
        if v is not None and v != plan.virtual_chunks:
            # the plan is the single source of truth: a loose v that
            # disagrees must not silently merge into the MeshSpec
            raise ValueError(
                f"virtual_pipeline_model_parallel_size={v} contradicts "
                f"plan={plan.describe()} (virtual_chunks="
                f"{plan.virtual_chunks}); pass the knob through the "
                f"plan, not alongside it")
        virtual_pipeline_model_parallel_size = (
            plan.virtual_chunks if plan.virtual_chunks > 1 else None)
    world_size = len(devices)
    model_parallel = (
        tensor_model_parallel_size * pipeline_model_parallel_size * context_parallel_size
    )
    if world_size % model_parallel != 0:
        raise RuntimeError(
            f"world size ({world_size}) is not divisible by "
            f"tp ({tensor_model_parallel_size}) x pp ({pipeline_model_parallel_size}) "
            f"x cp ({context_parallel_size})"
        )
    data_parallel_size = world_size // model_parallel
    spec = MeshSpec(
        data_parallel_size=data_parallel_size,
        tensor_model_parallel_size=tensor_model_parallel_size,
        pipeline_model_parallel_size=pipeline_model_parallel_size,
        context_parallel_size=context_parallel_size,
        expert_parallel_size=expert_parallel_size,
        virtual_pipeline_model_parallel_size=virtual_pipeline_model_parallel_size,
        pipeline_model_parallel_split_rank=pipeline_model_parallel_split_rank,
    )
    mesh = _build_mesh(
        devices, data_parallel_size, expert_parallel_size,
        pipeline_model_parallel_size, context_parallel_size,
        tensor_model_parallel_size,
    )
    _MESH, _SPEC = mesh, spec
    set_rank_info(get_rank_info())
    logger.info("initialized model parallel: %s", spec)
    return mesh


def make_mesh(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    context_parallel_size: int = 1,
    expert_parallel_size: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    plan: Optional["ParallelPlan"] = None,
) -> Mesh:
    """Build a mesh without installing it globally (for tests / local use).

    ``plan`` is the preferred spelling (ISSUE 12): axis sizes come from
    one validated :class:`~apex_tpu.plan.parallel_plan.ParallelPlan`,
    and the device list is sliced to exactly ``plan.world_size`` (the
    plan's dp is authoritative — a host exposing more devices must not
    silently widen dp). The positional sizes stay as the deprecated
    loose-kwarg shim."""
    if devices is None:
        devices = jax.devices()
    if plan is not None:
        (tensor_model_parallel_size, pipeline_model_parallel_size,
         context_parallel_size, expert_parallel_size,
         devices) = _apply_plan(plan, devices, (
             tensor_model_parallel_size, pipeline_model_parallel_size,
             context_parallel_size, expert_parallel_size))
    model_parallel = (
        tensor_model_parallel_size * pipeline_model_parallel_size * context_parallel_size
    )
    dp = len(devices) // model_parallel
    if dp == 0:
        raise RuntimeError(
            f"{len(devices)} device(s) cannot host tp ({tensor_model_parallel_size}) "
            f"x pp ({pipeline_model_parallel_size}) x cp ({context_parallel_size})"
        )
    return _build_mesh(
        devices[: dp * model_parallel], dp, expert_parallel_size,
        pipeline_model_parallel_size, context_parallel_size,
        tensor_model_parallel_size,
    )


def _build_mesh(devices, dp, ep, pp, cp, tp) -> Mesh:
    """The one place the device array is laid out. With ``ep > 1`` a
    dedicated expert axis splits out of dp (ep INSIDE dp: expert
    all_to_alls stay within each dp group's closer links) and the mesh is
    5-D; otherwise the classic 4-D layout."""
    if ep > 1:
        if dp % ep:
            # same validator (and message style) as every other door
            from apex_tpu.plan.parallel_plan import ParallelPlan, PlanError
            try:
                ParallelPlan(dp=dp, ep=ep)
            except PlanError as e:
                raise ValueError(str(e)) from None
            raise ValueError(  # pragma: no cover - plan rejects first
                f"expert_parallel_size ({ep}) must divide the "
                f"data-parallel extent ({dp})")
        device_array = np.asarray(devices).reshape(dp // ep, ep, pp, cp, tp)
        return Mesh(device_array, (DATA_AXIS, EXPERT_AXIS, PIPELINE_AXIS,
                                   CONTEXT_AXIS, TENSOR_AXIS))
    device_array = np.asarray(devices).reshape(dp, pp, cp, tp)
    return Mesh(device_array,
                (DATA_AXIS, PIPELINE_AXIS, CONTEXT_AXIS, TENSOR_AXIS))


def hybrid_device_order(devices: Sequence, model_parallel: int) -> list:
    """Reorder ``devices`` so the model-parallel axes (the mesh's inner
    ``model_parallel`` extent) stay INSIDE one slice's ICI and the
    data-parallel axis (outermost) spans slices over DCN.

    Multi-slice TPU pods expose ``device.slice_index``; within a slice,
    ``device.id`` preserves the ICI torus order jax already provides. The
    flat reshape in :func:`_build_mesh` then puts slice boundaries exactly
    at dp-group boundaries — dp all-reduces ride DCN, tp/cp/pp/ep
    collectives never leave a slice (the scaling-book hybrid recipe;
    jax's ``mesh_utils.create_hybrid_device_mesh`` does the same
    arrangement for the 2-level case).

    Pure list-ordering (no Mesh construction) so it is testable with stub
    devices. Raises if any slice's device count is not a multiple of
    ``model_parallel`` — a model group straddling DCN is the exact layout
    this function exists to prevent."""
    slices: dict = {}
    for d in devices:
        slices.setdefault(getattr(d, "slice_index", 0), []).append(d)
    if len(slices) == 1:
        return list(devices)  # single slice (or CPU): nothing to arrange
    for idx, devs in slices.items():
        if len(devs) % model_parallel:
            raise RuntimeError(
                f"slice {idx} holds {len(devs)} devices — not a multiple of "
                f"the model-parallel extent ({model_parallel}); a tp/pp/cp "
                f"group would straddle DCN")
    out = []
    for idx in sorted(slices):
        out.extend(sorted(slices[idx], key=lambda d: d.id))
    return out


def make_hybrid_mesh(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    context_parallel_size: int = 1,
    expert_parallel_size: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """:func:`make_mesh` with the multi-slice (DCN) device arrangement of
    :func:`hybrid_device_order` applied first. On a single slice (or CPU)
    this is exactly ``make_mesh``."""
    if devices is None:
        devices = jax.devices()
    # the contiguous inner block of _build_mesh's reshape: ep sits just
    # INSIDE dp in the 5-D layout, so ep all_to_alls are slice-local only
    # if ep is part of the extent the slice-divisibility guard covers
    inner = (expert_parallel_size * pipeline_model_parallel_size
             * context_parallel_size * tensor_model_parallel_size)
    return make_mesh(
        tensor_model_parallel_size, pipeline_model_parallel_size,
        context_parallel_size, expert_parallel_size,
        devices=hybrid_device_order(devices, inner))


def destroy_model_parallel() -> None:
    """Tear down global state (cf. ``parallel_state.py:555-580``)."""
    global _MESH, _SPEC
    _MESH, _SPEC = None, None
    set_rank_info("")


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError(
            "model parallel mesh is not initialized; call "
            "apex_tpu.parallel.initialize_model_parallel(...) first"
        )
    return _MESH


def get_mesh_spec() -> MeshSpec:
    if _SPEC is None:
        raise RuntimeError("model parallel mesh is not initialized")
    return _SPEC


# --- world-size accessors (host-side; cf. parallel_state.py:262-549) ---------

def get_data_parallel_world_size() -> int:
    return get_mesh_spec().data_parallel_size


def get_tensor_model_parallel_world_size() -> int:
    return get_mesh_spec().tensor_model_parallel_size


def get_pipeline_model_parallel_world_size() -> int:
    return get_mesh_spec().pipeline_model_parallel_size


def get_context_parallel_world_size() -> int:
    return get_mesh_spec().context_parallel_size


def get_expert_parallel_world_size() -> int:
    return get_mesh_spec().expert_parallel_size


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return get_mesh_spec().virtual_pipeline_model_parallel_size


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    """First decoder stage of a two-segment (encoder-decoder) pipeline, or
    None for single-segment models (``parallel_state.py:147-149``)."""
    return get_mesh_spec().pipeline_model_parallel_split_rank


def data_parallel_axis_names() -> tuple:
    """The mesh axes data parallelism spans: ``('dp',)`` normally,
    ``('dp', 'ep')`` when a dedicated expert axis is split out — pass to
    ``pmean``/``PartitionSpec`` so dp collectives and batch sharding cover
    the full data-parallel extent."""
    if get_mesh_spec().expert_parallel_size > 1:
        return (DATA_AXIS, EXPERT_AXIS)
    return (DATA_AXIS,)


def get_rank_info() -> str:
    """Short mesh descriptor for log records (cf. ``parallel_state.py:250-259``)."""
    if _SPEC is None:
        return "uninitialized"
    s = _SPEC
    return (
        f"dp{s.data_parallel_size}/pp{s.pipeline_model_parallel_size}"
        f"/cp{s.context_parallel_size}/tp{s.tensor_model_parallel_size}"
    )


# --- in-shard_map rank helpers ----------------------------------------------

# jax moved shard_map out of experimental and renamed its replication-check
# kwarg (check_rep -> check_vma) across releases; resolve both once here so
# the whole repo rides one entry point on any supported jax.
if hasattr(jax, "shard_map"):
    _jax_shard_map, _CHECK_KW = jax.shard_map, "check_vma"
else:  # pragma: no cover - jax-version dependent
    from jax.experimental.shard_map import shard_map as _jax_shard_map
    _CHECK_KW = "check_rep"


def shard_map(f, mesh=None, *, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` bound to the global mesh, with the
    varying-manual-axes check off by default: Megatron-style TP code is full
    of rank-dependent slices whose replication (post all-gather) the static
    checker cannot prove — the same reason the reference asserts its own
    invariants at runtime instead (e.g. ``distributed.py:340-348``).

    The global mesh is resolved at *call* time so wrappers may be built
    before ``initialize_model_parallel()`` and survive re-initialization.

    OLD-JAX HAZARD (the ``jax.experimental`` fallback, jax < 0.6):
    that implementation transposes ``lax.psum`` to ``psum`` (with the
    replication check on OR off), so ``jax.grad`` taken INSIDE the
    wrapper of a loss that explicitly ``psum``s yields gradients scaled
    by the axis size. The framework's own losses are unaffected (the
    TP/pipeline grad-parity suites pass on 0.4.x — their collectives ride
    custom VJPs with hand-written transposes, e.g.
    ``vocab_parallel_cross_entropy``), but user code differentiating a
    hand-psum'd scalar inside ``shard_map`` should either take the grad
    OUTSIDE the wrapper or divide by ``lax.axis_size`` on old jax."""
    if mesh is not None:
        return _jax_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            **{_CHECK_KW: check_vma}
        )

    def call(*args, **kwargs):
        return _jax_shard_map(
            f, mesh=get_mesh(), in_specs=in_specs, out_specs=out_specs,
            **{_CHECK_KW: check_vma},
        )(*args, **kwargs)

    return call


def axis_rank(axis: str) -> jax.Array:
    """Per-device rank along ``axis``; valid only inside shard_map/pjit with
    that axis bound (replaces get_*_rank, ``parallel_state.py:324+``)."""
    return jax.lax.axis_index(axis)


def is_pipeline_first_stage() -> jax.Array:
    return jax.lax.axis_index(PIPELINE_AXIS) == 0


def is_pipeline_last_stage() -> jax.Array:
    return jax.lax.axis_index(PIPELINE_AXIS) == jax.lax.axis_size(PIPELINE_AXIS) - 1


def is_pipeline_stage_before_split(rank=None) -> jax.Array:
    """This stage runs encoder blocks (reference ``parallel_state.py:338``).
    In-shard_map by default; pass an explicit ``rank`` for host-side use."""
    split = get_pipeline_model_parallel_split_rank()
    if rank is None:
        rank = jax.lax.axis_index(PIPELINE_AXIS)
    if split is None:
        return rank >= 0  # vacuously true, traced- and host-friendly
    return rank < split


def is_pipeline_stage_after_split(rank=None) -> jax.Array:
    """This stage runs decoder blocks (``parallel_state.py:355``)."""
    split = get_pipeline_model_parallel_split_rank()
    if rank is None:
        rank = jax.lax.axis_index(PIPELINE_AXIS)
    if split is None:
        return rank >= 0  # vacuously true
    return rank >= split


def is_pipeline_stage_at_split(rank=None) -> jax.Array:
    """Last encoder stage — the stage whose successor starts the decoder
    (``parallel_state.py:369-375``)."""
    split = get_pipeline_model_parallel_split_rank()
    if rank is None:
        rank = jax.lax.axis_index(PIPELINE_AXIS)
    if split is None:
        return rank < 0  # vacuously false
    return rank == split - 1
