"""Legacy manual mixed-precision API.

Re-design of ``apex.fp16_utils`` (``apex/fp16_utils/__init__.py:1-16``,
``fp16util.py``, ``fp16_optimizer.py``, ``loss_scaler.py``) — the pre-amp
manual API kept for parity. In JAX, "convert the network" is a pytree cast
and "master params" are a second pytree, so each reference entry point maps
to a small pure function; ``FP16_Optimizer`` wraps an optax transformation
with master-weight + loss-scaling bookkeeping.
"""

from apex_tpu.fp16_utils.fp16util import (  # noqa: F401
    BN_CONVERT_EXEMPT,
    FP16Model,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
)
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer  # noqa: F401
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler  # noqa: F401
