"""FP16_Optimizer — the legacy master-weights wrapper.

Re-design of ``apex/fp16_utils/fp16_optimizer.py:13-450``: wraps an inner
optimizer with fp32 master weights, (dynamic) loss scaling, overflow skip,
and master-grad clipping. The reference mutates the wrapped torch optimizer's
param groups; here the wrapper owns a state pytree and exposes
``backward``-less functional stepping (loss scaling happens in the user's
grad computation via ``scale_loss``) plus the reference's method surface for
familiarity.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from apex_tpu.amp import scaler as _fscaler
from apex_tpu.fp16_utils.fp16util import (
    master_params_to_model_params,
    model_grads_to_master_grads,
    prep_param_lists,
)
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler

PyTree = Any


class FP16_Optimizer:
    """Stateful wrapper (``fp16_optimizer.py:13``): holds (model params,
    fp32 masters, inner optax state, scaler); ``step(grads)`` unscales,
    checks overflow, updates masters, copies back to model dtype."""

    def __init__(self, optimizer: optax.GradientTransformation, params: PyTree,
                 static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None):
        self.inner = optimizer
        self.model_params, self.master_params = prep_param_lists(params)
        self.opt_state = optimizer.init(self.master_params)
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.loss_scaler = LossScaler(static_loss_scale)
        self.overflow = False

    @property
    def loss_scale(self) -> float:
        return self.loss_scaler.loss_scale

    def scale_loss(self, loss):
        """Multiply the loss before grad (`backward(loss)` analog)."""
        return loss * self.loss_scale

    def clip_master_grads(self, max_norm: float, grads: PyTree) -> PyTree:
        """Global-norm clip on master grads (``clip_master_grads``
        ``fp16_optimizer.py:373``)."""
        gnorm = optax.global_norm(grads)
        factor = jnp.minimum(1.0, max_norm / jnp.maximum(gnorm, 1e-12))
        return jax.tree.map(lambda g: g * factor, grads)

    def step(self, scaled_model_grads: PyTree, clip_grad_norm: Optional[float] = None):
        """One update from *scaled half-precision* grads; skips on overflow
        (the reference's skip-step patch, ``handle.py:128-154``)."""
        master_grads = model_grads_to_master_grads(scaled_model_grads)
        master_grads = jax.tree.map(lambda g: g / self.loss_scale, master_grads)
        self.overflow = not bool(_fscaler.all_finite(master_grads))
        self.loss_scaler.update_scale(self.overflow)
        if self.overflow:
            return self.model_params
        if clip_grad_norm is not None:
            master_grads = self.clip_master_grads(clip_grad_norm, master_grads)
        updates, self.opt_state = self.inner.update(
            master_grads, self.opt_state, self.master_params
        )
        self.master_params = optax.apply_updates(self.master_params, updates)
        self.model_params = master_params_to_model_params(
            self.model_params, self.master_params
        )
        return self.model_params

    # --- checkpointing (``fp16_optimizer.py:209-270``) -----------------------

    def state_dict(self) -> dict:
        return {
            "master_params": self.master_params,
            "opt_state": self.opt_state,
            "loss_scale": self.loss_scale,
            "dynamic": isinstance(self.loss_scaler, DynamicLossScaler),
            "unskipped": getattr(self.loss_scaler, "_unskipped", 0),
        }

    def load_state_dict(self, sd: dict) -> None:
        self.master_params = sd["master_params"]
        self.opt_state = sd["opt_state"]
        self.loss_scaler._scale = float(sd["loss_scale"])
        if sd.get("dynamic") and isinstance(self.loss_scaler, DynamicLossScaler):
            self.loss_scaler._unskipped = int(sd.get("unskipped", 0))
        self.model_params = master_params_to_model_params(
            self.model_params, self.master_params
        )
