"""Pytree casting utilities.

Re-design of ``apex/fp16_utils/fp16util.py``: ``network_to_half`` /
``convert_network`` keep batch-norm-ish leaves fp32 (the reference walks
modules and exempts ``torch.nn.modules.batchnorm._BatchNorm``); on a pytree
the exemption is by key-path match.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

PyTree = Any

# key-path substrings treated as batch-norm/normalization params (kept fp32),
# the pytree analog of the reference's isinstance(_BatchNorm) check
BN_CONVERT_EXEMPT = ("bn", "batchnorm", "batch_norm", "ln", "layernorm", "norm", "scale")


def _is_exempt(path: Tuple, exempt=BN_CONVERT_EXEMPT) -> bool:
    name = "/".join(str(p) for p in path).lower()
    return any(e in name for e in exempt)


def network_to_half(params: PyTree, dtype=jnp.bfloat16) -> PyTree:
    """Cast every floating leaf (``network_to_half``; the reference wraps
    in ``tofp16`` modules — here it is one tree cast)."""
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        params,
    )


def convert_network(params: PyTree, dtype=jnp.bfloat16,
                    exempt=BN_CONVERT_EXEMPT) -> PyTree:
    """Half-cast except normalization params (``convert_network`` —
    ``keep_batchnorm_fp32`` semantics, ``fp16util.py``)."""
    def cast(path, x):
        if not jnp.issubdtype(x.dtype, jnp.floating) or _is_exempt(path, exempt):
            return x
        return x.astype(dtype)
    return jax.tree_util.tree_map_with_path(cast, params)


def prep_param_lists(params: PyTree) -> Tuple[PyTree, PyTree]:
    """(model_params, fp32 master copies) — ``prep_param_lists``
    (``fp16util.py``; the reference also flattens, which the fused
    optimizers' chunk layout does on demand)."""
    master = jax.tree.map(lambda x: x.astype(jnp.float32), params)
    return params, master


def master_params_to_model_params(model: PyTree, master: PyTree) -> PyTree:
    """Copy master values into the model dtype (``fp16util.py``)."""
    return jax.tree.map(lambda mo, ma: ma.astype(mo.dtype), model, master)


def model_grads_to_master_grads(model_grads: PyTree) -> PyTree:
    """fp32 copies of (half) model grads (``fp16util.py``)."""
    return jax.tree.map(lambda g: g.astype(jnp.float32), model_grads)


def to_python_float(x) -> float:
    return float(x)


class FP16Model:
    """Half-precision model wrapper — ``FP16Model``
    (``apex/fp16_utils/fp16util.py:73-83``): converts the network
    batchnorm-safe (norm params stay fp32) and casts floating inputs to the
    half dtype before the forward.

    The reference wraps an ``nn.Module``; here a model is (apply_fn, params),
    so the wrapper holds the converted params and a callable.
    """

    def __init__(self, apply_fn: Callable, params: PyTree,
                 dtype=jnp.bfloat16, exempt=BN_CONVERT_EXEMPT):
        self.apply_fn = apply_fn
        self.params = convert_network(params, dtype, exempt)
        self.dtype = dtype

    def __call__(self, *inputs, **kwargs):
        def cast(x):
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(self.dtype)
            return x

        return self.apply_fn(self.params, *jax.tree.map(cast, inputs),
                             **jax.tree.map(cast, kwargs))
