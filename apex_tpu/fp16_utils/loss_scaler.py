"""Legacy LossScaler / DynamicLossScaler.

Re-design of ``apex/fp16_utils/loss_scaler.py``: stateful host-side objects
(the legacy API contract) delegating the math to the functional scaler in
:mod:`apex_tpu.amp.scaler` — same constants (init 2^16 dynamic, x2/2000
growth, /2 backoff).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.amp import scaler as _fscaler


class LossScaler:
    """Static scale (``loss_scaler.py`` LossScaler)."""

    def __init__(self, scale: float = 1.0):
        self._scale = float(scale)

    @property
    def loss_scale(self) -> float:
        return self._scale

    def scale_gradient(self, grads):
        return jax.tree.map(lambda g: g * self._scale, grads)

    def unscale(self, grads):
        return jax.tree.map(lambda g: g / self._scale, grads)

    def update_scale(self, overflow: bool) -> None:
        pass

    def has_overflow(self, grads) -> bool:
        return False


class DynamicLossScaler(LossScaler):
    """Dynamic scale (``loss_scaler.py`` DynamicLossScaler): /2 on overflow,
    x2 after ``scale_window`` clean steps."""

    def __init__(self, init_scale: float = 2.0 ** 16, scale_factor: float = 2.0,
                 scale_window: int = 2000):
        super().__init__(init_scale)
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, grads) -> bool:
        finite = _fscaler.all_finite(grads)
        return not bool(finite)

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            self._scale = max(self._scale / self.scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self.scale_window:
                self._scale *= self.scale_factor
                self._unskipped = 0
