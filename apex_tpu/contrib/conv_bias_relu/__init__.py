"""Fused Conv+Bias(+Mask)+ReLU.

Re-design of ``apex.contrib.conv_bias_relu``
(``apex/contrib/conv_bias_relu/conv_bias_relu.py:7-76``; cudnn-frontend
fused graphs). On TPU, convolution epilogues are XLA's own fusion domain —
these compositions compile to a single conv+epilogue program, which is the
whole content of the cudnn-frontend graphs the reference builds by hand.
NHWC layout throughout.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _conv(x, w, stride, padding):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def conv_bias(x, weight, bias, stride: int = 1, padding="SAME"):
    """``ConvBias`` (``conv_bias_relu.py:30-44``)."""
    return _conv(x, weight, stride, padding) + bias


def conv_bias_relu(x, weight, bias, stride: int = 1, padding="SAME"):
    """``ConvBiasReLU`` (``conv_bias_relu.py:7-28``)."""
    return jnp.maximum(conv_bias(x, weight, bias, stride, padding), 0.0)


def conv_bias_mask_relu(x, weight, bias, mask, stride: int = 1, padding="SAME"):
    """``ConvBiasMaskReLU`` (``conv_bias_relu.py:46-62``): elementwise mask
    before the ReLU (used for dropout-style masking in detection nets)."""
    return jnp.maximum(conv_bias(x, weight, bias, stride, padding) * mask, 0.0)


def conv_frozen_scale_bias_relu(x, weight, scale, bias, stride: int = 1, padding="SAME"):
    """``ConvFrozenScaleBiasReLU`` (``conv_bias_relu.py:64-76``): conv with a
    frozen-BN affine folded in."""
    return jnp.maximum(_conv(x, weight, stride, padding) * scale + bias, 0.0)
