"""FastLayerNorm — large-hidden LayerNorm.

Re-design of ``apex.contrib.layer_norm.FastLayerNorm``
(``apex/contrib/layer_norm/layer_norm.py:8-53``; kernels
``apex/contrib/csrc/layer_norm/ln_fwd_cuda_kernel.cu``). The reference ships
a second, hand-tuned LN for hidden sizes up to 65k; the Pallas LN already
streams arbitrary hidden sizes by sizing its row blocks to VMEM
(``_pick_block_rows``), so FastLayerNorm is the same kernel re-exported with
the contrib constructor surface.
"""

from apex_tpu.ops.layer_norm import FusedLayerNorm as FastLayerNorm  # noqa: F401
from apex_tpu.ops.layer_norm import fused_layer_norm as fast_layer_norm  # noqa: F401
