"""ASP: mask bookkeeping + optimizer integration.

Re-design of ``apex.contrib.sparsity.ASP`` (``apex/contrib/sparsity/asp.py:28-312``).
The reference walks module weights, allocates mask buffers, and patches
``optimizer.step`` to re-apply masks after every update; functionally that
is: (1) compute a mask pytree from the current weights, (2) wrap the
optimizer so updated params are re-masked each step — the same
"prune-and-keep-pruned" contract without monkey-patching.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from apex_tpu.contrib.sparsity.masklib import create_mask

PyTree = Any


def _default_eligible(path: str, w) -> bool:
    """Reference eligibility (asp.py:100-130): 2-D+ weights whose last dim
    is a multiple of 4; biases/norms are left dense."""
    return w.ndim >= 2 and w.shape[-1] % 4 == 0


class ASP:
    """Functional ASP.

    Usage (mirrors init_model_for_pruning → compute_sparse_masks →
    init_optimizer_for_pruning, asp.py:62-312)::

        asp = ASP()
        masks = asp.compute_sparse_masks(params)       # prune decision
        params = asp.apply_masks(params, masks)        # prune weights
        opt = asp.wrap_optimizer(optax.adam(1e-3), masks)  # keep pruned
    """

    def __init__(self, pattern: str = "m4n2_1d",
                 eligible: Callable[[str, Any], bool] = _default_eligible):
        self.pattern = pattern
        self.eligible = eligible

    def compute_sparse_masks(self, params: PyTree) -> PyTree:
        """Mask pytree: boolean masks for eligible weights; ineligible
        (dense) leaves get a scalar-True mask so the pytree structure stays
        identical to params (``compute_sparse_masks`` asp.py:177-229)."""
        def mk(path, w):
            name = "/".join(str(p) for p in path)
            if self.eligible(name, w):
                return create_mask(w, self.pattern)
            return jnp.ones((), bool)
        return jax.tree_util.tree_map_with_path(mk, params)

    def apply_masks(self, params: PyTree, masks: PyTree) -> PyTree:
        return jax.tree.map(
            lambda w, m: jnp.where(m, w, 0).astype(w.dtype), params, masks
        )

    def wrap_optimizer(
        self, opt: optax.GradientTransformation, masks: PyTree
    ) -> optax.GradientTransformation:
        """Re-apply masks inside the update (the reference's patched
        ``optimizer.step``, asp.py:231-259): masked weights stay exactly
        zero — updates for them are zeroed so w + u keeps the pattern."""

        def init(params):
            return opt.init(params)

        def update(grads, state, params=None):
            updates, state = opt.update(grads, state, params)
            if params is not None:
                # masked slots: update = -w so the post-step weight is 0
                updates = jax.tree.map(
                    lambda u, w, m: jnp.where(m, u, -w).astype(u.dtype),
                    updates, params, masks,
                )
            else:
                updates = jax.tree.map(
                    lambda u, m: jnp.where(m, u, 0).astype(u.dtype), updates, masks
                )
            return updates, state

        return optax.GradientTransformation(init, update)
