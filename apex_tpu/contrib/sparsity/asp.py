"""ASP: mask bookkeeping + optimizer integration.

Re-design of ``apex.contrib.sparsity.ASP`` (``apex/contrib/sparsity/asp.py:28-312``).
The reference walks module weights, allocates mask buffers, and patches
``optimizer.step`` to re-apply masks after every update; functionally that
is: (1) compute a mask pytree from the current weights, (2) wrap the
optimizer so updated params are re-masked each step — the same
"prune-and-keep-pruned" contract without monkey-patching.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from apex_tpu.contrib.sparsity.masklib import create_mask
from apex_tpu.contrib.sparsity.permutation import (
    apply_permutation,
    invert_permutation,
    search_for_good_permutation,
)

PyTree = Any


def _default_eligible(path: str, w) -> bool:
    """Reference eligibility (asp.py:100-130): 2-D+ weights whose last dim
    is a multiple of 4; biases/norms are left dense."""
    return w.ndim >= 2 and w.shape[-1] % 4 == 0


class ASP:
    """Functional ASP.

    Usage (mirrors init_model_for_pruning → compute_sparse_masks →
    init_optimizer_for_pruning, asp.py:62-312)::

        asp = ASP()
        masks = asp.compute_sparse_masks(params)       # prune decision
        params = asp.apply_masks(params, masks)        # prune weights
        opt = asp.wrap_optimizer(optax.adam(1e-3), masks)  # keep pruned
    """

    def __init__(self, pattern: str = "m4n2_1d",
                 eligible: Callable[[str, Any], bool] = _default_eligible):
        self.pattern = pattern
        self.eligible = eligible

    def compute_sparse_masks(self, params: PyTree) -> PyTree:
        """Mask pytree: boolean masks for eligible weights; ineligible
        (dense) leaves get a scalar-True mask so the pytree structure stays
        identical to params (``compute_sparse_masks`` asp.py:177-229)."""
        def mk(path, w):
            name = "/".join(str(p) for p in path)
            if self.eligible(name, w):
                return create_mask(w, self.pattern)
            return jnp.ones((), bool)
        return jax.tree_util.tree_map_with_path(mk, params)

    def apply_masks(self, params: PyTree, masks: PyTree) -> PyTree:
        return jax.tree.map(
            lambda w, m: jnp.where(m, w, 0).astype(w.dtype), params, masks
        )

    def search_permutations(self, params: PyTree) -> PyTree:
        """Per-eligible-weight input-channel permutations improving 2:4
        magnitude retention — the accuracy-preserving half of ASP
        (``permutation_lib.py:1-925``; search in
        ``permutation_search_kernels/``).

        Returns a pytree of ``np.ndarray`` permutations (identity for
        ineligible leaves, so the pytree structure matches ``params``). The
        reference propagates permutations through the traced ``torch.fx``
        graph so producer outputs and consumer inputs stay consistent; a
        functional pytree has no graph, so wiring a weight's permutation to
        its neighbors is the caller's job: permute this weight's *input*
        channels with the returned ``perm`` and the producing layer's
        *output* channels with ``invert_permutation(perm)`` (see
        ``permute_params``).
        """
        import numpy as np

        def search(path, w):
            name = "/".join(str(p) for p in path)
            if not self.eligible(name, w):
                return np.arange(w.shape[-1]) if w.ndim else np.arange(1)
            mat = jnp.reshape(w, (-1, w.shape[-1]))
            perm, improvement = search_for_good_permutation(mat)
            return perm if improvement > 0 else np.arange(w.shape[-1])

        return jax.tree_util.tree_map_with_path(search, params)

    def permute_params(self, params: PyTree, perms: PyTree) -> PyTree:
        """Apply input-channel permutations from :meth:`search_permutations`."""
        return jax.tree.map(
            lambda w, p: apply_permutation(w, p, axis=-1) if w.ndim else w,
            params, perms,
        )

    def wrap_optimizer(
        self, opt: optax.GradientTransformation, masks: PyTree
    ) -> optax.GradientTransformation:
        """Re-apply masks inside the update (the reference's patched
        ``optimizer.step``, asp.py:231-259): masked weights stay exactly
        zero — updates for them are zeroed so w + u keeps the pattern."""

        def init(params):
            return opt.init(params)

        def update(grads, state, params=None):
            updates, state = opt.update(grads, state, params)
            if params is not None:
                # masked slots: update = -w so the post-step weight is 0
                updates = jax.tree.map(
                    lambda u, w, m: jnp.where(m, u, -w).astype(u.dtype),
                    updates, params, masks,
                )
            else:
                updates = jax.tree.map(
                    lambda u, m: jnp.where(m, u, 0).astype(u.dtype), updates, masks
                )
            return updates, state

        return optax.GradientTransformation(init, update)
