"""2:4 structured-sparsity mask construction.

Re-design of ``apex/contrib/sparsity/sparse_masklib.py``: for every group of
4 consecutive weights along the input dimension, keep the 2 of largest
magnitude. The reference enumerates permutation patterns on the GPU; the
best-2-of-4 selection is an exact argsort over each group, which XLA
vectorizes fine.

TPU note (asp.py parity, not performance): TPUs have no 2:4 sparse MXU mode,
so the masks buy *model compression / regularization* semantics, not
speedups — the docstring of record for why this module keeps the pruning
logic but drops the reference's "2x math throughput" claim.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mask_2to4_best(w: jax.Array) -> jax.Array:
    """Boolean mask keeping the 2 largest-|w| of every 4 along the last dim.
    Requires last dim % 4 == 0 (the reference pads; we require)."""
    *lead, n = w.shape
    assert n % 4 == 0, f"last dim ({n}) must be a multiple of 4 for 2:4 sparsity"
    g = jnp.abs(w).reshape(*lead, n // 4, 4)
    # rank positions within each group; keep top-2
    order = jnp.argsort(g, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = ranks >= 2
    return mask.reshape(*lead, n)


def create_mask(w: jax.Array, pattern: str = "m4n2_1d") -> jax.Array:
    """``sparse_masklib.create_mask`` surface; only the production pattern
    (2:4 along rows, 'm4n2_1d') plus dense passthrough."""
    if pattern in ("m4n2_1d", "m4n2_2d_best", "m4n2_2d_greedy"):
        return mask_2to4_best(w)
    if pattern == "unstructured":
        raise NotImplementedError("unstructured pruning is out of ASP scope")
    raise ValueError(f"unknown sparsity pattern {pattern!r}")
