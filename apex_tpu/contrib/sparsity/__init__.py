"""ASP — automatic structured (2:4) sparsity.

Re-design of ``apex.contrib.sparsity.ASP``
(``apex/contrib/sparsity/asp.py:28-312``, mask patterns
``sparse_masklib.py``, channel-permutation search ``permutation_lib.py``).
"""

from apex_tpu.contrib.sparsity.asp import ASP  # noqa: F401
from apex_tpu.contrib.sparsity.masklib import (  # noqa: F401
    create_mask,
    mask_2to4_best,
)
from apex_tpu.contrib.sparsity.permutation import (  # noqa: F401
    apply_permutation,
    exhaustive_search,
    greedy_swap_search,
    invert_permutation,
    search_for_good_permutation,
    sum_after_2_to_4,
)
