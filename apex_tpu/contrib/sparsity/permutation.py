"""Channel-permutation search for 2:4 structured sparsity.

Re-design of the reference's permutation machinery
(``apex/contrib/sparsity/permutation_lib.py:1-925`` and
``permutation_search_kernels/`` — exhaustive stripe-group search plus CUDA
channel-swap kernels). Permuting the input channels of a weight matrix
before applying a 2:4 mask can substantially raise the magnitude retained —
the accuracy-preserving half of ASP.

TPU-native formulation (no CUDA kernel port): the greedy search scores
*every* column-pair swap at once on the MXU/VPU, instead of looping
``try_swap`` per pair (``permutation_utilities.py:83-102``):

With stripes of ``group=4`` columns, swapping column ``i`` (stripe ``a``)
with ``j`` (stripe ``b``) changes only stripes ``a`` and ``b``. Per row, the
2:4-retained sum of stripe ``a`` with ``i`` replaced by ``j`` has the closed
form ``t2 + relu(|w_j| - s2)`` where ``t2`` is the top-2 sum of the three
remaining columns and ``s2`` their second-largest magnitude. Summing over
rows gives a dense (C, C) improvement matrix from one broadcasted relu
contraction; each sweep applies the argmax swap. That is the whole search —
one matmul-shaped op per sweep, no per-pair kernel launches.

Exhaustive search (small C) mirrors ``exhaustive_search.py:93-117``:
enumerate canonical column-group assignments host-side, score them all in
one vmapped batch on device.

Scope vs the reference (VERDICT r5 Weak #6 — stated, not implicit): this
module implements exactly two searches — the vectorized global-window
greedy descent above and the tiny-C exhaustive — and deliberately none of
the reference's 925-LoC bounded-regrouping machinery
(``permutation_lib.py``: stripe-group checkpointing, escape heuristics,
per-pair CUDA swap kernels). The reference needs that machinery because
its greedy is *windowed* (bounded stripe groups) and per-pair serial; the
TPU formulation scores all C² swaps per sweep on the MXU, so the simple
global-argmax descent already lands near the optimum. Measured on a real
2:4-pruned layer (GPT-small ``mlp_down`` (32, 128) from the live model
init, scored blockwise at C=8 where exhaustive is tractable — 35
canonical assignments/block): greedy retains **99.94%** of the exhaustive
optimum's magnitude (96.1% of the achievable improvement over identity;
worst block 99.6%), asserted in
``tests/test_permutation.py::TestGreedyVsExhaustive``. The known gap:
pathological stripe arrangements where only a *joint* k>2-column rotation
escapes a local optimum; the reference's escape heuristics buy ~nothing
at these sizes and are out of scope until a model shows the gap.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

GROUP = 4  # 2:4 sparsity operates on stripes of 4 input channels


# --- retention metric ---------------------------------------------------------

def sum_after_2_to_4(matrix: jax.Array) -> jax.Array:
    """Total magnitude kept if a 2:4 mask were applied to ``matrix`` (rows x
    cols); the search objective (``permutation_utilities.py:49-81``)."""
    r, c = matrix.shape
    g = jnp.abs(matrix).reshape(r, c // GROUP, GROUP)
    top2 = jax.lax.top_k(g, 2)[0]
    return jnp.sum(top2)


# --- greedy swap search (any C) ----------------------------------------------

def _swap_improvements(matrix: jax.Array) -> jax.Array:
    """(C, C) matrix of retention deltas for swapping columns i and j."""
    r, c = matrix.shape
    ns = c // GROUP
    w = jnp.abs(matrix).astype(jnp.float32)  # (R, C)
    g = w.reshape(r, ns, GROUP)

    # per (row, column): top-2 sum and 2nd-largest of the 3 *other* columns
    # in its stripe (drop one member at a time)
    # others: (R, ns, GROUP(dropped), GROUP-1)
    idx = np.array([[k for k in range(GROUP) if k != d] for d in range(GROUP)])
    others = g[:, :, idx]  # (R, ns, GROUP, 3)
    o_sorted = jnp.sort(others, axis=-1)[..., ::-1]
    t2 = (o_sorted[..., 0] + o_sorted[..., 1]).reshape(r, c)  # (R, C)
    s2 = o_sorted[..., 1].reshape(r, c)

    # stripe retention per row, broadcast to columns
    stripe_ret = jnp.sum(jax.lax.top_k(g, 2)[0], axis=-1)  # (R, ns)
    ret_of_col_stripe = jnp.repeat(stripe_ret, GROUP, axis=1)  # (R, C)

    # M[i, j] = sum_r relu(|w[r, j]| - s2[r, i]): retention of stripe(i)
    # with column i replaced by column j, minus the constant t2 part.
    # One broadcasted contraction — this is the "all swaps at once" step.
    M = jnp.sum(jax.nn.relu(w[:, None, :] - s2[:, :, None]), axis=0)  # (C, C)
    T2 = jnp.sum(t2, axis=0)  # (C,)
    R_i = jnp.sum(ret_of_col_stripe, axis=0)  # (C,)

    new_i = T2[:, None] + M          # stripe(i) after i -> j
    new_j = T2[None, :] + M.T        # stripe(j) after j -> i
    delta = new_i + new_j - R_i[:, None] - R_i[None, :]

    # swaps within a stripe change nothing; mask them (and the diagonal)
    stripe_id = jnp.arange(c) // GROUP
    same = stripe_id[:, None] == stripe_id[None, :]
    return jnp.where(same, -jnp.inf, delta)


# module-scope wrapper so every search shares one trace cache (apexlint
# APX106: a per-call jax.jit(...) re-wraps and retraces every invocation)
_score_improvements = jax.jit(_swap_improvements)


def greedy_swap_search(
    matrix: jax.Array, *, max_sweeps: int = 256, tol: float = 1e-6,
) -> Tuple[np.ndarray, float]:
    """Greedy best-swap descent; returns (permutation, improvement).

    Host-side loop over device-evaluated sweeps: each sweep scores all C^2
    swaps at once and applies the best. Converges when no swap improves —
    same fixed point as the reference's bounded-window search escaping via
    ``try_swap`` (``permutation_utilities.py:83-102``), with a global window.
    """
    c = matrix.shape[1]
    perm = np.arange(c)
    work = jnp.asarray(matrix, jnp.float32)
    base = float(sum_after_2_to_4(work))

    improvement = 0.0
    for _ in range(max_sweeps):
        delta = _score_improvements(work)
        flat = int(jnp.argmax(delta))
        gain = float(delta.reshape(-1)[flat])
        if not np.isfinite(gain) or gain <= tol:
            break
        i, j = divmod(flat, c)
        perm[[i, j]] = perm[[j, i]]
        work = work.at[:, [i, j]].set(work[:, [j, i]])
        improvement += gain
    return perm, improvement


# --- exhaustive search (small C) ---------------------------------------------

def _canonical_group_assignments(c: int) -> List[np.ndarray]:
    """All unique column->stripe assignments (order inside a stripe and order
    of stripes is irrelevant — ``exhaustive_search.py:17-29``'s canonical
    form). Column 0 is pinned to the first stripe to quotient stripe order."""
    cols = list(range(c))
    perms: List[np.ndarray] = []

    def rec(remaining, groups):
        if not remaining:
            perms.append(np.array([col for grp in groups for col in grp]))
            return
        first, rest = remaining[0], remaining[1:]
        for combo in itertools.combinations(rest, GROUP - 1):
            grp = (first,) + combo
            left = [x for x in rest if x not in combo]
            rec(left, groups + [grp])

    rec(cols, [])
    return perms


def exhaustive_search(matrix: jax.Array) -> Tuple[np.ndarray, float]:
    """Try every unique permutation (C <= 8 in practice; the reference bails
    above ~1e10 combinations, ``exhaustive_search.py:93-99``)."""
    c = matrix.shape[1]
    cands = np.stack(_canonical_group_assignments(c))  # (P, C)
    w = jnp.asarray(matrix, jnp.float32)

    scores = jax.vmap(lambda p: sum_after_2_to_4(w[:, p]))(jnp.asarray(cands))
    best = int(jnp.argmax(scores))
    base = float(sum_after_2_to_4(w))
    return cands[best], float(scores[best]) - base


# --- driver -------------------------------------------------------------------

def search_for_good_permutation(
    matrix: jax.Array, *, max_sweeps: int = 256,
) -> Tuple[np.ndarray, float]:
    """Find an input-channel permutation improving 2:4 magnitude retention.

    Dispatcher in the spirit of ``accelerated_search_for_good_permutation``
    (``call_permutation_search_kernels.py:5``): exhaustive when the space is
    tiny, vectorized greedy otherwise. ``matrix`` is (rows, C) with C the
    channel dim to permute (torch-Linear weights come in as (out, in) —
    permute ``in``). Returns (permutation, retention_improvement).
    """
    c = matrix.shape[1]
    if c % GROUP:
        raise ValueError(f"column count {c} not a multiple of {GROUP}")
    if c <= 8:
        return exhaustive_search(matrix)
    return greedy_swap_search(matrix, max_sweeps=max_sweeps)


def apply_permutation(w: jax.Array, perm: np.ndarray, *, axis: int = -1) -> jax.Array:
    """Permute ``w`` along ``axis`` (the input-channel dim)."""
    return jnp.take(w, jnp.asarray(perm), axis=axis)


def invert_permutation(perm: np.ndarray) -> np.ndarray:
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    return inv
