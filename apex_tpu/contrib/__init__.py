"""Optional extensions — re-design of ``apex.contrib``.

Each submodule is import-on-demand like the reference (whose submodules each
require their own CUDA extension); here they are pure JAX/Pallas and always
available:

* ``contrib.optimizers`` — ZeRO-style distributed optimizers
* ``contrib.multihead_attn`` — self/enc-dec MHA modules (flash-backed)
* ``contrib.fmha`` — fused MHA (alias of flash attention, no seq cap)
* ``contrib.layer_norm`` — FastLayerNorm
* ``contrib.xentropy`` — fused softmax cross-entropy
* ``contrib.focal_loss`` — fused focal loss
* ``contrib.transducer`` — RNN-T joint + loss
* ``contrib.sparsity`` — ASP 2:4 structured sparsity
* ``contrib.groupbn`` — batch-norm over device sub-groups
* ``contrib.bottleneck`` / ``contrib.conv_bias_relu`` — fused conv blocks
"""
