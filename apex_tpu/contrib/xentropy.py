"""Fused softmax cross-entropy (contrib surface).

Re-export of :mod:`apex_tpu.ops.xentropy`, matching
``apex.contrib.xentropy.SoftmaxCrossEntropyLoss``
(``apex/contrib/xentropy/softmax_xentropy.py:4-28``).
"""

from apex_tpu.ops.xentropy import (  # noqa: F401
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)
