"""Optimizer-state sharding over the data-parallel axis (ZeRO).

Re-design of the reference's distributed optimizers
(``apex/contrib/optimizers/distributed_fused_lamb.py:10`` — param flattening
into blocks/chunks/shards, overlapped reduce-scatter during backward, shard
update, (optionally compressed) all-gather; ``distributed_fused_adam.py:9``).

TPU-native shape: the chunked mega-buffer of
:mod:`apex_tpu.optimizers.multi_tensor` partitions its chunk axis evenly over
``dp``. One step is exactly the reference's pipeline, as three XLA
collectives instead of hand-scheduled NCCL groups:

1. ``psum_scatter`` the flat gradient over dp → each device owns 1/dp of the
   (averaged) gradient (the reference's reduce-scatter during backward —
   overlap comes from the XLA scheduler);
2. fused Adam/LAMB update on the local shard (optimizer state m/v lives
   *only* sharded — the ZeRO memory saving);
3. ``all_gather`` the updated parameter shards (the reference's
   e5m2-compressed allgather becomes an optional bf16 cast).

Functions must run inside ``shard_map`` with ``axis_name`` bound. The
returned transformation is optax-shaped (init/update) so it slots into the
same training steps as the single-device fused optimizers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import multi_tensor as mt
from apex_tpu.parallel import mesh as mesh_lib

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ZeroState:
    count: jax.Array
    layout: mt.ChunkLayout
    # each (n_chunks/dp, chunk) fp32 — this rank's local shard. Moments
    # ("m"/"v") always; plus "master" (sharded fp32 master weights) when
    # the params are sub-fp32 (bf16/fp16 training — the reference's
    # mixed-precision DistributedFusedAdam keeps both fp32 and sharded).
    buffers: Dict[str, jax.Array]


class _ZeroOpt(NamedTuple):
    init: Any
    update: Any


def _pad_chunks(buf, dp):
    n = buf.shape[0]
    pad = (-n) % dp
    return jnp.pad(buf, ((0, pad), (0, 0))) if pad else buf


def _local_shard(buf, axis_name):
    """This rank's contiguous chunk-row shard (no comm; params are
    replicated so slicing is free)."""
    dp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    per = buf.shape[0] // dp
    return jax.lax.dynamic_slice_in_dim(buf, rank * per, per, axis=0)


def _make_zero(kernel, state_buffers, *, axis_name, chunk_size,
               all_gather_dtype, grad_reduce_dtype=None):
    if grad_reduce_dtype is not None and jnp.dtype(grad_reduce_dtype) not in (
            jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        raise ValueError(
            f"grad_reduce_dtype must be float32 or bfloat16 (fp16's "
            f"exponent range cannot carry a dp-way sum of loss-scaled "
            f"grads); got {jnp.dtype(grad_reduce_dtype)}")

    def _uniform_dtype(tree):
        dts = {x.dtype for x in jax.tree.leaves(tree)}
        return dts.pop() if len(dts) == 1 else None

    def init(params):
        # flatten_to_chunks upcasts to fp32 (the kernels' MATH_T), so the
        # m/v state is fp32 regardless of param dtype. Sub-fp32 params
        # ADDITIONALLY keep a SHARDED fp32 master copy — the reference's
        # mixed-precision semantics (``distributed_fused_adam.py:9``:
        # fp32 moments + master weights for fp16 training, both
        # 1/dp-sharded); without it the fp32 image would be re-derived
        # from the ROUNDED low-precision params every step. fp32 params
        # carry no master (it would duplicate the shard) — that path is
        # bitwise unchanged from the pre-master implementation.
        buf, layout = mt.flatten_to_chunks(params, mt.make_layout(params, chunk_size))
        dp = jax.lax.axis_size(axis_name)
        local = _local_shard(_pad_chunks(buf, dp), axis_name)
        buffers = {k: jnp.zeros(local.shape, jnp.float32)
                   for k in state_buffers}
        if any(x.dtype != jnp.float32 for x in jax.tree.leaves(params)):
            buffers["master"] = local  # already the fp32 upcast
        return ZeroState(
            count=jnp.zeros((), jnp.int32),
            layout=layout,
            buffers=buffers,
        )

    def update(grads, state, params):
        layout = state.layout
        dp = jax.lax.axis_size(axis_name)
        buffers_in = dict(state.buffers)
        master = buffers_in.pop("master", None)
        # flatten grads in their OWN dtype when it is bf16: the
        # reduce-scatter's wire bytes and staging memory halve, and
        # bf16's fp32-sized exponent range makes the low-precision sum
        # safe. fp16 (tiny exponent range — loss-scaled grads near 65504
        # would overflow a dp-way sum) and mixed/other dtypes keep the
        # fp32 mega-buffer, the pre-r5 behavior. The update math below
        # is fp32 either way. grad_reduce_dtype=jnp.float32 forces the
        # fp32 reduction for bf16 grads too (``allreduce_always_fp32``,
        # ``apex/parallel/distributed.py:166`` — at very large dp the
        # dp-way bf16 sum's rounding may matter more than the wire bytes).
        if grad_reduce_dtype is not None:
            gdt = jnp.dtype(grad_reduce_dtype)
        else:
            gdt = _uniform_dtype(grads)
        if gdt != jnp.bfloat16:
            gdt = jnp.float32
        gbuf, _ = mt.flatten_to_chunks(grads, layout, dtype=gdt)
        gbuf = _pad_chunks(gbuf, dp)

        # 1. reduce-scatter: mean gradient, sharded by chunk rows
        g_local = jax.lax.psum_scatter(
            gbuf, axis_name, scatter_dimension=0, tiled=True
        ).astype(jnp.float32) / dp
        if master is not None:
            # the persistent fp32 masters ARE the params; the replicated
            # low-precision tree never flattens (saves a full fp32
            # mega-buffer per step)
            p_local = master
        else:
            pbuf, _ = mt.flatten_to_chunks(params, layout)
            p_local = _local_shard(_pad_chunks(pbuf, dp), axis_name)

        # 2. fused update on the local fp32 shard
        count = state.count + 1
        new_p_local, new_buffers = kernel(
            g_local, p_local, buffers_in, count, layout, axis_name
        )
        if master is not None:
            new_buffers = dict(new_buffers, master=new_p_local)

        # 3. all-gather updated shards (optionally reduced precision, the
        # e5m2_allgather analog). With fp32 masters the gather defaults to
        # the PARAM dtype — params are the low-precision image of the
        # sharded masters, and the wire carries param-width bytes.
        gather_dt = all_gather_dtype or (
            _uniform_dtype(params) if master is not None else None)
        send = new_p_local.astype(gather_dt) if gather_dt else new_p_local
        full = jax.lax.all_gather(send, axis_name, axis=0, tiled=True)
        full = full.astype(jnp.float32)[: gbuf.shape[0]]

        new_params = mt.unflatten_from_chunks(full, layout, like=params)
        updates = jax.tree.map(lambda n, p: n - p.astype(n.dtype), new_params, params)
        return updates, ZeroState(count=count, layout=layout, buffers=new_buffers)

    return _ZeroOpt(init=init, update=update)


def distributed_fused_adam(
    learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
    adam_w_mode: bool = True, *, axis_name: str = mesh_lib.DATA_AXIS,
    chunk_size: int = mt.DEFAULT_CHUNK, all_gather_dtype=None,
    grad_reduce_dtype=None,
):
    """ZeRO Adam (``DistributedFusedAdam``, ``distributed_fused_adam.py:9``):
    m/v exist only as 1/dp shards."""

    def kernel(g, p, buffers, count, layout, axis):
        m, v = buffers["m"], buffers["v"]
        step = count.astype(jnp.float32)
        if not adam_w_mode and weight_decay:
            g = g + weight_decay * p
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** step)
        v_hat = v / (1 - b2 ** step)
        upd = m_hat / (jnp.sqrt(v_hat) + eps)
        if adam_w_mode and weight_decay:
            upd = upd + weight_decay * p
        lr = learning_rate(count - 1) if callable(learning_rate) else learning_rate
        return p - lr * upd, {"m": m, "v": v}

    return _make_zero(kernel, ("m", "v"), axis_name=axis_name,
                      chunk_size=chunk_size, all_gather_dtype=all_gather_dtype,
                      grad_reduce_dtype=grad_reduce_dtype)


def distributed_fused_lamb(
    learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.01,
    max_grad_norm: Optional[float] = None, *, axis_name: str = mesh_lib.DATA_AXIS,
    chunk_size: int = mt.DEFAULT_CHUNK, all_gather_dtype=None,
    grad_reduce_dtype=None,
):
    """ZeRO LAMB (``DistributedFusedLAMB``, ``distributed_fused_lamb.py:10``):
    per-tensor trust ratios from cross-shard psum'd norms, optional global
    grad-norm clip (the reference's fused L2-norm clipping)."""

    def kernel(g, p, buffers, count, layout, axis):
        m, v = buffers["m"], buffers["v"]
        step = count.astype(jnp.float32)

        if max_grad_norm:
            # global grad norm across every shard
            gsq = jax.lax.psum(jnp.sum(g * g), axis)
            gnorm = jnp.sqrt(gsq)
            g = g * jnp.minimum(1.0, max_grad_norm / jnp.maximum(gnorm, 1e-12))

        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** step)
        v_hat = v / (1 - b2 ** step)
        upd = m_hat / (jnp.sqrt(v_hat) + eps) + weight_decay * p

        # per-tensor norms: local segment partials + psum (each tensor's
        # chunks may live on several shards)
        seg = _local_segment_ids(layout, g.shape[0], axis)
        p_sq = jax.lax.psum(
            jax.ops.segment_sum(jnp.sum(p * p, 1), seg, num_segments=layout.n_tensors + 1),
            axis,
        )
        u_sq = jax.lax.psum(
            jax.ops.segment_sum(jnp.sum(upd * upd, 1), seg, num_segments=layout.n_tensors + 1),
            axis,
        )
        w_norm = jnp.sqrt(p_sq)
        u_norm = jnp.sqrt(u_sq)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        ratio = trust[seg][:, None]
        lr = learning_rate(count - 1) if callable(learning_rate) else learning_rate
        return p - lr * ratio * upd, {"m": m, "v": v}

    return _make_zero(kernel, ("m", "v"), axis_name=axis_name,
                      chunk_size=chunk_size, all_gather_dtype=all_gather_dtype,
                      grad_reduce_dtype=grad_reduce_dtype)


def _local_segment_ids(layout, local_rows, axis):
    """chunk→tensor ids for this rank's shard; padding chunks map to the
    sentinel segment n_tensors."""
    dp = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    full = layout.chunk_to_tensor
    n = full.shape[0]
    pad = (-n) % dp
    padded = jnp.concatenate(
        [full, jnp.full((pad,), layout.n_tensors, full.dtype)]
    ) if pad else full
    return jax.lax.dynamic_slice_in_dim(padded, rank * local_rows, local_rows, 0)


# --- shard import/export views (the checkpoint subsystem's substrate) --------
#
# The training loop holds ZeroState in the "rank-local" layout (each
# device's buffer IS its contiguous chunk-row shard; shard_map round-
# trips it with P() specs). Persistence needs the GLOBAL view — buffers
# stacked rank-major over dp, one dp-independent row space — which is
# exactly one identity shard_map away in either direction. The row math
# lives here next to _pad_chunks so the two can never drift.

def shard_row_range(n_chunks: int, dp: int, rank: int):
    """``(start, stop)`` of ``rank``'s rows in the PADDED global
    chunk-row space at width ``dp`` (the save/restore slicing rule —
    shared with :func:`apex_tpu.ckpt.manifest.shard_rows`)."""
    if dp < 1:
        raise ValueError(f"dp must be >= 1, got {dp}")
    if not 0 <= rank < dp:
        raise ValueError(f"rank {rank} out of range for dp={dp}")
    padded = n_chunks + ((-n_chunks) % dp)
    per = padded // dp
    return rank * per, (rank + 1) * per


def export_zero_shard(state: "ZeroState", rank: int, dp: int):
    """Host-side view of one rank's buffers out of a GATHERED state
    (global ``(padded_rows, chunk)`` buffers): the per-rank writer's
    input. Numpy slices — no copy until the writer serializes."""
    import numpy as np

    n = int(np.shape(state.layout.chunk_to_tensor)[0])
    lo, hi = shard_row_range(n, dp, rank)
    out = {}
    for name, buf in state.buffers.items():
        arr = np.asarray(buf)
        if arr.shape[0] != n + ((-n) % dp):
            raise ValueError(
                f"buffer {name!r} has {arr.shape[0]} rows; a gathered "
                f"state at dp={dp} over n_chunks={n} has "
                f"{n + ((-n) % dp)} — gather_zero_state first")
        out[name] = arr[lo:hi]
    return out


def zero_state_specs(state: "ZeroState", *, gathered: bool,
                     axis_name: str = mesh_lib.DATA_AXIS):
    """The shard_map spec pytree matching ``state``: every leaf
    replicated (``P()``) except the buffers, which are ``P(axis)`` in
    the gathered (global rank-major) view and ``P()`` in the rank-local
    training view."""
    from jax.sharding import PartitionSpec as P

    specs = jax.tree.map(lambda _: P(), state)
    if gathered:
        specs = dataclasses.replace(
            specs, buffers={k: P(axis_name) for k in state.buffers})
    return specs


# the jitted identity-reshard executables, keyed by everything that
# shapes the program: (mesh, axis, direction, state structure, buffer
# names). A per-call jax.jit(shard_map(lambda ...)) would RETRACE on
# every save — compile time would land inside the step window the ckpt
# bench measures as save_overhead_pct. Bounded in practice by the
# handful of (mesh, state-shape) pairs a process ever holds.
_RESHARD_CACHE: Dict[Any, Any] = {}


def _identity_reshard(state: "ZeroState", mesh, axis_name: str,
                      gathered_out: bool) -> "ZeroState":
    key = (mesh, axis_name, gathered_out, jax.tree.structure(state),
           tuple(sorted(state.buffers)))
    fn = _RESHARD_CACHE.get(key)
    if fn is None:
        fn = jax.jit(mesh_lib.shard_map(
            lambda s: s, mesh=mesh,
            in_specs=(zero_state_specs(state, gathered=not gathered_out,
                                       axis_name=axis_name),),
            out_specs=zero_state_specs(state, gathered=gathered_out,
                                       axis_name=axis_name),
        ))
        _RESHARD_CACHE[key] = fn
    return fn(state)


def gather_zero_state(state: "ZeroState", mesh, *,
                      axis_name: str = mesh_lib.DATA_AXIS) -> "ZeroState":
    """Rank-local training layout → GLOBAL view: buffers come back as
    ``(padded_rows, chunk)`` arrays stacked rank-major over ``dp`` (the
    checkpoint saver's input). An identity shard_map — no collective;
    the 'gather' is the output spec. Compiled once per (mesh, state
    shape): repeated saves reuse one executable."""
    return _identity_reshard(state, mesh, axis_name, gathered_out=True)


def scatter_zero_state(state: "ZeroState", mesh, *,
                       axis_name: str = mesh_lib.DATA_AXIS) -> "ZeroState":
    """GLOBAL view → rank-local training layout: each rank slices its
    contiguous chunk-row shard (the restore path's last hop). Inverse
    of :func:`gather_zero_state`; same one-executable caching."""
    return _identity_reshard(state, mesh, axis_name, gathered_out=False)


# class-style aliases (reference constructor surface)
DistributedFusedAdam = distributed_fused_adam
DistributedFusedLAMB = distributed_fused_lamb
