"""Optimizer-state sharding over the data-parallel axis (ZeRO).

Re-design of the reference's distributed optimizers
(``apex/contrib/optimizers/distributed_fused_lamb.py:10`` — param flattening
into blocks/chunks/shards, overlapped reduce-scatter during backward, shard
update, (optionally compressed) all-gather; ``distributed_fused_adam.py:9``).

TPU-native shape: the chunked mega-buffer of
:mod:`apex_tpu.optimizers.multi_tensor` partitions its chunk axis evenly over
``dp``. One step is exactly the reference's pipeline, as three XLA
collectives instead of hand-scheduled NCCL groups:

1. ``psum_scatter`` the flat gradient over dp → each device owns 1/dp of the
   (averaged) gradient (the reference's reduce-scatter during backward —
   overlap comes from the XLA scheduler);
2. fused Adam/LAMB update on the local shard (optimizer state m/v lives
   *only* sharded — the ZeRO memory saving);
3. ``all_gather`` the updated parameter shards (the reference's
   e5m2-compressed allgather becomes an optional bf16 cast).

Functions must run inside ``shard_map`` with ``axis_name`` bound. The
returned transformation is optax-shaped (init/update) so it slots into the
same training steps as the single-device fused optimizers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import multi_tensor as mt
from apex_tpu.parallel import mesh as mesh_lib

PyTree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ZeroState:
    count: jax.Array
    layout: mt.ChunkLayout
    buffers: Dict[str, jax.Array]  # each (n_chunks/dp, chunk) — local shard


class _ZeroOpt(NamedTuple):
    init: Any
    update: Any


def _pad_chunks(buf, dp):
    n = buf.shape[0]
    pad = (-n) % dp
    return jnp.pad(buf, ((0, pad), (0, 0))) if pad else buf


def _local_shard(buf, axis_name):
    """This rank's contiguous chunk-row shard (no comm; params are
    replicated so slicing is free)."""
    dp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    per = buf.shape[0] // dp
    return jax.lax.dynamic_slice_in_dim(buf, rank * per, per, axis=0)


def _make_zero(kernel, state_buffers, *, axis_name, chunk_size, all_gather_dtype):
    def init(params):
        buf, layout = mt.flatten_to_chunks(params, mt.make_layout(params, chunk_size))
        dp = jax.lax.axis_size(axis_name)
        local = _local_shard(_pad_chunks(buf, dp), axis_name)
        return ZeroState(
            count=jnp.zeros((), jnp.int32),
            layout=layout,
            buffers={k: jnp.zeros_like(local) for k in state_buffers},
        )

    def update(grads, state, params):
        layout = state.layout
        dp = jax.lax.axis_size(axis_name)
        gbuf, _ = mt.flatten_to_chunks(grads, layout)
        pbuf, _ = mt.flatten_to_chunks(params, layout)
        gbuf, pbuf = _pad_chunks(gbuf, dp), _pad_chunks(pbuf, dp)

        # 1. reduce-scatter: mean gradient, sharded by chunk rows
        g_local = jax.lax.psum_scatter(
            gbuf, axis_name, scatter_dimension=0, tiled=True
        ) / dp
        p_local = _local_shard(pbuf, axis_name)

        # 2. fused update on the local shard
        count = state.count + 1
        new_p_local, new_buffers = kernel(
            g_local, p_local, state.buffers, count, layout, axis_name
        )

        # 3. all-gather updated shards (optionally reduced precision, the
        # e5m2_allgather analog)
        send = new_p_local.astype(all_gather_dtype) if all_gather_dtype else new_p_local
        full = jax.lax.all_gather(send, axis_name, axis=0, tiled=True)
        full = full.astype(jnp.float32)[: gbuf.shape[0]]

        new_params = mt.unflatten_from_chunks(full, layout, like=params)
        updates = jax.tree.map(lambda n, p: n - p.astype(n.dtype), new_params, params)
        return updates, ZeroState(count=count, layout=layout, buffers=new_buffers)

    return _ZeroOpt(init=init, update=update)


def distributed_fused_adam(
    learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
    adam_w_mode: bool = True, *, axis_name: str = mesh_lib.DATA_AXIS,
    chunk_size: int = mt.DEFAULT_CHUNK, all_gather_dtype=None,
):
    """ZeRO Adam (``DistributedFusedAdam``, ``distributed_fused_adam.py:9``):
    m/v exist only as 1/dp shards."""

    def kernel(g, p, buffers, count, layout, axis):
        m, v = buffers["m"], buffers["v"]
        step = count.astype(jnp.float32)
        if not adam_w_mode and weight_decay:
            g = g + weight_decay * p
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** step)
        v_hat = v / (1 - b2 ** step)
        upd = m_hat / (jnp.sqrt(v_hat) + eps)
        if adam_w_mode and weight_decay:
            upd = upd + weight_decay * p
        lr = learning_rate(count - 1) if callable(learning_rate) else learning_rate
        return p - lr * upd, {"m": m, "v": v}

    return _make_zero(kernel, ("m", "v"), axis_name=axis_name,
                      chunk_size=chunk_size, all_gather_dtype=all_gather_dtype)


def distributed_fused_lamb(
    learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.01,
    max_grad_norm: Optional[float] = None, *, axis_name: str = mesh_lib.DATA_AXIS,
    chunk_size: int = mt.DEFAULT_CHUNK, all_gather_dtype=None,
):
    """ZeRO LAMB (``DistributedFusedLAMB``, ``distributed_fused_lamb.py:10``):
    per-tensor trust ratios from cross-shard psum'd norms, optional global
    grad-norm clip (the reference's fused L2-norm clipping)."""

    def kernel(g, p, buffers, count, layout, axis):
        m, v = buffers["m"], buffers["v"]
        step = count.astype(jnp.float32)

        if max_grad_norm:
            # global grad norm across every shard
            gsq = jax.lax.psum(jnp.sum(g * g), axis)
            gnorm = jnp.sqrt(gsq)
            g = g * jnp.minimum(1.0, max_grad_norm / jnp.maximum(gnorm, 1e-12))

        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** step)
        v_hat = v / (1 - b2 ** step)
        upd = m_hat / (jnp.sqrt(v_hat) + eps) + weight_decay * p

        # per-tensor norms: local segment partials + psum (each tensor's
        # chunks may live on several shards)
        seg = _local_segment_ids(layout, g.shape[0], axis)
        p_sq = jax.lax.psum(
            jax.ops.segment_sum(jnp.sum(p * p, 1), seg, num_segments=layout.n_tensors + 1),
            axis,
        )
        u_sq = jax.lax.psum(
            jax.ops.segment_sum(jnp.sum(upd * upd, 1), seg, num_segments=layout.n_tensors + 1),
            axis,
        )
        w_norm = jnp.sqrt(p_sq)
        u_norm = jnp.sqrt(u_sq)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        ratio = trust[seg][:, None]
        lr = learning_rate(count - 1) if callable(learning_rate) else learning_rate
        return p - lr * ratio * upd, {"m": m, "v": v}

    return _make_zero(kernel, ("m", "v"), axis_name=axis_name,
                      chunk_size=chunk_size, all_gather_dtype=all_gather_dtype)


def _local_segment_ids(layout, local_rows, axis):
    """chunk→tensor ids for this rank's shard; padding chunks map to the
    sentinel segment n_tensors."""
    dp = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    full = layout.chunk_to_tensor
    n = full.shape[0]
    pad = (-n) % dp
    padded = jnp.concatenate(
        [full, jnp.full((pad,), layout.n_tensors, full.dtype)]
    ) if pad else full
    return jax.lax.dynamic_slice_in_dim(padded, rank * local_rows, local_rows, 0)


# class-style aliases (reference constructor surface)
DistributedFusedAdam = distributed_fused_adam
DistributedFusedLAMB = distributed_fused_lamb
