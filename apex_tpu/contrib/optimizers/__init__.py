"""ZeRO-style distributed optimizers.

Re-design of ``apex.contrib.optimizers.DistributedFusedAdam`` /
``DistributedFusedLAMB`` (``apex/contrib/optimizers/distributed_fused_adam.py:9``,
``distributed_fused_lamb.py:10``).
"""

from apex_tpu.contrib.optimizers.distributed import (  # noqa: F401
    DistributedFusedAdam,
    DistributedFusedLAMB,
    distributed_fused_adam,
    distributed_fused_lamb,
)
