"""ZeRO-style distributed optimizers.

Re-design of ``apex.contrib.optimizers.DistributedFusedAdam`` /
``DistributedFusedLAMB`` (``apex/contrib/optimizers/distributed_fused_adam.py:9``,
``distributed_fused_lamb.py:10``).
"""

from apex_tpu.contrib.optimizers.distributed import (  # noqa: F401
    DistributedFusedAdam,
    DistributedFusedLAMB,
    distributed_fused_adam,
    distributed_fused_lamb,
)

# The reference also carries deprecated pre-`apex.optimizers` copies here
# (``apex/contrib/optimizers/fused_adam.py`` etc., kept for old import
# paths) and a contrib FP16_Optimizer for them
# (``contrib/optimizers/fp16_optimizer.py:4``). One implementation serves
# both import paths in this framework:
from apex_tpu.fp16_utils import FP16_Optimizer  # noqa: F401
from apex_tpu.optimizers import (  # noqa: F401
    FusedAdam,
    FusedLAMB,
    FusedSGD,
    fused_adam,
    fused_lamb,
    fused_sgd,
)
