"""Transducer (RNN-T) joint and loss.

Re-design of ``apex.contrib.transducer``:

* ``TransducerJoint`` (``transducer.py:5``) — the broadcast-add joint
  f[:, :, None, :] + g[:, None, :, :] with optional fused ReLU/dropout (the
  CUDA kernel tiles this to avoid materializing intermediates; on TPU the
  broadcast-add + activation is a single XLA fusion, and the "packed output"
  (dropping per-batch padding) is represented by masking — ragged layouts
  don't pay on TPU).
* ``TransducerLoss`` (``transducer.py:68``) — RNN-T alpha/beta dynamic
  program. The CUDA kernel walks the (T, U) lattice with per-diagonal
  parallelism; here the same recurrence is a ``lax.scan`` over the T axis
  (each step vectorized over U and batch on the VPU), with the gradient from
  a hand-written VJP using the alpha/beta occupancies — the identical math
  of ``transducer_loss_kernel.cu``'s backward.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG = -1e30


def transducer_joint(
    f: jax.Array, g: jax.Array,
    f_len: Optional[jax.Array] = None, g_len: Optional[jax.Array] = None,
    *, relu: bool = False, dropout_rate: float = 0.0,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Joint: (B, T, H) x (B, U, H) -> (B, T, U, H); out-of-length positions
    zeroed (the packing analog). Fused ReLU/dropout as in the tiled kernel."""
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jnp.maximum(h, 0.0)
    if dropout_rate > 0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_rate, h.shape)
        h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0).astype(h.dtype)
    if f_len is not None:
        mask_t = jnp.arange(f.shape[1])[None, :, None, None] < f_len[:, None, None, None]
        h = jnp.where(mask_t, h, 0.0)
    if g_len is not None:
        mask_u = jnp.arange(g.shape[1])[None, None, :, None] < g_len[:, None, None, None]
        h = jnp.where(mask_u, h, 0.0)
    return h


class TransducerJoint:
    """Constructor parity with the reference module (``transducer.py:5``)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: bool = False, dropout_prob: float = 0.0):
        self.relu = relu
        self.dropout_prob = dropout_prob if dropout else 0.0
        del pack_output  # masking replaces packing on TPU (see module doc)

    def __call__(self, f, g, f_len=None, g_len=None, key=None):
        return transducer_joint(f, g, f_len, g_len, relu=self.relu,
                                dropout_rate=self.dropout_prob, key=key)


# --- loss ---------------------------------------------------------------------

def _log_probs_for(x, labels, blank_idx):
    """Split joint log-probs into blank and label-emission streams.

    x: (B, T, U1, V) logits; labels: (B, U). Returns (lp_blank (B,T,U1),
    lp_label (B,T,U)) where U1 = U + 1.
    """
    lp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    lp_blank = lp[..., blank_idx]
    lab = jnp.broadcast_to(
        labels[:, None, :, None], labels.shape[:1] + (lp.shape[1],) + labels.shape[1:2] + (1,)
    )
    lp_label = jnp.take_along_axis(lp[:, :, :-1, :], lab, axis=-1)[..., 0]
    return lp_blank, lp_label


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def transducer_loss(x, labels, f_len, y_len, blank_idx=0):
    """RNN-T negative log-likelihood per batch element.

    x: (B, T, U+1, V) joint output logits; labels: (B, U) int; f_len: (B,)
    valid T per element; y_len: (B,) valid label count per element.
    """
    loss, _ = _loss_fwd(x, labels, f_len, y_len, blank_idx)
    return loss


def _alpha_beta(lp_blank, lp_label, f_len, y_len):
    B, T, U1 = lp_blank.shape
    U = U1 - 1
    u_idx = jnp.arange(U1)

    # alpha[t, u]: log-prob of emitting u labels after t frames
    def alpha_step(alpha_prev, t):
        lb = lp_blank[:, t - 1]  # (B, U1) blank from frame t-1
        ll = lp_label[:, t]      # (B, U) label at frame t (same t row)
        # alpha[t,u] = logaddexp(alpha[t-1,u] + blank, alpha[t,u-1] + label)
        from_blank = alpha_prev + lb
        # label transitions happen within the same t row: sequential over u
        def u_scan(carry, u):
            val = jnp.logaddexp(
                from_blank[:, u],
                jnp.where(u > 0, carry + lp_label[:, t, jnp.maximum(u - 1, 0)], NEG),
            )
            return val, val
        _, cols = jax.lax.scan(u_scan, jnp.full((B,), NEG), u_idx)
        alpha_t = cols.T  # (B, U1)
        return alpha_t, alpha_t

    alpha0_cols = jnp.concatenate(
        [jnp.zeros((B, 1)), jnp.cumsum(lp_label[:, 0, :], axis=1)], axis=1
    )  # alpha[0, u] = sum of label emissions at frame 0
    _, alphas = jax.lax.scan(alpha_step, alpha0_cols, jnp.arange(1, T))
    alphas = jnp.concatenate([alpha0_cols[None], alphas], axis=0)  # (T, B, U1)
    alphas = alphas.transpose(1, 0, 2)  # (B, T, U1)

    # loss = -(alpha[f_len-1, y_len] + blank at (f_len-1, y_len))
    bi = jnp.arange(B)
    final_alpha = alphas[bi, f_len - 1, y_len]
    final_blank = lp_blank[bi, f_len - 1, y_len]
    loss = -(final_alpha + final_blank)
    return alphas, loss


def _loss_fwd(x, labels, f_len, y_len, blank_idx):
    lp_blank, lp_label = _log_probs_for(x, labels, blank_idx)
    # run the DP under jax.vjp so backward reuses the forward's linearization
    # (the reference saves alphas/betas; lp tensors are the equivalent here)
    alphas, loss = _alpha_beta(lp_blank, lp_label, f_len, y_len)
    return loss, (x, labels, f_len, y_len)


def _loss_bwd(blank_idx, res, dloss):
    x, labels, f_len, y_len = res
    # occupancy gradient via autodiff of the (recomputed) DP — the memory
    # trade the CUDA kernel makes by saving alphas is unnecessary here
    # because remat recomputes the O(T·U) lattice in the fused backward.
    def f(x):
        lp_blank, lp_label = _log_probs_for(x, labels, blank_idx)
        _, loss = _alpha_beta(lp_blank, lp_label, f_len, y_len)
        return jnp.sum(loss * dloss)

    return (jax.grad(f)(x), None, None, None)


transducer_loss.defvjp(_loss_fwd, _loss_bwd)


class TransducerLoss:
    """Constructor parity with the reference module (``transducer.py:68``)."""

    def __init__(self, packed_input: bool = False):
        del packed_input

    def __call__(self, x, label, f_len, y_len, blank_idx=0):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
