"""RNN-T transducer joint + loss.

Re-design of ``apex.contrib.transducer`` (``apex/contrib/transducer/transducer.py:5,68``;
kernels ``transducer_joint_kernel.cu``, ``transducer_loss_kernel.cu``).
"""

from apex_tpu.contrib.transducer.transducer import (  # noqa: F401
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
)
