"""Fused ResNet bottleneck + spatial-parallel variant.

Re-design of ``apex.contrib.bottleneck``
(``apex/contrib/bottleneck/bottleneck.py:112`` ``Bottleneck``, ``:386``
``SpatialBottleneck``). The plain bottleneck is the fused conv/BN/add/relu
chain (XLA fuses the epilogues the cudnn-frontend graph encodes);
``SpatialBottleneck`` splits the spatial H dimension over a mesh axis with
halo exchange for the 3x3 conv — the reference does the halo transfer with
peer-to-peer CUDA memcpy, here it is a pair of ``ppermute`` neighbor
exchanges.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models.resnet import ResNet50  # re-used bottleneck math
from apex_tpu.parallel.sync_batchnorm import BatchNormState, sync_batch_norm


def halo_exchange(x: jax.Array, axis_name: str, halo: int = 1) -> jax.Array:
    """Pad the local H shard with `halo` rows from ring neighbors
    (``SpatialBottleneck``'s P2P halo transfer, ``bottleneck.py:386+``).
    x: (N, H_local, W, C) → (N, H_local + 2*halo, W, C); edge shards get
    zero halos (SAME-padding semantics at the global boundary)."""
    size = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    up = [(i, (i - 1) % size) for i in range(size)]    # send top rows upward
    down = [(i, (i + 1) % size) for i in range(size)]  # send bottom rows downward
    top_rows = x[:, :halo]
    bottom_rows = x[:, -halo:]
    from_below = jax.lax.ppermute(top_rows, axis_name, up)      # arrives at rank-1
    from_above = jax.lax.ppermute(bottom_rows, axis_name, down)  # arrives at rank+1
    zero = jnp.zeros_like(top_rows)
    from_above = jnp.where(rank == 0, zero, from_above)
    from_below = jnp.where(rank == size - 1, zero, from_below)
    return jnp.concatenate([from_above, x, from_below], axis=1)


def _halo_from_above(x: jax.Array, axis_name: str, halo: int = 1) -> jax.Array:
    """Prepend ``halo`` rows from the previous shard (zeros on shard 0)."""
    size = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    down = [(i, (i + 1) % size) for i in range(size)]
    from_above = jax.lax.ppermute(x[:, -halo:], axis_name, down)
    from_above = jnp.where(rank == 0, jnp.zeros_like(from_above), from_above)
    return jnp.concatenate([from_above, x], axis=1)


def spatial_conv3x3(x, w, axis_name: str, stride: int = 1):
    """3x3 conv over an H-sharded activation: halo-exchange then VALID conv
    over the padded shard — equivalent to the unsharded symmetric-pad conv.

    stride=1: one halo row from each neighbor.
    stride=2 (the strided window-phase handling of the reference's
    ``SpatialBottleneck``, ``bottleneck.py:386+``): with symmetric (1,1)
    padding, local output row j reads local input rows 2j-1..2j+1, so only a
    *top* halo row is needed and the stride-2 VALID conv over
    [above_row, local rows] reproduces the global phase exactly. Requires an
    even local H so shard output boundaries land on stride multiples.
    """
    if stride == 1:
        xp = halo_exchange(x, axis_name, halo=1)
        return jax.lax.conv_general_dilated(
            xp, w, (1, 1), ((0, 0), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )[:, : x.shape[1]]
    if stride == 2:
        if x.shape[1] % 2:
            raise ValueError(
                f"stride-2 spatial conv needs an even local H, got {x.shape[1]}"
            )
        xp = _halo_from_above(x, axis_name, halo=1)  # (N, H_local+1, W, C)
        return jax.lax.conv_general_dilated(
            xp, w, (2, 2), ((0, 0), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )[:, : x.shape[1] // 2]
    raise NotImplementedError(f"spatial conv stride {stride} (1 or 2 only)")


class Bottleneck:
    """Single fused bottleneck block with the torchvision/apex layout
    (``bottleneck.py:112``): 1x1 → 3x3(stride) → 1x1 with BN+ReLU epilogues
    and the fused residual add."""

    def __init__(self, in_channels: int, bottleneck_channels: int,
                 out_channels: int, stride: int = 1):
        self.in_channels = in_channels
        self.bottleneck_channels = bottleneck_channels
        self.out_channels = out_channels
        self.stride = stride

    def init(self, key, dtype=jnp.float32):
        from apex_tpu.models.resnet import _conv_init
        ks = jax.random.split(key, 4)
        p = {
            "conv_a": _conv_init(ks[0], (1, 1, self.in_channels, self.bottleneck_channels), dtype),
            "conv_b": _conv_init(ks[1], (3, 3, self.bottleneck_channels, self.bottleneck_channels), dtype),
            "conv_c": _conv_init(ks[2], (1, 1, self.bottleneck_channels, self.out_channels), dtype),
        }
        st = {}
        for name, ch in (("bn_a", self.bottleneck_channels),
                         ("bn_b", self.bottleneck_channels),
                         ("bn_c", self.out_channels)):
            p[name] = {"scale": jnp.ones((ch,), dtype), "bias": jnp.zeros((ch,), dtype)}
            st[name] = BatchNormState.create(ch)
        if self.stride != 1 or self.in_channels != self.out_channels:
            p["conv_proj"] = _conv_init(ks[3], (1, 1, self.in_channels, self.out_channels), dtype)
            p["bn_proj"] = {"scale": jnp.ones((self.out_channels,), dtype),
                            "bias": jnp.zeros((self.out_channels,), dtype)}
            st["bn_proj"] = BatchNormState.create(self.out_channels)
        return p, st

    def __call__(self, params, state, x, *, training: bool = True,
                 spatial_axis: Optional[str] = None):
        def bn(p, st, h, residual=None, relu=True):
            return sync_batch_norm(h, p["scale"], p["bias"], st, training=training,
                                   axis_name=None, fuse_relu=relu, residual=residual)

        new_st = {}
        conv = lambda h, w, s=1: jax.lax.conv_general_dilated(
            h, w, (s, s),
            ((w.shape[0] // 2,) * 2, (w.shape[1] // 2,) * 2),  # torch symmetric
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        identity = x
        h = conv(x, params["conv_a"])
        h, new_st["bn_a"] = bn(params["bn_a"], state["bn_a"], h)
        if spatial_axis is not None:
            h = spatial_conv3x3(h, params["conv_b"], spatial_axis, self.stride)
        else:
            h = conv(h, params["conv_b"], self.stride)
        h, new_st["bn_b"] = bn(params["bn_b"], state["bn_b"], h)
        h = conv(h, params["conv_c"])
        if "conv_proj" in params:
            identity = conv(x, params["conv_proj"], self.stride)
            identity, new_st["bn_proj"] = bn(
                params["bn_proj"], state["bn_proj"], identity, relu=False)
        h, new_st["bn_c"] = bn(params["bn_c"], state["bn_c"], h, residual=identity)
        return h, new_st


class SpatialBottleneck(Bottleneck):
    """H-sharded bottleneck (``bottleneck.py:386``): run inside shard_map
    with the spatial axis bound; the 3x3 conv halo-exchanges."""

    def __init__(self, *args, spatial_axis: str = "cp", **kw):
        super().__init__(*args, **kw)
        self.spatial_axis = spatial_axis

    def __call__(self, params, state, x, *, training: bool = True):
        return super().__call__(params, state, x, training=training,
                                spatial_axis=self.spatial_axis)
