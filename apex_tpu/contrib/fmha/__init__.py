"""FMHA — fused multi-head attention core.

Re-design of ``apex.contrib.fmha`` (``apex/contrib/fmha/fmha.py:33-76``).
The reference dispatches per-seqlen CUDA kernels valid only for fp16,
seq ∈ {128,256,384,512}, head_dim 64 on SM80; here it is simply the
blockwise flash kernel with none of those caps. The packed
(total_tokens, ...) varlen interface is emulated by segment masking.
"""

import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention


class FMHAFun:
    """API-shape parity with the reference's autograd function."""

    @staticmethod
    def apply(qkv, causal=False):
        """qkv: (batch, seq, 3, heads, head_dim) — the reference's packed
        layout (fmha.py:60-76)."""
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)
        k = qkv[:, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, 2].transpose(0, 2, 1, 3)
        o = flash_attention(q, k, v, causal=causal)
        return o.transpose(0, 2, 1, 3)


def fmha(qkv, causal: bool = False):
    return FMHAFun.apply(qkv, causal)


class FMHA:
    """Module-shape parity with the reference's ``FMHA`` wrapper
    (``apex/contrib/fmha/fmha.py:60-76``) — minus its seq<=512 / fp16 /
    SM80 restrictions, which the flash kernel does not have."""

    def __init__(self, causal: bool = False):
        self.causal = causal

    def __call__(self, qkv):
        return FMHAFun.apply(qkv, self.causal)
