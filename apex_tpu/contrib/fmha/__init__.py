"""FMHA — fused multi-head attention core.

Re-design of ``apex.contrib.fmha`` (``apex/contrib/fmha/fmha.py:33-76``).
The reference dispatches per-seqlen CUDA kernels valid only for fp16,
seq ∈ {128,256,384,512}, head_dim 64 on SM80; here it is the blockwise
flash kernel with none of those caps. Both reference surfaces exist:

- :func:`fmha_varlen` — the REAL reference interface: token-packed
  ``(total_tokens, 3, heads, head_dim)`` qkv with ``cu_seqlens``
  boundaries (BERT-style unpadded batching, ``fmha.py:35``) and
  in-kernel probs dropout (``p_dropout``). Internally the pack is
  scattered to the seq-major padded layout whose per-batch ``kv_lens``
  the kernels mask and block-skip natively, then gathered back — the
  scatter/gather is O(total·h·d) elementwise against the kernel's
  O(total·s) attention work.
- :func:`fmha` — the padded ``(batch, seq, 3, heads, head_dim)`` layout
  (no cu_seqlens needed when rows are equal length).
"""

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention, seed_from_key


def _unpack_indices(cu_seqlens, total):
    """(segment id, within-segment position) for each packed token."""
    tok = jnp.arange(total, dtype=jnp.int32)
    seg = jnp.searchsorted(cu_seqlens[1:], tok, side="right").astype(jnp.int32)
    pos = tok - cu_seqlens[seg]
    return seg, pos


def fmha_varlen(qkv, cu_seqlens, max_s: int, p_dropout: float = 0.0,
                is_training: bool = True, causal: bool = False,
                key: Optional[jax.Array] = None):
    """``FMHAFun.apply(qkv, cu_seqlens, p_dropout, max_s, is_training)``
    (``fmha.py:35-46``): qkv ``(total_tokens, 3, h, d)`` packed over
    variable-length batch rows, ``cu_seqlens`` ``(batch+1,)`` int32
    cumulative row boundaries (row r holds tokens
    ``[cu_seqlens[r], cu_seqlens[r+1])``), ``max_s`` the static pad
    length. Returns ``(total_tokens, h, d)``.

    Dropout (``p_dropout`` > 0 with ``is_training`` and a PRNG ``key``)
    is the in-kernel counter-hash probs dropout. Attention is per-row:
    tokens never attend across ``cu_seqlens`` boundaries (the kernels'
    per-batch ``kv_lens`` masking after scattering to the padded
    layout).

    ``max_s`` must be >= the longest row: the scatter into the padded
    (b, max_s, ...) layout DROPS out-of-bounds tokens (JAX scatter
    semantics), silently truncating any row longer than ``max_s``. With a
    concrete ``cu_seqlens`` that is checked eagerly here (raises); when
    ``cu_seqlens`` is traced (inside jit) the check cannot run and the
    truncation hazard is the CALLER's to exclude — pass the true padded
    length, as the reference API requires (``fmha.py:35``)."""
    total, three, h, d = qkv.shape
    if three != 3:
        raise ValueError(f"qkv must be (total, 3, h, d); got {qkv.shape}")
    b = cu_seqlens.shape[0] - 1
    if not isinstance(cu_seqlens, jax.core.Tracer):
        import numpy as np
        row_lens = np.diff(np.asarray(cu_seqlens))
        if row_lens.size and int(row_lens.max()) > max_s:
            raise ValueError(
                f"max_s ({max_s}) is smaller than the longest row "
                f"({int(row_lens.max())}): the padded-layout scatter would "
                f"silently drop that row's tokens past max_s")
    cu_seqlens = cu_seqlens.astype(jnp.int32)
    seg, pos = _unpack_indices(cu_seqlens, total)
    padded = jnp.zeros((b, max_s, 3, h, d), qkv.dtype).at[seg, pos].set(qkv)
    lens = jnp.diff(cu_seqlens)
    rate = float(p_dropout) if is_training else 0.0
    seed = None
    if rate > 0:
        if key is None:
            raise ValueError("p_dropout > 0 with is_training needs a PRNG "
                             "key")
        seed = seed_from_key(key)
    else:
        rate = 0.0
    o = flash_attention(
        padded[:, :, 0], padded[:, :, 1], padded[:, :, 2],
        causal=causal, layout="bshd", kv_lens=lens,
        dropout_rate=rate, dropout_seed=seed)
    return o[seg, pos]


class FMHAFun:
    """API-shape parity with the reference's autograd function."""

    @staticmethod
    def apply(qkv, causal=False):
        """qkv: (batch, seq, 3, heads, head_dim) — the equal-length padded
        layout (``fmha.py:60-76``); varlen batches use
        :func:`fmha_varlen`."""
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)
        k = qkv[:, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, 2].transpose(0, 2, 1, 3)
        o = flash_attention(q, k, v, causal=causal)
        return o.transpose(0, 2, 1, 3)


def fmha(qkv, causal: bool = False):
    return FMHAFun.apply(qkv, causal)


class FMHA:
    """Module-shape parity with the reference's ``FMHA`` wrapper
    (``apex/contrib/fmha/fmha.py:60-76``) — minus its seq<=512 / fp16 /
    SM80 restrictions, which the flash kernel does not have. Takes the
    packed varlen layout like the reference module: ``(total, 3·h·d)``
    flat or ``(total, 3, h, d)``."""

    def __init__(self, num_heads: int, head_dim: int, p_dropout: float = 0.0,
                 causal: bool = False):
        self.h, self.d = num_heads, head_dim
        self.p_dropout = p_dropout
        self.causal = causal

    def __call__(self, qkv, cu_seqlens, max_s: int, is_training: bool = True,
                 key: Optional[jax.Array] = None):
        total = qkv.shape[0]
        o = fmha_varlen(qkv.reshape(total, 3, self.h, self.d), cu_seqlens,
                        max_s, self.p_dropout, is_training,
                        causal=self.causal, key=key)
        return o.reshape(total, self.h * self.d)
