"""Multi-head attention modules.

Re-design of ``apex.contrib.multihead_attn``
(``apex/contrib/multihead_attn/self_multihead_attn.py:27``,
``encdec_multihead_attn.py``): self- and encoder-decoder attention with
optional fused pre-LayerNorm + residual-add (the reference's
``include_norm_add`` variants) and optional biases. The fused CUDA/CUTLASS
cores become one call into the blockwise flash kernel; the
``fast_mask_softmax_dropout`` path corresponds to the fused softmax +
explicit-key dropout here.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.lists import apply_op_rules
from apex_tpu.ops import fused_layer_norm
from apex_tpu.ops.attention import flash_attention, masked_scores


def _linear_init(key, shape, dtype):
    bound = 1.0 / jnp.sqrt(shape[-1])
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def _dropout(x, rate, key):
    if rate <= 0 or key is None:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def _attention(q, k, v, *, causal, rate, key):
    """Attention core. Without dropout (or at eval) this is the flash
    kernel; with probs dropout it is the reference's
    ``fast_mask_softmax_dropout`` semantics (dropout ON the attention
    weights, ``mask_softmax_dropout_func.py``) over materialized probs —
    the flash recurrence cannot drop individual weights."""
    if rate <= 0 or key is None:
        return flash_attention(q, k, v, causal=causal)
    q, k, v = apply_op_rules("attention", q, k, v)
    s = masked_scores(q, k, 1.0 / q.shape[-1] ** 0.5, causal)
    probs = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    probs = _dropout(probs, rate, key)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@dataclasses.dataclass
class SelfMultiheadAttn:
    """``SelfMultiheadAttn`` (``self_multihead_attn.py:27``): fused QKV
    projection, attention core, output projection; ``include_norm_add`` fuses
    a pre-LN and returns (out + residual)."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    separate_qkv_params: bool = False

    @property
    def head_dim(self) -> int:
        assert self.embed_dim % self.num_heads == 0
        return self.embed_dim // self.num_heads

    def init(self, key, dtype=jnp.float32) -> dict:
        k1, k2 = jax.random.split(key)
        e = self.embed_dim
        if self.separate_qkv_params:
            kq, kk, kv = jax.random.split(k1, 3)
            params = {
                "q_weight": _linear_init(kq, (e, e), dtype),
                "k_weight": _linear_init(kk, (e, e), dtype),
                "v_weight": _linear_init(kv, (e, e), dtype),
            }
        else:
            params = {"qkv_weight": _linear_init(k1, (3 * e, e), dtype)}
        params["out_weight"] = _linear_init(k2, (e, e), dtype)
        if self.bias:
            if self.separate_qkv_params:
                params.update(q_bias=jnp.zeros((e,), dtype),
                              k_bias=jnp.zeros((e,), dtype),
                              v_bias=jnp.zeros((e,), dtype))
            else:
                params["qkv_bias"] = jnp.zeros((3 * e,), dtype)
            params["out_bias"] = jnp.zeros((e,), dtype)
        if self.include_norm_add:
            params["ln_weight"] = jnp.ones((e,), dtype)
            params["ln_bias"] = jnp.zeros((e,), dtype)
        return params

    def __call__(self, params, x, *, causal: bool = False,
                 key: Optional[jax.Array] = None, is_training: bool = True):
        """x: (batch, seq, embed). Returns attention output (+ residual when
        include_norm_add)."""
        residual = x
        if self.include_norm_add:
            x = fused_layer_norm(x, params["ln_weight"], params["ln_bias"])
        b, s, e = x.shape
        h, d = self.num_heads, self.head_dim
        if self.separate_qkv_params:
            q = x @ params["q_weight"].T
            kk = x @ params["k_weight"].T
            v = x @ params["v_weight"].T
            if self.bias:
                q, kk, v = q + params["q_bias"], kk + params["k_bias"], v + params["v_bias"]
        else:
            qkv = x @ params["qkv_weight"].T
            if self.bias:
                qkv = qkv + params["qkv_bias"]
            q, kk, v = jnp.split(qkv, 3, axis=-1)

        def split_heads(t):
            return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

        o = _attention(split_heads(q), split_heads(kk), split_heads(v),
                       causal=causal,
                       rate=self.dropout if is_training else 0.0, key=key)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, e)
        o = o @ params["out_weight"].T
        if self.bias:
            o = o + params["out_bias"]
        if self.include_norm_add:
            o = o + residual
        return o


@dataclasses.dataclass
class EncdecMultiheadAttn:
    """``EncdecMultiheadAttn``: Q from the decoder stream, K/V from the
    encoder memory (``encdec_multihead_attn.py``)."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    def init(self, key, dtype=jnp.float32) -> dict:
        k1, k2, k3 = jax.random.split(key, 3)
        e = self.embed_dim
        params = {
            "q_weight": _linear_init(k1, (e, e), dtype),
            "kv_weight": _linear_init(k2, (2 * e, e), dtype),
            "out_weight": _linear_init(k3, (e, e), dtype),
        }
        if self.bias:
            params.update(q_bias=jnp.zeros((e,), dtype),
                          kv_bias=jnp.zeros((2 * e,), dtype),
                          out_bias=jnp.zeros((e,), dtype))
        if self.include_norm_add:
            params["ln_weight"] = jnp.ones((e,), dtype)
            params["ln_bias"] = jnp.zeros((e,), dtype)
        return params

    def __call__(self, params, query, memory, *, key: Optional[jax.Array] = None,
                 is_training: bool = True):
        residual = query
        if self.include_norm_add:
            query = fused_layer_norm(query, params["ln_weight"], params["ln_bias"])
        b, sq, e = query.shape
        sk = memory.shape[1]
        h, d = self.num_heads, self.head_dim
        q = query @ params["q_weight"].T
        kv = memory @ params["kv_weight"].T
        if self.bias:
            q = q + params["q_bias"]
            kv = kv + params["kv_bias"]
        kk, v = jnp.split(kv, 2, axis=-1)
        q = q.reshape(b, sq, h, d).transpose(0, 2, 1, 3)
        kk = kk.reshape(b, sk, h, d).transpose(0, 2, 1, 3)
        v = v.reshape(b, sk, h, d).transpose(0, 2, 1, 3)
        o = _attention(q, kk, v, causal=False,
                       rate=self.dropout if is_training else 0.0, key=key)
        o = o.transpose(0, 2, 1, 3).reshape(b, sq, e)
        o = o @ params["out_weight"].T
        if self.bias:
            o = o + params["out_bias"]
        if self.include_norm_add:
            o = o + residual
        return o
