"""Multi-head attention modules.

Re-design of ``apex.contrib.multihead_attn``
(``apex/contrib/multihead_attn/self_multihead_attn.py:27``,
``encdec_multihead_attn.py``): self- and encoder-decoder attention with
optional fused pre-LayerNorm + residual-add (the reference's
``include_norm_add`` variants), optional biases, additive attention masks
and key-padding masks. Everything — including probs dropout and both mask
families — runs through the blockwise flash kernel: dropout is the
kernel's in-kernel counter-hash dropout, the additive ``attn_mask`` is the
kernel's fused score-bias operand, and ``key_padding_mask`` rides the same
operand per batch (the ``pad_lens`` form keeps the O(rows) varlen fast
path). The reference needs four CUDA variants for this matrix
(``fast_self_multihead_attn{,_bias,_mask,_bias_additive_mask}``,
``self_multihead_attn.py:36-88``); here it is one kernel family with
optional operands.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import fused_layer_norm
from apex_tpu.ops.attention import flash_attention, seed_from_key

# Additive mask value for excluded keys. Finite (not -inf) so a row whose
# keys are ALL padded yields a uniform-softmax output instead of NaN —
# such rows are meaningless either way (the reference NaNs there), but
# finite outputs keep grad pipelines alive when users mask sloppily.
_MASKED = -1e9


def _linear_init(key, shape, dtype):
    bound = 1.0 / jnp.sqrt(shape[-1])
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def _norm_attn_mask(attn_mask, h, sq, sk):
    """Additive attn_mask → the kernel's (hb, sq, sk) bias operand.
    Accepts (sq, sk) shared over batch+heads or (hb, sq, sk) with hb | h
    per-head (broadcast over batch)."""
    if attn_mask.ndim == 2:
        attn_mask = attn_mask[None]
    if attn_mask.ndim != 3 or attn_mask.shape[1:] != (sq, sk) or h % attn_mask.shape[0]:
        raise ValueError(
            f"attn_mask must be (sq, sk) or (hb, sq, sk) with hb | heads; "
            f"got {attn_mask.shape} for h={h}, sq={sq}, sk={sk}")
    return attn_mask


def _attention(q, k, v, *, causal, rate, key, attn_mask=None,
               key_padding_mask=None, pad_lens=None):
    """Attention core over (b, s, h, d) operands — ONE call into the flash
    family for the whole option matrix:

    - probs dropout (``rate`` > 0 with a PRNG ``key``) is IN-KERNEL
      (the reference's fused ``fast_mask_softmax_dropout``); the softmax
      normalizer is pre-dropout, so E[output] = no-dropout output.
    - ``attn_mask``: ADDITIVE (sq, sk) or (hb, sq, sk) score mask →
      the kernel's fused bias operand (``self_multihead_attn.py:144-198``
      additive-mask variants).
    - ``pad_lens`` (b,) int32 valid-key lengths: the varlen fast path —
      O(b) metadata, masked KV blocks skipped in-kernel. The form padded
      batches should use.
    - ``key_padding_mask`` (b, sk) bool/int, nonzero = EXCLUDE (the
      reference's ByteTensor convention): arbitrary per-batch patterns.
      Rides the bias operand with batch-major bias rows: operands are
      flattened HEAD-major (h, b, s, d) so bias row ``t % b`` selects the
      batch — the kernel's modulo row-sharing, unchanged, gives per-batch
      masks. Costs a materialized (b, sq, sk) fp32 mask (the same memory
      class as the reference's (b, 1, sq, sk) mask tensor,
      ``csrc/megatron/scaled_masked_softmax.cpp:85-94``) and two head
      transposes; prefer ``pad_lens`` when padding is a suffix.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    rate = float(rate)
    if rate > 0 and key is None:
        # fmha_varlen parity: training-mode dropout without a key used to
        # silently run dropout-free — a silent train/eval mismatch; fail
        raise ValueError(
            "dropout > 0 with is_training=True needs a PRNG key (pass "
            "key=..., or is_training=False for eval)")
    seed = seed_from_key(key) if rate > 0 else None
    if attn_mask is not None:
        attn_mask = _norm_attn_mask(attn_mask, h, sq, sk)
    if key_padding_mask is not None:
        if pad_lens is not None:
            raise ValueError(
                "key_padding_mask and pad_lens are two spellings of key "
                "padding — pass one (pad_lens is the fast path)")
        if attn_mask is not None:
            # reference parity: self_multihead_attn.py:188 asserts the two
            # are mutually exclusive (pad_lens + attn_mask DO compose)
            raise ValueError(
                "attn_mask and key_padding_mask are mutually exclusive "
                "(use pad_lens for padding composed with attn_mask)")
        if key_padding_mask.shape != (b, sk):
            raise ValueError(
                f"key_padding_mask must be (batch, src_len) = ({b}, {sk}); "
                f"got {key_padding_mask.shape}")
        bias = jnp.broadcast_to(
            jnp.where(key_padding_mask.astype(bool)[:, None, :],
                      jnp.float32(_MASKED), jnp.float32(0)),
            (b, sq, sk))
        # head-major flattening: rows t = h_i·b + b_i, bias row t % b = b_i
        o = flash_attention(
            q.transpose(2, 0, 1, 3), k.transpose(2, 0, 1, 3),
            v.transpose(2, 0, 1, 3), causal=causal, bias=bias,
            dropout_rate=rate, dropout_seed=seed)
        return o.transpose(1, 2, 0, 3)
    if pad_lens is not None:
        pad_lens = jnp.asarray(pad_lens, jnp.int32)
        if pad_lens.shape != (b,):
            raise ValueError(
                f"pad_lens must be per-batch ({b},); got {pad_lens.shape}")
    return flash_attention(q, k, v, causal=causal, layout="bshd",
                           kv_lens=pad_lens, bias=attn_mask,
                           dropout_rate=rate, dropout_seed=seed)


@dataclasses.dataclass
class SelfMultiheadAttn:
    """``SelfMultiheadAttn`` (``self_multihead_attn.py:27``): fused QKV
    projection, attention core, output projection; ``include_norm_add`` fuses
    a pre-LN and returns (out + residual)."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    separate_qkv_params: bool = False

    @property
    def head_dim(self) -> int:
        assert self.embed_dim % self.num_heads == 0
        return self.embed_dim // self.num_heads

    def init(self, key, dtype=jnp.float32) -> dict:
        k1, k2 = jax.random.split(key)
        e = self.embed_dim
        if self.separate_qkv_params:
            kq, kk, kv = jax.random.split(k1, 3)
            params = {
                "q_weight": _linear_init(kq, (e, e), dtype),
                "k_weight": _linear_init(kk, (e, e), dtype),
                "v_weight": _linear_init(kv, (e, e), dtype),
            }
        else:
            params = {"qkv_weight": _linear_init(k1, (3 * e, e), dtype)}
        params["out_weight"] = _linear_init(k2, (e, e), dtype)
        if self.bias:
            if self.separate_qkv_params:
                params.update(q_bias=jnp.zeros((e,), dtype),
                              k_bias=jnp.zeros((e,), dtype),
                              v_bias=jnp.zeros((e,), dtype))
            else:
                params["qkv_bias"] = jnp.zeros((3 * e,), dtype)
            params["out_bias"] = jnp.zeros((e,), dtype)
        if self.include_norm_add:
            params["ln_weight"] = jnp.ones((e,), dtype)
            params["ln_bias"] = jnp.zeros((e,), dtype)
        return params

    def __call__(self, params, x, *, causal: bool = False,
                 attn_mask: Optional[jax.Array] = None,
                 key_padding_mask: Optional[jax.Array] = None,
                 pad_lens: Optional[jax.Array] = None,
                 key: Optional[jax.Array] = None, is_training: bool = True):
        """x: (batch, seq, embed). Returns attention output (+ residual when
        include_norm_add).

        ``attn_mask``: additive (sq, sk) or (hb, sq, sk) score mask (fused
        into the kernel). ``key_padding_mask``: (batch, src_len), nonzero =
        exclude that key (reference ByteTensor semantics,
        ``self_multihead_attn.py:144-151``); mutually exclusive with
        attn_mask. ``pad_lens``: (batch,) valid key lengths — the varlen
        fast path for suffix padding; composes with attn_mask/causal."""
        residual = x
        if self.include_norm_add:
            x = fused_layer_norm(x, params["ln_weight"], params["ln_bias"])
        b, s, e = x.shape
        h, d = self.num_heads, self.head_dim
        if self.separate_qkv_params:
            q = x @ params["q_weight"].T
            kk = x @ params["k_weight"].T
            v = x @ params["v_weight"].T
            if self.bias:
                q, kk, v = q + params["q_bias"], kk + params["k_bias"], v + params["v_bias"]
        else:
            qkv = x @ params["qkv_weight"].T
            if self.bias:
                qkv = qkv + params["qkv_bias"]
            q, kk, v = jnp.split(qkv, 3, axis=-1)

        # (b, s, h, d) — the seq-major layout the projection GEMMs emit;
        # the kernel's bshd index maps read it with no transpose copies
        o = _attention(q.reshape(b, s, h, d), kk.reshape(b, s, h, d),
                       v.reshape(b, s, h, d), causal=causal,
                       rate=self.dropout if is_training else 0.0, key=key,
                       attn_mask=attn_mask, key_padding_mask=key_padding_mask,
                       pad_lens=pad_lens)
        o = o.reshape(b, s, e)
        o = o @ params["out_weight"].T
        if self.bias:
            o = o + params["out_bias"]
        if self.include_norm_add:
            o = o + residual
        return o


@dataclasses.dataclass
class EncdecMultiheadAttn:
    """``EncdecMultiheadAttn``: Q from the decoder stream, K/V from the
    encoder memory (``encdec_multihead_attn.py``)."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    def init(self, key, dtype=jnp.float32) -> dict:
        k1, k2, k3 = jax.random.split(key, 3)
        e = self.embed_dim
        params = {
            "q_weight": _linear_init(k1, (e, e), dtype),
            "kv_weight": _linear_init(k2, (2 * e, e), dtype),
            "out_weight": _linear_init(k3, (e, e), dtype),
        }
        if self.bias:
            params.update(q_bias=jnp.zeros((e,), dtype),
                          kv_bias=jnp.zeros((2 * e,), dtype),
                          out_bias=jnp.zeros((e,), dtype))
        if self.include_norm_add:
            params["ln_weight"] = jnp.ones((e,), dtype)
            params["ln_bias"] = jnp.zeros((e,), dtype)
        return params

    def __call__(self, params, query, memory, *,
                 attn_mask: Optional[jax.Array] = None,
                 key_padding_mask: Optional[jax.Array] = None,
                 pad_lens: Optional[jax.Array] = None,
                 key: Optional[jax.Array] = None,
                 is_training: bool = True):
        """``key_padding_mask`` (batch, src_len) excludes padded ENCODER
        keys (``encdec_multihead_attn.py:106-119``); ``pad_lens`` (batch,)
        is its varlen fast-path form (valid memory lengths)."""
        residual = query
        if self.include_norm_add:
            query = fused_layer_norm(query, params["ln_weight"], params["ln_bias"])
        b, sq, e = query.shape
        sk = memory.shape[1]
        h, d = self.num_heads, self.head_dim
        q = query @ params["q_weight"].T
        kv = memory @ params["kv_weight"].T
        if self.bias:
            q = q + params["q_bias"]
            kv = kv + params["kv_bias"]
        kk, v = jnp.split(kv, 2, axis=-1)
        o = _attention(q.reshape(b, sq, h, d), kk.reshape(b, sk, h, d),
                       v.reshape(b, sk, h, d), causal=False,
                       rate=self.dropout if is_training else 0.0, key=key,
                       attn_mask=attn_mask, key_padding_mask=key_padding_mask,
                       pad_lens=pad_lens)
        o = o.reshape(b, sq, e)
        o = o @ params["out_weight"].T
        if self.bias:
            o = o + params["out_bias"]
        if self.include_norm_add:
            o = o + residual
        return o
