"""Fused focal loss (contrib surface) — re-export of
:mod:`apex_tpu.ops.focal_loss` (``apex/contrib/focal_loss/focal_loss.py:6-60``)."""

from apex_tpu.ops.focal_loss import focal_loss  # noqa: F401
