"""Group batch-norm: BN statistics over device sub-groups.

Re-design of ``apex.contrib.groupbn`` (``apex/contrib/groupbn/batch_norm.py:7,101``):
the reference's ``bn_group`` exchanges partial stats between 2/4/8 GPUs over
raw CUDA IPC handles with fused add+relu epilogues. On TPU the sub-group is a
*mesh sub-axis*: splitting the dp axis as ('dp_outer', 'bn') and reducing
over 'bn' reproduces bn_group semantics with a compiled ICI collective —
no IPC plumbing to re-build. This module provides that axis-splitting helper
plus a BatchNorm2d_NHWC-shaped wrapper over sync_batch_norm (which already
fuses the add+relu epilogue).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from apex_tpu.parallel import mesh as mesh_lib
from apex_tpu.parallel.sync_batchnorm import BatchNormState, SyncBatchNorm, sync_batch_norm


def split_data_axis_for_bn(mesh: Mesh, bn_group: int) -> Mesh:
    """Split the mesh's dp axis into ('dp_outer', 'bn') with |bn|=bn_group —
    the analog of creating a BN process sub-group
    (``apex/parallel/__init__.py:58-95`` / groupbn's bn_group arg)."""
    if bn_group <= 1:
        return mesh
    names = mesh.axis_names
    shape = [mesh.shape[n] for n in names]
    di = names.index(mesh_lib.DATA_AXIS)
    if shape[di] % bn_group:
        raise ValueError(f"dp size {shape[di]} not divisible by bn_group {bn_group}")
    new_shape = shape[:di] + [shape[di] // bn_group, bn_group] + shape[di + 1:]
    new_names = list(names[:di]) + ["dp_outer", "bn"] + list(names[di + 1:])
    return Mesh(mesh.devices.reshape(new_shape), tuple(new_names))


class BatchNorm2d_NHWC(SyncBatchNorm):
    """``bnp.BatchNorm2d_NHWC`` surface (``batch_norm.py:7``): NHWC BN with
    optional fused residual-add + ReLU, stats over the 'bn' sub-axis."""

    def __init__(self, num_features: int, fuse_relu: bool = False,
                 bn_group: int = 1, **kw):
        axis = "bn" if bn_group > 1 else None
        super().__init__(num_features, axis_name=axis, fuse_relu=fuse_relu, **kw)
