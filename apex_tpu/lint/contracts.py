"""JXP contract library: reusable invariant checks over traced jaxprs.

Where apexlint's APX rules judge *source text*, a JXP contract judges the
*traced program* — the jaxpr the compiler actually sees. Each contract is
a small declarative object (code + human description + a check over the
shared :mod:`apex_tpu.lint.jaxpr_check` walk); an entrypoint in
:mod:`apex_tpu.lint.entrypoints` declares the set it must satisfy, and
the migrated test suites assert the same objects directly
(:func:`assert_contracts`) — one engine owns every jaxpr invariant that
used to live as a one-off duck-typed walker in a test file.

Code families (catalogue with bad/good traces: ``docs/api/lint.md``):

* **JXP1xx** program structure — :func:`scan_count` (JXP101),
  :func:`scan_length` (JXP102): the schedule-geometry witnesses (the zb
  dW sweep is "a third scan of exactly M·v ticks").
* **JXP2xx** donation — :func:`donation_honored` (JXP201: a buffer
  donated into a pjit eqn is dead; reading it afterwards is
  use-after-free at the XLA level), :func:`donation_rebound` (JXP202: a
  donated operand with no same-aval output cannot have its buffer
  reused — the donation silently buys nothing).
* **JXP3xx** aval shape — :func:`no_aval_matching` (JXP301): no
  intermediate anywhere in the program matches a forbidden shape
  pattern (the bucketed-bias memory claim: no two >= seq dims).
* **JXP4xx** collective inventory — :func:`no_full_width_all_gather`
  (JXP401), :func:`ppermute_present` (JXP402),
  :func:`collective_free_region` (JXP403).
* **JXP5xx** precision — :func:`fp32_accumulation` (JXP501: a scan
  carry accumulated by add in bf16/fp16 loses mantissa every tick).
* **JXP6xx** static peak memory (apexmem) — :func:`peak_memory_bound`
  (JXP601: the donation-aware liveness peak of
  :func:`apex_tpu.lint.liveness.analyze` stays under a byte budget),
  :func:`donation_aliased` (JXP602: a donated buffer is provably
  counted once — the alias survives the liveness accounting, not just
  the JXP202 aval match).

Stdlib-only, like the rest of the package: contracts consume the
duck-typed walk, never jax itself.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, List, Optional, Sequence, Tuple

from apex_tpu.lint.jaxpr_check import (
    EqnSite,
    as_jaxpr,
    collective_axes,
    collective_kind,
    iter_levels,
    iter_sites,
    sub_jaxprs,
)

_LOW_PRECISION = ("bfloat16", "float16")
_ACCUM_PRIMS = ("add", "add_any")

#: the JXP contract catalogue: code -> (name, one-line summary). The
#: docs-catalogue test enforces a ``### JXPnnn`` entry with bad/good
#: trace snippets in docs/api/lint.md for every row, the same discipline
#: as the APX rule registry; ``--list-rules`` prints it after the AST
#: rules.
JXP_CODES = {
    "JXP101": ("scan-count",
               "the number of scan eqns anywhere in the program matches "
               "the declared count/bounds"),
    "JXP102": ("scan-length",
               "a scan of exactly N static ticks exists (or, forbidden, "
               "does not) — the schedule-geometry witness"),
    "JXP201": ("donation-use-after-donate",
               "no value read (or returned) after its buffer was donated "
               "into a pjit call"),
    "JXP202": ("donated-not-rebound",
               "every donated operand has a same-aval output to rebind — "
               "a donation with no matching output buys nothing"),
    "JXP301": ("no-aval-matching",
               "no eqn operand/output matches a forbidden shape pattern "
               "(Pallas kernel bodies exempt — VMEM tiles, not HBM)"),
    "JXP401": ("no-full-width-all-gather",
               "no all_gather over the named axis anywhere in the "
               "program — the overlapped-ring acceptance"),
    "JXP402": ("ppermute-present",
               "at least one ppermute over the named axis — the ring / "
               "pipeline-hop witness"),
    "JXP403": ("collective-free-region",
               "no collective primitive under paths matching a regex "
               "(a region that matches nothing is itself a violation)"),
    "JXP501": ("fp32-accumulation",
               "no scan carry accumulated by add in bf16/fp16 — "
               "accumulate fp32, downcast once"),
    "JXP601": ("peak-memory-bound",
               "the donation-aware static liveness peak of the traced "
               "program stays under a byte budget"),
    "JXP602": ("donation-aliased",
               "the liveness analysis finds the named donated buffer "
               "really aliased input->output (counted once, not twice)"),
}


@dataclasses.dataclass(frozen=True)
class ContractFinding:
    code: str      #: JXPnnn
    contract: str  #: the contract instance's human label
    path: str      #: jaxpr path of the offending site ("" = whole program)
    message: str

    def render(self) -> str:
        where = self.path or "<top>"
        return f"{self.code} [{where}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Contract:
    code: str
    name: str
    describe: str  #: instance description, parameters included
    check: Callable[["Walk"], List[ContractFinding]]


class Walk:
    """One materialized walk of a jaxpr, shared by every contract checked
    against it (the walker runs once, not once per contract)."""

    def __init__(self, jaxpr_like):
        self.jaxpr = as_jaxpr(jaxpr_like)
        self.sites: List[EqnSite] = list(iter_sites(self.jaxpr))

    def levels(self):
        return iter_levels(self.jaxpr)

    def scans(self) -> List[EqnSite]:
        return [s for s in self.sites if s.prim == "scan"]


def check_jaxpr(jaxpr_like, contracts: Sequence[Contract]
                ) -> List[ContractFinding]:
    """Check every contract against one traced program; returns the
    flattened findings (empty = all contracts hold)."""
    walk = jaxpr_like if isinstance(jaxpr_like, Walk) else Walk(jaxpr_like)
    findings: List[ContractFinding] = []
    for c in contracts:
        findings.extend(c.check(walk))
    return findings


def assert_contracts(jaxpr_like, contracts: Sequence[Contract]) -> None:
    """Raise ``AssertionError`` listing every violated contract — the
    drop-in replacement for the hand-rolled jaxpr asserts the test
    suites used to carry."""
    findings = check_jaxpr(jaxpr_like, contracts)
    if findings:
        raise AssertionError(
            "jaxpr contract violation(s):\n  "
            + "\n  ".join(f.render() for f in findings))


# --- JXP1xx: program structure ------------------------------------------------

def scan_count(expected: Optional[int] = None, *,
               min_count: Optional[int] = None,
               max_count: Optional[int] = None) -> Contract:
    """JXP101: the number of ``scan`` eqns anywhere in the program
    (sub-jaxprs included) matches. Pin ``expected`` exactly, or bound
    with ``min_count``/``max_count``."""
    label = f"scan_count(expected={expected}, min={min_count}, " \
            f"max={max_count})"

    def check(walk: Walk) -> List[ContractFinding]:
        n = len(walk.scans())
        problems = []
        if expected is not None and n != expected:
            problems.append(f"program has {n} scan(s), expected {expected}")
        if min_count is not None and n < min_count:
            problems.append(f"program has {n} scan(s), expected >= "
                            f"{min_count}")
        if max_count is not None and n > max_count:
            problems.append(f"program has {n} scan(s), expected <= "
                            f"{max_count}")
        return [ContractFinding("JXP101", label, "", m) for m in problems]

    return Contract("JXP101", "scan-count", label, check)


def scan_length(length: int, *, min_count: int = 1,
                forbid: bool = False) -> Contract:
    """JXP102: a ``scan`` of exactly ``length`` static ticks exists (at
    least ``min_count`` of them) — the zb dW-deferral witness ("a third
    scan of exactly M·v ticks"). ``forbid=True`` inverts it: NO scan of
    that length may exist (the 1f1b control: its dW rides the full
    backward sweep, so an M·v-length scan would mean the wrong schedule
    traced)."""
    label = f"scan_length({length}, min_count={min_count}, forbid={forbid})"

    def check(walk: Walk) -> List[ContractFinding]:
        hits = [s for s in walk.scans()
                if s.eqn.params.get("length") == length]
        if forbid:
            return [ContractFinding(
                "JXP102", label, s.path,
                f"forbidden scan of length {length} present")
                for s in hits]
        if len(hits) < min_count:
            got = sorted(s.eqn.params.get("length") for s in walk.scans()
                         if isinstance(s.eqn.params.get("length"), int))
            return [ContractFinding(
                "JXP102", label, "",
                f"expected >= {min_count} scan(s) of length {length}, "
                f"found {len(hits)} (lengths present: {got})")]
        return []

    return Contract("JXP102", "scan-length", label, check)


# --- JXP2xx: donation ---------------------------------------------------------

def donation_honored() -> Contract:
    """JXP201: no value read after its buffer was donated — a var passed
    in a donated position of a pjit eqn must not feed any LATER eqn of
    the same level, nor that level's outputs (XLA may have reused the
    buffer; the read is use-after-free). Literals are skipped — a
    literal has no buffer to donate."""
    label = "donation_honored()"

    def check(walk: Walk) -> List[ContractFinding]:
        findings = []
        for path, jaxpr in walk.levels():
            seen_donation = set()
            for eqn in jaxpr.eqns:
                if seen_donation:
                    for var in eqn.invars:
                        if not hasattr(var, "val") and var in seen_donation:
                            findings.append(ContractFinding(
                                "JXP201", label, path,
                                f"donated buffer {var} is read by a later "
                                f"`{eqn.primitive.name}` eqn after the "
                                "pjit call that donated it"))
                if eqn.primitive.name == "pjit":
                    donated = eqn.params.get("donated_invars") or ()
                    for var, is_donated in zip(eqn.invars, donated):
                        if is_donated and not hasattr(var, "val"):
                            seen_donation.add(var)
            for var in getattr(jaxpr, "outvars", ()):
                if not hasattr(var, "val") and var in seen_donation:
                    findings.append(ContractFinding(
                        "JXP201", label, path,
                        f"donated buffer {var} is returned from the "
                        "enclosing program after donation"))
        return findings

    return Contract("JXP201", "donation-use-after-donate", label, check)


def donation_rebound() -> Contract:
    """JXP202: every donated operand has a same-aval output to rebind —
    a pjit eqn donating an aval it produces fewer outputs of cannot
    reuse the buffer (jax warns 'Some donated buffers were not usable'
    at run time; this is the same check at trace time, multiset-matched
    per (shape, dtype))."""
    label = "donation_rebound()"

    def _aval_key(var):
        aval = getattr(var, "aval", None)
        return (tuple(getattr(aval, "shape", ())),
                str(getattr(aval, "dtype", "?")))

    def check(walk: Walk) -> List[ContractFinding]:
        findings = []
        for path, jaxpr in walk.levels():
            for eqn in jaxpr.eqns:
                if eqn.primitive.name != "pjit":
                    continue
                donated = eqn.params.get("donated_invars") or ()
                if not any(donated):
                    continue
                out_counts: dict = {}
                for var in eqn.outvars:
                    k = _aval_key(var)
                    out_counts[k] = out_counts.get(k, 0) + 1
                for var, is_donated in zip(eqn.invars, donated):
                    if not is_donated or hasattr(var, "val"):
                        continue
                    k = _aval_key(var)
                    if out_counts.get(k, 0) > 0:
                        out_counts[k] -= 1
                    else:
                        shape, dtype = k
                        findings.append(ContractFinding(
                            "JXP202", label, path,
                            f"donated operand {dtype}{list(shape)} has no "
                            "matching-aval output to rebind — the "
                            "donation buys nothing (jax: 'donated "
                            "buffers were not usable')"))
        return findings

    return Contract("JXP202", "donated-not-rebound", label, check)


# --- JXP3xx: aval shape -------------------------------------------------------

def no_aval_matching(pred: Callable[[Tuple[int, ...]], bool],
                     label: str) -> Contract:
    """JXP301: no eqn operand or output ANYWHERE in the program (Pallas
    kernel bodies excepted — their avals are VMEM tiles, while the claim
    is about HBM arrays; a kernel's HBM operands are still checked at
    its ``pallas_call`` eqn) has a shape matching ``pred``. The
    bucketed-bias memory witness:
    ``no_aval_matching(lambda s: sum(d >= seq for d in s) >= 2,
    "materialized O(s^2) bias/score")``."""
    full = f"no_aval_matching({label})"

    def check(walk: Walk) -> List[ContractFinding]:
        findings = []
        for site in walk.sites:
            if site.under_kernel():
                continue
            for var in list(site.eqn.invars) + list(site.eqn.outvars):
                shape = tuple(getattr(getattr(var, "aval", None), "shape",
                                      ()) or ())
                if shape and pred(shape):
                    findings.append(ContractFinding(
                        "JXP301", full, site.path,
                        f"aval {list(shape)} at `{site.prim}` matches "
                        f"forbidden pattern: {label}"))
        return findings

    return Contract("JXP301", "no-aval-matching", full, check)


# --- JXP4xx: collective inventory ---------------------------------------------

def _on_axis(eqn, axis: str) -> bool:
    return axis in collective_axes(eqn)


def no_full_width_all_gather(axis: str) -> Contract:
    """JXP401: no ``all_gather`` over ``axis`` anywhere in the program —
    the overlapped-ring acceptance (an explicit full-width gather of the
    activation is exactly what the ppermute ring exists to avoid; on an
    ``overlap_comm`` path its presence means the blocking fallback
    traced)."""
    label = f"no_full_width_all_gather({axis!r})"

    def check(walk: Walk) -> List[ContractFinding]:
        return [ContractFinding(
            "JXP401", label, s.path,
            f"full-width `{s.prim}` over axis {axis!r} "
            f"(payload {eqn_shapes(s.eqn)})")
            for s in walk.sites
            if s.prim in ("all_gather", "all_gather_invariant")
            and _on_axis(s.eqn, axis)]

    return Contract("JXP401", "no-full-width-all-gather", label, check)


def ppermute_present(axis: str) -> Contract:
    """JXP402: at least one ``ppermute`` over ``axis`` — the ring /
    pipeline-hop witness (its absence on an overlapped path means the
    ring never traced)."""
    label = f"ppermute_present({axis!r})"

    def check(walk: Walk) -> List[ContractFinding]:
        if any(s.prim == "ppermute" and _on_axis(s.eqn, axis)
               for s in walk.sites):
            return []
        return [ContractFinding(
            "JXP402", label, "",
            f"no ppermute over axis {axis!r} anywhere in the program")]

    return Contract("JXP402", "ppermute-present", label, check)


def collective_free_region(path_pattern: str, *,
                           region: str = "") -> Contract:
    """JXP403: no collective primitive in any eqn whose path matches
    ``path_pattern`` (a regex over the walker's ``/``-joined segments —
    scans embed their length, so the zb dW sweep is targetable as
    ``r"scan:12"``). A pattern matching NO site at all is itself a
    violation: a typo'd region must not silently pass. ``region`` names
    the region in messages."""
    name = region or path_pattern or "<whole program>"
    label = f"collective_free_region({name})"
    rx = re.compile(path_pattern)

    def check(walk: Walk) -> List[ContractFinding]:
        in_region = [s for s in walk.sites if rx.search(s.path)]
        if not in_region:
            return [ContractFinding(
                "JXP403", label, "",
                f"no eqn matches region pattern {path_pattern!r} — the "
                "region does not exist in this program")]
        return [ContractFinding(
            "JXP403", label, s.path,
            f"collective `{s.prim}` (axis {collective_axes(s.eqn)}) "
            f"inside the {name} region, declared collective-free")
            for s in in_region if collective_kind(s.eqn) is not None]

    return Contract("JXP403", "collective-free-region", label, check)


# --- JXP6xx: static peak memory (apexmem) -------------------------------------

def peak_memory_bound(limit_bytes: int, *,
                      arg_families: Optional[Sequence[str]] = None
                      ) -> Contract:
    """JXP601: the donation-aware static liveness peak
    (:func:`apex_tpu.lint.liveness.analyze`) of the whole traced
    program stays ``<= limit_bytes``. This is the per-entrypoint HBM
    gate ``python -m apex_tpu.lint --jaxpr --memory --budget-file F``
    enforces, usable directly in tests via :func:`assert_contracts`.
    ``arg_families`` optionally labels the program's flattened invars
    so the violation message carries the family breakdown."""
    label = f"peak_memory_bound({limit_bytes})"

    def check(walk: Walk) -> List[ContractFinding]:
        from apex_tpu.lint import liveness

        rep = liveness.analyze(walk.jaxpr, arg_families=arg_families)
        if rep.peak_bytes <= limit_bytes:
            return []
        fams = ", ".join(f"{k}={v}" for k, v in rep.families.items()
                         if v)
        return [ContractFinding(
            "JXP601", label, "",
            f"static peak HBM {rep.peak_bytes} bytes "
            f"({rep.peak_bytes / 2**20:.2f} MB) exceeds the bound "
            f"{limit_bytes} bytes ({limit_bytes / 2**20:.2f} MB); "
            f"at-peak families: {fams or 'none'}")]

    return Contract("JXP601", "peak-memory-bound", label, check)


def donation_aliased(name: str = "donated buffer", *,
                     min_bytes: int = 1) -> Contract:
    """JXP602: the liveness analysis finds at least ``min_bytes`` of
    donation-aliased buffer — i.e. some donated operand's bytes are
    provably counted ONCE (input aliased to a same-aval output), the
    serving invariant behind the donated-and-rebound paged pool.
    Stronger than JXP202 (which only checks a matching output *exists*):
    this asserts the alias survives the full liveness accounting —
    the donated buffer is dead at the donation point, so the rebind
    really reuses it. ``name`` labels the buffer in messages."""
    label = f"donation_aliased({name!r}, min_bytes={min_bytes})"

    def check(walk: Walk) -> List[ContractFinding]:
        from apex_tpu.lint import liveness

        rep = liveness.analyze(walk.jaxpr)
        if rep.donation_aliased_bytes >= min_bytes:
            return []
        return [ContractFinding(
            "JXP602", label, "",
            f"{name}: expected >= {min_bytes} donation-aliased bytes, "
            f"liveness found {rep.donation_aliased_bytes} — no donated "
            "operand is rebound in place (the pool would cost its "
            "bytes twice)")]

    return Contract("JXP602", "donation-aliased", label, check)


# --- JXP5xx: precision --------------------------------------------------------

def fp32_accumulation() -> Contract:
    """JXP501: no scan carry accumulated by ``add`` in bf16/fp16 — a
    low-precision running sum loses mantissa every tick (the reason the
    schedules' main grads and the ring dW folds accumulate in fp32 and
    downcast once at the end). A bf16 carry that is merely threaded
    (not add-produced) is fine."""
    label = "fp32_accumulation()"

    def check(walk: Walk) -> List[ContractFinding]:
        findings = []
        for site in walk.scans():
            num_carry = site.eqn.params.get("num_carry")
            body = None
            for val in site.eqn.params.values():
                for j in sub_jaxprs(val):
                    body = j
                    break
                if body is not None:
                    break
            if body is None or not isinstance(num_carry, int):
                continue
            producers = {}
            for eqn in body.eqns:
                for var in eqn.outvars:
                    producers[var] = eqn
            for var in list(body.outvars)[:num_carry]:
                prod = producers.get(var)
                if prod is None or prod.primitive.name not in _ACCUM_PRIMS:
                    continue
                dtype = str(getattr(getattr(var, "aval", None), "dtype", ""))
                if dtype in _LOW_PRECISION:
                    findings.append(ContractFinding(
                        "JXP501", label, site.path,
                        f"scan carry accumulated by `"
                        f"{prod.primitive.name}` in {dtype} — accumulate "
                        "in fp32 and downcast once after the scan"))
        return findings

    return Contract("JXP501", "fp32-accumulation", label, check)


def eqn_shapes(eqn) -> List[list]:
    """Operand shapes of one eqn (for messages)."""
    return [list(getattr(getattr(v, "aval", None), "shape", ()) or ())
            for v in eqn.invars]
