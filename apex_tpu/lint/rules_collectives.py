"""APX4xx — collective and mesh-axis hygiene.

Collectives reference mesh axes by *name*; a typo'd axis string is not a
compile error until the collective actually executes under a mesh that
lacks it — often only on multi-host hardware, far from the edit. The
repo's canonical axes are ``dp/tp/pp/cp/ep``
(``apex_tpu.parallel.mesh``); anything else in a string literal is either
a typo or a local convention worth baselining with a reason.

Rules
-----
APX401  unknown-collective-axis   psum/pmean/ppermute/axis_index/… with a
                                  string-literal axis outside dp/tp/pp/cp/ep
APX402  unknown-partition-axis    PartitionSpec naming an axis outside the
                                  known mesh axes (shard_map in_specs/
                                  out_specs included — they are built of
                                  PartitionSpecs)
APX403  blocking-collective-feeds-matmul
                                  a ``lax.all_gather`` result feeding a
                                  matmul/einsum, or a matmul feeding
                                  ``lax.psum_scatter`` — inside shard_map
                                  these blocking boundary collectives stall
                                  the MXU; ``ops.collective_matmul`` /
                                  ``overlap_comm=True`` overlaps them
                                  (advisory)
APX404  blocking-p2p-feeds-stage  a ``lax.ppermute`` / pipeline p2p helper
                                  result feeding a stage/block body (or a
                                  matmul) in the same scope — the blocking
                                  hop serializes with the compute where
                                  ``p2p_communication.rotate_overlapped``
                                  / ``overlap_p2p=True`` hides it behind
                                  the stage (advisory, mirrors APX403 at
                                  the pp boundary)
APX405  collective-under-divergent-cond
                                  ``lax.cond``/``lax.switch`` whose
                                  branches issue DIFFERENT collective
                                  sets — under shard_map/pmap a
                                  device-varying predicate sends chips
                                  down different branches, and the chip
                                  whose branch psums waits forever for
                                  the chip whose branch doesn't (hoist
                                  the collective out of the cond, or
                                  make every branch issue the same
                                  collectives)
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from apex_tpu.lint.core import KNOWN_MESH_AXES, ModuleContext, rule

#: collective → positional index of its axis-name argument
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "all_gather": 1, "psum_scatter": 1, "pswapaxes": 1, "all_to_all": 1,
    "axis_index": 0, "axis_size": 0,
}


def _axis_literals(node) -> List[ast.Constant]:
    """String constants inside an axis argument (plain or tuple/list)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _collective_axis_arg(call: ast.Call, pos: int) -> Optional[ast.expr]:
    # only `axis_name=` names a mesh axis; `axis=` on all_gather/
    # psum_scatter/all_to_all is the array-DIMENSION int and must not
    # shadow a typo'd positional axis name
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


#: callables that BIND new axis names: a psum over such a name is legal
_BINDERS = frozenset({"pmap", "vmap", "xmap", "shard_map", "Mesh",
                      "make_mesh"})


def _bound_axis_names(ctx: ModuleContext) -> frozenset:
    """Axis names bound by pmap/vmap/shard_map/Mesh calls in this module
    (ISSUE spec: 'not drawn from the known mesh axes OR an enclosing
    binder'). Module-wide, not scope-exact — a typo only escapes if the
    same typo also appears in a binder, which is then consistent code."""
    bound = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = ctx.call_name(node) or ""
        if canon.rsplit(".", 1)[-1] not in _BINDERS:
            continue
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                bound.update(lit.value for lit in _axis_literals(kw.value))
        # positional spellings: Mesh(devices, ("x", "y")) and
        # pmap(f, "batch")
        if canon.rsplit(".", 1)[-1] in ("Mesh", "make_mesh", "pmap") and \
                len(node.args) >= 2:
            bound.update(lit.value for lit in _axis_literals(node.args[1]))
    return frozenset(bound)


@rule("APX401", "unknown-collective-axis",
      "collective with a string-literal axis name outside the repo's mesh "
      "axes dp/tp/pp/cp/ep or an enclosing binder")
def check_apx401(ctx: ModuleContext):
    allowed = KNOWN_MESH_AXES | _bound_axis_names(ctx)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = ctx.call_name(node) or ""
        short = canon.rsplit(".", 1)[-1]
        if short not in _COLLECTIVES:
            continue
        if not (canon.startswith("jax.lax.") or canon.startswith("lax.")
                or canon == short):
            continue
        axis_arg = _collective_axis_arg(node, _COLLECTIVES[short])
        if axis_arg is None:
            continue
        for lit in _axis_literals(axis_arg):
            if lit.value not in allowed:
                yield ctx.finding(
                    lit, "APX401",
                    f"`{short}` over axis {lit.value!r} — not one of the "
                    f"mesh's axes ({'/'.join(sorted(KNOWN_MESH_AXES))}) "
                    "nor bound by a pmap/vmap/shard_map/Mesh in this "
                    "module; a typo'd axis only fails when the collective "
                    "runs under a real mesh (use the mesh_lib.*_AXIS "
                    "constants)")


# --- APX403: blocking boundary collective around a matmul ---------------------

_MM_SHORT = frozenset({"dot", "matmul", "einsum", "dot_general", "tensordot"})


def _is_lax_call(ctx: ModuleContext, node, name: str) -> bool:
    canon = ctx.call_name(node) or ""
    return canon in (f"jax.lax.{name}", f"lax.{name}", name)


def _is_matmul_call(ctx: ModuleContext, node) -> bool:
    canon = ctx.call_name(node) or ""
    short = canon.rsplit(".", 1)[-1]
    if short not in _MM_SHORT:
        return False
    return (canon == short
            or canon.startswith(("jax.numpy.", "numpy.", "jax.lax.",
                                 "lax.")))


@rule("APX403", "blocking-collective-feeds-matmul",
      "a lax.all_gather result feeding a matmul/einsum (or a matmul "
      "feeding lax.psum_scatter) — the blocking boundary collective "
      "stalls the MXU inside shard_map where the ring-overlapped "
      "collective matmul (ops.collective_matmul / overlap_comm=True) "
      "hides it behind the chunk GEMMs (advisory)")
def check_apx403(ctx: ModuleContext):
    from apex_tpu.lint.rules_pallas import (_expr_has, _scope_bodies,
                                            _scope_nodes, _taint_names)

    def is_all_gather(call):
        return _is_lax_call(ctx, call, "all_gather")

    def is_matmul(call):
        return _is_matmul_call(ctx, call)

    for body in _scope_bodies(ctx.tree):
        stmts = _scope_nodes(body)
        gathered = _taint_names(stmts, is_all_gather)
        matmuled = _taint_names(stmts, is_matmul)
        for node in stmts:
            if not isinstance(node, ast.Call):
                continue
            if _is_matmul_call(ctx, node):
                # an all-gather result among the matmul operands
                operands = list(node.args) + [k.value for k in node.keywords]
                for arg in operands:
                    if _expr_has(is_all_gather, arg, gathered):
                        yield ctx.finding(
                            node, "APX403",
                            "all-gather result feeds this matmul — inside "
                            "shard_map the blocking gather stalls the MXU "
                            "for the full boundary latency; "
                            "ops.collective_matmul.all_gather_matmul (or "
                            "overlap_comm=True on the linear) overlaps "
                            "the transfer with per-chunk GEMMs (advisory)")
                        break
            elif _is_lax_call(ctx, node, "psum_scatter") and node.args:
                if _expr_has(is_matmul, node.args[0], matmuled):
                    yield ctx.finding(
                        node, "APX403",
                        "matmul result feeds this psum_scatter — inside "
                        "shard_map the blocking reduce-scatter stalls the "
                        "MXU after the GEMM completes; "
                        "ops.collective_matmul.matmul_reduce_scatter (or "
                        "overlap_comm=True on the linear) computes one "
                        "output shard per ring step instead (advisory)")


# --- APX404: blocking p2p hop feeding a stage body ---------------------------

#: pipeline p2p helpers whose result is a received activation (the
#: BLOCKING rotation primitives of
#: transformer.pipeline_parallel.p2p_communication, fused pairs included)
_P2P_SHORT = frozenset({"send_forward", "send_backward", "recv_forward",
                        "recv_backward", "_rotate",
                        "send_forward_recv_backward",
                        "send_backward_recv_forward"})

#: callee-name fragments that mark a pipeline stage body — the compute an
#: overlapped hop could hide behind (overlap-capable path exists:
#: rotate_overlapped / pipeline_spmd_forward(overlap_p2p=True)). "chunk"
#: is deliberately absent: the collective-matmul rings' per-chunk GEMM on
#: a just-arrived ppermute piece IS the overlapped pattern.
_STAGE_FRAGMENTS = ("stage", "block", "layer")


def _is_p2p_call(ctx: ModuleContext, node) -> bool:
    canon = ctx.call_name(node) or ""
    short = canon.rsplit(".", 1)[-1]
    if short == "ppermute":
        return (canon.startswith(("jax.lax.", "lax.")) or canon == short)
    # bare or through the p2p_communication module/aliases; the set
    # holds the BLOCKING helpers only, so rotate_overlapped never taints
    return short in _P2P_SHORT


def _is_stage_call(ctx: ModuleContext, node) -> bool:
    canon = ctx.call_name(node) or ""
    short = canon.rsplit(".", 1)[-1].lower()
    return any(f in short for f in _STAGE_FRAGMENTS)


@rule("APX404", "blocking-p2p-feeds-stage",
      "a lax.ppermute / pipeline p2p helper result feeding a stage/block "
      "body (or a matmul) in the same scope — the blocking hop serializes "
      "with compute that p2p_communication.rotate_overlapped / "
      "overlap_p2p=True would hide it behind (advisory)")
def check_apx404(ctx: ModuleContext):
    from apex_tpu.lint.rules_pallas import (_expr_has, _scope_bodies,
                                            _scope_nodes, _taint_names)

    def is_p2p(call):
        return _is_p2p_call(ctx, call)

    for body in _scope_bodies(ctx.tree):
        stmts = _scope_nodes(body)
        hopped = _taint_names(stmts, is_p2p)
        for node in stmts:
            if not isinstance(node, ast.Call):
                continue
            if not (_is_stage_call(ctx, node)
                    or _is_matmul_call(ctx, node)):
                continue
            operands = list(node.args) + [k.value for k in node.keywords]
            for arg in operands:
                if _expr_has(is_p2p, arg, hopped):
                    yield ctx.finding(
                        node, "APX404",
                        "a blocking p2p hop result feeds this stage body "
                        "— inside shard_map the ppermute serializes with "
                        "the compute that follows it, the exact stall "
                        "shape the ring-overlapped collectives (APX403) "
                        "eliminate for TP; "
                        "p2p_communication.rotate_overlapped (or "
                        "overlap_p2p=True on the pipeline schedule) "
                        "issues the hop, runs the hop-independent stage "
                        "body, and consumes the arrival next tick "
                        "(advisory)")
                    break


# --- APX405: collective under a divergent cond -------------------------------

#: the SYNCHRONIZING collectives — every participating chip must issue
#: them; axis_index/axis_size are local queries and can't deadlock
_SYNC_COLLECTIVES = frozenset(_COLLECTIVES) - {"axis_index", "axis_size"}


def _branch_callables(ctx: ModuleContext, call: ast.Call
                      ) -> Optional[List[ast.expr]]:
    """The branch-callable expressions of a ``lax.cond``/``lax.switch``
    call, or None when the call shape is not the branch form (operand
    positions, unpacked branch lists, …) — unresolvable means silent,
    never a guess."""
    if _is_lax_call(ctx, call, "cond"):
        branches = list(call.args[1:3])
        for kw in call.keywords:
            if kw.arg in ("true_fun", "false_fun"):
                branches.append(kw.value)
        return branches if len(branches) >= 2 else None
    if _is_lax_call(ctx, call, "switch"):
        if len(call.args) >= 2 and isinstance(call.args[1],
                                              (ast.List, ast.Tuple)):
            return list(call.args[1].elts)
    return None


def _branch_collectives(ctx: ModuleContext, branch: ast.expr,
                        defs) -> Optional[frozenset]:
    """The set of synchronizing-collective names a branch body issues,
    or None when the branch is not statically resolvable (a partial, an
    attribute, a name with no module-level def)."""
    if isinstance(branch, ast.Lambda):
        body = branch
    elif isinstance(branch, ast.Name):
        body = defs.get(branch.id)
        if body is None:
            return None
    else:
        return None
    found = set()
    for node in ast.walk(body):
        if not isinstance(node, ast.Call):
            continue
        canon = ctx.call_name(node) or ""
        short = canon.rsplit(".", 1)[-1]
        if short in _SYNC_COLLECTIVES and (
                canon.startswith(("jax.lax.", "lax.")) or canon == short):
            found.add(short)
    return frozenset(found)


@rule("APX405", "collective-under-divergent-cond",
      "lax.cond/lax.switch whose branches issue different collective "
      "sets — under shard_map/pmap a device-varying predicate deadlocks "
      "the chips whose branch collects against the chips whose branch "
      "doesn't")
def check_apx405(ctx: ModuleContext):
    defs = {node.name: node for node in ast.walk(ctx.tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        branches = _branch_callables(ctx, node)
        if not branches:
            continue
        sets = [_branch_collectives(ctx, b, defs) for b in branches]
        if any(s is None for s in sets):
            continue  # an unresolvable branch: stay silent, never guess
        if len(set(sets)) <= 1 or not any(sets):
            continue
        which = "cond" if _is_lax_call(ctx, node, "cond") else "switch"
        parts = ", ".join(
            "{" + ", ".join(sorted(s)) + "}" if s else "{}" for s in sets)
        yield ctx.finding(
            node, "APX405",
            f"`lax.{which}` branches issue different collective sets "
            f"({parts}) — a device-varying predicate sends chips down "
            "different branches, and a chip whose branch issues the "
            "collective blocks forever waiting for a chip whose branch "
            "does not; hoist the collective out of the cond, or make "
            "every branch issue the same collectives (e.g. psum a zero "
            "in the cheap branch)")


def _is_partition_spec(ctx: ModuleContext, call: ast.Call) -> bool:
    canon = ctx.call_name(call) or ""
    return canon.endswith(".PartitionSpec") or canon == "PartitionSpec"


def _spec_axis_literals(call: ast.Call) -> Iterable[ast.Constant]:
    for arg in call.args:
        yield from _axis_literals(arg)


@rule("APX402", "unknown-partition-axis",
      "PartitionSpec naming an axis outside the known mesh axes — "
      "shard_map in_specs/out_specs with such a spec fail only when the "
      "mesh is live")
def check_apx402(ctx: ModuleContext):
    allowed = KNOWN_MESH_AXES | _bound_axis_names(ctx)
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _is_partition_spec(ctx, node)):
            continue
        for lit in _spec_axis_literals(node):
            if lit.value not in allowed:
                yield ctx.finding(
                    lit, "APX402",
                    f"PartitionSpec axis {lit.value!r} is not one of the "
                    f"mesh's axes ({'/'.join(sorted(KNOWN_MESH_AXES))}) "
                    "nor bound by a Mesh/pmap/shard_map in this module — "
                    "the spec only fails at shard_map/jit time under a "
                    "mesh that lacks it")
