"""APX5xx — PRNG and precision discipline.

The motivating bug is real and local: ``multihead_attn`` accepted a
dropout rate in training mode and, when no PRNG key arrived, silently ran
dropout-free — a train/eval mismatch nothing surfaced until the fmha
parity round. Constant ``PRNGKey(0)`` in library code is the same family
(every process, every step, the same randomness), and fp32/bf16 literal
cast mixing inside one expression silently promotes back to fp32 —
defeating the downcast the author thought they applied.

Rules
-----
APX501  dropout-without-key   a def taking a dropout rate and a training
                              flag but no PRNG key/rng/seed parameter
APX502  constant-prng-key     jax.random.PRNGKey(<literal>) in non-test
                              library code
APX503  mixed-precision-cast  one binop mixing an .astype(bf16) operand
                              with an .astype(fp32) operand
"""

from __future__ import annotations

import ast

from apex_tpu.lint.core import ModuleContext, rule

_TRAINING_PARAMS = frozenset({
    "is_training", "training", "train", "is_train", "deterministic",
})


def _keyish(name: str) -> bool:
    n = name.lower()
    return any(tok in n for tok in ("key", "rng", "seed", "prng"))


def _dropoutish(name: str) -> bool:
    # "drop" must appear: a bare `rate` is the conventional learning/decay
    # rate name and carries no dropout intent
    n = name.lower()
    if _keyish(n):
        return False
    return "dropout" in n or n in ("p_drop", "drop_rate", "drop_p")


@rule("APX501", "dropout-without-key",
      "a function taking a dropout rate and a training flag but no PRNG "
      "key parameter cannot honor the rate — the multihead_attn "
      "silent-no-dropout bug shape")
def check_apx501(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        args = node.args
        names = [a.arg for a in
                 list(getattr(args, "posonlyargs", [])) + args.args
                 + args.kwonlyargs]
        has_dropout = any(_dropoutish(n) for n in names)
        has_training = any(n in _TRAINING_PARAMS for n in names)
        has_key = any(_keyish(n) for n in names)
        if has_dropout and has_training and not has_key:
            yield ctx.finding(
                node, "APX501",
                f"`{node.name}` accepts a dropout rate and a training flag "
                "but no PRNG key/rng/seed parameter — with no key it can "
                "only drop out deterministically or not at all (the "
                "multihead_attn bug); accept a key and raise when "
                "rate > 0 in training without one")


@rule("APX502", "constant-prng-key",
      "jax.random.PRNGKey(<int literal>) in non-test code — identical "
      "randomness every process and every call")
def check_apx502(ctx: ModuleContext):
    if ctx.is_testlike_path():
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = ctx.call_name(node) or ""
        if not (canon.endswith("random.PRNGKey")
                or canon.endswith("random.key")):
            continue
        seed_arg = node.args[0] if node.args else None
        if seed_arg is None:
            for kw in node.keywords:
                if kw.arg == "seed":
                    seed_arg = kw.value
        if isinstance(seed_arg, ast.Constant) and \
                isinstance(seed_arg.value, int):
            yield ctx.finding(
                node, "APX502",
                f"constant PRNG key `{ast.unparse(node)}` in library code "
                "— every process and every call draws the same stream; "
                "thread a key in, or fold_in rank/step")


def _cast_dtype(expr) -> str:
    """'bf16' / 'fp32' when ``expr`` is an explicit literal cast there."""
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr == "astype" and expr.args:
        return _dtype_token(expr.args[0])
    return ""


def _dtype_token(node) -> str:
    text = ""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value
    elif isinstance(node, (ast.Attribute, ast.Name)):
        text = ast.unparse(node)
    if text.endswith("bfloat16") or text == "bf16":
        return "bf16"
    if text.endswith("float32") or text == "fp32":
        return "fp32"
    return ""


@rule("APX503", "mixed-precision-cast",
      "one binary op mixing an .astype(bfloat16) operand with an "
      ".astype(float32) operand — the bf16 downcast silently promotes "
      "straight back to fp32")
def check_apx503(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.BinOp):
            continue
        kinds = {_cast_dtype(node.left), _cast_dtype(node.right)}
        if kinds == {"bf16", "fp32"}:
            yield ctx.finding(
                node, "APX503",
                "mixing .astype(bfloat16) and .astype(float32) operands "
                "in one op — jnp promotes the pair to fp32, so the bf16 "
                "cast only costs precision without saving bytes; cast "
                "once, after the op")
