"""CLI for apexlint. ``python -m apex_tpu.lint --help``.

Exit codes: 0 = clean (after suppressions/baseline), 1 = findings,
2 = usage or baseline error. The tier-1 gate
(tests/test_lint.py::TestDogfoodGate) runs exactly this entry point over
``apex_tpu/`` and fails on non-zero.

The repo's committed baseline (``tools/apexlint_baseline.json`` next to
the ``apex_tpu`` package) loads by default so a bare
``python -m apex_tpu.lint apex_tpu/`` judges the tree the way CI does;
``--baseline FILE`` substitutes another, ``--no-baseline`` disables.
Unused-entry warnings only fire for an explicit ``--baseline`` (a partial
run — one file — legitimately misses most default-baseline entries).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from apex_tpu import lint


def default_baseline_path() -> str:
    """The committed repo baseline, resolved package-relative (cwd-proof)."""
    import apex_tpu
    pkg = os.path.dirname(os.path.abspath(apex_tpu.__file__))
    return os.path.join(os.path.dirname(pkg), "tools",
                        "apexlint_baseline.json")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.lint",
        description="TPU tracing-hazard and kernel-constraint linter "
                    "(rule catalogue: docs/api/lint.md)")
    p.add_argument("paths", nargs="*", help=".py files or directories")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", metavar="FILE",
                   help="JSON baseline of documented-intentional findings "
                        "(entries carry a reason); matched by (path, code). "
                        "Default: the repo's tools/apexlint_baseline.json "
                        "when present")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the default repo baseline")
    p.add_argument("--select", metavar="CODES",
                   help="comma-separated code prefixes to run (e.g. "
                        "APX1,APX301)")
    p.add_argument("--ignore", metavar="CODES",
                   help="comma-separated code prefixes to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    return p


def _codes(arg):
    if not arg:
        return None
    return [c.strip().upper() for c in arg.split(",") if c.strip()]


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        for r in lint.iter_rules():
            print(f"{r.code}  {r.name}: {r.summary}")
        return 0
    if not args.paths:
        print("error: no paths given (try `python -m apex_tpu.lint "
              "apex_tpu/`)", file=sys.stderr)
        return 2

    try:
        findings, stats = lint.lint_paths(
            args.paths, select=_codes(args.select), ignore=_codes(args.ignore))
    except (FileNotFoundError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    explicit = baseline_path is not None
    if baseline_path is None and not args.no_baseline:
        cand = default_baseline_path()
        if os.path.exists(cand):
            baseline_path = cand
    baselined, unused = 0, []
    if baseline_path:
        try:
            entries = lint.load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        findings, baselined, unused = lint.apply_baseline(findings, entries)
        if not explicit:
            unused = []  # partial runs legitimately miss default entries

    report = lint.build_report(findings, stats, baselined)
    if args.format == "json":
        print(json.dumps(report, indent=1))
    else:
        for f in findings:
            print(f.render())
        print(f"{len(findings)} finding(s) in {stats['files_scanned']} "
              f"file(s) ({stats['suppressed_inline']} inline-suppressed, "
              f"{baselined} baselined)")
    for e in unused:
        print(f"warning: unused baseline entry {e['path']}:{e['code']} "
              f"({e['reason']}) — remove it", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
