"""CLI for apexlint. ``python -m apex_tpu.lint --help``.

Exit codes: 0 = clean (after suppressions/baseline), 1 = findings,
2 = usage or baseline error. The tier-1 gate
(tests/test_lint.py::TestDogfoodGate) runs exactly this entry point over
``apex_tpu/`` and fails on non-zero.

``--jaxpr`` switches from AST rules over source paths to JXP contracts
over TRACED programs: every registered entrypoint
(``apex_tpu.lint.entrypoints``; ``--entrypoint NAME`` to select) is
traced with ``jax.make_jaxpr`` on the virtual CPU mesh (no device
execution of the traced program) and judged against its declared
contract set. Findings ride the same report/baseline machinery as AST
findings, keyed ``(path="jaxpr:<entrypoint>", code)``. The same trace
feeds the planner's static cost substrate: ``--static-cost FILE``
writes the schema-validated ``kind:"static_cost"`` artifacts (JSONL,
one per entrypoint; gated by ``tools/validate_metrics.py
--static-cost``), and ``--costdb FILE`` prints the predicted-vs-
calibrated table against a measured CostDB
(``bench.py --profile --costdb``), flagging collectives the trace
contains but the CostDB has never priced.

``--memory`` (with ``--jaxpr``) runs the apexmem donation-aware
liveness analysis (``apex_tpu.lint.liveness``) over the same traces
and prints each entrypoint's static peak-HBM bound with its family
breakdown (params/optimizer/activations/kv_pool/temps);
``--budget-file F`` turns the table into a CLEAN/VIOLATION gate
against checked-in per-entrypoint byte budgets
(``tools/memory_budgets.json`` in CI), and ``--static-memory FILE``
writes the schema-validated ``kind:"static_memory"`` JSONL artifacts
(gated by ``tools/validate_metrics.py --static-memory``).

The repo's committed baseline (``tools/apexlint_baseline.json`` next to
the ``apex_tpu`` package) loads by default so a bare
``python -m apex_tpu.lint apex_tpu/`` judges the tree the way CI does;
``--baseline FILE`` substitutes another, ``--no-baseline`` disables.
Unused-entry warnings only fire for an explicit ``--baseline`` (a partial
run — one file — legitimately misses most default-baseline entries).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from apex_tpu import lint


def default_baseline_path() -> str:
    """The committed repo baseline, resolved package-relative (cwd-proof)."""
    import apex_tpu
    pkg = os.path.dirname(os.path.abspath(apex_tpu.__file__))
    return os.path.join(os.path.dirname(pkg), "tools",
                        "apexlint_baseline.json")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.lint",
        description="TPU tracing-hazard and kernel-constraint linter "
                    "(rule catalogue: docs/api/lint.md)")
    p.add_argument("paths", nargs="*", help=".py files or directories")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", metavar="FILE",
                   help="JSON baseline of documented-intentional findings "
                        "(entries carry a reason); matched by (path, code). "
                        "Default: the repo's tools/apexlint_baseline.json "
                        "when present")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the default repo baseline")
    p.add_argument("--select", metavar="CODES",
                   help="comma-separated code prefixes to run (e.g. "
                        "APX1,APX301)")
    p.add_argument("--ignore", metavar="CODES",
                   help="comma-separated code prefixes to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--jaxpr", action="store_true",
                   help="check JXP contracts over the traced entrypoint "
                        "programs instead of AST rules over source paths")
    p.add_argument("--entrypoint", action="append", metavar="NAME",
                   help="jaxpr mode: check only this registered "
                        "entrypoint (repeatable; default: all)")
    p.add_argument("--list-entrypoints", action="store_true",
                   help="print the registered jaxpr entrypoints and exit")
    p.add_argument("--static-cost", metavar="FILE", dest="static_cost",
                   help="jaxpr mode: write the kind:'static_cost' "
                        "artifacts (JSONL, one per entrypoint)")
    p.add_argument("--memory", action="store_true",
                   help="jaxpr mode: run the donation-aware liveness "
                        "analysis (apexmem) and report each entrypoint's "
                        "static peak-HBM bound with its family breakdown")
    p.add_argument("--budget-file", metavar="FILE", dest="budget_file",
                   help="with --memory: judge each peak CLEAN/VIOLATION "
                        "against the checked-in per-entrypoint byte "
                        "budgets (tools/memory_budgets.json); violations "
                        "and missing entries are JXP601 findings")
    p.add_argument("--static-memory", metavar="FILE", dest="static_memory",
                   help="jaxpr mode: write the kind:'static_memory' "
                        "artifacts (JSONL, one per entrypoint; implies "
                        "--memory)")
    p.add_argument("--costdb", metavar="FILE",
                   help="jaxpr mode: print the predicted-vs-calibrated "
                        "table against a measured CostDB artifact")
    p.add_argument("--strict", action="store_true",
                   help="with --costdb: exit nonzero when any traced "
                        "cost key has no CostDB row — the planner's "
                        "blind-spot surface as an exit code (and the "
                        "report's structured 'uncalibrated' section), "
                        "not table prose for CI to scrape")
    return p


def _codes(arg):
    if not arg:
        return None
    return [c.strip().upper() for c in arg.split(",") if c.strip()]


def _apply_baseline(args, findings):
    """Shared baseline logic of the AST and jaxpr modes. Returns
    ``(findings, baselined, unused)`` or an int error exit code."""
    baseline_path = args.baseline
    explicit = baseline_path is not None
    if baseline_path is None and not args.no_baseline:
        cand = default_baseline_path()
        if os.path.exists(cand):
            baseline_path = cand
    baselined, unused = 0, []
    if baseline_path:
        try:
            entries = lint.load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        findings, baselined, unused = lint.apply_baseline(findings, entries)
        if not explicit:
            unused = []  # partial runs legitimately miss default entries
    return findings, baselined, unused


def _emit_report(args, findings, stats, baselined, unused, report):
    if args.format == "json":
        print(json.dumps(report, indent=1))
    else:
        for f in findings:
            print(f.render())
        noun = "entrypoint" if report.get("mode") == "jaxpr" else "file"
        print(f"{len(findings)} finding(s) in {stats['files_scanned']} "
              f"{noun}(s) ({stats['suppressed_inline']} inline-suppressed, "
              f"{baselined} baselined)")
    for e in unused:
        print(f"warning: unused baseline entry {e['path']}:{e['code']} "
              f"({e['reason']}) — remove it", file=sys.stderr)


def _prepare_virtual_devices():
    """jaxpr mode traces shard_map programs over 4-wide meshes; the
    virtual CPU mesh needs the host-platform device count forced BEFORE
    the jax backend initializes (same pattern as bench.py). An already-
    initialized backend (the in-process test harness, which forces 8
    devices itself) is left alone, and an ambient JAX_PLATFORMS wins."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")


def _format_diff_table(name: str, diff: dict) -> str:
    lines = [f"static-cost vs CostDB — {name}:"]
    header = (f"  {'key':<24} {'calls':>6} {'per-step':>12} "
              f"{'measured rate':>14} {'pred ms':>8}  status")
    lines.append(header)
    for row in diff["rows"]:
        amount = (f"{row['bytes']} B" if row["unit"] == "bytes"
                  else f"{row['flops']:.3g} F")
        if row["calibrated"]:
            rate = row["rate"]
            unit = "B/s" if row["unit"] == "bytes" else "F/s"
            status = "calibrated"
            pred = f"{row['predicted_ms']:.3g}"
            rate_s = f"{rate:.3g} {unit}"
        else:
            status = "UNCALIBRATED (absent from CostDB)"
            pred, rate_s = "-", "-"
        lines.append(f"  {row['key']:<24} {row['calls']:>6} {amount:>12} "
                     f"{rate_s:>14} {pred:>8}  {status}")
    if diff["uncovered"]:
        lines.append(
            f"  !! {len(diff['uncovered'])} key(s) in the trace have no "
            f"CostDB row: {', '.join(diff['uncovered'])}")
    else:
        lines.append("  all traced keys calibrated")
    return "\n".join(lines)


def _format_memory_table(mems: list, gated: bool) -> str:
    lines = ["static memory — donation-aware liveness peaks (apexmem):"]
    lines.append(f"  {'entrypoint':<28} {'peak MB':>9} {'aliased MB':>11} "
                 f"{'stash MB':>9} {'while!':>6}"
                 + ("  verdict" if gated else ""))
    mb = 1024.0 * 1024.0
    for m in mems:
        row = (f"  {m['entrypoint']:<28} {m['peak_bytes'] / mb:>9.3f} "
               f"{m['donation_aliased_bytes'] / mb:>11.3f} "
               f"{m['stash_bytes'] / mb:>9.3f} "
               f"{m['unbounded_stash_sites']:>6}")
        if gated:
            row += f"  {m.get('verdict', '-')}"
        lines.append(row)
    return "\n".join(lines)


def _jaxpr_main(args) -> int:
    if args.paths:
        print("error: --jaxpr mode takes no source paths; select traced "
              "programs with --entrypoint NAME", file=sys.stderr)
        return 2
    if args.strict and not args.costdb:
        # usage error — before any entrypoint is traced
        print("error: --strict judges CostDB coverage; pass --costdb "
              "FILE", file=sys.stderr)
        return 2
    if args.budget_file and not args.memory:
        print("error: --budget-file gates the liveness peaks; pass "
              "--memory", file=sys.stderr)
        return 2
    budgets = None
    if args.budget_file:
        # read before any entrypoint is traced: a bad budget file is a
        # usage error, not 17 traces followed by one
        try:
            with open(args.budget_file, encoding="utf-8") as fh:
                budgets = json.load(fh)["budgets"]
        except (OSError, json.JSONDecodeError, KeyError) as e:
            print(f"error: cannot read budget file {args.budget_file}: "
                  f"{e!r}", file=sys.stderr)
            return 2
    _prepare_virtual_devices()
    from apex_tpu.lint import entrypoints as eps
    from apex_tpu.lint.core import _code_selected

    if args.list_entrypoints:
        for name in eps.names():
            ep = eps.get(name)
            print(f"{name}  {ep.description}")
            for c in ep.contracts():
                print(f"    {c.code}  {c.describe}")
        return 0

    names = args.entrypoint or eps.names()
    unknown = [n for n in names if n not in eps.REGISTRY]
    if unknown:
        print(f"error: unknown entrypoint(s): {', '.join(unknown)}; "
              f"registered: {', '.join(eps.names())}", file=sys.stderr)
        return 2

    select, ignore = _codes(args.select), _codes(args.ignore)
    memory_on = bool(args.memory or args.static_memory)
    findings, costs, mems = [], [], []
    for name in names:
        if memory_on:
            contract_findings, cost, mem = eps.check(name, memory=True)
            mems.append(mem)
        else:
            contract_findings, cost = eps.check(name)
        costs.append(cost)
        for cf in contract_findings:
            if not _code_selected(cf.code, select, ignore):
                continue
            findings.append(lint.Finding(
                f"jaxpr:{name}", 1, 0, cf.code,
                f"[{cf.path or '<top>'}] {cf.message} ({cf.contract})"))
    if budgets is not None:
        for mem in mems:
            name = mem["entrypoint"]
            limit = budgets.get(name)
            if limit is None:
                mem["verdict"] = "VIOLATION"
                msg = (f"[<top>] entrypoint has no budget entry in "
                       f"{args.budget_file} (static peak "
                       f"{mem['peak_bytes']} bytes) — every gated "
                       f"program needs a checked-in bound "
                       f"(peak-memory-bound)")
            else:
                mem["budget_bytes"] = int(limit)
                if mem["peak_bytes"] <= limit:
                    mem["verdict"] = "CLEAN"
                    continue
                mem["verdict"] = "VIOLATION"
                msg = (f"[<top>] static peak HBM {mem['peak_bytes']} "
                       f"bytes ({mem['peak_mb']:.3f} MB) exceeds the "
                       f"checked-in budget {limit} bytes "
                       f"(peak-memory-bound)")
            if _code_selected("JXP601", select, ignore):
                findings.append(lint.Finding(
                    f"jaxpr:{name}", 1, 0, "JXP601", msg))
    findings.sort(key=lint.Finding.sort_key)

    applied = _apply_baseline(args, findings)
    if isinstance(applied, int):
        return applied
    findings, baselined, unused = applied

    stats = {"files_scanned": len(names), "suppressed_inline": 0}
    report = lint.build_report(findings, stats, baselined)
    report["mode"] = "jaxpr"
    report["entrypoints"] = list(names)

    if args.static_cost:
        from apex_tpu.monitor import schema as mon_schema
        with open(args.static_cost, "w") as fh:
            for cost in costs:
                errors = mon_schema.validate(cost)
                if errors:  # pragma: no cover - emitter bug guard
                    print("error: refusing to write invalid static_cost "
                          f"for {cost.get('entrypoint')!r}: {errors}",
                          file=sys.stderr)
                    return 2
                fh.write(json.dumps(cost) + "\n")
        report["static_cost_path"] = args.static_cost

    if memory_on:
        report["memory"] = mems
    if args.static_memory:
        from apex_tpu.monitor import schema as mon_schema
        with open(args.static_memory, "w") as fh:
            for mem in mems:
                errors = mon_schema.validate(mem)
                if errors:  # pragma: no cover - emitter bug guard
                    print("error: refusing to write invalid "
                          f"static_memory for {mem.get('entrypoint')!r}: "
                          f"{errors}", file=sys.stderr)
                    return 2
                fh.write(json.dumps(mem) + "\n")
        report["static_memory_path"] = args.static_memory

    tables = []
    uncalibrated = {}
    if args.costdb:
        from apex_tpu.prof.calibrate import diff_static_cost, validate_costdb
        try:
            with open(args.costdb, encoding="utf-8") as fh:
                db = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: cannot read costdb {args.costdb}: {e}",
                  file=sys.stderr)
            return 2
        errors = validate_costdb(db)
        if errors:
            print(f"error: {args.costdb} is not a valid costdb artifact: "
                  f"{errors}", file=sys.stderr)
            return 2
        report["costdb_diff"] = {}
        for cost in costs:
            diff = diff_static_cost(cost, db)
            report["costdb_diff"][cost["entrypoint"]] = diff
            if diff["uncovered"]:
                uncalibrated[cost["entrypoint"]] = diff["uncovered"]
            tables.append(_format_diff_table(cost["entrypoint"], diff))
        # the blind-spot surface as DATA (ISSUE 12 satellite): the
        # planner and CI consume this section (and --strict's exit
        # code) instead of scraping the "!! ... UNCALIBRATED" prose
        report["uncalibrated"] = uncalibrated

    _emit_report(args, findings, stats, baselined, unused, report)
    if args.format != "json":
        if memory_on:
            print(_format_memory_table(mems, gated=budgets is not None))
        for table in tables:
            print(table)
    if findings:
        return 1
    if args.strict and uncalibrated:
        n = sum(len(v) for v in uncalibrated.values())
        print(f"strict: {n} traced cost key(s) have no CostDB row: "
              + "; ".join(f"{ep}: {', '.join(keys)}"
                          for ep, keys in sorted(uncalibrated.items())),
              file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        for r in lint.iter_rules():
            print(f"{r.code}  {r.name}: {r.summary}")
        from apex_tpu.lint.contracts import JXP_CODES
        for code, (name, summary) in sorted(JXP_CODES.items()):
            print(f"{code}  {name} (--jaxpr contract): {summary}")
        return 0
    if (args.jaxpr or args.entrypoint or args.list_entrypoints
            or args.static_cost or args.costdb or args.memory
            or args.static_memory or args.budget_file):
        return _jaxpr_main(args)
    if not args.paths:
        print("error: no paths given (try `python -m apex_tpu.lint "
              "apex_tpu/`)", file=sys.stderr)
        return 2

    try:
        findings, stats = lint.lint_paths(
            args.paths, select=_codes(args.select), ignore=_codes(args.ignore))
    except (FileNotFoundError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    applied = _apply_baseline(args, findings)
    if isinstance(applied, int):
        return applied
    findings, baselined, unused = applied

    report = lint.build_report(findings, stats, baselined)
    _emit_report(args, findings, stats, baselined, unused, report)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
