"""APX3xx — Pallas TPU kernel constraints.

Mosaic tiles VMEM as (sublane, lane) = (8, 128) fp32 tiles (bf16 packs
(16, 128), int8 (32, 128) — all multiples of the fp32 tile, so the base
multiple is the sound static check; see /opt/skills guides and PERF.md's
retile notes). Block shapes off the tile force relayouts or padding on
every grid step — the exact class of silent perf bug the fmha_varlen
truncation round came from. And every kernel in this repo must stay
runnable off-TPU: ``ops/`` convention plumbs ``interpret=`` through each
``pl.pallas_call`` so the CPU suite executes the real kernel bodies
(``APEX_TPU_PALLAS=interpret``).

Rules
-----
APX301  blockspec-off-tile        literal trailing block dims not multiples
                                  of (8, 128) (size-1 dims exempt)
APX302  index-map-arity           BlockSpec index_map lambda whose arity
                                  differs from the literal grid rank — it
                                  positionally ignores (or invents) a grid
                                  axis
APX303  pallas-call-no-interpret  pl.pallas_call without an ``interpret=``
                                  kwarg — unrunnable in the CPU test suite
APX304  materialized-bias-into-flash  a materialized full-(h, sq, sk)
                                  relative bias (``relative_bias(...)`` /
                                  ``BucketedBias.materialize(...)``)
                                  feeding a fused-attention ``bias=``
                                  operand — O(h·s²) HBM that defeats the
                                  kernel; pass the BucketedBias itself
"""

from __future__ import annotations

import ast
from typing import Optional

from apex_tpu.lint.core import ModuleContext, rule

_SUBLANE, _LANE = 8, 128


def _is_blockspec(ctx: ModuleContext, call: ast.Call) -> bool:
    canon = ctx.call_name(call) or ""
    return canon.endswith(".BlockSpec") or canon == "BlockSpec"


def _is_pallas_call(ctx: ModuleContext, call: ast.Call) -> bool:
    canon = ctx.call_name(call) or ""
    return canon.endswith(".pallas_call") or canon == "pallas_call"


def _block_shape(call: ast.Call) -> Optional[ast.Tuple]:
    if call.args and isinstance(call.args[0], ast.Tuple):
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "block_shape" and isinstance(kw.value, ast.Tuple):
            return kw.value
    return None


@rule("APX301", "blockspec-off-tile",
      "BlockSpec trailing block dims must be multiples of the (8, 128) "
      "TPU tile (dtype-packed tiles are multiples of it too); size-1 "
      "dims are exempt")
def check_apx301(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and _is_blockspec(ctx, node)):
            continue
        shape = _block_shape(node)
        if shape is None or len(shape.elts) < 1:
            continue
        dims = shape.elts
        checks = []
        if len(dims) >= 1:
            checks.append((dims[-1], _LANE, "last (lane)"))
        if len(dims) >= 2:
            checks.append((dims[-2], _SUBLANE, "second-to-last (sublane)"))
        for expr, mult, which in checks:
            if isinstance(expr, ast.Constant) and isinstance(expr.value, int) \
                    and not isinstance(expr.value, bool):
                v = expr.value
                if v != 1 and v % mult:
                    yield ctx.finding(
                        expr, "APX301",
                        f"{which} block dim {v} is not a multiple of "
                        f"{mult} — Mosaic pads every grid step to the "
                        f"({_SUBLANE}, {_LANE}) tile (bf16/int8 tiles are "
                        "multiples of it); round the block up or fold the "
                        "ragged edge into masking")


def _grid_rank(call: ast.Call) -> Optional[int]:
    for kw in call.keywords:
        if kw.arg != "grid":
            continue
        if isinstance(kw.value, ast.Tuple):
            return len(kw.value.elts)
        if isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, int):
            return 1
    return None


def _index_map(call: ast.Call) -> Optional[ast.Lambda]:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Lambda):
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "index_map" and isinstance(kw.value, ast.Lambda):
            return kw.value
    return None


@rule("APX302", "index-map-arity",
      "a BlockSpec index_map whose lambda arity differs from the grid rank "
      "positionally ignores (or invents) a grid axis")
def check_apx302(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and _is_pallas_call(ctx, node)):
            continue
        rank = _grid_rank(node)
        if rank is None:
            continue
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Call) and _is_blockspec(ctx, sub)):
                continue
            lam = _index_map(sub)
            if lam is None:
                continue
            if lam.args.vararg is not None:
                continue  # `lambda *ixs:` handles every grid rank
            # bound constants (lambda i, j, g=group: ...) are not grid axes
            arity = len(lam.args.args) - len(lam.args.defaults)
            if arity != rank:
                yield ctx.finding(
                    lam, "APX302",
                    f"index_map takes {arity} grid indices but the grid "
                    f"has rank {rank} — the map ignores or invents a grid "
                    "axis (intentional value-level broadcast like "
                    "`lambda i, j: (i, 0)` is fine and not flagged)")


_ATTN_SINKS = ("flash_attention", "fused_qkv_attention", "ring_attention",
               "ulysses_attention")


def _is_bias_materializer(ctx: ModuleContext, call: ast.Call) -> bool:
    canon = ctx.call_name(call) or ""
    return (canon == "relative_bias" or canon.endswith(".relative_bias")
            or canon.endswith(".materialize"))


def _expr_has(pred, expr: ast.expr, tainted: set) -> bool:
    """Does ``expr`` contain a call matching ``pred`` or a tainted name?"""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and pred(sub):
            return True
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return True
    return False


def _taint_names(stmts, pred) -> set:
    """Flow-insensitive per-scope taint fixpoint: names assigned (anywhere
    in the scope) from an expression containing a ``pred`` call or an
    already-tainted name — iterated so ``a = seed(...); b = a[0]`` taints
    ``b`` too. Shared by APX304 and APX403 (one copy of the taint
    semantics; per-scope via :func:`_scope_bodies`/:func:`_scope_nodes`)."""
    tainted: set = set()
    changed = True
    while changed:
        changed = False
        for node in stmts:
            if isinstance(node, ast.Assign) and _expr_has(
                    pred, node.value, tainted):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name) and n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
    return tainted


def _scope_nodes(body):
    """All AST nodes lexically inside ``body``, NOT descending into nested
    function definitions (each function is its own taint scope; lambdas
    stay in-scope — they close over the same names)."""
    out = []
    stack = list(body)
    while stack:
        n = stack.pop()
        out.append(n)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested scope: listed, never entered
        stack.extend(ast.iter_child_nodes(n))
    return out


def _scope_bodies(tree: ast.Module):
    """Per-lexical-scope statement lists: module top level (function
    bodies excluded) + each function — the flow-insensitive scoping the
    taint rules use."""
    yield tree.body
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n.body


@rule("APX304", "materialized-bias-into-flash",
      "a materialized full-(h, sq, sk) relative bias feeding a fused-"
      "attention bias= operand — O(h·s²) HBM where the bucketed table "
      "operand computes the same bias in-kernel from O(buckets·h)")
def check_apx304(ctx: ModuleContext):
    def is_materializer(call):
        return _is_bias_materializer(ctx, call)

    for body in _scope_bodies(ctx.tree):
        stmts = _scope_nodes(body)
        tainted = _taint_names(stmts, is_materializer)
        for node in stmts:
            if not isinstance(node, ast.Call):
                continue
            canon = ctx.call_name(node) or ""
            if not any(canon == s or canon.endswith("." + s)
                       for s in _ATTN_SINKS):
                continue
            bias_expr = None
            for kw in node.keywords:
                if kw.arg == "bias":
                    bias_expr = kw.value
            if (bias_expr is None and canon.endswith("fused_qkv_attention")
                    and len(node.args) >= 5):
                bias_expr = node.args[4]  # (x, w_qkv, b_qkv, w_out, bias)
            if bias_expr is None:
                continue
            if _expr_has(is_materializer, bias_expr, tainted):
                yield ctx.finding(
                    bias_expr, "APX304",
                    "materialized (h, sq, sk) relative bias feeds a "
                    "fused-attention call — O(h·s²) HBM (1.6 GB fp32 at "
                    "s=8192, h=6) that the kernel exists to avoid; pass "
                    "the BucketedBias table operand instead (the kernels "
                    "recompute the bias per tile from O(buckets·h))")


@rule("APX303", "pallas-call-no-interpret",
      "pl.pallas_call without an interpret= kwarg — the repo's ops/ "
      "convention requires the interpret-mode fallback so CPU tests "
      "execute the real kernel body")
def check_apx303(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and _is_pallas_call(ctx, node)):
            continue
        kw_names = {kw.arg for kw in node.keywords}
        if "interpret" in kw_names:
            continue
        if None in kw_names:  # **kwargs may carry interpret through
            continue
        yield ctx.finding(
            node, "APX303",
            "pallas_call without interpret= — plumb the op's interpret "
            "flag (ops/_backend.interpret_mode()) through so the kernel "
            "runs in the CPU suite (APEX_TPU_PALLAS=interpret)")
