"""apexlint engine: rule registry, per-module analysis context, taint
propagation, suppression, baseline, and the report document.

Why a repo-local linter instead of flake8 plugins: the hazards that have
actually cost this repo debugging rounds are *JAX-semantic*, not syntactic —
Python control flow on traced values, donated buffers read after the jitted
call, Pallas block shapes off the (8, 128) tile, collectives naming axes the
mesh doesn't define, dropout-rate parameters with no PRNG-key path (the
``multihead_attn`` bug). The reference ships the same kind of correctness
tooling next to its kernels (ASP mask checkers; pyprof's static analyzers
over 26 op families, PAPER §5); this module is that discipline for the
tracing-time failure modes a JAX/Pallas rewrite trades CUDA's compile-time
type errors for.

Everything here is stdlib-only (``ast`` + ``json``): the analysis never
imports jax, so it cannot be confused — or broken — by the jax version it
is vetting code against (jax API drift is one of the bug classes it
catches). The ``python -m apex_tpu.lint`` entry does import the parent
``apex_tpu`` package (which imports jax) — a totally broken jax install
therefore breaks the CLI, not the engine; the escape hatch is copying the
``apex_tpu/lint`` directory out as a standalone package (its internal
imports are the only non-stdlib ones and are all within the package).
:func:`lint_source` guards against a partially-imported engine by
refusing to run with an empty rule registry.

Analysis model
--------------
One :class:`ModuleContext` per file carries the parsed tree, import-alias
resolution (``jnp`` → ``jax.numpy``), a parent map, and per-line suppression
sets. Rules are plain functions registered with :func:`rule`; each walks the
tree itself (files are small; a shared dispatch loop would save nothing).

The tracing rules (APX1xx) use a deliberately *flow-insensitive* taint pass:
parameters of a jit-traced function are tainted, assignments propagate taint,
and reads of statically-known properties (``.shape``/``.ndim``/``.dtype``/
``.size``, ``len()``, ``isinstance()``, ``is None`` checks) launder it.
Flow-insensitivity overapproximates; the escape hatches are
``# apexlint: disable=CODE`` on the flagged line and the committed baseline
(every entry carrying a human reason).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

#: Canonical mesh axis names (apex_tpu.parallel.mesh). Collective/partition
#: rules treat any other string-literal axis as a typo until baselined.
KNOWN_MESH_AXES = frozenset({"dp", "tp", "pp", "cp", "ep"})

#: Attribute reads that are static at trace time — accessing them on a traced
#: array yields a Python value, so they END a taint chain.
STATIC_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size", "itemsize", "sharding", "aval",
    "weak_type",
})

#: Host calls whose result is static regardless of argument taint.
#: (getattr is NOT here: getattr(x, "T") on a traced array is traced —
#: it launders only when the attribute name is itself a static property.)
_LAUNDERING_CALLS = frozenset({"len", "isinstance", "type", "hasattr",
                               "id", "repr"})

PARSE_ERROR_CODE = "APX000"


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    code: str
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def sort_key(self):
        return (self.path, self.line, self.col, self.code)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"


@dataclasses.dataclass(frozen=True)
class Rule:
    code: str
    name: str
    summary: str
    check: Callable[["ModuleContext"], Iterable[Finding]]


REGISTRY: Dict[str, Rule] = {}


def rule(code: str, name: str, summary: str):
    """Register a rule. ``check(ctx)`` yields :class:`Finding`."""

    def deco(fn):
        if code in REGISTRY:  # pragma: no cover - programming error
            raise ValueError(f"duplicate rule code {code}")
        REGISTRY[code] = Rule(code, name, summary, fn)
        return fn

    return deco


# --- per-module context -------------------------------------------------------

# codes matched strictly so trailing prose is allowed:
#   x = ...  # apexlint: disable=APX301 - ragged edge is masked in-kernel
_SUPPRESS_RE = re.compile(
    r"#\s*apexlint:\s*disable=(all|APX\d{3}(?!\d)(?:\s*,\s*APX\d{3}(?!\d))*)",
    re.IGNORECASE)


class ModuleContext:
    def __init__(self, path: str, source: str, tree: ast.Module,
                 scan_rel: Optional[str] = None):
        self.path = path
        #: path relative to the scanned root (lint_paths sets it) — the
        #: part of the path the REPO is responsible for; test-likeness is
        #: judged on this so an ancestor directory named tests/examples
        #: outside the checkout cannot disable rules
        self.scan_rel = scan_rel if scan_rel is not None else path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.aliases = _collect_aliases(tree)
        self.defs: Dict[str, ast.FunctionDef] = {}
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, node)
        self.suppressions = _collect_suppressions(source, self.lines)

    # -- name resolution ------------------------------------------------------

    def canonical(self, node) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with import aliases expanded:
        ``pl.BlockSpec`` → ``jax.experimental.pallas.BlockSpec``. None for
        anything that isn't a plain dotted chain."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(self.aliases.get(node.id, node.id))
            return ".".join(reversed(parts))
        return None

    def call_name(self, call: ast.Call) -> Optional[str]:
        return self.canonical(call.func)

    def ancestors(self, node):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node) -> Optional[ast.FunctionDef]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def finding(self, node, code: str, message: str) -> Finding:
        return Finding(self.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), code, message)

    def is_testlike_path(self) -> bool:
        """Test/example code is exempt from library-discipline rules
        (APX502). Directory components must match EXACTLY ('tests', not
        any prefix) so an absolute checkout path like /home/testuser/...
        cannot silently disable rules for the whole library; only the file
        basename itself is prefix-matched."""
        parts = self.scan_rel.replace("\\", "/").lower().split("/")
        dirs, base = parts[:-1], parts[-1]
        if any(d in ("test", "tests", "testing", "example", "examples",
                     "fixtures") for d in dirs):
            return True
        return base.startswith(("test_", "test.", "conftest", "example"))


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _comment_texts(source: str, lines: Sequence[str]):
    """(lineno, comment text) pairs — real COMMENT tokens only, so a
    directive spelled inside a string literal is not a directive. Falls
    back to whole-line scanning if tokenization fails (the file may be
    mid-edit; a missed suppression is safer than a phantom one)."""
    import io
    import tokenize
    try:
        return [(tok.start[0], tok.string) for tok in
                tokenize.generate_tokens(io.StringIO(source).readline)
                if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return list(enumerate(lines, start=1))


def _collect_suppressions(source: str,
                          lines: Sequence[str]) -> Dict[int, frozenset]:
    out: Dict[int, frozenset] = {}
    for i, text in _comment_texts(source, lines):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        raw = m.group(1).strip()
        if raw.lower() == "all":
            out[i] = frozenset({"all"})
        else:
            out[i] = frozenset(c.strip().upper() for c in raw.split(",")
                               if c.strip())
    return out


# --- taint (APX1xx support) ---------------------------------------------------

def is_none_check(test: ast.expr) -> bool:
    """``x is None`` / ``x is not None`` — a static pytree-structure check,
    legal on traced values (None never traces)."""
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
            and (any(isinstance(c, ast.Constant) and c.value is None
                     for c in test.comparators)
                 or (isinstance(test.left, ast.Constant)
                     and test.left.value is None)))


def expr_taint(expr: ast.expr, tainted: frozenset) -> bool:
    """Is any value flowing out of ``expr`` derived from a tainted name —
    stopping at statically-known properties (shape/dtype/len/...)?"""
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Attribute):
        if expr.attr in STATIC_ATTRS:
            return False
        return expr_taint(expr.value, tainted)
    if isinstance(expr, ast.Call):
        fname = None
        if isinstance(expr.func, ast.Name):
            fname = expr.func.id
        if fname in _LAUNDERING_CALLS:
            return False
        if fname == "getattr" and len(expr.args) >= 2 and \
                isinstance(expr.args[1], ast.Constant) and \
                expr.args[1].value in STATIC_ATTRS:
            return False  # getattr(x, "shape"): static like x.shape
        args = list(expr.args) + [k.value for k in expr.keywords]
        if isinstance(expr.func, ast.Attribute):
            args.append(expr.func.value)
        return any(expr_taint(a, tainted) for a in args)
    if isinstance(expr, ast.Compare):
        if is_none_check(expr):
            return False
        return any(expr_taint(e, tainted)
                   for e in [expr.left] + list(expr.comparators))
    if isinstance(expr, ast.Constant):
        return False
    if isinstance(expr, (ast.Lambda, ast.FunctionDef)):
        return False
    return any(expr_taint(child, tainted)
               for child in ast.iter_child_nodes(expr)
               if isinstance(child, ast.expr))


def _assign_targets(node) -> List[str]:
    names: List[str] = []

    def rec(t):
        if isinstance(t, ast.Name):
            names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                rec(e)
        elif isinstance(t, ast.Starred):
            rec(t.value)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            rec(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
        rec(node.target)
    return names


def tainted_names(fn: ast.FunctionDef, static_names: frozenset) -> frozenset:
    """Flow-insensitive taint fixpoint: traced params + everything assigned
    from a tainted expression anywhere in the function body."""
    args = fn.args
    params = [a.arg for a in
              list(getattr(args, "posonlyargs", [])) + args.args
              + args.kwonlyargs]
    if args.vararg:
        params.append(args.vararg.arg)
    if args.kwarg:
        params.append(args.kwarg.arg)
    cache = getattr(fn, "_apexlint_taint", None)
    if cache is None:
        cache = fn._apexlint_taint = {}
    if static_names in cache:
        return cache[static_names]
    taint = {p for p in params if p not in static_names and p != "self"}

    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            value = None
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = node.value
            elif isinstance(node, ast.AugAssign):
                value = node.value
            elif isinstance(node, ast.For):
                value = node.iter
            if value is None:
                continue
            if expr_taint(value, frozenset(taint)):
                for name in _assign_targets(node):
                    if name not in taint:
                        taint.add(name)
                        changed = True
    cache[static_names] = frozenset(taint)
    return cache[static_names]


# --- jit-wrap discovery (shared by APX1xx/2xx) --------------------------------

JIT_WRAPPERS = frozenset({
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit", "pjit.pjit",
})


def _is_trace_wrapper(canon: Optional[str]) -> bool:
    """jit/pjit plus the other tracers the ISSUE spec names: shard_map
    (any spelling — the repo's own mesh.shard_map included) and pmap.
    Functions wrapped by any of these have traced parameters."""
    if canon is None:
        return False
    return (canon in JIT_WRAPPERS
            or canon == "shard_map" or canon.endswith(".shard_map")
            or canon in ("jax.pmap", "pmap"))


def _const_int(node) -> Optional[int]:
    """An int literal, including negative ones (``-1`` parses as
    ``UnaryOp(USub, Constant)``)."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_int(node.operand)
        return None if inner is None else -inner
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _const_int_seq(node) -> Optional[List[int]]:
    """Literal int / tuple-or-list of int literals → list of ints; None when
    the value isn't statically readable (a variable, a computed tuple)."""
    single = _const_int(node)
    if single is not None:
        return [single]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            v = _const_int(e)
            if v is None:
                return None
            out.append(v)
        return out
    return None


def _const_str_seq(node) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    return None


@dataclasses.dataclass
class JitSite:
    """One jax.jit/pjit wrap: the Call (or bare decorator) node, the wrapped
    FunctionDef when resolvable, and the statically-readable kwargs."""
    node: ast.AST
    fn: Optional[ast.FunctionDef]
    static_argnums: Optional[List[int]] = None
    static_argnames: Optional[List[str]] = None
    donate_argnums: Optional[List[int]] = None
    donate_argnames: Optional[List[str]] = None
    raw_kwargs: dict = dataclasses.field(default_factory=dict)
    #: True when jit wrapped a BOUND method (``jax.jit(self._step)``):
    #: argnum indices then count from the first post-self parameter. A
    #: DECORATED method is wrapped unbound — indices count ``self`` at 0.
    bound: bool = False


def _read_jit_kwargs(site: JitSite, call: ast.Call):
    for kw in call.keywords:
        if kw.arg is None:
            continue
        site.raw_kwargs[kw.arg] = kw.value
        if kw.arg == "static_argnums":
            site.static_argnums = _const_int_seq(kw.value)
        elif kw.arg == "static_argnames":
            site.static_argnames = _const_str_seq(kw.value)
        elif kw.arg == "donate_argnums":
            site.donate_argnums = _const_int_seq(kw.value)
        elif kw.arg == "donate_argnames":
            site.donate_argnames = _const_str_seq(kw.value)


def positional_params(fn, bound: bool = True) -> List[str]:
    """Positional parameter names of a FunctionDef/Lambda as an argnum
    index space. ``bound=True`` (a ``jax.jit(self.method)`` value wrap)
    drops ``self`` — jit saw the bound method; ``bound=False`` (a
    decorator on the def) keeps it — jit wraps the unbound function and
    index 0 IS ``self``."""
    args = fn.args
    pos = [a.arg for a in list(getattr(args, "posonlyargs", [])) + args.args]
    if bound and pos and pos[0] == "self":
        pos = pos[1:]
    return pos


def is_unbound_method(fn) -> bool:
    pos = [a.arg for a in
           list(getattr(fn.args, "posonlyargs", [])) + fn.args.args]
    return bool(pos) and pos[0] == "self"


def jit_sites(ctx: ModuleContext) -> List[JitSite]:
    """Every trace-wrap in the module: decorators (bare, call, or
    functools.partial(jax.jit, ...)) and ``jax.jit(f, ...)`` /
    ``shard_map(f, ...)`` / ``pmap(f, ...)`` value calls whose wrapped
    function is resolvable. Cached per context — six rules consult this."""
    cached = getattr(ctx, "_jit_sites", None)
    if cached is not None:
        return cached
    sites: List[JitSite] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                site = _jit_site_from_decorator(ctx, dec, node)
                if site:
                    sites.append(site)
        elif isinstance(node, ast.Call):
            canon = ctx.call_name(node)
            if _is_trace_wrapper(canon) and node.args:
                target = node.args[0]
                fn, bound = None, False
                if isinstance(target, ast.Name):
                    fn = ctx.defs.get(target.id)
                elif isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "self":
                    fn = ctx.defs.get(target.attr)
                    bound = True
                elif isinstance(target, ast.Lambda):
                    fn = None
                site = JitSite(node, fn, bound=bound)
                _read_jit_kwargs(site, node)
                sites.append(site)
    ctx._jit_sites = sites
    return sites


def _jit_site_from_decorator(ctx, dec, fn) -> Optional[JitSite]:
    canon = ctx.canonical(dec)
    if _is_trace_wrapper(canon):
        return JitSite(dec, fn)
    if isinstance(dec, ast.Call):
        fcanon = ctx.call_name(dec)
        if _is_trace_wrapper(fcanon):
            site = JitSite(dec, fn)
            _read_jit_kwargs(site, dec)
            return site
        if fcanon in ("functools.partial", "partial") and dec.args and \
                _is_trace_wrapper(ctx.canonical(dec.args[0])):
            site = JitSite(dec, fn)
            _read_jit_kwargs(site, dec)
            return site
    return None


def traced_functions(ctx: ModuleContext) -> List[Tuple[ast.FunctionDef,
                                                       frozenset]]:
    """(function, static param names) pairs for every def whose body jax
    traces. static_argnums are resolved to names through the def's
    positional parameter list (``self`` skipped for bound-method wraps)."""
    out = {}
    for site in jit_sites(ctx):
        if site.fn is None:
            continue
        statics = set(site.static_argnames or [])
        pos = positional_params(site.fn, site.bound)
        for idx in site.static_argnums or []:
            real = idx if idx >= 0 else len(pos) + idx
            if 0 <= real < len(pos):
                statics.add(pos[real])
        key = site.fn
        # a function wrapped more than once is traced with EVERY wrap's
        # arguments: only params static in ALL wraps are safely static
        # (union would let one static wrap silence hazards in the others)
        if key in out:
            out[key] = out[key] & frozenset(statics)
        else:
            out[key] = frozenset(statics)
    return list(out.items())


# --- running ------------------------------------------------------------------

def _iter_py_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """(path, scan_rel) pairs; scan_rel is the path below the scanned
    argument (argument basename included) — the part the repo owns."""
    files: List[Tuple[str, str]] = []
    for p in paths:
        if os.path.isdir(p):
            base = os.path.basename(os.path.normpath(os.path.abspath(p)))
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for n in sorted(names):
                    if n.endswith(".py"):
                        fp = os.path.join(root, n)
                        files.append(
                            (fp, os.path.join(base, os.path.relpath(fp, p))))
        elif p.endswith(".py"):
            files.append((p, os.path.basename(p)))
        else:
            raise FileNotFoundError(f"not a .py file or directory: {p}")
    return files


def _norm(path: str) -> str:
    return os.path.normpath(path).replace(os.sep, "/")


def _code_selected(code: str, select, ignore) -> bool:
    if select and not any(code.startswith(s) for s in select):
        return False
    if ignore and any(code.startswith(s) for s in ignore):
        return False
    return True


def lint_source(source: str, path: str = "<memory>.py",
                select: Optional[Sequence[str]] = None,
                ignore: Optional[Sequence[str]] = None,
                scan_rel: Optional[str] = None,
                ) -> Tuple[List[Finding], int]:
    """Lint one source string. Returns (findings, inline_suppressed_count).
    The API entry the fixture tests and the docs pre-flight example use."""
    if not REGISTRY:
        raise RuntimeError(
            "no rules registered — import apex_tpu.lint (which loads the "
            "rule modules), not apex_tpu.lint.core alone; an empty "
            "registry would report every file as clean")
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(_norm(path), e.lineno or 1, e.offset or 0,
                        PARSE_ERROR_CODE,
                        f"file does not parse: {e.msg}")], 0
    ctx = ModuleContext(_norm(path), source, tree,
                        scan_rel=_norm(scan_rel) if scan_rel else None)
    findings: List[Finding] = []
    for code in sorted(REGISTRY):
        if not _code_selected(code, select, ignore):
            continue
        findings.extend(REGISTRY[code].check(ctx))
    kept, suppressed = [], 0
    for f in findings:
        sup = ctx.suppressions.get(f.line, frozenset())
        if "all" in sup or f.code in sup:
            suppressed += 1
        else:
            kept.append(f)
    kept.sort(key=Finding.sort_key)
    return kept, suppressed


def lint_paths(paths: Sequence[str],
               select: Optional[Sequence[str]] = None,
               ignore: Optional[Sequence[str]] = None,
               ) -> Tuple[List[Finding], dict]:
    files = _iter_py_files(paths)
    findings: List[Finding] = []
    inline = 0
    for fp, scan_rel in files:
        try:
            import tokenize
            with tokenize.open(fp) as fh:  # honors PEP 263 coding lines
                src = fh.read()
        except (UnicodeDecodeError, SyntaxError, LookupError) as e:
            findings.append(Finding(_norm(fp), 1, 0, PARSE_ERROR_CODE,
                                    f"file cannot be decoded: {e}"))
            continue
        got, sup = lint_source(src, path=fp, select=select, ignore=ignore,
                               scan_rel=scan_rel)
        findings.extend(got)
        inline += sup
    findings.sort(key=Finding.sort_key)
    return findings, {"files_scanned": len(files),
                      "suppressed_inline": inline}


# --- baseline -----------------------------------------------------------------

class BaselineError(ValueError):
    pass


def load_baseline(path: str) -> List[dict]:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or not isinstance(doc.get("entries"), list):
        raise BaselineError(
            f"{path}: baseline must be {{'version': 1, 'entries': [...]}}")
    entries = doc["entries"]
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise BaselineError(f"{path}: entries[{i}] is not an object")
        for field in ("path", "code", "reason"):
            if not isinstance(e.get(field), str) or not e[field].strip():
                raise BaselineError(
                    f"{path}: entries[{i}] missing non-empty '{field}' — "
                    "every baselined finding must carry its reason")
    return entries


def _baseline_matches(entry: dict, finding: Finding) -> bool:
    ep, fp = _norm(entry["path"]), _norm(finding.path)
    return (entry["code"] == finding.code
            and (fp == ep or fp.endswith("/" + ep)))


def apply_baseline(findings: List[Finding], entries: List[dict]
                   ) -> Tuple[List[Finding], int, List[dict]]:
    """Returns (kept findings, baselined count, unused entries)."""
    used = [False] * len(entries)
    kept: List[Finding] = []
    baselined = 0
    for f in findings:
        hit = False
        for i, e in enumerate(entries):
            if _baseline_matches(e, f):
                used[i] = True
                hit = True
        if hit:
            baselined += 1
        else:
            kept.append(f)
    unused = [e for e, u in zip(entries, used) if not u]
    return kept, baselined, unused


# --- report document ----------------------------------------------------------

REPORT_VERSION = 1


def build_report(findings: List[Finding], stats: dict,
                 baselined: int = 0) -> dict:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.code] = counts.get(f.code, 0) + 1
    return {
        "tool": "apexlint",
        "version": REPORT_VERSION,
        "findings": [f.to_dict() for f in findings],
        "counts": counts,
        "files_scanned": stats.get("files_scanned", 0),
        "suppressed_inline": stats.get("suppressed_inline", 0),
        "suppressed_baseline": baselined,
    }


# APX = AST rules; JXP = jaxpr contracts (`--jaxpr` runs report through
# the same document, so the validator accepts both families)
_CODE_RE = re.compile(r"^(APX|JXP)\d{3}$")


def validate_report(obj) -> List[str]:
    """Schema check for ``--format json`` output — consumed by
    ``tools/validate_metrics.py --lint-report`` so the lint artifact is
    gated the same way bench/gate artifacts are."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return ["lint report is not a JSON object"]
    if obj.get("tool") != "apexlint":
        problems.append("tool != 'apexlint'")
    if obj.get("version") != REPORT_VERSION:
        problems.append(f"version != {REPORT_VERSION}")
    findings = obj.get("findings")
    if not isinstance(findings, list):
        problems.append("findings is not a list")
        findings = []
    counts: Dict[str, int] = {}
    for i, f in enumerate(findings):
        where = f"findings[{i}]"
        if not isinstance(f, dict):
            problems.append(f"{where} is not an object")
            continue
        if not (isinstance(f.get("path"), str) and f["path"]):
            problems.append(f"{where}.path missing/empty")
        if not (isinstance(f.get("line"), int) and f["line"] >= 1):
            problems.append(f"{where}.line must be an int >= 1")
        if not (isinstance(f.get("col"), int) and f["col"] >= 0):
            problems.append(f"{where}.col must be an int >= 0")
        code = f.get("code")
        if not (isinstance(code, str) and _CODE_RE.match(code)):
            problems.append(f"{where}.code must match APXnnn")
        else:
            counts[code] = counts.get(code, 0) + 1
        if not (isinstance(f.get("message"), str) and f["message"].strip()):
            problems.append(f"{where}.message missing/empty")
    if isinstance(obj.get("counts"), dict):
        if obj["counts"] != counts and not problems:
            problems.append(
                f"counts {obj['counts']} disagree with findings {counts}")
    else:
        problems.append("counts is not an object")
    for field in ("files_scanned", "suppressed_inline", "suppressed_baseline"):
        v = obj.get(field)
        if not (isinstance(v, int) and v >= 0):
            problems.append(f"{field} must be an int >= 0")
    return problems
