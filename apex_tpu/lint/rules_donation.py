"""APX2xx — buffer-donation and aliasing hygiene.

``donate_argnums`` hands the argument's HBM to XLA: the caller's array is
dead after the call. Reading it afterwards returns garbage (on TPU) or a
``deleted buffer`` error (with checks on) — and the failure only reproduces
on hardware, which is exactly why it belongs in a static pass. The decode
engine donates its KV cache (``inference/engine.py``); its generate loop is
the canonical *correct* pattern (re-bind the donated buffer from the call
result every iteration).

Rules
-----
APX201  use-after-donation       a donated argument read after the jitted
                                 call in the same function body
APX202  donated-not-rebound      a donating call inside a Python loop whose
                                 donated argument is never re-bound from the
                                 result — next iteration reuses a dead buffer

Known limitations (conservative false NEGATIVES, never false positives):
a donating call nested inside a ``with``/``try`` body only scans its own
block for later reads (the post-block scan is reserved for straight-line
calls — branch-nested donations would otherwise flag reads on paths where
the donation never ran), and the jit-target resolution here is a local
sibling of ``core.jit_sites``'s value-call arm (kept separate because this
module needs call-site index spaces, not def-site ones).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from apex_tpu.lint.core import (ModuleContext, is_unbound_method, jit_sites,
                                positional_params, rule)

_JIT_NAMES = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")


def _wrapped_def(ctx: ModuleContext, call: ast.Call):
    """(fn, bound) for the FunctionDef/Lambda a ``jax.jit(target, ...)``
    call wraps, when resolvable (plain name, ``self.x`` bound method, or
    inline lambda)."""
    if not call.args:
        return None, False
    target = call.args[0]
    if isinstance(target, ast.Name):
        return ctx.defs.get(target.id), False
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and target.value.id == "self":
        return ctx.defs.get(target.attr), True
    if isinstance(target, ast.Lambda):
        return target, False
    return None, False


def _donated_indices(ctx: ModuleContext, call: ast.Call) -> Optional[List[int]]:
    """Donated CALL-SITE positional indices of a jit value-wrap:
    donate_argnums directly (jit saw exactly the callable the call site
    sees, bound or not), or donate_argnames resolved through the wrapped
    function's parameter list."""
    from apex_tpu.lint.core import _const_int_seq, _const_str_seq
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _const_int_seq(kw.value)
        if kw.arg == "donate_argnames":
            names = _const_str_seq(kw.value)
            fn, bound = _wrapped_def(ctx, call)
            if names and fn is not None:
                pos = positional_params(fn, bound)
                return [pos.index(n) for n in names if n in pos] or None
            return None
    return None


def _donating_callables(ctx: ModuleContext) -> Dict[str, List[int]]:
    """Names (plain or ``self.X`` attribute) bound to a jax.jit(...) result
    with donated arguments → donated positional indices."""
    out: Dict[str, List[int]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        if not (isinstance(call, ast.Call)
                and ctx.call_name(call) in _JIT_NAMES):
            continue
        idxs = _donated_indices(ctx, call)
        if not idxs:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = idxs
            elif isinstance(t, ast.Attribute):
                out[t.attr] = idxs
    for site in jit_sites(ctx):
        if site.fn is None:
            continue
        idxs = site.donate_argnums
        if not idxs and site.donate_argnames:
            pos = positional_params(site.fn, site.bound)
            idxs = [pos.index(n) for n in site.donate_argnames if n in pos]
        if idxs and not site.bound and is_unbound_method(site.fn):
            # decorated method: jit indices count `self` at 0, but the
            # `obj.step(...)` call site the map is consulted at does not
            idxs = [i - 1 for i in idxs if i >= 1]
        if idxs:
            out[site.fn.name] = idxs
    return out


def _donated_args(ctx: ModuleContext, call: ast.Call,
                  donors: Dict[str, List[int]]) -> List[Tuple[str, ast.Call]]:
    """(name, call) for each plain-Name positional argument of ``call`` that
    the callee donates."""
    idxs: Optional[List[int]] = None
    f = call.func
    if isinstance(f, ast.Name) and f.id in donors:
        idxs = donors[f.id]
    elif isinstance(f, ast.Attribute) and f.attr in donors:
        idxs = donors[f.attr]
    elif isinstance(f, ast.Call) and ctx.call_name(f) in _JIT_NAMES:
        idxs = _donated_indices(ctx, f)
    if not idxs:
        return []
    out = []
    for i in idxs:
        if 0 <= i < len(call.args) and isinstance(call.args[i], ast.Name):
            out.append((call.args[i].id, call))
    return out


def _name_events(body_nodes, name: str):
    """(lineno, col, kind) for every use of ``name``; kind in
    {'load', 'store'}. ``del x`` reads nothing and unbinds the name, so
    it counts as a store (it safely ends the use-after-donation scan)."""
    events = []
    for stmt in body_nodes:
        for node in ast.walk(stmt):
            if isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name) and \
                    node.target.id == name:
                # `a += 1` LOADS a before storing — on a donated buffer
                # that load is the hazard, not a safe re-bind
                events.append((node.target.lineno,
                               node.target.col_offset, "load"))
            if isinstance(node, ast.Name) and node.id == name:
                kind = "load" if isinstance(node.ctx, ast.Load) else "store"
                events.append((node.lineno, node.col_offset, kind))
    return sorted(events)


def _enclosing_loop(ctx: ModuleContext, node) -> Optional[ast.AST]:
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.For, ast.While)):
            return anc
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
    return None


def _stmt_of(ctx: ModuleContext, node):
    """The outermost statement of the enclosing scope containing ``node``.
    Anchoring 'after the call' at the scope-level statement (not the
    innermost one) keeps reads in a sibling `else:` branch — which can
    never execute after the donating call — out of APX201's line scan."""
    cur = node
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Module)):
            return cur
        cur = anc
    return cur


def _inner_stmt_of(ctx: ModuleContext, node):
    """The innermost simple statement containing ``node`` — the unit the
    same-statement read check (`out = step(x, y) + x`) operates on."""
    cur = node
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Module, ast.For, ast.While, ast.If,
                            ast.With, ast.Try)):
            return cur
        cur = anc
    return cur


def _in_span(node, ev_line, ev_col) -> bool:
    if ev_line < node.lineno or ev_line > node.end_lineno:
        return False
    if ev_line == node.lineno and ev_col < node.col_offset:
        return False
    if ev_line == node.end_lineno and ev_col >= node.end_col_offset:
        return False
    return True


def _following_in_same_body(ctx: ModuleContext, inner):
    """Statements after ``inner`` in the statement list that contains it —
    code that definitely executes after the call on the same path."""
    parent = ctx.parents.get(inner)
    if parent is None:
        return []
    for field in ("body", "orelse", "finalbody"):
        stmts = getattr(parent, field, None)
        if isinstance(stmts, list) and inner in stmts:
            return stmts[stmts.index(inner) + 1:]
    return []


@rule("APX201", "use-after-donation",
      "a parameter listed in donate_argnums is read after the jitted call — "
      "its buffer was handed to XLA and is dead")
def check_apx201(ctx: ModuleContext):
    donors = _donating_callables(ctx)
    if not donors:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        pairs = _donated_args(ctx, node, donors)
        if not pairs or _enclosing_loop(ctx, node) is not None:
            continue  # loop bodies are APX202's flow
        fn = ctx.enclosing_function(node)
        scope = fn.body if fn is not None else ctx.tree.body
        stmt = _stmt_of(ctx, node)
        inner = _inner_stmt_of(ctx, node)
        after = getattr(stmt, "end_lineno", stmt.lineno)
        # reads after the outer compound are only checked when the call is
        # NOT nested in a branch — a conditional donation does not make a
        # post-branch read dead on every path (branch-internal reads below
        # stay covered either way)
        straight_line = inner is stmt
        for name, call in pairs:
            # a read in the donating statement but OUTSIDE the call itself
            # (`out = step(x, y) + x`) executes after the call returns —
            # use-after-donation in one line
            same_stmt = [(ln, c, k) for ln, c, k
                         in _name_events([inner], name)
                         if not _in_span(call, ln, c)]
            if any(k == "load" for _, _, k in same_stmt):
                yield ctx.finding(
                    call, "APX201",
                    f"`{name}` is donated to this call and read again in "
                    "the same statement — that read executes after the "
                    "call returns, on a dead buffer")
                continue
            # `x = step(x, ...)`: the statement's own assignment re-binds
            # the donated name the moment the call returns
            if any(k == "store" for _, _, k in same_stmt):
                continue
            stmts = list(_following_in_same_body(ctx, inner))
            if straight_line:
                stmts.extend(s for s in scope
                             if s.lineno > after and s not in stmts)
            # statement-wise, in order: within one statement the RHS
            # loads execute BEFORE the target store (`x = x * 2` after
            # donating x reads the dead buffer, then re-binds)
            for s in sorted(stmts, key=lambda s: s.lineno):
                evs = _name_events([s], name)
                loads = [e for e in evs if e[2] == "load"]
                if loads:
                    yield ctx.finding(
                        call, "APX201",
                        f"`{name}` is donated to this call but read "
                        f"again at line {loads[0][0]} — the donated "
                        "buffer is dead after the call; re-bind it from "
                        "the result or drop the donation")
                    break
                if any(e[2] == "store" for e in evs):
                    break  # re-bound before any read: fine


@rule("APX202", "donated-not-rebound-in-loop",
      "a donating call inside a loop whose donated argument is never "
      "re-bound from the result — iteration 2 feeds a dead buffer")
def check_apx202(ctx: ModuleContext):
    donors = _donating_callables(ctx)
    if not donors:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        loop = _enclosing_loop(ctx, node)
        if loop is None:
            continue
        for name, call in _donated_args(ctx, node, donors):
            stored = any(kind == "store" for _, _, kind
                         in _name_events(loop.body, name))
            # `for b in bufs: step(b)` — the loop target is a FRESH
            # buffer each iteration, never a donated-dead one
            from apex_tpu.lint.core import _assign_targets
            if isinstance(loop, ast.For) and name in _assign_targets(loop):
                stored = True
            if not stored:
                yield ctx.finding(
                    call, "APX202",
                    f"`{name}` is donated inside this loop but never "
                    "re-bound from the call result — the next iteration "
                    "passes a buffer XLA already reused; thread it through "
                    "(`x, ... = step(x, ...)`) or use lax.scan/fori_loop")
