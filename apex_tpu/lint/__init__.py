"""apexlint — TPU tracing-hazard and kernel-constraint static analysis.

Usage (CLI)::

    python -m apex_tpu.lint apex_tpu/ [--format text|json]
        [--baseline tools/apexlint_baseline.json]
        [--select APX1,APX301] [--ignore APX5] [--list-rules]

Usage (API)::

    from apex_tpu import lint
    findings, suppressed = lint.lint_source(src, path="x.py")
    findings, stats = lint.lint_paths(["apex_tpu/"])

Rule families (catalogue with bad/good snippets: docs/api/lint.md):

* **APX1xx** tracing/recompile hazards (control flow, concretization,
  host numpy on traced values; static_argnums hygiene)
* **APX2xx** donation/aliasing (use-after-donation, donated buffers not
  re-threaded through loops)
* **APX3xx** Pallas kernel constraints ((8, 128) tiling, index-map arity,
  interpret-mode fallback convention, materialized O(s²) bias into fused
  attention)
* **APX4xx** collective/axis hygiene (axis names outside dp/tp/pp/cp/ep)
* **APX5xx** PRNG and precision discipline (dropout without a key,
  constant PRNG keys, bf16/fp32 cast mixing)

Beyond the AST rules, ``python -m apex_tpu.lint --jaxpr`` checks **JXP
contracts** over *traced programs* (``apex_tpu.lint.contracts`` /
``jaxpr_check``): scan geometry (JXP1xx), donation honored at the pjit
level (JXP2xx), forbidden aval shapes (JXP3xx), collective inventory —
ppermute present, no full-width all_gather, collective-free regions
(JXP4xx), and fp32 accumulation (JXP5xx) — against the registered
flagship entrypoints (``apex_tpu.lint.entrypoints``), with the same
walk also emitting the planner's ``static_cost`` artifact.

Suppression: ``# apexlint: disable=APX101`` (comma-separated, or ``all``)
on the flagged line; repo-wide intentional findings live in
``tools/apexlint_baseline.json`` — every entry carries a ``reason``
(jaxpr findings baseline by ``(path="jaxpr:<entrypoint>", code)``).

The lint package itself imports only the stdlib (``ast``/``json``) — the
analysis cannot be confused by the jax version it vets. The
``python -m apex_tpu.lint`` CLI does ride the parent ``apex_tpu`` import
(which imports jax); see ``core.py``'s docstring for driving the engine
jax-free.
"""

from apex_tpu.lint.core import (  # noqa: F401
    Finding,
    KNOWN_MESH_AXES,
    PARSE_ERROR_CODE,
    REGISTRY,
    REPORT_VERSION,
    Rule,
    apply_baseline,
    build_report,
    lint_paths,
    lint_source,
    load_baseline,
    validate_report,
)

# importing the rule modules populates REGISTRY
from apex_tpu.lint import (  # noqa: E402,F401
    rules_collectives,
    rules_donation,
    rules_pallas,
    rules_prng,
    rules_tracing,
)

# the jaxpr-level layer (`--jaxpr`): stdlib-only like the AST rules —
# contracts/jaxpr_check walk duck-typed jaxpr objects; only
# lint.entrypoints (imported lazily by the CLI) touches jax
from apex_tpu.lint import contracts, jaxpr_check  # noqa: E402,F401
from apex_tpu.lint.contracts import (  # noqa: F401
    Contract,
    ContractFinding,
    assert_contracts,
    check_jaxpr,
)
from apex_tpu.lint.jaxpr_check import static_cost  # noqa: F401


def iter_rules():
    """Registered rules in code order."""
    return [REGISTRY[c] for c in sorted(REGISTRY)]
