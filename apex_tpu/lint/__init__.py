"""apexlint — TPU tracing-hazard and kernel-constraint static analysis.

Usage (CLI)::

    python -m apex_tpu.lint apex_tpu/ [--format text|json]
        [--baseline tools/apexlint_baseline.json]
        [--select APX1,APX301] [--ignore APX5] [--list-rules]

Usage (API)::

    from apex_tpu import lint
    findings, suppressed = lint.lint_source(src, path="x.py")
    findings, stats = lint.lint_paths(["apex_tpu/"])

Rule families (catalogue with bad/good snippets: docs/api/lint.md):

* **APX1xx** tracing/recompile hazards (control flow, concretization,
  host numpy on traced values; static_argnums hygiene)
* **APX2xx** donation/aliasing (use-after-donation, donated buffers not
  re-threaded through loops)
* **APX3xx** Pallas kernel constraints ((8, 128) tiling, index-map arity,
  interpret-mode fallback convention, materialized O(s²) bias into fused
  attention)
* **APX4xx** collective/axis hygiene (axis names outside dp/tp/pp/cp/ep)
* **APX5xx** PRNG and precision discipline (dropout without a key,
  constant PRNG keys, bf16/fp32 cast mixing)

Suppression: ``# apexlint: disable=APX101`` (comma-separated, or ``all``)
on the flagged line; repo-wide intentional findings live in
``tools/apexlint_baseline.json`` — every entry carries a ``reason``.

The lint package itself imports only the stdlib (``ast``/``json``) — the
analysis cannot be confused by the jax version it vets. The
``python -m apex_tpu.lint`` CLI does ride the parent ``apex_tpu`` import
(which imports jax); see ``core.py``'s docstring for driving the engine
jax-free.
"""

from apex_tpu.lint.core import (  # noqa: F401
    Finding,
    KNOWN_MESH_AXES,
    PARSE_ERROR_CODE,
    REGISTRY,
    REPORT_VERSION,
    Rule,
    apply_baseline,
    build_report,
    lint_paths,
    lint_source,
    load_baseline,
    validate_report,
)

# importing the rule modules populates REGISTRY
from apex_tpu.lint import (  # noqa: E402,F401
    rules_collectives,
    rules_donation,
    rules_pallas,
    rules_prng,
    rules_tracing,
)


def iter_rules():
    """Registered rules in code order."""
    return [REGISTRY[c] for c in sorted(REGISTRY)]
