"""apexmem: donation-aware buffer-lifetime analysis over traced jaxprs.

The planner prices *time* from the exact traced bytes/FLOPs of
:func:`apex_tpu.lint.jaxpr_check.static_cost`; this module gives *memory*
the same treatment — a static peak-HBM bound read off the program the
compiler actually sees, instead of the hand closed form in
``apex_tpu/plan/cost.py`` that knows nothing about donation, zb dW
stashes, or the paged KV pool. AMP (arXiv:2210.07297) treats memory
feasibility as a first-class pruning predicate in strategy search;
apexmem is that predicate, derived from the trace (the veScale,
arXiv:2509.07003, argument: check the program, don't assume the math).

Liveness model (the contract the hand-computed fixtures in
``tests/test_liveness.py`` pin byte-exactly)
--------------------------------------------
Eqns are walked in execution order per sub-jaxpr level with a live-set
in bytes:

* a var is live from its defining eqn until after its **last use at
  that level** (level outputs live through the end);
* **pinned inputs** (the level's non-donated invars and constvars)
  stay resident for the whole level even if read early — the caller
  still owns those buffers;
* at each eqn the footprint is ``live-before + new output bytes +
  inner extra`` (outputs materialize while operands are still held);
* **donation aliases input to output**: at a ``pjit`` eqn with
  ``donated_invars``, each donated operand at its last use is multiset-
  matched to a same-``(shape, dtype)`` output; the matched output takes
  over the donor's buffer (zero new bytes, family inherited) — a
  donated-and-rebound pool costs its bytes ONCE. The same reuse applies
  to a first-order eqn whose dying *transient* operand matches an
  output aval (XLA's buffer reuse of a freed operand) — but never
  across other higher-order eqns, whose operands coexist with their
  outputs for the body's whole duration;
* **scan** contributes ``carry + max-per-iteration-live + length×stash``:
  the stacked ys outputs ARE the ``length×stash`` term (their avals
  carry the leading length dim — zb's M·v deferred-dW stash is priced
  explicitly, tallied in ``stash_bytes`` and attributed to the
  ``activations`` family); a transient init-carry dying at the scan
  aliases the carry output (the working carry is double-buffer-free),
  and the body's per-iteration transient peak beyond its own inputs is
  the ``inner extra``;
* **cond** branches are alternatives: inner extra is the family-wise
  max over branches (the PR-10 branch-max idiom), never the sum;
* **while** trip counts are not static: the body contributes ONE
  iteration's extra and the site is tallied in
  ``unbounded_stash_sites`` — flagged, never silently multiplied;
* **Pallas kernel bodies are skipped** (VMEM tiles, not HBM); the
  ``pallas_call`` eqn's HBM operands/outputs are counted like any
  other eqn's;
* other sub-jaxpr eqns (pjit/remat/shard_map/custom_vjp) descend with
  operand families and donation flags propagated; their contribution is
  the inner peak beyond the operand bytes already counted at this
  level (clamped family-wise at zero).

Every byte at the peak belongs to one **family** —
``params`` / ``optimizer`` / ``activations`` (batch inputs and scan
stashes) / ``kv_pool`` / ``temps`` (everything transient). Top-level
invars are labelled by the caller (``arg_families``, one label per
flattened invar — :func:`apex_tpu.lint.entrypoints.arg_families` builds
it for registered entrypoints); intermediates default to ``temps``
except scan stashes (``activations``) and donation-aliased outputs
(donor's family).

Like the rest of the lint package this module imports nothing outside
the stdlib: jaxprs are walked duck-typed, the analysis never imports
the jax it is vetting.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from apex_tpu.lint.jaxpr_check import (
    _KERNEL_PRIMS,
    as_jaxpr,
    aval_bytes,
    sub_jaxprs,
)

#: the five HBM families every live byte is attributed to
FAMILIES = ("params", "optimizer", "activations", "kv_pool", "temps")


def _is_lit(var) -> bool:
    """Literals carry ``.val`` and have no buffer."""
    return hasattr(var, "val")


def _akey(var) -> Tuple[Tuple[int, ...], str]:
    aval = getattr(var, "aval", None)
    return (tuple(getattr(aval, "shape", ()) or ()),
            str(getattr(aval, "dtype", "?")))


@dataclasses.dataclass
class _Stats:
    peak: int
    peak_fams: Dict[str, int]
    aliased: int      #: bytes saved by pjit donation aliasing
    stash: int        #: stacked scan-ys bytes (the length×stash term)
    whiles: int       #: while bodies seen (bound excludes trip count)
    eqns: int


@dataclasses.dataclass(frozen=True)
class MemoryReport:
    """The static peak-HBM bound of one traced program."""
    entrypoint: str
    peak_bytes: int
    families: Dict[str, int]          #: bytes per family AT the peak
    donation_aliased_bytes: int
    stash_bytes: int
    unbounded_stash_sites: int
    eqns: int

    def record(self) -> Dict[str, Any]:
        """The closed ``kind: "static_memory"`` artifact
        (:data:`apex_tpu.monitor.schema.STATIC_MEMORY_SCHEMA`, gated by
        ``tools/validate_metrics.py --static-memory``)."""
        from apex_tpu.monitor.registry import SCHEMA_VERSION

        return {
            "schema": SCHEMA_VERSION,
            "kind": "static_memory",
            "entrypoint": self.entrypoint,
            "peak_bytes": int(self.peak_bytes),
            "peak_mb": round(self.peak_bytes / 2 ** 20, 3),
            "families": {f: int(self.families.get(f, 0))
                         for f in FAMILIES},
            "donation_aliased_bytes": int(self.donation_aliased_bytes),
            "stash_bytes": int(self.stash_bytes),
            "unbounded_stash_sites": int(self.unbounded_stash_sites),
            "eqns": int(self.eqns),
            "source": "liveness",
        }


def _map_operands(name: str, eqn, sub, fam_of: Dict[Any, str]
                  ) -> Tuple[List[str], List[bool]]:
    """(families, reusable) for one sub-jaxpr's invars, propagated from
    the eqn operands they bind: pjit carries its donation flags down
    (a donated inner input may die at its last inner use), a scan's
    carry slots are working buffers, everything else is pinned for the
    sub-level's duration. A layout we cannot map positionally (while's
    split cond/body consts) degrades to all-temps/pinned — an upper
    bound, never an undercount."""
    ops = list(eqn.invars)
    if name == "cond":
        ops = ops[1:]  # operand 0 is the branch index/predicate
    n = len(sub.invars)
    if len(ops) != n:
        return ["temps"] * n, [False] * n
    fams = ["temps" if _is_lit(v) else fam_of.get(v, "temps")
            for v in ops]
    reuse = [False] * n
    if name == "pjit":
        donated = eqn.params.get("donated_invars") or ()
        if len(donated) == n:
            reuse = [bool(d) for d in donated]
    elif name == "scan":
        nc = eqn.params.get("num_consts")
        nk = eqn.params.get("num_carry")
        if isinstance(nc, int) and isinstance(nk, int) and nc + nk <= n:
            reuse = [False] * nc + [True] * nk + [False] * (n - nc - nk)
    return fams, reuse


def _level(j, fams: Sequence[str], reusable: Sequence[bool]) -> _Stats:
    eqns = list(j.eqns)
    n = len(eqns)

    # prepass: last use per var at THIS level, and donation points
    last_use: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not _is_lit(v):
                last_use[v] = i
    for v in getattr(j, "outvars", ()):
        if not _is_lit(v):
            last_use[v] = n
    donated_at: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        if eqn.primitive.name == "pjit":
            donated = eqn.params.get("donated_invars") or ()
            for v, d in zip(eqn.invars, donated):
                if d and not _is_lit(v) and v not in donated_at:
                    donated_at[v] = i

    pinned = set()
    fam_of: Dict[Any, str] = {}
    live: Dict[Any, int] = {}
    for v in getattr(j, "constvars", ()):
        fam_of[v] = "temps"
        pinned.add(v)
        live[v] = aval_bytes(v)
    for v, f, r in zip(j.invars, fams, reusable):
        fam_of[v] = f
        if not r:
            pinned.add(v)
        live[v] = aval_bytes(v)

    def _release(v) -> int:
        # donation consumes the buffer at the donating eqn (JXP201
        # guarantees no later read); pinned inputs live to level end
        if v in donated_at:
            return donated_at[v]
        if v in pinned:
            return n
        return last_use.get(v, -1)

    live_f = {f: 0 for f in FAMILIES}
    for v, b in live.items():
        live_f[fam_of[v]] += b
    peak = sum(live.values())
    peak_f = dict(live_f)
    aliased = stash = whiles = 0
    eqn_count = 0

    # inputs never read (and not donated/pinned) free right after entry
    for v in list(live):
        if _release(v) < 0:
            live_f[fam_of[v]] -= live.pop(v)

    for i, eqn in enumerate(eqns):
        eqn_count += 1
        name = eqn.primitive.name
        subs: List[Any] = []
        for val in eqn.params.values():
            subs.extend(sub_jaxprs(val))

        extra_f = {f: 0 for f in FAMILIES}
        if subs and name not in _KERNEL_PRIMS:
            per_sub = []
            for sub in subs:
                sfams, sreuse = _map_operands(name, eqn, sub, fam_of)
                st = _level(sub, sfams, sreuse)
                aliased += st.aliased
                stash += st.stash
                whiles += st.whiles
                eqn_count += st.eqns
                inv_f = {f: 0 for f in FAMILIES}
                for v, f in zip(sub.invars, sfams):
                    inv_f[f] += aval_bytes(v)
                per_sub.append({f: max(0, st.peak_fams[f] - inv_f[f])
                                for f in FAMILIES})
            for f in FAMILIES:
                extra_f[f] = max(ps[f] for ps in per_sub)
        if name == "while":
            whiles += 1

        # aliasing: which outputs take over a dying operand's buffer
        # instead of allocating. Three sound cases: (1) pjit donation —
        # the caller handed the buffer over (tallied for JXP602);
        # (2) a scan's init carry dying at the scan — the running carry
        # slot reuses it (the carry is sequential, never coexistent);
        # (3) first-order eqns whose dying transient operand matches an
        # output aval — XLA's buffer reuse of a freed operand. Higher-
        # order eqns other than (1)/(2) get NO generic reuse: their
        # operands are read throughout the body while outputs are
        # written, so the buffers genuinely coexist.
        alias_fam: Dict[Any, str] = {}
        nk = eqn.params.get("num_carry") if name == "scan" else None
        avail_don: Dict[Any, List[Any]] = {}
        avail_gen: Dict[Any, List[Any]] = {}
        if name == "pjit":
            donated = eqn.params.get("donated_invars") or ()
            for v, d in zip(eqn.invars, donated):
                if d and not _is_lit(v) and _release(v) == i:
                    avail_don.setdefault(_akey(v), []).append(v)
        elif name == "scan":
            nc = eqn.params.get("num_consts")
            if isinstance(nc, int) and isinstance(nk, int):
                for c in range(nk):
                    if nc + c >= len(eqn.invars) or c >= len(eqn.outvars):
                        break
                    v, o = eqn.invars[nc + c], eqn.outvars[c]
                    if (not _is_lit(v) and v not in pinned
                            and v not in donated_at
                            and _release(v) == i and _akey(v) == _akey(o)):
                        alias_fam[o] = fam_of[v]
        elif not subs:
            seen = set()
            for v in eqn.invars:
                if (not _is_lit(v) and v not in seen and v not in pinned
                        and v not in donated_at and _release(v) == i):
                    seen.add(v)
                    avail_gen.setdefault(_akey(v), []).append(v)
        for o in eqn.outvars:
            if o in alias_fam:
                continue
            k = _akey(o)
            if avail_don.get(k):
                alias_fam[o] = fam_of[avail_don[k].pop(0)]
                aliased += aval_bytes(o)
            elif avail_gen.get(k):
                alias_fam[o] = fam_of[avail_gen[k].pop(0)]

        out_fam: Dict[Any, str] = {}
        out_new_f = {f: 0 for f in FAMILIES}
        for idx, o in enumerate(eqn.outvars):
            if o in alias_fam:
                out_fam[o] = alias_fam[o]
                continue  # takes over the donor's live bytes
            if name == "scan" and isinstance(nk, int) and idx >= nk:
                out_fam[o] = "activations"  # stacked per-tick stash
                stash += aval_bytes(o)
            else:
                out_fam[o] = "temps"
            out_new_f[out_fam[o]] += aval_bytes(o)

        # scan/while outputs (stacked ys, the threaded carry) accumulate
        # WHILE the body runs, so they add to the body's transient peak;
        # a call-like eqn's outputs either already exist at the inner
        # peak moment (then they are inside `extra`) or do not exist yet
        # (then `out_new` is the larger later moment) — take the max,
        # not the sum, or every pjit output double-counts.
        if subs and name not in _KERNEL_PRIMS and name not in (
                "scan", "while"):
            if sum(extra_f.values()) >= sum(out_new_f.values()):
                during_f = {f: live_f[f] + extra_f[f] for f in FAMILIES}
            else:
                during_f = {f: live_f[f] + out_new_f[f] for f in FAMILIES}
        else:
            during_f = {f: live_f[f] + out_new_f[f] + extra_f[f]
                        for f in FAMILIES}
        during = sum(during_f.values())
        if during > peak:
            peak, peak_f = during, during_f

        for v in [v for v in live if _release(v) == i]:
            live_f[fam_of[v]] -= live.pop(v)
        for o in eqn.outvars:
            if _is_lit(o):
                continue
            fam_of[o] = out_fam[o]
            if _release(o) > i:
                b = aval_bytes(o)
                live[o] = b
                live_f[fam_of[o]] += b

    return _Stats(peak, peak_f, aliased, stash, whiles, eqn_count)


def analyze(jaxpr_like, *, arg_families: Optional[Sequence[str]] = None,
            entrypoint: str = "") -> MemoryReport:
    """The static peak-HBM bound of one traced program.

    ``arg_families`` labels the program's (flattened) invars, one of
    :data:`FAMILIES` each — the length must match ``len(jaxpr.invars)``
    exactly (a silently mislabelled operand would corrupt the family
    breakdown). ``None`` labels every input ``temps``: the peak is
    still exact, only the attribution is flat.
    """
    j = as_jaxpr(jaxpr_like)
    invars = list(j.invars)
    if arg_families is None:
        fams: List[str] = ["temps"] * len(invars)
    else:
        fams = list(arg_families)
        if len(fams) != len(invars):
            raise ValueError(
                f"arg_families has {len(fams)} labels for "
                f"{len(invars)} jaxpr invars — pass one label per "
                "flattened input leaf")
        bad = sorted(set(fams) - set(FAMILIES))
        if bad:
            raise ValueError(
                f"unknown families {bad}; valid: {list(FAMILIES)}")
    st = _level(j, fams, [False] * len(invars))
    return MemoryReport(
        entrypoint=entrypoint,
        peak_bytes=st.peak,
        families={f: st.peak_fams.get(f, 0) for f in FAMILIES},
        donation_aliased_bytes=st.aliased,
        stash_bytes=st.stash,
        unbounded_stash_sites=st.whiles,
        eqns=st.eqns,
    )
