"""APX1xx — tracing/recompile hazards.

The bug class: code inside a ``jax.jit``/``pjit``-traced function treating a
traced value as a Python value. On CUDA these were compile-time type errors;
under tracing they surface as ``ConcretizationTypeError`` at best and as
silent per-call recompilation or host round-trips at worst (the jax-version
drift round broke ~160 seed tests on exactly this seam).

Rules
-----
APX101  python-control-flow-on-traced   ``if``/``while`` on a traced value
APX102  concretization-call             ``int()``/``float()``/``bool()``/
                                        ``.item()``/``.tolist()`` on traced
APX103  host-numpy-on-traced            ``np.*`` applied to traced values
APX104  bad-static-argnums              non-int static_argnums, out-of-range
                                        indices, unknown static_argnames
APX105  alias-shadowing-parameter       a parameter named np/jnp/pl/... —
                                        inside that scope the "module" is
                                        data (the host-call confusion vector)
APX106  jit-in-body                     jax.jit of a module-level function
                                        inside another function body — a
                                        fresh wrapper (and retrace) per call
"""

from __future__ import annotations

import ast

from apex_tpu.lint.core import (JIT_WRAPPERS, JitSite, ModuleContext,
                                expr_taint, is_none_check, jit_sites,
                                positional_params, rule, traced_functions)

_CONCRETIZERS = {"int", "float", "bool", "complex"}
_CONCRETIZER_METHODS = {"item", "tolist", "__bool__", "__int__", "__float__"}


@rule("APX101", "python-control-flow-on-traced",
      "Python if/while branches on a value derived from a jit-traced "
      "parameter; use jax.lax.cond/select or jnp.where")
def check_apx101(ctx: ModuleContext):
    for fn, statics in traced_functions(ctx):
        taint = _fn_taint(fn, statics)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                if is_none_check(node.test):
                    continue
                if expr_taint(node.test, taint):
                    yield ctx.finding(
                        node, "APX101",
                        f"`{_kw(node)}` on a value derived from a traced "
                        f"parameter of jitted `{fn.name}` — this forces "
                        "concretization (ConcretizationTypeError) or a "
                        "retrace per value; restructure with jax.lax.cond/"
                        "jnp.where, or mark the driving argument static")
            elif isinstance(node, ast.IfExp):
                if not is_none_check(node.test) and \
                        expr_taint(node.test, taint):
                    yield ctx.finding(
                        node, "APX101",
                        f"conditional expression on a traced value inside "
                        f"jitted `{fn.name}`; use jnp.where/lax.select")


def _kw(node):
    return "if" if isinstance(node, (ast.If, ast.IfExp)) else "while"


@rule("APX102", "concretization-call",
      "int()/float()/bool()/.item()/.tolist() on a traced value inside a "
      "jitted function — a host sync the trace cannot express")
def check_apx102(ctx: ModuleContext):
    for fn, statics in traced_functions(ctx):
        taint = _fn_taint(fn, statics)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) and \
                    node.func.id in _CONCRETIZERS and node.args and \
                    expr_taint(node.args[0], taint):
                yield ctx.finding(
                    node, "APX102",
                    f"`{node.func.id}()` on a traced value inside jitted "
                    f"`{fn.name}` raises ConcretizationTypeError at trace "
                    "time; keep it an array (astype) or mark the argument "
                    "static")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _CONCRETIZER_METHODS and \
                    expr_taint(node.func.value, taint):
                yield ctx.finding(
                    node, "APX102",
                    f"`.{node.func.attr}()` on a traced value inside jitted "
                    f"`{fn.name}` forces a device→host transfer the trace "
                    "cannot express")


@rule("APX103", "host-numpy-on-traced",
      "host numpy applied to traced values inside a jitted function — "
      "silently concretizes (or fails); use jnp")
def check_apx103(ctx: ModuleContext):
    for fn, statics in traced_functions(ctx):
        taint = _fn_taint(fn, statics)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            canon = ctx.call_name(node)
            if not canon or not (canon == "numpy"
                                 or canon.startswith("numpy.")):
                continue
            args = list(node.args) + [k.value for k in node.keywords]
            if any(expr_taint(a, taint) for a in args):
                yield ctx.finding(
                    node, "APX103",
                    f"`{ast.unparse(node.func)}` called on a traced value "
                    f"inside jitted `{fn.name}` — host numpy concretizes "
                    "its inputs; use the jnp equivalent (host numpy on "
                    "static shapes/constants is fine)")


@rule("APX104", "bad-static-argnums",
      "static_argnums entries that are not ints, index past the wrapped "
      "function's positional parameters, or static_argnames naming a "
      "parameter that does not exist")
def check_apx104(ctx: ModuleContext):
    for site in jit_sites(ctx):
        yield from _check_site(ctx, site)


def _check_site(ctx: ModuleContext, site: JitSite):
    raw_nums = site.raw_kwargs.get("static_argnums")
    if raw_nums is not None and site.static_argnums is None and \
            _has_wrong_type_literal(raw_nums):
        # only literal elements of a WRONG type are provably bad; Name
        # elements (static_argnums=(AXIS,)) are legal and unreadable, and
        # static_argnums=None is jax's own default
        yield ctx.finding(
            raw_nums, "APX104",
            "static_argnums must be int positions; strings belong in "
            "static_argnames, and array-valued statics are unhashable — "
            "jit will reject or silently retrace per call")
        return
    if site.fn is None:
        return
    args = site.fn.args
    pos = positional_params(site.fn, site.bound)
    for idx in site.static_argnums or []:
        real = idx if idx >= 0 else len(pos) + idx
        if not 0 <= real < len(pos):
            yield ctx.finding(
                site.raw_kwargs.get("static_argnums", site.node), "APX104",
                f"static_argnums={idx} is out of range for "
                f"`{site.fn.name}` ({len(pos)} positional parameter(s))")
        else:
            default = _default_for(args, pos, real)
            if pos[real] == "self":
                continue  # decorated method: index 0 is self, no default
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                yield ctx.finding(
                    site.raw_kwargs.get("static_argnums", site.node),
                    "APX104",
                    f"static_argnums={idx} marks `{pos[real]}` static but "
                    "its default is an unhashable "
                    f"{type(default).__name__.lower()} literal — jit "
                    "requires hashable statics")
    names = {a.arg for a in (list(getattr(args, "posonlyargs", []))
                             + args.args + args.kwonlyargs)}
    for name in site.static_argnames or []:
        if name not in names:
            yield ctx.finding(
                site.raw_kwargs.get("static_argnames", site.node), "APX104",
                f"static_argnames={name!r} does not name a parameter of "
                f"`{site.fn.name}`")


#: Conventional array-ecosystem module aliases. A parameter wearing one of
#: these names turns every ``np.``/``pl.`` expression in its scope into an
#: attribute read on DATA — the exact confusion APX103 exists to catch, one
#: edit away. (The reference's ``(b, np, sq, sk)`` softmax signature is the
#: canonical offender.)
_MODULE_ALIASES = frozenset({
    "np", "numpy", "jnp", "jax", "lax", "pl", "pltpu", "jr", "jsp",
})


@rule("APX105", "alias-shadowing-parameter",
      "a parameter named np/jnp/jax/lax/pl/pltpu/jr shadows the "
      "conventional module alias — inside that scope the module is data")
def check_apx105(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            continue
        args = node.args
        for a in (list(getattr(args, "posonlyargs", [])) + args.args
                  + args.kwonlyargs):
            if a.arg in _MODULE_ALIASES:
                fname = getattr(node, "name", "<lambda>")
                yield ctx.finding(
                    a if hasattr(a, "lineno") else node, "APX105",
                    f"parameter `{a.arg}` of `{fname}` shadows the "
                    f"conventional `{a.arg}` module alias — any "
                    f"`{a.arg}.` expression in this scope silently reads "
                    "an attribute off data instead of calling the module; "
                    "rename the parameter")


@rule("APX106", "jit-in-body",
      "jax.jit applied to a module-level function inside another function "
      "body — builds a fresh wrapper (and retraces) every call; hoist the "
      "jitted callable to module scope")
def check_apx106(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = ctx.call_name(node)
        if canon not in JIT_WRAPPERS:
            continue
        if ctx.enclosing_function(node) is None:
            continue  # module scope: the correct place
        if not node.args or not isinstance(node.args[0], ast.Name):
            continue  # jitting a parameter/closure/bound method: not
            # hoistable, the wrapper legitimately lives here
        target = node.args[0].id
        fn = ctx.defs.get(target)
        if fn is None or ctx.enclosing_function(fn) is not None:
            continue  # not a module-level def
        if any(not isinstance(kw.value, (ast.Constant, ast.Tuple, ast.List))
               for kw in node.keywords):
            continue  # kwargs capture local state; hoisting would change them
        parent = ctx.parents.get(node)
        if isinstance(parent, ast.Assign) and any(
                isinstance(t, ast.Attribute) for t in parent.targets):
            continue  # `self.step = jax.jit(f, ...)`: deliberately
            # once-per-instance (the decode-engine pattern)
        yield ctx.finding(
            node, "APX106",
            f"jax.jit(`{target}`) inside a function body builds a fresh "
            "wrapper — and a fresh trace — per invocation of the "
            "enclosing function; hoist `= jax.jit(...)` to module scope "
            "so the trace cache is shared across calls")


def _has_wrong_type_literal(node) -> bool:
    """A static_argnums value provably not int positions: a non-int,
    non-None literal (str/float/bytes), directly or as a container
    element."""
    def bad(e):
        return (isinstance(e, ast.Constant) and e.value is not None
                and not (isinstance(e.value, int)
                         and not isinstance(e.value, bool)))
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(bad(e) for e in node.elts)
    return bad(node)


def _default_for(args: ast.arguments, pos, idx):
    """Default expr for positional parameter index ``idx`` (post-self)."""
    all_pos = [a.arg for a in
               list(getattr(args, "posonlyargs", [])) + args.args]
    shift = len(all_pos) - len(pos)  # 1 when self was dropped
    j = idx + shift - (len(all_pos) - len(args.defaults))
    if 0 <= j < len(args.defaults):
        return args.defaults[j]
    return None


def _fn_taint(fn, statics):
    from apex_tpu.lint.core import tainted_names
    return tainted_names(fn, statics)
