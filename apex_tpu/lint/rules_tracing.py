"""APX1xx — tracing/recompile hazards.

The bug class: code inside a ``jax.jit``/``pjit``-traced function treating a
traced value as a Python value. On CUDA these were compile-time type errors;
under tracing they surface as ``ConcretizationTypeError`` at best and as
silent per-call recompilation or host round-trips at worst (the jax-version
drift round broke ~160 seed tests on exactly this seam).

Rules
-----
APX101  python-control-flow-on-traced   ``if``/``while`` on a traced value
APX102  concretization-call             ``int()``/``float()``/``bool()``/
                                        ``.item()``/``.tolist()`` on traced
APX103  host-numpy-on-traced            ``np.*`` applied to traced values
APX104  bad-static-argnums              non-int static_argnums, out-of-range
                                        indices, unknown static_argnames
APX105  alias-shadowing-parameter       a parameter named np/jnp/pl/... —
                                        inside that scope the "module" is
                                        data (the host-call confusion vector)
APX106  jit-in-body                     jax.jit of a module-level function
                                        inside another function body — a
                                        fresh wrapper (and retrace) per call
APX107  unordered-iteration-in-trace    iterating a set (or the views of a
                                        set-ordered dict) inside a jitted/
                                        scanned body — hash order varies per
                                        process, so each process traces a
                                        DIFFERENT jaxpr: spurious jit-cache
                                        misses and irreproducible programs
"""

from __future__ import annotations

import ast

from apex_tpu.lint.core import (JIT_WRAPPERS, JitSite, ModuleContext,
                                expr_taint, is_none_check, jit_sites,
                                positional_params, rule, traced_functions)

_CONCRETIZERS = {"int", "float", "bool", "complex"}
_CONCRETIZER_METHODS = {"item", "tolist", "__bool__", "__int__", "__float__"}


@rule("APX101", "python-control-flow-on-traced",
      "Python if/while branches on a value derived from a jit-traced "
      "parameter; use jax.lax.cond/select or jnp.where")
def check_apx101(ctx: ModuleContext):
    for fn, statics in traced_functions(ctx):
        taint = _fn_taint(fn, statics)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                if is_none_check(node.test):
                    continue
                if expr_taint(node.test, taint):
                    yield ctx.finding(
                        node, "APX101",
                        f"`{_kw(node)}` on a value derived from a traced "
                        f"parameter of jitted `{fn.name}` — this forces "
                        "concretization (ConcretizationTypeError) or a "
                        "retrace per value; restructure with jax.lax.cond/"
                        "jnp.where, or mark the driving argument static")
            elif isinstance(node, ast.IfExp):
                if not is_none_check(node.test) and \
                        expr_taint(node.test, taint):
                    yield ctx.finding(
                        node, "APX101",
                        f"conditional expression on a traced value inside "
                        f"jitted `{fn.name}`; use jnp.where/lax.select")


def _kw(node):
    return "if" if isinstance(node, (ast.If, ast.IfExp)) else "while"


@rule("APX102", "concretization-call",
      "int()/float()/bool()/.item()/.tolist() on a traced value inside a "
      "jitted function — a host sync the trace cannot express")
def check_apx102(ctx: ModuleContext):
    for fn, statics in traced_functions(ctx):
        taint = _fn_taint(fn, statics)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) and \
                    node.func.id in _CONCRETIZERS and node.args and \
                    expr_taint(node.args[0], taint):
                yield ctx.finding(
                    node, "APX102",
                    f"`{node.func.id}()` on a traced value inside jitted "
                    f"`{fn.name}` raises ConcretizationTypeError at trace "
                    "time; keep it an array (astype) or mark the argument "
                    "static")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _CONCRETIZER_METHODS and \
                    expr_taint(node.func.value, taint):
                yield ctx.finding(
                    node, "APX102",
                    f"`.{node.func.attr}()` on a traced value inside jitted "
                    f"`{fn.name}` forces a device→host transfer the trace "
                    "cannot express")


@rule("APX103", "host-numpy-on-traced",
      "host numpy applied to traced values inside a jitted function — "
      "silently concretizes (or fails); use jnp")
def check_apx103(ctx: ModuleContext):
    for fn, statics in traced_functions(ctx):
        taint = _fn_taint(fn, statics)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            canon = ctx.call_name(node)
            if not canon or not (canon == "numpy"
                                 or canon.startswith("numpy.")):
                continue
            args = list(node.args) + [k.value for k in node.keywords]
            if any(expr_taint(a, taint) for a in args):
                yield ctx.finding(
                    node, "APX103",
                    f"`{ast.unparse(node.func)}` called on a traced value "
                    f"inside jitted `{fn.name}` — host numpy concretizes "
                    "its inputs; use the jnp equivalent (host numpy on "
                    "static shapes/constants is fine)")


@rule("APX104", "bad-static-argnums",
      "static_argnums entries that are not ints, index past the wrapped "
      "function's positional parameters, or static_argnames naming a "
      "parameter that does not exist")
def check_apx104(ctx: ModuleContext):
    for site in jit_sites(ctx):
        yield from _check_site(ctx, site)


def _check_site(ctx: ModuleContext, site: JitSite):
    raw_nums = site.raw_kwargs.get("static_argnums")
    if raw_nums is not None and site.static_argnums is None and \
            _has_wrong_type_literal(raw_nums):
        # only literal elements of a WRONG type are provably bad; Name
        # elements (static_argnums=(AXIS,)) are legal and unreadable, and
        # static_argnums=None is jax's own default
        yield ctx.finding(
            raw_nums, "APX104",
            "static_argnums must be int positions; strings belong in "
            "static_argnames, and array-valued statics are unhashable — "
            "jit will reject or silently retrace per call")
        return
    if site.fn is None:
        return
    args = site.fn.args
    pos = positional_params(site.fn, site.bound)
    for idx in site.static_argnums or []:
        real = idx if idx >= 0 else len(pos) + idx
        if not 0 <= real < len(pos):
            yield ctx.finding(
                site.raw_kwargs.get("static_argnums", site.node), "APX104",
                f"static_argnums={idx} is out of range for "
                f"`{site.fn.name}` ({len(pos)} positional parameter(s))")
        else:
            default = _default_for(args, pos, real)
            if pos[real] == "self":
                continue  # decorated method: index 0 is self, no default
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                yield ctx.finding(
                    site.raw_kwargs.get("static_argnums", site.node),
                    "APX104",
                    f"static_argnums={idx} marks `{pos[real]}` static but "
                    "its default is an unhashable "
                    f"{type(default).__name__.lower()} literal — jit "
                    "requires hashable statics")
    names = {a.arg for a in (list(getattr(args, "posonlyargs", []))
                             + args.args + args.kwonlyargs)}
    for name in site.static_argnames or []:
        if name not in names:
            yield ctx.finding(
                site.raw_kwargs.get("static_argnames", site.node), "APX104",
                f"static_argnames={name!r} does not name a parameter of "
                f"`{site.fn.name}`")


#: Conventional array-ecosystem module aliases. A parameter wearing one of
#: these names turns every ``np.``/``pl.`` expression in its scope into an
#: attribute read on DATA — the exact confusion APX103 exists to catch, one
#: edit away. (The reference's ``(b, np, sq, sk)`` softmax signature is the
#: canonical offender.)
_MODULE_ALIASES = frozenset({
    "np", "numpy", "jnp", "jax", "lax", "pl", "pltpu", "jr", "jsp",
})


@rule("APX105", "alias-shadowing-parameter",
      "a parameter named np/jnp/jax/lax/pl/pltpu/jr shadows the "
      "conventional module alias — inside that scope the module is data")
def check_apx105(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            continue
        args = node.args
        for a in (list(getattr(args, "posonlyargs", [])) + args.args
                  + args.kwonlyargs):
            if a.arg in _MODULE_ALIASES:
                fname = getattr(node, "name", "<lambda>")
                yield ctx.finding(
                    a if hasattr(a, "lineno") else node, "APX105",
                    f"parameter `{a.arg}` of `{fname}` shadows the "
                    f"conventional `{a.arg}` module alias — any "
                    f"`{a.arg}.` expression in this scope silently reads "
                    "an attribute off data instead of calling the module; "
                    "rename the parameter")


@rule("APX106", "jit-in-body",
      "jax.jit applied to a module-level function inside another function "
      "body — builds a fresh wrapper (and retraces) every call; hoist the "
      "jitted callable to module scope")
def check_apx106(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = ctx.call_name(node)
        if canon not in JIT_WRAPPERS:
            continue
        if ctx.enclosing_function(node) is None:
            continue  # module scope: the correct place
        if not node.args or not isinstance(node.args[0], ast.Name):
            continue  # jitting a parameter/closure/bound method: not
            # hoistable, the wrapper legitimately lives here
        target = node.args[0].id
        fn = ctx.defs.get(target)
        if fn is None or ctx.enclosing_function(fn) is not None:
            continue  # not a module-level def
        if any(not isinstance(kw.value, (ast.Constant, ast.Tuple, ast.List))
               for kw in node.keywords):
            continue  # kwargs capture local state; hoisting would change them
        parent = ctx.parents.get(node)
        if isinstance(parent, ast.Assign) and any(
                isinstance(t, ast.Attribute) for t in parent.targets):
            continue  # `self.step = jax.jit(f, ...)`: deliberately
            # once-per-instance (the decode-engine pattern)
        yield ctx.finding(
            node, "APX106",
            f"jax.jit(`{target}`) inside a function body builds a fresh "
            "wrapper — and a fresh trace — per invocation of the "
            "enclosing function; hoist `= jax.jit(...)` to module scope "
            "so the trace cache is shared across calls")


#: set-producing builtins: their iteration order is the hash order, which
#: PYTHONHASHSEED re-rolls per process
_SET_MAKERS = frozenset({"set", "frozenset"})
#: unordered-view methods: on a set-ordered dict these iterate in the
#: order the set inserted
_DICT_VIEWS = frozenset({"values", "keys", "items"})
#: wrappers that PRESERVE their argument's order (list(set(...)) is still
#: hash-ordered); sorted() is the launder and is handled separately
_ORDER_PRESERVING = frozenset({"list", "tuple", "iter", "reversed",
                               "enumerate", "dict"})
_SCAN_WRAPPERS = frozenset({
    "jax.lax.scan", "lax.scan", "jax.lax.map", "lax.map",
    "jax.lax.fori_loop", "lax.fori_loop", "jax.lax.while_loop",
    "lax.while_loop", "jax.checkpoint", "jax.remat",
})


def _unordered_expr(node, unordered: frozenset) -> bool:
    """Does ``node`` evaluate to a hash-ordered iterable — a set, a
    set-derived container, or an order-preserving wrap of one?
    ``sorted()`` (and ``min``/``max``/``sum``/``len``) launder."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Name):
        return node.id in unordered
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.BitXor)):
        # set algebra (d.keys() - frozen, a | b) keeps the disorder
        return (_unordered_expr(node.left, unordered)
                or _unordered_expr(node.right, unordered))
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
        return (_unordered_expr(node.left, unordered)
                or _unordered_expr(node.right, unordered))
    if isinstance(node, ast.DictComp):
        return any(_unordered_expr(g.iter, unordered)
                   for g in node.generators)
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name):
            fid = node.func.id
            if fid in _SET_MAKERS:
                return True
            if fid in _ORDER_PRESERVING:
                return any(_unordered_expr(a, unordered) for a in node.args)
            return False  # sorted() and every other call launder
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in _DICT_VIEWS:
                return _unordered_expr(node.func.value, unordered)
            if node.func.attr in ("union", "intersection", "difference",
                                  "symmetric_difference", "copy"):
                return _unordered_expr(node.func.value, unordered)
    return False


def _unordered_names(fn) -> frozenset:
    """Flow-insensitive fixpoint over a function body: names assigned
    from a set-valued (or set-ordered) expression. A name that ALSO has
    an ordered (re)assignment — ``ks = sorted(ks)`` — is laundered: the
    rule's own recommended fix must not keep firing on the fixed code,
    so a grow pass (any unordered assignment taints) is followed by a
    shrink pass (any ordered assignment launders, cascading to names
    derived from the laundered one). The shrink optimistically
    under-approximates on genuinely mixed reassignment, the right
    direction for a linter."""
    assigns: dict = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign)) and \
                node.value is not None:
            for name in _assign_target_names(node):
                assigns.setdefault(name, []).append(node.value)
    names: set = set()
    changed = True
    while changed:  # grow
        changed = False
        for name, values in assigns.items():
            if name not in names and any(
                    _unordered_expr(v, frozenset(names)) for v in values):
                names.add(name)
                changed = True
    changed = True
    while changed:  # shrink: a sorted()-style reassignment launders
        changed = False
        for name in list(names):
            if any(not _unordered_expr(v, frozenset(names))
                   for v in assigns.get(name, [])):
                names.discard(name)
                changed = True
    return frozenset(names)


def _assign_target_names(node):
    from apex_tpu.lint.core import _assign_targets
    return _assign_targets(node)


def _traced_and_scanned(ctx: ModuleContext):
    """The APX107 scope: jit/pjit/shard_map-wrapped defs PLUS defs passed
    as the body of lax.scan/map/fori_loop/while_loop (a scanned body is
    traced every bit as much as a jitted one, and its jaxpr is baked
    into the enclosing program)."""
    fns = {fn for fn, _ in traced_functions(ctx)}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if ctx.call_name(node) not in _SCAN_WRAPPERS:
            continue
        for arg in node.args[:2]:  # body (and fori's body at index 2 - 1)
            if isinstance(arg, ast.Name) and arg.id in ctx.defs:
                fns.add(ctx.defs[arg.id])
        for arg in node.args[2:3]:
            if isinstance(arg, ast.Name) and arg.id in ctx.defs:
                fns.add(ctx.defs[arg.id])
    return fns


@rule("APX107", "unordered-iteration-in-trace",
      "iterating a set / the views of a set-ordered dict inside a jitted "
      "or scanned body — hash order varies per process, so each process "
      "traces a different jaxpr (spurious cache misses); sort first")
def check_apx107(ctx: ModuleContext):
    for fn in _traced_and_scanned(ctx):
        unordered = _unordered_names(fn)
        for node in ast.walk(fn):
            iters = []
            if isinstance(node, ast.For):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                   ast.DictComp)):
                iters.extend(g.iter for g in node.generators)
            for it in iters:
                if _unordered_expr(it, unordered):
                    yield ctx.finding(
                        node, "APX107",
                        f"iteration over a hash-ordered iterable inside "
                        f"traced `{fn.name}` — set order varies with "
                        "PYTHONHASHSEED, so every process traces a "
                        "DIFFERENT jaxpr (spurious jit-cache misses, "
                        "irreproducible programs); iterate "
                        "`sorted(...)` instead")


def _has_wrong_type_literal(node) -> bool:
    """A static_argnums value provably not int positions: a non-int,
    non-None literal (str/float/bytes), directly or as a container
    element."""
    def bad(e):
        return (isinstance(e, ast.Constant) and e.value is not None
                and not (isinstance(e.value, int)
                         and not isinstance(e.value, bool)))
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(bad(e) for e in node.elts)
    return bad(node)


def _default_for(args: ast.arguments, pos, idx):
    """Default expr for positional parameter index ``idx`` (post-self)."""
    all_pos = [a.arg for a in
               list(getattr(args, "posonlyargs", [])) + args.args]
    shift = len(all_pos) - len(pos)  # 1 when self was dropped
    j = idx + shift - (len(all_pos) - len(args.defaults))
    if 0 <= j < len(args.defaults):
        return args.defaults[j]
    return None


def _fn_taint(fn, statics):
    from apex_tpu.lint.core import tainted_names
    return tainted_names(fn, statics)
