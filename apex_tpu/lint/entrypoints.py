"""Registered jaxpr-check entrypoints: the programs whose contracts the
repo guarantees, traced and judged by ``python -m apex_tpu.lint --jaxpr``.

Each entrypoint declares (a) a builder returning ``(fn, args)`` at smoke
scale — traced with ``jax.make_jaxpr`` on the virtual CPU mesh, NO
device execution of the traced program — and (b) the JXP contract set
that program must satisfy (:mod:`apex_tpu.lint.contracts`). The tier-1
gate (``tests/test_jaxpr_check.py::TestJaxprGate``) runs the CLI over
every registered entrypoint and fails on non-baselined violations, the
same discipline as the apexlint dogfood gate.

The flagship surfaces registered here mirror the invariants the test
suites used to assert with one-off walkers:

* ``gpt_fwd_bwd`` — the training step (donation honored AND rebound
  through the jitted step; no low-precision scan accumulation);
* ``flash_bias_fwd_bwd`` — the bucketed-relative-bias kernel path, fwd
  and grad (no materialized O(s²) bias/score aval — PR 4's memory
  claim);
* ``collective_matmul_ring`` — the overlapped Column→Row chain
  (``ppermute`` present, no full-width ``all_gather`` over tp — PR 5's
  acceptance);
* ``pipeline_{1f1b,interleaved,zb}[_overlap]`` — the schedule family
  (forward-sweep geometry, the zb dW sweep of exactly M·v ticks that is
  collective-free, the 1f1b control with NO such sweep — PR 8's
  acceptance);
* ``serve_prefill`` / ``serve_decode`` — the serving engine's jitted
  bodies traced with copy-on-write block tables IN PLAY (a warm prefix
  cache, shared refcounted blocks in the table row, a non-zero resume
  frontier — all host bookkeeping, no device work): pool donated and
  rebound, single-chip bodies collective-free — PR 7's contract held
  under serving tier 2's sharing machinery;
* ``spec_verify`` / ``serve_decode_quantized`` — the speculative-
  decoding round (k+1 drafted tokens scored + the fused verify tail in
  one body) and the int8-KV decode step (quantize-on-write + in-pool
  scale planes), each with the COW tables in play: pool donated and
  rebound, collective-free — ISSUE 15's two new device programs under
  the same contract set;
* ``spec_verify_tree`` — the TREE speculative round (branching x depth
  drafted nodes scored under the ancestor tree-attention mask in one
  forward + the fused tree-verify tail, only the winning path
  committed): pool donated and rebound, collective-free — ISSUE 19's
  device program under the same contract set;
* ``serve_prefill_tp`` / ``serve_decode_tp`` — the tensor-parallel
  serving bodies (pool sharded over kv_heads, projections riding the
  collective-matmul ring): pool donated and rebound, ``ppermute`` over
  tp present, NO full-width ``all_gather`` over tp — ISSUE 17's
  bigger-than-one-chip acceptance under the same COW operands.

Tracing the same programs also yields their
:func:`~apex_tpu.lint.jaxpr_check.static_cost` reports — the planner's
predicted-bytes/FLOPs substrate (``--static-cost`` /
``--costdb`` on the CLI).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Tuple

from apex_tpu.lint import contracts as jc

#: smoke-scale pipeline geometry shared by every pipeline entrypoint:
#: S stages on the pp mesh, M microbatches, v virtual chunks — small
#: enough to trace in well under a second, big enough that the forward
#: sweep, dX sweep, and dW sweep lengths are pairwise distinct. The
#: interleaved schedule needs M divisible by S (2·S under overlap_p2p),
#: hence its own M.
_PP_S, _PP_M = 4, 6
_PP_M_INTERLEAVED = 8
_PP_HID = 16


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    name: str
    description: str
    #: () -> (fn, args): the traceable callable and example operands
    build: Callable[[], Tuple[Callable, Tuple]]
    #: () -> the contract set this program must satisfy
    contracts: Callable[[], List[jc.Contract]]


REGISTRY: Dict[str, EntryPoint] = {}


def register(name: str, description: str,
             contracts: Callable[[], List[jc.Contract]]):
    """Decorator registering a builder as a named entrypoint."""

    def deco(build):
        if name in REGISTRY:  # pragma: no cover - programming error
            raise ValueError(f"duplicate entrypoint {name!r}")
        REGISTRY[name] = EntryPoint(name, description, build, contracts)
        return build

    return deco


def names() -> List[str]:
    return sorted(REGISTRY)


def get(name: str) -> EntryPoint:
    if name not in REGISTRY:
        raise KeyError(
            f"unknown entrypoint {name!r}; registered: {', '.join(names())}")
    return REGISTRY[name]


def trace(name: str):
    """Trace one entrypoint to its ClosedJaxpr (CPU, no execution of the
    traced program — builders may run tiny eager setup like param init)."""
    import jax

    ep = get(name)
    fn, args = ep.build()
    return jax.make_jaxpr(fn)(*args)


def check(name: str, *, memory: bool = False):
    """Trace + contract-check one entrypoint. Returns
    ``(contract findings, static_cost artifact)`` — with the
    ``static_memory`` artifact of :mod:`apex_tpu.lint.liveness` as a
    third element when ``memory=True`` (same single trace)."""
    import jax

    from apex_tpu.lint import jaxpr_check as jx

    ep = get(name)
    fn, args = ep.build()
    closed = jax.make_jaxpr(fn)(*args)
    walk = jc.Walk(closed)
    findings = jc.check_jaxpr(walk, ep.contracts())
    cost = jx.static_cost(closed, entrypoint=name)
    if not memory:
        return findings, cost
    from apex_tpu.lint import liveness

    rep = liveness.analyze(closed, arg_families=arg_families(name, args),
                           entrypoint=name)
    return findings, cost, rep.record()


def static_memory(name: str):
    """Trace one entrypoint and run the donation-aware liveness
    analysis over it. Returns the
    :class:`~apex_tpu.lint.liveness.MemoryReport` (peak bytes, family
    breakdown, donation-aliased bytes, stash bytes)."""
    import jax

    from apex_tpu.lint import liveness

    ep = get(name)
    fn, args = ep.build()
    closed = jax.make_jaxpr(fn)(*args)
    return liveness.analyze(closed, arg_families=arg_families(name, args),
                            entrypoint=name)


# --- per-entrypoint memory families (apexmem) ---------------------------------

#: family label per POSITIONAL builder arg for the liveness analysis —
#: every traced invar inherits the label of the pytree arg it is a leaf
#: of (:func:`arg_families` does the flattening). Callables resolve
#: plan-dependent signatures (``planned_gpt_step``) at build time.
_SERVE_DECODE_FAMS = ("params", "kv_pool", "temps", "temps", "temps",
                      "temps")
_SERVE_PREFILL_FAMS = ("params", "kv_pool", "temps", "temps", "temps",
                       "temps", "temps")
_PIPE_FAMS = ("params", "activations", "activations")


def _planned_arg_families():
    """Mirror of ``_build_planned_gpt_step``'s four signature variants."""
    plan = active_plan()
    if plan.pp > 1 and plan.tp > 1:
        # (stage params, chain weights, microbatches, targets, chain x)
        return ("params", "params", "activations", "activations",
                "activations")
    if plan.pp > 1:
        return _PIPE_FAMS
    if plan.tp > 1:
        return ("params", "activations")
    return ARG_FAMILIES["gpt_fwd_bwd"]


ARG_FAMILIES = {
    "gpt_fwd_bwd": ("params", "optimizer", "activations", "activations"),
    "flash_bias_fwd_bwd": ("activations", "activations", "activations",
                           "params"),
    "collective_matmul_ring": ("activations", "params", "params",
                               "params", "params"),
    "pipeline_1f1b": _PIPE_FAMS,
    "pipeline_1f1b_overlap": _PIPE_FAMS,
    "pipeline_interleaved": _PIPE_FAMS,
    "pipeline_interleaved_overlap": _PIPE_FAMS,
    "pipeline_zb": _PIPE_FAMS,
    "pipeline_zb_overlap": _PIPE_FAMS,
    "planned_gpt_step": _planned_arg_families,
    "serve_prefill": _SERVE_PREFILL_FAMS,
    "serve_prefill_tp": _SERVE_PREFILL_FAMS,
    "serve_decode": _SERVE_DECODE_FAMS,
    "serve_decode_tp": _SERVE_DECODE_FAMS,
    "serve_decode_quantized": _SERVE_DECODE_FAMS,
    "serve_swap": _SERVE_DECODE_FAMS,
    "spec_verify": ("params", "kv_pool", "temps", "temps", "temps",
                    "temps", "temps"),
    "spec_verify_tree": ("params", "kv_pool", "temps", "temps", "temps",
                         "temps", "temps", "temps", "temps"),
}


def arg_families(name: str, args) -> Tuple[str, ...]:
    """One family label per traced invar: the per-positional-arg spec in
    :data:`ARG_FAMILIES` flattened over each arg's pytree leaves."""
    import jax

    spec = ARG_FAMILIES.get(name)
    if spec is None:  # pragma: no cover - registration-time error
        raise KeyError(f"entrypoint {name!r} has no ARG_FAMILIES entry")
    if callable(spec):
        spec = spec()
    if len(spec) != len(args):
        raise ValueError(
            f"{name}: ARG_FAMILIES lists {len(spec)} positional args, "
            f"builder returned {len(args)}")
    out: List[str] = []
    for fam, arg in zip(spec, args):
        out.extend([fam] * len(jax.tree.leaves(arg)))
    return tuple(out)


# --- GPT flagship train step --------------------------------------------------

def _gpt_smoke_model():
    import jax.random as jr

    from apex_tpu.models import GPTConfig, GPTModel

    cfg = GPTConfig(vocab_size=256, max_seq_len=128, hidden_size=64,
                    num_layers=2, num_heads=4, tp_size=1, remat=False,
                    attention_impl="flash")
    model = GPTModel(cfg)
    # the key only seeds example operands for jax.make_jaxpr — the traced
    # program, not the values, is what the contracts judge (same rationale
    # as the baselined DecodeEngine dummy key); likewise every other
    # PRNGKey(0) in this module
    return model, model.init(jr.PRNGKey(0))  # apexlint: disable=APX502


@register(
    "gpt_fwd_bwd",
    "flagship GPT train step (value_and_grad + adam) under donation",
    lambda: [jc.donation_honored(), jc.donation_rebound(),
             jc.fp32_accumulation()])
def _build_gpt_fwd_bwd():
    import jax
    import jax.numpy as jnp
    import optax

    model, params = _gpt_smoke_model()
    opt = optax.adam(1e-4)
    opt_state = opt.init(params)

    def train_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss_fn)(params, tokens,
                                                        targets)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    tokens = jnp.zeros((2, 128), jnp.int32)
    return step, (params, opt_state, tokens, tokens)


# --- bucketed-bias flash attention --------------------------------------------

_BIAS_SEQ = 256


@register(
    "flash_bias_fwd_bwd",
    "flash attention with the bucketed relative bias, fwd+grad "
    "(no materialized O(s^2) bias/score aval)",
    lambda: [jc.no_aval_matching(
        lambda shape: sum(1 for d in shape if d >= _BIAS_SEQ) >= 2,
        f"two dims >= seq ({_BIAS_SEQ}): a materialized bias/score")])
def _build_flash_bias():
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.attention import BucketedBias, flash_attention

    s, h, d = _BIAS_SEQ, 2, 64
    q = jnp.zeros((h, s, d), jnp.float32)
    tab = jnp.zeros((32, h), jnp.float32)

    def loss(q, k, v, tab):
        bias = BucketedBias(tab, bidirectional=True, max_distance=64)
        out = flash_attention(q, k, v, causal=False, bias=bias,
                              impl="pallas")
        return jnp.sum(out ** 2)

    return jax.grad(loss, argnums=(0, 1, 2, 3)), (q, q, q, tab)


# --- overlapped collective matmul ---------------------------------------------

@register(
    "collective_matmul_ring",
    "overlapped Column->Row TP chain (SP) — ppermute ring, no "
    "full-width all_gather",
    lambda: [jc.ppermute_present("tp"),
             jc.no_full_width_all_gather("tp")])
def _build_collective_matmul_ring():
    return _collective_matmul_chain(overlap=True)


def _collective_matmul_chain(overlap: bool, grad: bool = True,
                             tp: int = 4):
    import jax
    import jax.numpy as jnp
    import jax.random as jr
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer import tensor_parallel as tp_lib

    # dims scale with tp so planned_gpt_step can trace the chain at the
    # active plan's width (tp=4 keeps the historical shape)
    s, b, din, dhid, dout = 3 * tp, 2, 8, 6 * tp, 8
    mesh = mesh_lib.make_mesh(tensor_model_parallel_size=tp)
    col = tp_lib.ColumnParallelLinear(din, dhid, tp_size=tp, bias=True,
                                      sequence_parallel=True, seq_dim=1,
                                      overlap_comm=overlap)
    row = tp_lib.RowParallelLinear(dhid, dout, tp_size=tp, bias=True,
                                   sequence_parallel=True, seq_dim=1,
                                   overlap_comm=overlap)

    def block(x, wc, bc, wr, br):
        hcol = col({"weight": wc, "bias": bc}, x)
        return row({"weight": wr, "bias": br},
                   jax.nn.gelu(hcol, approximate=True))

    def loss(x, wc, bc, wr, br):
        sm = mesh_lib.shard_map(
            block, mesh=mesh,
            in_specs=(P(None, "tp"), P("tp", None), P("tp"),
                      P(None, "tp"), P()),
            out_specs=P(None, "tp"))
        return jnp.sum(jnp.sin(sm(x, wc, bc, wr, br).astype(jnp.float32)))

    key = jr.PRNGKey(0)  # apexlint: disable=APX502
    args = (jr.normal(key, (b, s, din)),
            jr.normal(key, (dhid, din)) * 0.3,
            jnp.zeros((dhid,)),
            jr.normal(key, (dout, dhid)) * 0.3,
            jnp.zeros((dout,)))
    if not grad:
        return loss, args
    return jax.value_and_grad(loss, argnums=(0, 1, 2, 3, 4)), args


# --- pipeline schedule family -------------------------------------------------

def _pipeline_m(schedule: str) -> int:
    return _PP_M_INTERLEAVED if schedule == "interleaved" else _PP_M


def _pipeline_geometry(schedule: str, overlap_p2p: bool, v: int,
                       *, S: int = None, M: int = None):
    """(fwd_ticks, dw_ticks) from the canonical unit-cost model — the
    same closed form ``monitor.pipeline_cost_model`` prices (kept in one
    place so the contract set and the cost model cannot drift apart)."""
    from apex_tpu.monitor.hooks import pipeline_cost_model

    cost = pipeline_cost_model(M or _pipeline_m(schedule), S or _PP_S, v,
                               schedule="zb" if schedule == "zb" else "1f1b",
                               overlap_p2p=overlap_p2p)
    return cost["fwd_ticks"], cost["bwd_dw_ticks"]


def _pipeline_contracts(schedule: str, overlap_p2p: bool, v: int,
                        *, S: int = None, M: int = None
                        ) -> List[jc.Contract]:
    fwd_ticks, _ = _pipeline_geometry(schedule, overlap_p2p, v, S=S, M=M)
    mv = (M or _pipeline_m(schedule)) * v
    cons = [jc.ppermute_present("pp"),
            jc.scan_length(fwd_ticks, min_count=2),  # fwd + backward sweep
            jc.fp32_accumulation()]
    if schedule == "zb":
        # the dW-deferral ORDER witness: a third scan of exactly M·v
        # real-item ticks, and that whole sweep is collective-free
        cons.append(jc.scan_length(mv))
        cons.append(jc.collective_free_region(
            rf"(^|/)scan:{mv}(\.\d+)?(/|$)", region="deferred-dW sweep"))
    else:
        # the autodiff control: dW rides the full-length backward scan,
        # garbage lanes included — no M·v-tick sweep may exist
        cons.append(jc.scan_length(mv, forbid=True))
    return cons


def _build_pipeline(schedule: str, overlap_p2p: bool, v: int = 1,
                    *, S: int = None, M: int = None):
    import jax
    import jax.numpy as jnp
    import jax.random as jr
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer.pipeline_parallel import schedules

    S, M, hid = S or _PP_S, M or _pipeline_m(schedule), _PP_HID
    mesh = mesh_lib.make_mesh(pipeline_model_parallel_size=S)
    key = jr.PRNGKey(0)  # apexlint: disable=APX502

    def stage_fn(params, x):
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        return x + h @ params["w2"]

    def one(k):
        k1, k2 = jr.split(k)
        return {"w1": jr.normal(k1, (hid, hid)) * 0.3,
                "b1": jnp.zeros((hid,)),
                "w2": jr.normal(k2, (hid, hid)) * 0.3}

    def loss_head(out, tgt):
        return jnp.mean((out - tgt) ** 2)

    if schedule == "interleaved":
        plist = [one(jr.fold_in(key, i)) for i in range(S * v)]
        # device r holds chunks [stage r, stage r+S, ...]: (v, S, ...)
        chunks = [[plist[c * S + r] for r in range(S)] for c in range(v)]
        stacked = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[jax.tree.map(lambda *ys: jnp.stack(ys), *row)
              for row in chunks])
        spec = jax.tree.map(lambda _: P(None, "pp"), stacked)
        take = lambda p: jax.tree.map(lambda x: x[:, 0], p)
        lift = lambda g: jax.tree.map(lambda x: x[:, None], g)
    else:
        plist = [one(jr.fold_in(key, i)) for i in range(S)]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *plist)
        spec = jax.tree.map(lambda _: P("pp"), stacked)
        take = lambda p: jax.tree.map(lambda x: x[0], p)
        lift = lambda g: jax.tree.map(lambda x: x[None], g)

    def run(p, m, t):
        if schedule == "zb":
            loss, g = schedules.forward_backward_pipelining_zero_bubble(
                stage_fn, loss_head, take(p), m, t, virtual_chunks=v,
                overlap_p2p=overlap_p2p)
        elif schedule == "interleaved":
            loss, g = schedules.forward_backward_pipelining_with_interleaving(
                stage_fn, loss_head, take(p), m, t, virtual_chunks=v,
                overlap_p2p=overlap_p2p)
        else:
            loss, g = schedules.forward_backward_pipelining_without_interleaving(
                stage_fn, loss_head, take(p), m, t,
                overlap_p2p=overlap_p2p)
        return loss, lift(g)

    fn = mesh_lib.shard_map(run, mesh=mesh, in_specs=(spec, P(), P()),
                            out_specs=(P(), spec))
    mbs = jr.normal(jr.fold_in(key, 71), (M, 2, hid))
    tgts = jr.normal(jr.fold_in(key, 72), (M, 2, hid))
    return fn, (stacked, mbs, tgts)


def _register_pipeline(schedule: str, overlap_p2p: bool, v: int = 1):
    suffix = "_overlap" if overlap_p2p else ""
    name = f"pipeline_{schedule}{suffix}"
    desc = (f"{schedule} pipeline schedule fwd+bwd "
            f"(S={_PP_S}, M={_pipeline_m(schedule)}, v={v}, "
            f"overlap_p2p={overlap_p2p})")

    @register(name, desc,
              lambda: _pipeline_contracts(schedule, overlap_p2p, v))
    def _build(schedule=schedule, overlap_p2p=overlap_p2p, v=v):
        return _build_pipeline(schedule, overlap_p2p, v)


for _overlap in (False, True):
    _register_pipeline("1f1b", _overlap)
    _register_pipeline("interleaved", _overlap, v=2)
    _register_pipeline("zb", _overlap)


# --- serving engine bodies ----------------------------------------------------

def _serving_engine():
    import jax.numpy as jnp

    from apex_tpu.serving import ServingEngine

    model, params = _gpt_smoke_model()
    engine = ServingEngine(model, num_slots=4, block_size=32)
    return engine, params, jnp


def _cow_scheduler(engine):
    """A scheduler with COW block tables IN PLAY — pure host
    bookkeeping, no device execution: request A's prompt is walked
    through the chunked-prefill protocol (dummy sampled tokens) so its
    two full system-prompt blocks land in the prefix cache, then
    request B sharing that system prompt admits against the warm cache.
    B's table row now carries refcounted SHARED block ids and a
    non-zero resume frontier; the traced serving programs get exactly
    these operands, so the donation/collective-free contracts are
    asserted on the shapes the tier-2 engine really dispatches.
    Returns ``(sched, slot_b, resume_start)``."""
    import numpy as np

    from apex_tpu.serving import Request

    B = engine.block_size
    sched = engine.make_scheduler()
    sysp = (np.arange(2 * B, dtype=np.int32) * 7 + 3) % 97
    a = Request(rid=0, prompt=np.concatenate(
        [sysp, np.ones(3, np.int32)]), max_new_tokens=4)
    sched.submit(a)
    sched.admit(0.0)
    while True:  # host-side prefill protocol: chunks never hit a device
        w = sched.next_prefill(0.0)
        if w is None:
            break
        sched.note_prefill(w, 1, 0.0)
    b = Request(rid=1, prompt=np.concatenate(
        [sysp, np.full(5, 2, np.int32)]), max_new_tokens=4)
    sched.submit(b)
    (slot_b,) = sched.admit(0.0)
    shared = sched._slots[slot_b].shared_blocks
    if shared != 2:  # the COW setup itself must not silently decay
        raise RuntimeError(
            f"serve entrypoint expected 2 shared prefix blocks in play, "
            f"got {shared}")
    return sched, slot_b, shared * B


@register(
    "serve_prefill",
    "serving chunked-prefill body with COW block tables in play "
    "(shared-prefix resume; pool donated+rebound, collective-free)",
    lambda: [jc.donation_honored(), jc.donation_rebound(),
             jc.donation_aliased("paged KV pool"),
             jc.collective_free_region("", region="serving prefill body")])
def _build_serve_prefill():
    import jax.random as jr

    engine, params, jnp = _serving_engine()
    sched, slot_b, start = _cow_scheduler(engine)
    pool = engine.init_pool()
    C = engine.prefill_chunk_size
    # the REAL table row: leading entries are refcounted shared blocks,
    # the chunk resumes at the shared-prefix frontier
    table_row = jnp.asarray(sched.tables.row(slot_b))
    tokens = jnp.zeros((C,), jnp.int32)
    live = min(C, len(sched._slots[slot_b].eprompt) - start)
    return engine.prefill_chunk, (params, pool, table_row, tokens,
                                  jnp.int32(start), jnp.int32(live),
                                  jr.PRNGKey(0))  # apexlint: disable=APX502


# --- the planner's chosen plan ------------------------------------------------

#: the default ParallelPlan `planned_gpt_step` traces when no plan is
#: supplied: the multichip gate topology (dp2×tp2×pp2, zb) — the
#: planner's most-searched corner stays contract-checked on every gate
#: run even without an explicit pick
_DEFAULT_PLAN_JSON = {"dp": 2, "tp": 2, "pp": 2, "pp_schedule": "zb",
                      "sequence_parallel": True}


def active_plan():
    """The ParallelPlan `planned_gpt_step` traces: ``APEX_TPU_PLAN``
    (a :meth:`ParallelPlan.to_json` object / JSON string) when set —
    how ``bench.py --plan`` and CI point the JXP gate at the planner's
    *chosen* plan — else the gate-topology default."""
    import os

    from apex_tpu.plan.parallel_plan import ParallelPlan

    env = os.environ.get("APEX_TPU_PLAN")
    if env:
        return ParallelPlan.from_json(env)
    return ParallelPlan.from_json(dict(_DEFAULT_PLAN_JSON))


def _planned_m(plan) -> int:
    """Microbatch count for the traced schedule: fills the pipeline and
    divides the (overlap-doubled) injection group at any v."""
    return 2 * plan.pp * max(plan.virtual_chunks, 1)


def _planned_schedule(plan) -> str:
    """The schedule-family name the plan's knobs select — ONE
    derivation shared by the contract set and the builder, so the
    program and the contracts judging it cannot drift apart."""
    if plan.pp_schedule == "1f1b" and plan.virtual_chunks > 1:
        return "interleaved"
    return plan.pp_schedule


def _planned_contracts() -> List[jc.Contract]:
    """The JXP contracts the active plan's knobs engage — donation
    always; the schedule family's scan/collective geometry when the
    plan pipelines; the ring-overlap acceptance when it overlaps tp.
    The knob families COMPOSE (the builder traces the pp schedule AND
    the tp chain as one program when a plan carries both), so a
    dp2×tp2×pp2 tp_overlap pick is checked against the overlap
    invariants too — never vacuously gated. This is how the planner
    can never pick a plan that violates a shipped invariant:
    `python -m apex_tpu.lint --jaxpr --entrypoint planned_gpt_step`
    with APEX_TPU_PLAN set to the chosen plan."""
    plan = active_plan()
    cons = [jc.donation_honored(), jc.donation_rebound(),
            jc.fp32_accumulation()]
    if plan.pp > 1:
        cons.extend(c for c in _pipeline_contracts(
            _planned_schedule(plan), plan.overlap_p2p,
            plan.virtual_chunks, S=plan.pp, M=_planned_m(plan))
            if c.code != "JXP501")  # fp32_accumulation already present
    if plan.tp > 1 and plan.tp_overlap:
        cons.append(jc.ppermute_present("tp"))
        cons.append(jc.no_full_width_all_gather("tp"))
    return cons


@register(
    "planned_gpt_step",
    "train step under the ACTIVE ParallelPlan (APEX_TPU_PLAN env or "
    "the dp2×tp2×pp2 zb gate default) — donation + the plan's "
    "schedule/overlap contracts",
    _planned_contracts)
def _build_planned_gpt_step():
    """One traced program per plan, composing the knob families: the
    plan's REAL pipeline schedule (when pp > 1) and the tp boundary
    chain at the plan's width/overlap (when tp > 1) run inside one
    donating SGD step, so every engaged contract judges the same
    program. The chain introduces no scans (rings unroll), so the
    schedule's scan-length witnesses cannot collide with it."""
    import jax

    plan = active_plan()
    pipe = chain = None
    if plan.pp > 1:
        pipe = _build_pipeline(
            _planned_schedule(plan), plan.overlap_p2p,
            plan.virtual_chunks, S=plan.pp, M=_planned_m(plan))
    if plan.tp > 1:
        chain = _collective_matmul_chain(overlap=plan.tp_overlap,
                                         tp=plan.tp)
    if pipe is None and chain is None:
        # dp-only plan: the flagship smoke train step (already donating)
        return _build_gpt_fwd_bwd()

    if pipe is not None and chain is not None:
        fn, (params, mbs, tgts) = pipe
        vg, (x, *ws) = chain

        def train(p, ws, m, t, x):
            loss_p, g = fn(p, m, t)
            loss_c, grads = vg(x, *ws)
            new_p = jax.tree.map(lambda a, b: a - 0.01 * b, p, g)
            new_w = [w - 0.01 * gw for w, gw in zip(ws, grads[1:])]
            return new_p, new_w, loss_p + loss_c

        return (jax.jit(train, donate_argnums=(0, 1)),
                (params, list(ws), mbs, tgts, x))
    if pipe is not None:
        fn, (params, mbs, tgts) = pipe

        def train(p, m, t):
            loss, g = fn(p, m, t)
            return jax.tree.map(lambda a, b: a - 0.01 * b, p, g), loss

        return jax.jit(train, donate_argnums=(0,)), (params, mbs, tgts)
    vg, (x, *ws) = chain

    def train(ws, x):
        loss, grads = vg(x, *ws)
        return [w - 0.01 * g for w, g in zip(ws, grads[1:])], loss

    return jax.jit(train, donate_argnums=(0,)), (list(ws), x)


@register(
    "serve_swap",
    "serving decode step immediately AFTER a weight hot-swap "
    "(checkpoint params swapped into the live engine between dispatch "
    "steps; pool donated+rebound, collective-free — the same compiled "
    "program, new operand contents)",
    lambda: [jc.donation_honored(), jc.donation_rebound(),
             jc.donation_aliased("paged KV pool"),
             jc.collective_free_region("",
                                       region="serving hot-swap step")])
def _build_serve_swap():
    """The hot-swap contract as a traced program: the engine's decode
    step with the SWAPPED param tree as its operand. The swap itself is
    host-side (ISSUE 14: a contents-only mutation validated by
    ``_validate_swap_avals`` — exercised here so the entrypoint fails
    loudly if the contract ever starts mutating avals), so the traced
    program is the ordinary decode body; the contracts assert that the
    step a freshly-swapped engine dispatches still donates + rebinds
    the pool and stays collective-free."""
    import jax
    import jax.random as jr

    engine, params, jnp = _serving_engine()
    sched, _, _ = _cow_scheduler(engine)
    pool = engine.init_pool()
    # the swapped tree: same avals, new contents (a restored
    # checkpoint's params — here a structural clone stands in)
    new_params = jax.tree.map(jnp.asarray, params)
    engine._validate_swap_avals(params, new_params)
    batch = sched.decode_batch(0.0)
    if batch is None:
        raise RuntimeError(
            "serve_swap entrypoint expected a live decode batch")
    toks, lens = batch
    tables = jnp.asarray(sched.tables.asarray())
    return engine.decode_step, (new_params, pool, tables,
                                jnp.asarray(toks), jnp.asarray(lens),
                                jr.PRNGKey(0))  # apexlint: disable=APX502


@register(
    "serve_decode",
    "serving paged decode step with COW block tables in play "
    "(shared prefix blocks in the table; pool donated+rebound, "
    "collective-free)",
    lambda: [jc.donation_honored(), jc.donation_rebound(),
             jc.donation_aliased("paged KV pool"),
             jc.collective_free_region("", region="serving decode body")])
def _build_serve_decode():
    import jax.random as jr

    engine, params, jnp = _serving_engine()
    sched, _, _ = _cow_scheduler(engine)
    pool = engine.init_pool()
    # the REAL operands the tier-2 engine dispatches: request A is
    # decoding (its batch allocates through the refcounted pool), the
    # full table carries shared prefix block ids, dead slots ride 0s
    batch = sched.decode_batch(0.0)
    if batch is None:
        raise RuntimeError(
            "serve entrypoint expected a live decode batch")
    toks, lens = batch
    tables = jnp.asarray(sched.tables.asarray())
    return engine.decode_step, (params, pool, tables,
                                jnp.asarray(toks), jnp.asarray(lens),
                                jr.PRNGKey(0))  # apexlint: disable=APX502


_SPEC_K = 2  # smoke-scale draft length: the verify program's static k


@register(
    "spec_verify",
    "serving speculative round: k+1 drafted tokens scored + fused "
    "verify tail, COW tables in play, draft rows reserved past the "
    "frontier (pool donated+rebound, collective-free)",
    lambda: [jc.donation_honored(), jc.donation_rebound(),
             jc.donation_aliased("paged KV pool"),
             jc.collective_free_region("", region="spec verify body")])
def _build_spec_verify():
    import jax.random as jr
    import numpy as np

    engine, params, jnp = _serving_engine()
    sched, _, _ = _cow_scheduler(engine)
    pool = engine.init_pool()
    # the REAL spec-round operands: the decode batch with the k draft
    # rows reserved (the lookahead allocation note_spec later rewinds),
    # shared prefix blocks in the table, dead slots riding 0s
    batch = sched.decode_batch(0.0, lookahead=_SPEC_K)
    if batch is None:
        raise RuntimeError(
            "spec_verify entrypoint expected a live decode batch")
    toks, lens = batch
    S = engine.num_slots
    drafted = np.zeros((S, _SPEC_K), np.int32)
    tok_mat = np.zeros((S, _SPEC_K + 1), np.int32)
    tok_mat[:, 0] = toks
    tables = jnp.asarray(sched.tables.asarray())
    return engine.spec_step, (params, pool, tables,
                              jnp.asarray(tok_mat), jnp.asarray(lens),
                              jnp.asarray(drafted),
                              jr.PRNGKey(0))  # apexlint: disable=APX502


# smoke-scale tree topology: 2 branches x depth 2 (4 drafted nodes)
_TREE_BRANCHING, _TREE_DEPTH = 2, 2


@register(
    "spec_verify_tree",
    "serving TREE speculative round: branching x depth drafted nodes "
    "scored under the anc tree-attention mask in ONE forward + fused "
    "tree-verify tail, only the winning path committed to the pool "
    "(pool donated+rebound, collective-free)",
    lambda: [jc.donation_honored(), jc.donation_rebound(),
             jc.donation_aliased("paged KV pool"),
             jc.collective_free_region("", region="tree verify body")])
def _build_spec_verify_tree():
    import jax.random as jr
    import numpy as np

    from apex_tpu.spec.tree import draft_tree

    engine, params, jnp = _serving_engine()
    sched, _, _ = _cow_scheduler(engine)
    pool = engine.init_pool()
    # the REAL tree-round operands: the decode batch with depth draft
    # rows reserved, the topology's parent/ancestor arrays tiled over
    # the slot array (constant CONTENTS — the executable is pinned per
    # (num_nodes+1, depth+1)), dead slots riding 0s
    tree = draft_tree(_TREE_BRANCHING, _TREE_DEPTH)
    batch = sched.decode_batch(0.0, lookahead=_TREE_DEPTH)
    if batch is None:
        raise RuntimeError(
            "spec_verify_tree entrypoint expected a live decode batch")
    toks, lens = batch
    S = engine.num_slots
    tok_mat = np.zeros((S, tree.n1), np.int32)
    tok_mat[:, 0] = toks
    parents, anc = tree.operands(S)
    levels = np.arange(_TREE_DEPTH + 1, dtype=np.int32)
    tables = jnp.asarray(sched.tables.asarray())
    return engine.spec_tree_step, (params, pool, tables,
                                   jnp.asarray(tok_mat),
                                   jnp.asarray(lens),
                                   jnp.asarray(parents),
                                   jnp.asarray(anc),
                                   jnp.asarray(levels),
                                   jr.PRNGKey(0))  # apexlint: disable=APX502


@register(
    "serve_decode_quantized",
    "serving paged decode step over the INT8 block pool (quantize-on-"
    "write + per-block-row scale planes, COW tables in play; pool "
    "donated+rebound, collective-free)",
    lambda: [jc.donation_honored(), jc.donation_rebound(),
             jc.donation_aliased("paged KV pool"),
             jc.collective_free_region(
                 "", region="quantized serving decode body")])
def _build_serve_decode_quantized():
    import jax.numpy as jnp
    import jax.random as jr

    from apex_tpu.serving import ServingEngine

    model, params = _gpt_smoke_model()
    engine = ServingEngine(model, num_slots=4, block_size=32,
                           kv_dtype="int8")
    sched, _, _ = _cow_scheduler(engine)
    pool = engine.init_pool()
    batch = sched.decode_batch(0.0)
    if batch is None:
        raise RuntimeError(
            "quantized serve entrypoint expected a live decode batch")
    toks, lens = batch
    tables = jnp.asarray(sched.tables.asarray())
    return engine.decode_step, (params, pool, tables,
                                jnp.asarray(toks), jnp.asarray(lens),
                                jr.PRNGKey(0))  # apexlint: disable=APX502


# --- tensor-parallel serving bodies (ISSUE 17) --------------------------------

def _tp_serving_engine():
    """The tp=2 ServingEngine over the smoke model, with the SAME COW
    scheduler state in play as the single-chip serve entrypoints: the
    sharded-pool programs are judged on the operands the disaggregated
    tier really dispatches (shared refcounted prefix blocks in the
    tables, params pre-sharded P('tp'), pool k/v sharded over the
    kv-head axis)."""
    import jax.numpy as jnp

    from apex_tpu.plan.parallel_plan import ParallelPlan
    from apex_tpu.serving import ServingEngine

    model, params = _gpt_smoke_model()
    engine = ServingEngine(model, num_slots=4, block_size=32,
                           plan=ParallelPlan(tp=2))
    params = engine._prepare_params(params)
    return engine, params, jnp


_TP_SERVE_CONTRACTS = lambda: [  # noqa: E731 — mirrors the lambdas above
    jc.donation_honored(), jc.donation_rebound(),
    jc.donation_aliased("paged KV pool"),
    jc.ppermute_present("tp"), jc.no_full_width_all_gather("tp")]


@register(
    "serve_prefill_tp",
    "tp=2 serving chunked-prefill body: pool sharded over kv_heads, "
    "QKV/output projections on the ppermute ring (pool donated+"
    "rebound; no full-width all_gather over tp)",
    _TP_SERVE_CONTRACTS)
def _build_serve_prefill_tp():
    import jax.random as jr

    engine, params, jnp = _tp_serving_engine()
    sched, slot_b, start = _cow_scheduler(engine)
    pool = engine.init_pool()
    C = engine.prefill_chunk_size
    table_row = jnp.asarray(sched.tables.row(slot_b))
    tokens = jnp.zeros((C,), jnp.int32)
    live = min(C, len(sched._slots[slot_b].eprompt) - start)
    return engine.prefill_chunk, (params, pool, table_row, tokens,
                                  jnp.int32(start), jnp.int32(live),
                                  jr.PRNGKey(0))  # apexlint: disable=APX502


@register(
    "serve_decode_tp",
    "tp=2 serving paged decode step: per-shard paged attention over "
    "the contiguous kv-head slice, psum-composed sampling tail (pool "
    "donated+rebound; ppermute ring, no full-width all_gather over tp)",
    _TP_SERVE_CONTRACTS)
def _build_serve_decode_tp():
    import jax.random as jr

    engine, params, jnp = _tp_serving_engine()
    sched, _, _ = _cow_scheduler(engine)
    pool = engine.init_pool()
    batch = sched.decode_batch(0.0)
    if batch is None:
        raise RuntimeError(
            "tp serve entrypoint expected a live decode batch")
    toks, lens = batch
    tables = jnp.asarray(sched.tables.asarray())
    return engine.decode_step, (params, pool, tables,
                                jnp.asarray(toks), jnp.asarray(lens),
                                jr.PRNGKey(0))  # apexlint: disable=APX502
