"""jaxpr-level static analysis: the recursive walker and static cost
extraction the JXP contracts (:mod:`apex_tpu.lint.contracts`) and the
planner's predicted-cost substrate share.

Why a jaxpr walker next to the AST linter: apexlint (APX rules) sees
source text — it can say "this *call* looks like it materializes a bias"
but not "the traced program *contains* an ``(h, sq, sk)`` intermediate".
The invariants this repo actually lives and dies by — no full-width
``all_gather`` on an overlapped ring, the zb schedule's third scan of
exactly ``M·v`` ticks, donation honored, no O(s²) bias aval — are
properties of the *jaxpr*, the program the compiler actually sees. Until
this module they were enforced by one-off duck-typed walkers scattered
through ``tests/test_pipeline.py``, ``tests/test_attention.py`` and
``tests/test_collective_matmul.py``; this is the one shared engine.

The same walk yields the planner's static cost model for free
(:func:`static_cost`): every collective eqn carries its payload aval and
axis, every ``dot_general`` its FLOPs, and enclosing ``scan`` lengths
give static execution counts — AMP-style plan search (arXiv:2210.07297)
prices candidate plans from exactly these numbers, and veScale
(arXiv:2509.07003) is the argument for deriving them from the traced
program rather than hand math.

Like the rest of the lint package this module imports NOTHING outside
the stdlib: jaxpr objects are walked duck-typed (``.eqns`` /
``.jaxpr`` / ``.primitive.name`` / ``.aval``), the same convention the
migrated test walkers used, so the analysis survives jax's core/extend
reshuffles and never imports the jax it is vetting. Callers hand in
whatever ``jax.make_jaxpr`` returned.

Walk model
----------
:func:`iter_sites` yields one :class:`EqnSite` per equation at every
nesting level, descending into EVERY sub-jaxpr found in ``eqn.params``
(pjit's ``jaxpr``, scan's ``jaxpr``, while's ``cond_jaxpr``/
``body_jaxpr``, cond's ``branches``, custom_vjp/jvp's ``fun_jaxpr``/
``call_jaxpr``, shard_map's ``jaxpr``, remat, pallas_call — anything
Jaxpr-shaped, listed or bare). Each site carries:

* ``path`` — ``/``-joined segments of the higher-order eqns containing
  it (``"pjit:step/scan:6"``); scan segments embed the static length,
  pjit segments the wrapped function name, so contracts can target
  regions by regex (the zb dW sweep is ``scan:<M·v>``);
* ``mult`` — the product of enclosing scan lengths: the number of times
  the eqn executes per call of the traced program (the unit
  ``monitor.hooks.count_collective`` counts in);
* ``bounded`` — False under a ``while`` body, whose trip count is not
  static (cost rows fed from such sites are flagged, never silently
  priced).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Tuple

#: jaxpr collective primitive name -> the counter kind
#: ``monitor.hooks.count_collective`` uses for the same traffic, so a
#: StaticCostReport's kind×axis keys join 1:1 against counted bytes and
#: the CostDB's calibrated rows.
COLLECTIVE_PRIMS = {
    "psum": "psum",
    "psum2": "psum",
    "all_gather": "all_gather",
    "all_gather_invariant": "all_gather",
    "reduce_scatter": "psum_scatter",
    "psum_scatter": "psum_scatter",
    "ppermute": "ppermute",
    "pshuffle": "ppermute",
    "all_to_all": "all_to_all",
}

#: primitives whose sub-jaxpr is a KERNEL body (VMEM tiles, priced by
#: measured kernel events, not the static walker) — the walker descends
#: for completeness but cost/aval accounting skips anything under them
_KERNEL_PRIMS = ("pallas_call",)


@dataclasses.dataclass(frozen=True)
class EqnSite:
    """One equation at one nesting level of a walked jaxpr."""
    path: str      #: containing higher-order path ("" = top level)
    eqn: Any       #: the JaxprEqn (duck-typed)
    mult: int      #: static executions per program call (scan lengths)
    bounded: bool  #: False when under a while body (unknown trip count)

    @property
    def prim(self) -> str:
        return self.eqn.primitive.name

    def under_kernel(self) -> bool:
        """True inside a Pallas kernel body: avals there are VMEM tiles,
        not HBM arrays — the O(s²) claims and the byte accounting are
        about what exists OUTSIDE kernels (kernel operands are checked
        at the pallas_call eqn itself, which is never under_kernel)."""
        return any(seg.split(":", 1)[0] in _KERNEL_PRIMS
                   for seg in self.path.split("/") if seg)


# --- duck-typed jaxpr plumbing -----------------------------------------------

def as_jaxpr(obj):
    """The raw Jaxpr behind a ClosedJaxpr / Jaxpr / anything wearing one.
    The ``.jaxpr`` unwrap is checked FIRST: a ClosedJaxpr proxies
    ``.eqns`` but not ``.outvars``, so the eqns check alone would hand
    callers a half-jaxpr."""
    inner = getattr(obj, "jaxpr", None)
    if hasattr(inner, "eqns"):
        return inner
    if hasattr(obj, "eqns"):
        return obj
    raise TypeError(
        f"not a jaxpr: {type(obj).__name__} (pass jax.make_jaxpr(fn)(*args) "
        "or its .jaxpr)")


def sub_jaxprs(val) -> Iterator[Any]:
    """Every Jaxpr nested in one ``eqn.params`` value — bare, closed, or
    inside a list/tuple (cond's ``branches``)."""
    if hasattr(getattr(val, "jaxpr", None), "eqns"):
        yield val.jaxpr
    elif hasattr(val, "eqns"):
        yield val
    elif isinstance(val, (list, tuple)):
        for item in val:
            yield from sub_jaxprs(item)


def _segment(eqn) -> str:
    """Path segment for one higher-order eqn: scans embed their static
    length (``scan:6`` — how contracts target the zb dW sweep), pjit its
    wrapped-function name (``pjit:train_step``)."""
    name = eqn.primitive.name
    if name == "scan":
        length = eqn.params.get("length")
        if isinstance(length, int):
            return f"scan:{length}"
    if name == "pjit":
        fn_name = eqn.params.get("name")
        if isinstance(fn_name, str) and fn_name:
            return f"pjit:{fn_name}"
    return name


def iter_sites(jaxpr_like, *, path: str = "", mult: int = 1,
               bounded: bool = True) -> Iterator[EqnSite]:
    """Yield an :class:`EqnSite` for every eqn at every nesting level."""
    j = as_jaxpr(jaxpr_like)
    for eqn in j.eqns:
        yield EqnSite(path, eqn, mult, bounded)
        subs: List[Any] = []
        for val in eqn.params.values():
            subs.extend(sub_jaxprs(val))
        if not subs:
            continue
        name = eqn.primitive.name
        child_mult, child_bounded = mult, bounded
        if name == "scan":
            length = eqn.params.get("length")
            if isinstance(length, int):
                child_mult = mult * length
        elif name == "while":
            child_bounded = False
        seg = _segment(eqn)
        for i, sub in enumerate(subs):
            child = f"{path}/{seg}" if path else seg
            if len(subs) > 1:
                child = f"{child}.{i}"
            yield from iter_sites(sub, path=child, mult=child_mult,
                                  bounded=child_bounded)


def iter_levels(jaxpr_like, *, path: str = "") -> Iterator[Tuple[str, Any]]:
    """Yield ``(path, jaxpr)`` for every nesting level — the per-level
    view the donation contracts need (use-after-donate is a statement
    about *later eqns of the same level*, which the flat site stream
    cannot express)."""
    j = as_jaxpr(jaxpr_like)
    yield path, j
    for eqn in j.eqns:
        subs: List[Any] = []
        for val in eqn.params.values():
            subs.extend(sub_jaxprs(val))
        if not subs:
            continue
        seg = _segment(eqn)
        for i, sub in enumerate(subs):
            child = f"{path}/{seg}" if path else seg
            if len(subs) > 1:
                child = f"{child}.{i}"
            yield from iter_levels(sub, path=child)


def scan_sites(jaxpr_like) -> List[EqnSite]:
    """Every ``scan`` eqn anywhere in the program (any nesting level)."""
    return [s for s in iter_sites(jaxpr_like) if s.prim == "scan"]


def scan_lengths(jaxpr_like) -> List[int]:
    """Every static scan length anywhere in the program — the trace-time
    geometry the pipeline schedules compile to (the former
    ``tests/test_pipeline.py`` helper, now shared)."""
    out = []
    for s in scan_sites(jaxpr_like):
        length = s.eqn.params.get("length")
        if isinstance(length, int):
            out.append(length)
    return out


# --- per-eqn accounting -------------------------------------------------------

def collective_kind(eqn) -> Optional[str]:
    """The hook-counter kind of a collective eqn, None for anything else."""
    return COLLECTIVE_PRIMS.get(eqn.primitive.name)


def collective_axes(eqn) -> Tuple[str, ...]:
    """Mesh axis names a collective eqn rides (``axis_name`` or ``axes``
    param, normalized to a tuple of strings)."""
    params = eqn.params
    axes = params.get("axis_name", params.get("axes", ()))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def aval_bytes(var) -> int:
    """Static byte size of one var's aval; 0 when not statically known
    (abstract tokens, polymorphic dims)."""
    aval = getattr(var, "aval", None)
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    try:
        return int(size) * dtype.itemsize
    except TypeError:
        return 0


def eqn_input_bytes(eqn) -> int:
    """Payload bytes of one collective eqn: the sum of its operand avals
    — the same per-call accounting ``monitor.hooks.tree_bytes`` applies
    to the payload a ``count_traffic`` call site passes (a multi-leaf
    psum is one eqn with one invar per leaf)."""
    return sum(aval_bytes(v) for v in eqn.invars)


def dot_flops(eqn) -> float:
    """FLOPs of one ``dot_general``: ``2 · batch · m · n · k`` read off
    the operand avals and dimension numbers (the multiply-add convention
    XLA's ``model_flops`` uses, so static classes join the CostDB's
    measured GEMM classes)."""
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    a = getattr(eqn.invars[0], "aval", None)
    b = getattr(eqn.invars[1], "aval", None)
    ashape = getattr(a, "shape", None)
    bshape = getattr(b, "shape", None)
    if ashape is None or bshape is None:
        return 0.0
    k = _prod(ashape[i] for i in lc)
    batch = _prod(ashape[i] for i in lb)
    m = _prod(ashape[i] for i in range(len(ashape))
              if i not in lc and i not in lb)
    n = _prod(bshape[i] for i in range(len(bshape))
              if i not in rc and i not in rb)
    return 2.0 * batch * m * n * k


def _prod(it) -> int:
    out = 1
    for v in it:
        out *= int(v)
    return out


def pow2_floor(x: float) -> int:
    """Power-of-two floor (1 below 2) — the same bucket key
    ``prof.calibrate.size_bucket`` uses, duplicated here so the lint
    package stays stdlib-only (parity is pinned by
    ``tests/test_jaxpr_check.py::TestStaticCost::test_bucket_parity``)."""
    b = 1
    while b * 2 <= x:
        b *= 2
    return b


# --- StaticCostReport ---------------------------------------------------------

def _new_acc() -> Dict[str, Any]:
    return {"collectives": {}, "gemms": {}, "eqns": 0, "unbounded": 0}


def _merge_max(parent: Dict[str, Any], branches: List[Dict[str, Any]]
               ) -> None:
    """Fold cond-branch accumulators into the parent: exactly ONE branch
    executes per call, so branch costs are ALTERNATIVES — summing them
    would silently overstate every cond-bearing program. Per key the
    field-wise max over branches (the tightest per-key upper bound
    expressible without knowing the predicate) is reduced FIRST, then
    ADDED to the parent's running totals — the same key outside the
    cond is a separate execution, never absorbed by (or absorbing) the
    branch cost. eqns stay a walk statistic and sum."""
    for table in ("collectives", "gemms"):
        best: Dict[str, Dict[str, Any]] = {}
        for branch in branches:
            for key, ent in branch[table].items():
                dst = best.setdefault(key, {field: 0 for field in ent})
                for field, v in ent.items():
                    dst[field] = max(dst[field], v)
        for key, ent in best.items():
            dst = parent[table].setdefault(
                key, {field: 0 for field in ent})
            for field, v in ent.items():
                dst[field] += v
    parent["eqns"] += sum(b["eqns"] for b in branches)
    parent["unbounded"] += max((b["unbounded"] for b in branches),
                               default=0)


def _accumulate(jaxpr_like, mult: int, bounded: bool,
                acc: Dict[str, Any]) -> None:
    j = as_jaxpr(jaxpr_like)
    for eqn in j.eqns:
        acc["eqns"] += 1
        name = eqn.primitive.name
        kind = collective_kind(eqn)
        if kind is not None:
            axis = ",".join(collective_axes(eqn))
            key = f"{kind}[{axis}]"
            if not bounded:
                acc["unbounded"] += 1
            ent = acc["collectives"].setdefault(key,
                                                {"calls": 0, "bytes": 0})
            ent["calls"] += mult
            ent["bytes"] += eqn_input_bytes(eqn) * mult
        elif name == "dot_general":
            flops = dot_flops(eqn)
            if flops > 0:
                if not bounded:
                    acc["unbounded"] += 1
                key = f"flops_{pow2_floor(flops)}"
                ent = acc["gemms"].setdefault(key,
                                              {"calls": 0, "flops": 0.0})
                ent["calls"] += mult
                ent["flops"] += flops * mult
        if name in _KERNEL_PRIMS:
            continue  # kernel bodies: VMEM tiles, priced by measured events
        subs: List[Any] = []
        for val in eqn.params.values():
            subs.extend(sub_jaxprs(val))
        if not subs:
            continue
        if name == "cond" and len(subs) > 1:
            branch_accs = []
            for sub in subs:
                branch = _new_acc()
                _accumulate(sub, mult, bounded, branch)
                branch_accs.append(branch)
            _merge_max(acc, branch_accs)
            continue
        child_mult, child_bounded = mult, bounded
        if name == "scan":
            length = eqn.params.get("length")
            if isinstance(length, int):
                child_mult = mult * length
        elif name == "while":
            child_bounded = False
        for sub in subs:
            _accumulate(sub, child_mult, child_bounded, acc)


def static_cost(jaxpr_like, *, entrypoint: str = "") -> Dict[str, Any]:
    """Accumulate the walked program into a ``kind: "static_cost"``
    artifact: per-collective calls/bytes by ``<kind>[<axis>]`` and
    per-GEMM calls/FLOPs by power-of-two FLOPs class, every count
    multiplied by enclosing scan lengths (a ppermute inside the
    ``M·v + S − 1``-tick pipeline scan is that many executions per
    step).

    The kind×axis keys are exactly the ``monitor.hooks.count_collective``
    tags and the CostDB's collective keys; the GEMM class keys are
    ``prof.calibrate.gemm_samples``'s — so ``prof.calibrate
    .diff_static_cost`` can line predicted bytes/FLOPs up against
    calibrated rates with a plain dict join. Pallas kernel bodies are
    skipped (their operands are accounted at the ``pallas_call`` eqn's
    level; in-kernel FLOPs are priced by the CostDB's measured kernel
    events, which the static walker cannot see per-grid-point).
    Collectives under a ``while`` body are counted ONCE and tallied in
    ``unbounded_sites`` — a row fed by an unknown trip count must
    be flagged, not silently priced. ``cond`` branches are ALTERNATIVES
    (one executes per call): per key the report takes the field-wise max
    over branches rather than summing them.

    Schema: :data:`apex_tpu.monitor.schema.STATIC_COST_SCHEMA`, gated by
    ``tools/validate_metrics.py --static-cost``.
    """
    acc = _new_acc()
    _accumulate(jaxpr_like, 1, True, acc)
    from apex_tpu.monitor.registry import SCHEMA_VERSION

    collectives, gemms = acc["collectives"], acc["gemms"]
    return {
        "schema": SCHEMA_VERSION,
        "kind": "static_cost",
        "entrypoint": entrypoint,
        "collectives": {k: collectives[k] for k in sorted(collectives)},
        "gemms": {k: gemms[k] for k in sorted(gemms)},
        "total_collective_bytes": sum(e["bytes"]
                                      for e in collectives.values()),
        "total_gemm_flops": sum(e["flops"] for e in gemms.values()),
        "eqns": acc["eqns"],
        "unbounded_sites": acc["unbounded"],
    }
