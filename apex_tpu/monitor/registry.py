"""Host-side metrics registry + structured JSONL event stream.

The reference apex ships a profiler (``apex.pyprof``) but no runtime
*metrics* story: loss-scale trajectories, skipped-step counts, pipeline
bubble fractions and collective volumes are all invisible unless the user
hand-rolls printf telemetry. This module is the missing layer — a single
process-wide :class:`MetricsRegistry` holding

* **counters**   — monotonically increasing totals (collective calls/bytes,
  overflow steps);
* **gauges**     — last-value observations (loss scale, grad norm,
  bubble fraction);
* **timers**     — (count, total seconds) accumulators driven by the
  :meth:`MetricsRegistry.timer` context manager;

and a structured **JSONL emitter**: every record is one JSON object per
line, stamped with the schema version, wall-clock offset, host process
index and the mesh rank string registered via
:func:`apex_tpu.utils.logging.set_rank_info`.

Design constraints (in priority order):

1. **Near-zero overhead when disabled.** The module-level registry is
   ``None`` until :func:`enable` is called; every public entry point and
   every instrumentation hook starts with a single attribute load and
   ``is None`` test — no dict lookups, no string formatting, no device
   syncs.
2. **Honest artifacts.** :func:`check_record_honesty` refuses any record
   that claims success (``ok: true`` / ``status: "OK"``) while carrying a
   non-finite number anywhere in its payload; the emitter enforces it on
   every write (VERDICT r5 weak #1: a skip sentinel once printed as
   ``nan … OK``).
3. **Host-side by construction.** Hooks never reach into traced values at
   run time; per-step numbers are pulled from state the training loop
   already holds (scaler state, grads) and static facts (shapes, schedule
   geometry) are recorded at trace time. See ``docs/OBSERVABILITY.md`` for
   the overhead accounting.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import time
from typing import Any, Dict, Optional, TextIO

# trace imports nothing from the monitor package at module level (it
# lazy-imports this module inside functions), so this edge is acyclic;
# emit() reads its ambient trace-id stack and flight-recorder ring
# directly as attribute loads to keep the per-record cost flat
from apex_tpu.monitor import trace as _trace

SCHEMA_VERSION = 1

# The process-wide registry. ``None`` means monitoring is disabled and every
# hook is a two-instruction no-op.
_REGISTRY: Optional["MetricsRegistry"] = None


def _rank_info() -> str:
    from apex_tpu.utils import logging as log_util

    return log_util.get_rank_info()


def _process_index() -> int:
    from apex_tpu.utils import logging as log_util

    try:
        return int(log_util.process_index())
    except (TypeError, ValueError):
        return 0


# --- honesty checks ----------------------------------------------------------

def _nonfinite_paths(obj: Any, path: str = "") -> list:
    """Paths of every non-finite float inside ``obj`` (dicts/lists/floats)."""
    bad = []
    if isinstance(obj, dict):
        for k, v in obj.items():
            bad.extend(_nonfinite_paths(v, f"{path}.{k}" if path else str(k)))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            bad.extend(_nonfinite_paths(v, f"{path}[{i}]"))
    elif isinstance(obj, float) and not math.isfinite(obj):
        bad.append(path or "<root>")
    return bad


_NONFINITE_STRINGS = {"nan", "inf", "-inf", "infinity", "-infinity"}


def _stringified_nonfinite_paths(obj: Any, path: str = "") -> list:
    """Paths of stringified non-finite values ('nan'/'inf'...) — what
    :func:`_jsonify` turns non-finite floats into. Skip-reason prose
    (``reason`` keys) is exempt."""
    bad = []
    if isinstance(obj, dict):
        for k, v in obj.items():
            if k == "reason":
                continue
            bad.extend(_stringified_nonfinite_paths(
                v, f"{path}.{k}" if path else str(k)))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            bad.extend(_stringified_nonfinite_paths(v, f"{path}[{i}]"))
    elif isinstance(obj, str) and obj.strip().lower() in _NONFINITE_STRINGS:
        bad.append(path or "<root>")
    return bad


def _claims_success(record: Dict[str, Any]) -> bool:
    if record.get("ok") is True:
        return True
    status = record.get("status")
    return isinstance(status, str) and status.upper() == "OK"


def check_record_honesty(record: Dict[str, Any]) -> None:
    """Raise ``ValueError`` if ``record`` reports success but contains a
    non-finite number — as a float OR already stringified (the emitter
    checks the post-:func:`_jsonify` form, so numpy/jax nan scalars cannot
    slip through as strings). A metric that could not be measured must be
    encoded as an explicit skip (``{"skipped": true, "reason": ...}``),
    never as ``nan`` riding inside an OK artifact."""
    if _claims_success(record):
        bad = _nonfinite_paths(record) + _stringified_nonfinite_paths(record)
        if bad:
            raise ValueError(
                "refusing to emit a success record carrying non-finite "
                f"values at {bad}; encode unmeasured metrics as "
                '{"skipped": true, "reason": ...} instead'
            )


def _jsonify(obj: Any) -> Any:
    """Make ``obj`` strictly JSON-serializable: numpy/jax scalars become
    Python numbers and non-finite floats become explicit strings (plain
    ``json`` would emit the invalid literal ``NaN``)."""
    if isinstance(obj, dict):
        return {str(k): _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    if isinstance(obj, bool) or obj is None or isinstance(obj, (int, str)):
        return obj
    if isinstance(obj, float):
        if math.isfinite(obj):
            return obj
        return repr(obj)  # 'nan' / 'inf' / '-inf', flagged by validators
    # numpy / jax scalars and 0-d arrays
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return _jsonify(item())
        except (TypeError, ValueError):
            pass
    return str(obj)


class MetricsRegistry:
    """Counters, gauges and timers with an optional JSONL sink.

    All mutation happens on the host; values are plain Python numbers.
    One registry is typically installed process-wide via :func:`enable`,
    but standalone instances work too (tests construct their own).
    """

    def __init__(self, sink: Optional[TextIO] = None, *,
                 clock=time.perf_counter):
        self._sink = sink
        self._owns_sink = False
        self._buffering = 0
        self._clock = clock
        self._t0 = clock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, list] = {}  # name -> [count, total_s]
        self.step_index: Optional[int] = None
        self._step_t0: Optional[float] = None
        self._step_counters0: Dict[str, float] = {}
        self._step_timers0: Dict[str, list] = {}

    # -- primitive metrics ---------------------------------------------------

    def counter(self, name: str, value: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + value

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = float(value)

    def observe_seconds(self, name: str, seconds: float) -> None:
        slot = self.timers.setdefault(name, [0, 0.0])
        slot[0] += 1
        slot[1] += seconds

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.observe_seconds(name, self._clock() - t0)

    # -- event stream --------------------------------------------------------

    def emit(self, kind: str, **fields) -> Dict[str, Any]:
        """Emit one structured record; returns the record dict (written as
        one JSONL line when a sink is attached)."""
        record = {
            "schema": SCHEMA_VERSION,
            "kind": kind,
            "t_s": round(self._clock() - self._t0, 6),
            # the unified clock: perf_counter_ns shares CLOCK_MONOTONIC
            # with span t0_ns and the serve clock, so every stream joins
            # on one base (the per-process clock_sync record anchors it
            # to wall time)
            "t_ns": _trace.monotonic_ns(),
            "process": _process_index(),
            "rank": _rank_info(),
        }
        if _trace._STACK:
            record["trace_id"] = _trace._STACK[-1]
        record.update(fields)  # explicit trace_id=/t_ns= fields win
        # jsonify BEFORE the honesty check: numpy/jax nan scalars become
        # python floats/strings first, so they cannot evade the check
        record = _jsonify(record)
        check_record_honesty(record)
        fr = _trace._FLIGHT
        if fr is not None:
            # the flight ring sees every record even with NO sink — that
            # is what makes degraded sink-less runs debuggable post-hoc
            fr._ring.append(record)
        if self._sink is not None:
            self._sink.write(json.dumps(record) + "\n")
            if not self._buffering:
                self._sink.flush()
        return record

    @contextlib.contextmanager
    def buffered(self):
        """Suppress the per-record sink flush inside the block (one
        flush on exit). High-rate emitters (the serving telemetry's
        per-transition lifecycle records) wrap their hot loop in this:
        the OS still sees every line in order, just without an fsync-ish
        flush per token-scale event. Nests; flushes when the outermost
        block exits."""
        self._buffering += 1
        try:
            yield
        finally:
            self._buffering -= 1
            if self._buffering == 0 and self._sink is not None:
                self._sink.flush()

    def emit_meta(self, **fields) -> Dict[str, Any]:
        """Run header: device/model facts the report needs (device kind,
        peak FLOP/s, model FLOPs per token, config)."""
        return self.emit("meta", **fields)

    def emit_event(self, name: str, **fields) -> Dict[str, Any]:
        return self.emit("event", name=name, **fields)

    def _emit_status_record(self, kind: str, status: str,
                            **fields) -> Dict[str, Any]:
        """Shared construction for the status-carrying bench records
        (``decode``, ``longseq_bias``): "OK" puts the record under the
        honesty rule (finite numbers or explicit ``("skipped", reason)``
        tuples only); "SKIP" requires a ``reason``."""
        if status not in ("OK", "SKIP"):
            raise ValueError(f"status must be OK|SKIP, got {status!r}")
        if status == "SKIP" and not fields.get("reason"):
            raise ValueError(f"a SKIP {kind} record must carry a reason")
        for name, v in list(fields.items()):
            if (isinstance(v, tuple) and len(v) == 2
                    and v[0] == "skipped"):
                fields[name] = {"skipped": True, "reason": str(v[1])}
        return self.emit(kind, status=status, **fields)

    def emit_decode(self, status: str, **fields) -> Dict[str, Any]:
        """Serving-bench record (``bench.py --decode``)."""
        return self._emit_status_record("decode", status, **fields)

    def emit_longseq_bias(self, status: str, **fields) -> Dict[str, Any]:
        """Long-seq in-kernel-bias bench record (``bench.py
        --longseq-bias``): bucketed vs materialized relative-bias flash,
        tokens/s + HBM high-water."""
        return self._emit_status_record("longseq_bias", status, **fields)

    def emit_tp_overlap(self, status: str, **fields) -> Dict[str, Any]:
        """TP-overlap bench record (``bench.py --tp-overlap``):
        ring-overlapped vs blocking boundary-collective tokens/s at
        tp >= 2."""
        return self._emit_status_record("tp_overlap", status, **fields)

    def emit_serve(self, status: str, **fields) -> Dict[str, Any]:
        """Continuous-batching serving record (``bench.py --serve``):
        offered-load sweep through the paged ServingEngine — per-token
        latency / TTFT percentiles, tokens/s under churn, occupancy."""
        return self._emit_status_record("serve", status, **fields)

    def emit_serve_window(self, status: str, **fields) -> Dict[str, Any]:
        """Live serving-SLO window record
        (:meth:`apex_tpu.serving.telemetry.ServeTelemetry.maybe_window`):
        sliding-window tokens/s + latency quantiles + queue/occupancy/
        pool state + the ``serve_anomaly`` section. Same OK/SKIP
        semantics as ``serve``."""
        return self._emit_status_record("serve_window", status, **fields)

    def emit_pipeline(self, status: str, **fields) -> Dict[str, Any]:
        """Pipeline-schedule bench record (``bench.py --pipeline``):
        zero-bubble vs autodiff-1f1b tokens/s at pp >= 2, bubble %
        measured by step_anatomy on TPU / the trace-time unit-cost
        geometry off-TPU."""
        return self._emit_status_record("pipeline", status, **fields)

    def emit_plan(self, status: str, **fields) -> Dict[str, Any]:
        """Auto-parallelism planner record (``bench.py --plan``): the
        searched ranking, the chosen ``ParallelPlan``, predicted step
        time + confidence, and predicted-vs-measured error when a
        measured run followed (``apex_tpu.plan.search``)."""
        return self._emit_status_record("plan", status, **fields)

    def emit_serve_plan(self, status: str, **fields) -> Dict[str, Any]:
        """Serving-plan search record (``bench.py --serve --plan-serve``):
        the trace-replay-priced serving-knob search — candidate grid,
        chosen ``ServePlan`` + predicted tokens/s / TTFT / KV-pool
        footprint + confidence, hand-config comparison, and the live
        re-plan witnesses (``apex_tpu.plan.serve``)."""
        return self._emit_status_record("serve_plan", status, **fields)

    def emit_profile(self, status: str, **fields) -> Dict[str, Any]:
        """Step-anatomy profile record (``bench.py --profile``): spans +
        device trace fused into the per-step compute/collective/bubble/
        host-gap breakdown plus the calibrated CostDB artifact."""
        return self._emit_status_record("profile", status, **fields)

    def emit_ckpt(self, status: str, **fields) -> Dict[str, Any]:
        """Elastic-checkpoint bench record (``bench.py --ckpt``):
        measured async-save cost (snapshot/write/overhead) plus the
        bitwise and elastic resume witnesses (:mod:`apex_tpu.ckpt`)."""
        return self._emit_status_record("ckpt", status, **fields)

    def emit_spec(self, status: str, **fields) -> Dict[str, Any]:
        """Speculative-decoding bench record (``bench.py --spec``):
        tokens/s/request with a drafter vs the non-speculative baseline
        (batch 1 and under churn), acceptance rate, and the int8-KV
        quantization leg's bounded logit error vs the float oracle."""
        return self._emit_status_record("spec", status, **fields)

    def emit_tp_serve(self, status: str, **fields) -> Dict[str, Any]:
        """Tensor-parallel serving bench record (``bench.py --serve
        --plan-tp N``): churn tokens/s with the paged pool sharded over
        kv_heads and ring-overlapped projections, the tp=1 baseline and
        greedy-parity witness, per-decode-step collective traffic, and
        the disaggregated prefill→decode handoff leg (TTFT, streamed
        blocks/bytes, digest verification)."""
        return self._emit_status_record("tp_serve", status, **fields)

    def emit_serve_attribution(self, status: str,
                               **fields) -> Dict[str, Any]:
        """Per-request latency-attribution record — the fields come from
        :func:`apex_tpu.monitor.trace.serve_attribution` (queue /
        prefill / decode / spec / spec-rewind / preempt-wait /
        recompute / swap-pause partition of every request's measured
        [submit, finish] window). OK only for real measurements; the
        closed schema is the ServePlan pricing input."""
        return self._emit_status_record("serve_attribution", status,
                                        **fields)

    # -- step lifecycle ------------------------------------------------------

    def begin_step(self, step: Optional[int] = None) -> None:
        """Open a step window: counter/timer deltas accumulated until
        :meth:`end_step` are attributed to this step."""
        if step is not None:
            self.step_index = step
        elif self.step_index is None:
            self.step_index = 0
        else:
            self.step_index += 1
        self._step_t0 = self._clock()
        self._step_counters0 = dict(self.counters)
        self._step_timers0 = {k: list(v) for k, v in self.timers.items()}

    def end_step(self, **fields) -> Dict[str, Any]:
        """Close the step window and emit a ``step`` record carrying the
        window's counter deltas, the current gauges, timer deltas, and any
        caller fields (``tokens=...``, ``loss=...``, or an explicit
        ``dur_s=...`` overriding the wall-clock window)."""
        dur = fields.pop("dur_s", None)
        if dur is None:
            # 0.0 when begin_step was never called — the schema requires a
            # number and a zero-length window is what actually elapsed
            dur = (self._clock() - self._step_t0
                   if self._step_t0 is not None else 0.0)
        deltas = {
            k: v - self._step_counters0.get(k, 0)
            for k, v in self.counters.items()
            if v != self._step_counters0.get(k, 0)
        }
        timer_deltas = {}
        for k, (n, tot) in self.timers.items():
            n0, t0 = self._step_timers0.get(k, (0, 0.0))
            if n != n0:
                timer_deltas[k] = {"count": n - n0,
                                   "total_s": round(tot - t0, 6)}
        record = self.emit(
            "step",
            step=self.step_index if self.step_index is not None else 0,
            dur_s=dur,
            counters=deltas,
            # lifetime totals ride along so counts that accrued OUTSIDE any
            # step window (trace-time collective counting during warm-up
            # happens before step 0's baseline) still reach the report
            counters_total=dict(self.counters),
            gauges=dict(self.gauges),
            timers=timer_deltas,
            **fields,
        )
        # re-baseline so a second end_step without begin_step reports only
        # what accrued since this record, never the same deltas twice
        self._step_t0 = None
        self._step_counters0 = dict(self.counters)
        self._step_timers0 = {k: list(v) for k, v in self.timers.items()}
        return record

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self._sink is not None and self._owns_sink:
            self._sink.close()
        self._sink = None


# --- module-level enable/disable ---------------------------------------------

def enable(path: Optional[str] = None, *,
           stream: Optional[TextIO] = None,
           append: bool = False) -> MetricsRegistry:
    """Install the process-wide registry.

    ``path`` opens a JSONL file — truncated by default so one file is one
    run and ``monitor report`` never mixes a stale run's steps into this
    run's headline; pass ``append=True`` to accumulate runs (the report
    then only aggregates the last run, split at ``meta`` records).
    ``stream`` attaches an already-open text sink; with neither, metrics
    accumulate in memory only. Returns the registry. Idempotent in the
    sense that a second call replaces the first registry (closing its
    sink if owned).
    """
    global _REGISTRY
    # open the new sink BEFORE tearing down the old registry: a failed
    # enable (bad path, path+stream) must leave the active stream intact
    sink = stream
    owns = False
    if path is not None:
        if stream is not None:
            raise ValueError("pass either path or stream, not both")
        sink = open(path, "a" if append else "w")
        owns = True
    if _REGISTRY is not None:
        _REGISTRY.close()
    reg = MetricsRegistry(sink)
    reg._owns_sink = owns
    _REGISTRY = reg
    # one clock_sync per process: the monotonic<->wall anchor that lets
    # `monitor trace` join streams from different processes (and a
    # device trace) without skew. Emitted before any meta record, so
    # consumers must read the whole stream, not the last-run split.
    reg.emit("clock_sync", mono_ns=_trace.monotonic_ns(),
             wall_s=time.time(), clock="perf_counter_ns",
             pid=os.getpid())
    return reg


def disable() -> None:
    """Tear down the process-wide registry; hooks return to no-ops."""
    global _REGISTRY
    if _REGISTRY is not None:
        _REGISTRY.close()
    _REGISTRY = None


def enabled() -> bool:
    return _REGISTRY is not None


def get_registry() -> Optional[MetricsRegistry]:
    return _REGISTRY


def enable_from_env(env_var: str = "APEX_TPU_MONITOR") -> Optional[MetricsRegistry]:
    """Enable when ``$APEX_TPU_MONITOR`` names a JSONL path (the hook bench
    and the gate driver use); no-op otherwise."""
    path = os.environ.get(env_var)
    if not path:
        return None
    return enable(path)


# module-level conveniences mirroring the registry methods; all are no-ops
# while disabled (one load + one is-None test on the fast path)

def counter(name: str, value: float = 1) -> None:
    r = _REGISTRY
    if r is not None:
        r.counter(name, value)


def gauge(name: str, value: float) -> None:
    r = _REGISTRY
    if r is not None:
        r.gauge(name, value)


def observe_seconds(name: str, seconds: float) -> None:
    r = _REGISTRY
    if r is not None:
        r.observe_seconds(name, seconds)


@contextlib.contextmanager
def timer(name: str):
    r = _REGISTRY
    if r is None:
        yield
    else:
        with r.timer(name):
            yield


def emit_event(name: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_event(name, **fields)
    return None


def emit_meta(**fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_meta(**fields)
    return None


def emit_decode(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_decode(status, **fields)
    return None


def emit_longseq_bias(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_longseq_bias(status, **fields)
    return None


def emit_tp_overlap(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_tp_overlap(status, **fields)
    return None


def emit_serve(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_serve(status, **fields)
    return None


def emit_serve_window(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_serve_window(status, **fields)
    return None


def emit_pipeline(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_pipeline(status, **fields)
    return None


def emit_plan(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_plan(status, **fields)
    return None


def emit_serve_plan(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_serve_plan(status, **fields)
    return None


def emit_profile(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_profile(status, **fields)
    return None


def emit_ckpt(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_ckpt(status, **fields)
    return None


def emit_spec(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_spec(status, **fields)
    return None


def emit_tp_serve(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_tp_serve(status, **fields)
    return None


def emit_serve_attribution(status: str, **fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.emit_serve_attribution(status, **fields)
    return None


def begin_step(step: Optional[int] = None) -> None:
    r = _REGISTRY
    if r is not None:
        r.begin_step(step)


def end_step(**fields) -> Optional[Dict[str, Any]]:
    r = _REGISTRY
    if r is not None:
        return r.end_step(**fields)
    return None
