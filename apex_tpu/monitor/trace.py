"""Request-scoped tracing: one trace id end-to-end, a unified clock,
Chrome-trace export, TTFT attribution, and the anomaly flight recorder.

The serving stack emits three observability streams — ``span`` records
(:mod:`apex_tpu.monitor.spans`), ``serve_event`` lifecycle records
(:mod:`apex_tpu.serving.telemetry`), and step/bench records — which
before this module shared no correlation key and no common clock, so
"where did THIS request's TTFT go?" had no answer across a preemption
or a spec round. This is the missing layer, the TPU-native successor of
the reference's pyprof NVTX-range→kernel join:

* **Trace ids** — :func:`new_trace_id` mints a process-unique id per
  serve request (the telemetry stamps it on the
  :class:`~apex_tpu.serving.scheduler.Request` at submit, where it
  survives evict → re-admit → resume), per serve call / generate call /
  checkpoint save (ambient, via :func:`trace_context`). The registry
  stamps the innermost ambient id on every record it emits; explicit
  ``trace_id=`` fields win (interleaved requests cannot share one
  ambient id).
* **Unified clock** — every emitted record carries ``t_ns`` from
  :func:`monotonic_ns` (``time.perf_counter_ns`` — the SAME
  ``CLOCK_MONOTONIC`` base as span ``t0_ns`` and the serve clock), and
  :func:`~apex_tpu.monitor.registry.enable` emits one per-process
  ``clock_sync`` record (``mono_ns`` ↔ ``wall_s``) so merged timelines
  never skew between streams or processes.
* **Chrome/Perfetto export** — :func:`chrome_trace` /
  :func:`write_chrome_trace` merge a JSONL stream (plus an optional
  :mod:`apex_tpu.prof.trace_reader` device trace via the existing
  scope-prefix join) into trace-event JSON: one track per rank (span
  records), one per request (queue / prefill / decode / spec / preempt
  slices reconstructed from the lifecycle records, every slice carrying
  the request's ``trace_id``). ``python -m apex_tpu.monitor trace`` is
  the CLI.
* **TTFT/latency attribution** — :func:`serve_attribution` decomposes
  each request's end-to-end latency into queue / prefill / decode /
  spec / spec-rewind / preempt-wait / recompute / swap-pause
  components. The components PARTITION ``[submit, finish]`` (decode is
  the measured interval remainder after the spec/swap carve-outs), so
  per request they sum to the measured e2e latency up to rounding —
  the closed ``serve_attribution`` record is the priced-phase input
  ServePlan pricing consumes. ``monitor report --attribution`` renders
  it; ``bench.py --serve`` emits it.
* **Anomaly flight recorder** — :class:`FlightRecorder`, a bounded ring
  of the most recent raw records (fed by the registry's emit path, so
  it accumulates even when NO JSONL sink is attached), dumped to a
  timestamped closed-schema JSON file when the ``serve_anomaly`` layer
  fires (SLO burn, straggler, leak — the telemetry dumps once per
  reason), on SIGTERM (:func:`install_signal_handler`), or on demand.

Disabled-path contract: none of this changes the single ``is None``
test — the ambient stack is consulted only inside an already-emitting
registry, the flight ring only when one was enabled, and a process that
never calls :func:`~apex_tpu.monitor.registry.enable` builds no records
at all.
"""

from __future__ import annotations

import collections
import contextlib
import gzip
import itertools
import json
import os
import signal as _signal
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "monotonic_ns", "monotonic_s", "new_trace_id", "current_trace_id",
    "trace_context", "FlightRecorder", "enable_flight_recorder",
    "disable_flight_recorder", "get_flight_recorder", "flight_dump",
    "install_signal_handler", "serve_attribution", "ATTR_COMPONENTS",
    "chrome_trace", "write_chrome_trace",
]

# THE clock: every stream measures on this one monotonic base —
# registry `t_ns`, span `t0_ns`, the serve clock, telemetry overhead
# accounting. One symbol, imported everywhere, so the unification is a
# grep-able fact rather than a convention.
monotonic_ns = time.perf_counter_ns
monotonic_s = time.perf_counter

# --- trace ids + ambient context ---------------------------------------------

_RUN = f"{os.getpid():x}"
_COUNTER = itertools.count(1)

# the ambient trace-id stack, innermost last (mirrors spans._STACK:
# serving/training are single-threaded per process, so a plain list
# keeps the cost at one attribute load + truthiness test per emit)
_STACK: List[str] = []


def new_trace_id(prefix: str = "req") -> str:
    """A process-unique trace id: ``<prefix>-<pid hex>-<seq hex>``.
    Cheap (one counter increment), monotone within a process, and
    collision-free across processes via the pid component."""
    return f"{prefix}-{_RUN}-{next(_COUNTER):04x}"


def current_trace_id() -> Optional[str]:
    """The innermost ambient trace id (None outside any context)."""
    return _STACK[-1] if _STACK else None


@contextlib.contextmanager
def trace_context(trace_id: str):
    """Make ``trace_id`` ambient for the block: every record the
    registry emits inside (spans, windows, step/ckpt/spec records)
    carries it unless the emitter stamped an explicit ``trace_id=``
    field (per-request serve events do — interleaved requests cannot
    share one ambient id). Nests; two list ops per block."""
    _STACK.append(str(trace_id))
    try:
        yield trace_id
    finally:
        _STACK.pop()


# --- anomaly flight recorder -------------------------------------------------

class FlightRecorder:
    """Bounded ring of the most recent raw monitor records, dumped to a
    timestamped JSON file on demand. The registry's emit path feeds the
    ring directly (post-jsonify, pre-sink), so it accumulates even when
    the registry has NO sink attached — a degraded run is debuggable
    post-hoc without paying for a full JSONL stream.

    The dump is one closed-schema ``flight_recorder_dump`` record (see
    :mod:`apex_tpu.monitor.schema`; ``tools/validate_metrics.py
    --trace`` gates it) carrying the ring verbatim plus the dump
    instant on both clocks.
    """

    def __init__(self, capacity: int = 256, out_dir: str = ".",
                 prefix: str = "flight"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.out_dir = str(out_dir)
        self.prefix = str(prefix)
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self.dumps: List[str] = []
        self._seen_reasons: set = set()

    def __len__(self) -> int:
        return len(self._ring)

    def record(self, rec: Dict[str, Any]) -> None:
        self._ring.append(rec)

    def dump(self, reason: str, *, once: bool = False) -> Optional[str]:
        """Write the ring to ``<out_dir>/<prefix>-<pid>-<n>-<wall>.json``
        and return the path. ``once=True`` dedups by reason (the anomaly
        layer's mode: the FIRST SLO burn dumps, the thousandth does
        not). The ring is NOT cleared — a later, worse anomaly still
        sees the full recent history."""
        if once and reason in self._seen_reasons:
            return None
        self._seen_reasons.add(reason)
        from apex_tpu.monitor.registry import (SCHEMA_VERSION,
                                               _process_index, _rank_info)
        events = list(self._ring)
        wall = time.time()
        rec = {
            "schema": SCHEMA_VERSION,
            "kind": "flight_recorder_dump",
            "reason": str(reason),
            "capacity": self.capacity,
            "num_events": len(events),
            "mono_ns": monotonic_ns(),
            "wall_s": wall,
            "pid": os.getpid(),
            "process": _process_index(),
            "rank": _rank_info(),
            "events": events,
        }
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(
            self.out_dir,
            f"{self.prefix}-{os.getpid()}-{len(self.dumps)}-{int(wall)}.json")
        with open(path, "w") as fh:
            json.dump(rec, fh)
        self.dumps.append(path)
        return path


# the process-wide recorder; None = no ring, zero cost on the emit path
# beyond one attribute load + is-None test
_FLIGHT: Optional[FlightRecorder] = None


def enable_flight_recorder(capacity: int = 256, out_dir: str = ".", *,
                           prefix: str = "flight",
                           signals: bool = False) -> FlightRecorder:
    """Install the process-wide flight recorder (the registry's emit
    path starts feeding it immediately). ``signals=True`` additionally
    chains a SIGTERM handler that dumps before the previous disposition
    runs. Records only accumulate while the monitor registry is
    enabled — a sink is NOT required (that is the point)."""
    global _FLIGHT
    _FLIGHT = FlightRecorder(capacity, out_dir, prefix=prefix)
    if signals:
        install_signal_handler()
    return _FLIGHT


def disable_flight_recorder() -> None:
    global _FLIGHT
    _FLIGHT = None


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _FLIGHT


def flight_dump(reason: str, *, once: bool = True) -> Optional[str]:
    """Dump the process-wide ring (no-op returning None when no
    recorder is installed). ``once=True`` (the default — what the
    anomaly layer uses) dedups by reason."""
    fr = _FLIGHT
    if fr is None:
        return None
    return fr.dump(reason, once=once)


def install_signal_handler(signum: int = _signal.SIGTERM):
    """Chain a flight-recorder dump in front of the existing ``signum``
    disposition: the ring is written with reason ``signal:<n>`` and the
    previous handler (or the default action) then runs, so a SIGTERM'd
    degraded run leaves its last-N events behind. Returns the previous
    handler."""
    prev = _signal.getsignal(signum)

    def _handler(sig, frame):
        flight_dump(f"signal:{sig}", once=False)
        if callable(prev):
            prev(sig, frame)
        elif prev == _signal.SIG_DFL:
            _signal.signal(sig, _signal.SIG_DFL)
            os.kill(os.getpid(), sig)

    _signal.signal(signum, _handler)
    return prev


# --- TTFT / latency attribution ----------------------------------------------

# the closed component set (mirrors schema._ATTR_COMPONENTS): every
# request's [submit, finish] wall time is partitioned into exactly
# these, so their sum IS the measured e2e latency up to rounding
ATTR_COMPONENTS = ("queue_ms", "prefill_ms", "decode_ms", "spec_ms",
                   "spec_rewind_ms", "preempt_wait_ms", "recompute_ms",
                   "swap_pause_ms")


def _request_timelines(records: Iterable[Dict[str, Any]]
                       ) -> Tuple[Dict[int, Dict[str, Any]],
                                  List[Dict[str, Any]]]:
    """Reconstruct each request's lifecycle from ``serve_event``
    records (a JSONL stream's dicts, or the telemetry's in-memory
    ledger — same shape): per rid, the component ledger, the named
    phase intervals (for the Chrome export), the spec round slices,
    and the submit/finish stamps. Engine-level events (rid -1) return
    separately; ``swap`` events with a duration are carved out of any
    decode interval that contains them (the whole slot array pauses
    for a hot-swap)."""
    serve_events: List[Tuple[float, int, Dict[str, Any]]] = []
    for idx, r in enumerate(records):
        if r.get("kind") != "serve_event" or "rid" not in r:
            continue
        serve_events.append((float(r.get("at_s", 0.0)), idx, r))
    serve_events.sort(key=lambda t: (t[0], t[1]))  # stable on emit order

    by_rid: Dict[int, List[Dict[str, Any]]] = {}
    engine: List[Dict[str, Any]] = []
    for _, _, e in serve_events:
        rid = int(e["rid"])
        (engine if rid == -1 else by_rid.setdefault(rid, [])).append(e)
    swaps = [e for e in engine if e.get("phase") == "swap"]

    out: Dict[int, Dict[str, Any]] = {}
    for rid, evs in sorted(by_rid.items()):
        row = {c: 0.0 for c in ATTR_COMPONENTS}
        intervals: List[Tuple[str, float, float]] = []
        decode_ivs: List[Tuple[float, float]] = []
        spec_slices: List[Tuple[float, float, str]] = []
        state: Optional[str] = None  # queued|prefill|recompute|decode|preempt
        mark: Optional[float] = None
        submit_at = finish_at = None
        trace_id: Optional[str] = None
        evictions = spec_rounds = 0

        def close(upto: float) -> None:
            # fold the open interval [mark, upto) into its component
            nonlocal mark
            if state is None or mark is None:
                return
            if state == "decode":
                decode_ivs.append((mark, upto))
            else:
                key = {"queued": "queue_ms", "prefill": "prefill_ms",
                       "recompute": "recompute_ms",
                       "preempt": "preempt_wait_ms"}[state]
                row[key] += (upto - mark) * 1e3
                name = {"queued": "queue", "preempt": "preempt"}.get(
                    state, state)
                intervals.append((name, mark, upto))
            mark = upto

        for e in evs:
            ph, at = e.get("phase"), float(e.get("at_s", 0.0))
            if trace_id is None and e.get("trace_id"):
                trace_id = e["trace_id"]
            if ph == "submit":
                submit_at = at
                state, mark = "queued", at
            elif ph == "admit":
                close(at)
                state = "recompute" if e.get("resumed") else "prefill"
                mark = at
            elif ph == "first_token":
                close(at)
                state, mark = "decode", at
            elif ph == "decode":
                if e.get("resumed"):
                    close(at)  # the re-prefill's recompute ends here
                if state != "decode":
                    state, mark = "decode", at
            elif ph == "spec":
                spec_rounds += 1
                dur_s = float(e.get("dur_ms") or 0.0) * 1e-3
                key = ("spec_ms" if int(e.get("accepted_len") or 0) > 0
                       else "spec_rewind_ms")
                row[key] += dur_s * 1e3
                spec_slices.append((at - dur_s, at, key))
            elif ph == "evict":
                evictions += 1
                close(at)
                state, mark = "preempt", at
            elif ph == "finish":
                finish_at = at
                close(at)
                state, mark = None, None

        # decode is the interval REMAINDER: raw decode wall minus the
        # spec rounds and swap pauses that ran inside it — the
        # partition property (components sum to e2e) falls out
        decode_raw_s = sum(b - a for a, b in decode_ivs)
        for s in swaps:
            s_at = float(s.get("at_s", 0.0))
            s_dur = float(s.get("dur_ms") or 0.0)
            if s_dur and any(a <= s_at <= b for a, b in decode_ivs):
                row["swap_pause_ms"] += s_dur
        carve = (row["spec_ms"] + row["spec_rewind_ms"]
                 + row["swap_pause_ms"])
        row["decode_ms"] = max(decode_raw_s * 1e3 - carve, 0.0)
        intervals.extend(("decode", a, b) for a, b in decode_ivs)

        out[rid] = dict(row=row, intervals=intervals,
                        spec_slices=spec_slices, submit_at=submit_at,
                        finish_at=finish_at, trace_id=trace_id,
                        evictions=evictions, spec_rounds=spec_rounds)
    return out, engine


def serve_attribution(records: Iterable[Dict[str, Any]], *,
                      per_request: bool = True) -> Dict[str, Any]:
    """The ``serve_attribution`` record's fields from a record stream
    (or the telemetry's in-memory event ledger). Pass the result to
    :meth:`MetricsRegistry.emit_serve_attribution` with a status (OK
    only for real-hardware measurements, like every bench record).
    Requests without both a ``submit`` and a ``finish`` event are
    counted in ``unattributed``, never silently rowed."""
    timelines, _ = _request_timelines(records)
    rows: List[Dict[str, Any]] = []
    unattributed = 0
    for rid, t in sorted(timelines.items()):
        if t["submit_at"] is None or t["finish_at"] is None:
            unattributed += 1
            continue
        e2e = (t["finish_at"] - t["submit_at"]) * 1e3
        comp = sum(t["row"].values())
        r: Dict[str, Any] = {"rid": rid}
        if t["trace_id"]:
            r["trace_id"] = t["trace_id"]
        r.update({k: round(v, 3) for k, v in t["row"].items()})
        r.update(e2e_ms=round(e2e, 3), components_ms=round(comp, 3),
                 residual_pct=(round(abs(comp - e2e) / e2e * 100.0, 3)
                               if e2e > 0 else 0.0),
                 evictions=t["evictions"], spec_rounds=t["spec_rounds"])
        rows.append(r)
    fields: Dict[str, Any] = dict(
        requests=len(rows),
        unattributed=unattributed,
        components={c: round(sum(r[c] for r in rows), 3)
                    for c in ATTR_COMPONENTS},
        e2e_ms_total=round(sum(r["e2e_ms"] for r in rows), 3),
        components_ms_total=round(sum(r["components_ms"] for r in rows),
                                  3),
        max_residual_pct=(max(r["residual_pct"] for r in rows)
                          if rows else
                          ("skipped", "no finished requests in stream")),
    )
    if per_request:
        fields["per_request"] = rows
    return fields


# --- Chrome/Perfetto trace-event export --------------------------------------

def chrome_trace(records: Iterable[Dict[str, Any]],
                 device_events=None) -> Dict[str, Any]:
    """Merge a monitor JSONL stream into Chrome trace-event JSON
    (``chrome://tracing`` / Perfetto): one track per rank (span
    records on the unified ``t_ns`` clock), one per serve engine
    (rid -1 lifecycle events: stragglers, swaps), and one NAMED track
    per request whose queue / prefill / decode / spec / preempt slices
    all carry the request's ``trace_id``. ``device_events`` (a
    :func:`apex_tpu.prof.trace_reader.read_trace` result) rides along
    on offset process ids via the existing scope-prefix join.

    Serve-clock events join the span clock through each record's
    ``t_ns`` stamp (the median ``t_ns - at_s`` offset of the stream);
    streams predating the unified clock export with a zero offset —
    request tracks stay mutually consistent, only rank↔request skew is
    then unknowable."""
    recs = list(records)
    spans = [r for r in recs if r.get("kind") == "span"]
    clock_syncs = [r for r in recs if r.get("kind") == "clock_sync"]
    offs = sorted(
        r["t_ns"] - float(r.get("at_s", 0.0)) * 1e9
        for r in recs
        if r.get("kind") == "serve_event"
        and isinstance(r.get("t_ns"), int) and "at_s" in r)
    off_ns = offs[len(offs) // 2] if offs else 0.0

    events: List[Dict[str, Any]] = []
    pids: Dict[Any, int] = {}

    def pid_of(key: Any, name: str) -> int:
        if key not in pids:
            pids[key] = len(pids) + 1
            events.append({"ph": "M", "pid": pids[key],
                           "name": "process_name",
                           "args": {"name": name}})
        return pids[key]

    def us(at_s: float) -> float:
        return (at_s * 1e9 + off_ns) / 1e3

    for s in spans:
        pid = pid_of(("rank", s.get("process", 0), s.get("rank", "")),
                     f"rank {s.get('rank', '?')} "
                     f"(process {s.get('process', 0)})")
        args = {k: s[k] for k in ("coll", "axis", "bytes", "traced",
                                  "step", "trace_id") if k in s}
        events.append({"ph": "X", "pid": pid, "tid": 1,
                       "name": s.get("name", "span"),
                       "ts": s.get("t0_ns", 0) / 1e3,
                       "dur": max(s.get("dur_ns", 0), 1) / 1e3,
                       "args": args})

    timelines, engine = _request_timelines(recs)
    for e in engine:  # stragglers + swaps: the engine's own track
        pid = pid_of(("engine", e.get("process", 0)),
                     f"serve engine (process {e.get('process', 0)})")
        dur_ms = float(e.get("dur_ms") or 0.0)
        at = float(e.get("at_s", 0.0))
        name = e.get("phase", "event")
        if e.get("straggler"):
            name = "straggler_step"
        args = {k: e[k] for k in ("step", "swap_source",
                                  "ratio_to_median", "trace_id")
                if k in e}
        events.append({"ph": "X", "pid": pid, "tid": 1, "name": name,
                       "ts": us(at - dur_ms * 1e-3),
                       "dur": max(dur_ms * 1e3, 1.0), "args": args})

    for rid, t in sorted(timelines.items()):
        label = f"req {rid}"
        if t["trace_id"]:
            label += f" [{t['trace_id']}]"
        pid = pid_of(("req", rid), label)
        args = {"rid": rid}
        if t["trace_id"]:
            args["trace_id"] = t["trace_id"]
        for name, a, b in sorted(t["intervals"], key=lambda x: x[1]):
            events.append({"ph": "X", "pid": pid, "tid": 1, "name": name,
                           "ts": us(a),
                           "dur": max((b - a) * 1e6, 0.001),
                           "args": dict(args)})
        for a, b, key in t["spec_slices"]:
            events.append({"ph": "X", "pid": pid, "tid": 2,
                           "name": ("spec" if key == "spec_ms"
                                    else "spec_rewind"),
                           "ts": us(a),
                           "dur": max((b - a) * 1e6, 0.001),
                           "args": dict(args)})
        if t["spec_slices"]:
            events.append({"ph": "M", "pid": pid, "tid": 2,
                           "name": "thread_name",
                           "args": {"name": "spec rounds"}})

    if device_events:
        # the device half rides the existing scope-prefix machinery;
        # its pids offset past ours so tracks never collide
        from apex_tpu.prof import trace_reader as _tr
        merged = _tr.merged_timeline([], device_events)
        base = 1000
        for ev in merged.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = base + int(ev.get("pid", 0))
            events.append(ev)

    out: Dict[str, Any] = {"traceEvents": events,
                           "displayTimeUnit": "ms"}
    if clock_syncs:
        out["otherData"] = {"clock_sync": clock_syncs[0]}
    return out


def write_chrome_trace(path: str, records: Iterable[Dict[str, Any]],
                       device_events=None, *,
                       doc: Optional[Dict[str, Any]] = None) -> str:
    """Write :func:`chrome_trace` to ``path`` (gzipped when it ends in
    ``.gz`` — both chrome://tracing and Perfetto load either form).
    Returns the path. ``doc`` short-circuits the build when the caller
    already holds the :func:`chrome_trace` result (the CLI inspects it
    before writing)."""
    trace = chrome_trace(records, device_events) if doc is None else doc
    if path.endswith(".gz"):
        with gzip.open(path, "wt") as fh:
            json.dump(trace, fh)
    else:
        with open(path, "w") as fh:
            json.dump(trace, fh)
    return path
