"""CLI entry: ``python -m apex_tpu.monitor report events.jsonl``."""

import sys

from apex_tpu.monitor.report import main

if __name__ == "__main__":
    sys.exit(main())
