"""CLI entry: ``python -m apex_tpu.monitor report events.jsonl`` (step
summary, ``--serve-timeline``, ``--attribution``) and ``python -m
apex_tpu.monitor trace events.jsonl`` (Chrome trace-event export)."""

import sys

from apex_tpu.monitor.report import main

if __name__ == "__main__":
    sys.exit(main())
