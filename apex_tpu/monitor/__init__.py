"""Unified training telemetry.

The subsystem the reference apex never had: ``apex.pyprof`` profiles
kernels after the fact, but nothing in the reference answers "what is my
loss scale doing", "how many steps did AMP skip", "what is the pipeline
bubble costing me" *while training runs*. ``apex_tpu.monitor`` is that
layer:

* :mod:`~apex_tpu.monitor.registry` — host-side metrics registry
  (counters / gauges / timers), rank-tagged via
  :func:`apex_tpu.utils.logging.set_rank_info`, with a structured JSONL
  emitter and near-zero overhead when disabled;
* :mod:`~apex_tpu.monitor.hooks` — instrumentation hooks for the hot
  paths: AMP scaler, optimizers (grad/update norms), pipeline schedules
  (geometry + bubble fraction), collectives (count + bytes per traced
  step);
* :mod:`~apex_tpu.monitor.spans` — step-anatomy spans: host enter/exit
  timestamps + the ``jax.named_scope`` join key into device traces
  (``prof.trace_reader`` correlates the two and ``monitor report
  --anatomy`` prints the per-step breakdown);
* :mod:`~apex_tpu.monitor.schema` — JSON schemas + validator shared by
  the monitor stream, ``bench.py`` artifacts and the multichip gate
  (``tools/validate_metrics.py`` is the CLI);
* :mod:`~apex_tpu.monitor.report` — ``python -m apex_tpu.monitor report
  events.jsonl`` aggregates the stream into a step-timeline summary
  (tokens/s, spec-peak MFU, overflow rate, bubble %);
* :mod:`~apex_tpu.monitor.trace` — request-scoped tracing: one
  ``trace_id`` end-to-end (minted per serve request / serve call /
  generate / checkpoint save, stamped on every record), the unified
  monotonic clock behind ``t_ns``/``clock_sync``, Chrome trace-event
  export (``python -m apex_tpu.monitor trace``), per-request latency
  attribution (``report --attribution``) and the anomaly flight
  recorder (a bounded ring of recent records, dumped on
  ``serve_anomaly``/SIGTERM even when no JSONL sink is attached).

Quick start::

    from apex_tpu import monitor

    monitor.enable("events.jsonl")          # or APEX_TPU_MONITOR=...
    monitor.emit_meta(device_kind=..., model_flops_per_token=...)
    for step in range(n_steps):
        monitor.begin_step()
        with monitor.timer("train/step"):
            params, opt_state, scaler, loss = train_step(...)
            jax.block_until_ready(loss)
        monitor.hooks.observe_scaler(scaler)
        monitor.end_step(tokens=batch * seq, loss=float(loss))

See ``docs/OBSERVABILITY.md`` for the event schema and overhead notes.
"""

from apex_tpu.monitor import hooks  # noqa: F401
from apex_tpu.monitor.registry import (  # noqa: F401
    SCHEMA_VERSION,
    MetricsRegistry,
    begin_step,
    check_record_honesty,
    counter,
    disable,
    emit_ckpt,
    emit_decode,
    emit_event,
    emit_longseq_bias,
    emit_meta,
    emit_pipeline,
    emit_plan,
    emit_profile,
    emit_serve,
    emit_serve_attribution,
    emit_serve_plan,
    emit_serve_window,
    emit_spec,
    emit_tp_overlap,
    emit_tp_serve,
    enable,
    enable_from_env,
    enabled,
    end_step,
    gauge,
    get_registry,
    observe_seconds,
    timer,
)
from apex_tpu.monitor.hooks import (  # noqa: F401
    count_collective,
    observe_grads,
    observe_optimizer_step,
    observe_scaler,
    observe_updates,
    pipeline_bubble_fraction,
    pipeline_cost_model,
    record_pipeline_schedule,
    tree_bytes,
)
from apex_tpu.monitor.histogram import StreamingHistogram  # noqa: F401
from apex_tpu.monitor.spans import collective_span, span, span_path  # noqa: F401
from apex_tpu.monitor.schema import gate_metrics, validate, validate_jsonl  # noqa: F401
from apex_tpu.monitor.report import (  # noqa: F401
    PEAK_FLOPS_BY_DEVICE,
    aggregate,
    format_attribution,
    format_serve_timeline,
    serve_attribution_record,
    serve_timeline,
    spec_peak_flops,
)
from apex_tpu.monitor import trace  # noqa: F401
from apex_tpu.monitor.trace import (  # noqa: F401
    chrome_trace,
    current_trace_id,
    enable_flight_recorder,
    disable_flight_recorder,
    flight_dump,
    get_flight_recorder,
    new_trace_id,
    serve_attribution,
    trace_context,
    write_chrome_trace,
)
