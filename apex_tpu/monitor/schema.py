"""JSON schemas for monitor events and bench/gate artifacts, plus a
self-contained validator.

One schema family covers every JSON artifact the repo emits:

* monitor JSONL records (``kind`` ∈ meta/event/step/gate/decode/
  longseq_bias/tp_overlap/serve/serve_event/serve_window) — the stream
  written by :mod:`apex_tpu.monitor.registry` (``decode`` is the
  single-batch serving record ``bench.py --decode`` emits; ``serve``
  the continuous-batching offered-load record of ``bench.py --serve``;
  ``serve_event``/``serve_window`` the request-lifecycle and live-SLO
  records of :mod:`apex_tpu.serving.telemetry`; ``tp_overlap`` the
  ring-overlapped-vs-blocking record of ``bench.py --tp-overlap``);
* ``BENCH_*.json``-style bench result objects (the line ``bench.py``
  prints);
* the MULTICHIP gate record printed by ``__graft_entry__.dryrun_multichip``.

The validator implements the JSON-Schema subset these schemas use
(``type``, ``properties``, ``required``, ``items``, ``enum``,
``additionalProperties``) so validation works without the ``jsonschema``
package; when that package is importable, :func:`validate` cross-checks
against it too (belt and braces — the schemas stay standard JSON Schema).

Honesty rule (enforced here *and* at the emitter): a record that reports
success (``ok: true`` or ``status: "OK"``) must not contain a non-finite
number or a stringified ``'nan'``/``'inf'`` metric anywhere. Skipped
metrics appear as ``{"skipped": true, "reason": ...}`` objects.
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, Iterable, List, Tuple

from apex_tpu.monitor.registry import (
    SCHEMA_VERSION,
    _nonfinite_paths,
    _stringified_nonfinite_paths,
)

# value of a gate metric: a finite number, or an explicit skip marker
_METRIC_VALUE = {
    "anyOf": [
        {"type": "number"},
        {
            "type": "object",
            "properties": {
                "skipped": {"enum": [True]},
                "reason": {"type": "string"},
            },
            "required": ["skipped", "reason"],
            "additionalProperties": False,
        },
    ]
}

_COMMON = {
    "schema": {"enum": [SCHEMA_VERSION]},
    "kind": {"type": "string"},
    "t_s": {"type": "number"},
    "process": {"type": "integer"},
    "rank": {"type": "string"},
    # the unified monotonic clock (ISSUE 16): every record the registry
    # emits is stamped with time.perf_counter_ns() — the SAME base spans'
    # t0_ns and the serve clock ride — and, when an ambient trace context
    # is active (or the emitter stamped one explicitly), the request/step/
    # save-scoped trace_id that joins records across streams
    "t_ns": {"type": "integer"},
    "trace_id": {"type": "string"},
}

STEP_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["step"]},
        "step": {"type": "integer"},
        "dur_s": {"type": "number"},
        "counters": {"type": "object"},
        "counters_total": {"type": "object"},
        "gauges": {"type": "object"},
        "timers": {"type": "object"},
        "tokens": {"type": "number"},
        "loss": {"anyOf": [{"type": "number"}, {"type": "string"}]},
    },
    "required": ["schema", "kind", "step", "dur_s", "counters", "gauges"],
}

META_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["meta"]},
        "device_kind": {"type": "string"},
        "peak_flops": {"anyOf": [{"type": "number"}, {"type": "null"}]},
        "model_flops_per_token": {"type": "number"},
    },
    "required": ["schema", "kind"],
}

EVENT_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["event"]},
        "name": {"type": "string"},
    },
    "required": ["schema", "kind", "name"],
}

GATE_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["gate"]},
        "name": {"type": "string"},
        "ok": {"type": "boolean"},
        "metrics": {"type": "object",
                    "additionalProperties": _METRIC_VALUE},
    },
    "required": ["schema", "kind", "name", "ok", "metrics"],
}

BENCH_SCHEMA = {
    "type": "object",
    "properties": {
        "metric": {"type": "string"},
        "value": {"type": "number"},
        "unit": {"type": "string"},
        "vs_baseline": {"type": "number"},
        "mfu": {"anyOf": [{"type": "number"}, {"type": "null"}]},
        "model_tflops": {"anyOf": [{"type": "number"}, {"type": "null"}]},
        "spread_pct": {"type": "number"},
        "pass_times_ms": {"type": "array", "items": {"type": "number"}},
    },
    "required": ["metric", "value", "unit"],
}

# serving-bench step event (`python bench.py --decode`): one record per
# decode bench run. status "OK" engages the honesty rule (no non-finite
# values anywhere); a leg that cannot be measured honestly (e.g. the naive
# recompute baseline off-TPU) rides as an explicit skip object, and an
# entirely unmeasurable leg is status "SKIP" with a reason — never nan.
DECODE_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["decode"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "tokens_per_s": _METRIC_VALUE,   # decode throughput per chip
        "prefill_ms": _METRIC_VALUE,     # one prompt through prefill
        "spread_pct": _METRIC_VALUE,     # (max-min)/min over timed passes
        "naive_tokens_per_s": _METRIC_VALUE,  # recompute-the-prefix baseline
        "vs_naive": _METRIC_VALUE,            # cached / naive ratio
        "batch": {"type": "integer"},
        "prompt_len": {"type": "integer"},
        "new_tokens": {"type": "integer"},
        "max_seq_len": {"type": "integer"},
        "pass_times_ms": {"type": "array", "items": {"type": "number"}},
        "config": {"type": "object"},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status"],
}

# long-sequence in-kernel-bias bench record (`python bench.py
# --longseq-bias`): fwd+bwd flash attention with the BUCKETED relative
# bias vs the MATERIALIZED (h, s, s) operand at long seq — tokens/s and
# the HBM high-water of each. Same status semantics as `decode`: "OK"
# engages the honesty rule; a leg that cannot be measured honestly rides
# as an explicit skip object; off-TPU the record is status "SKIP" with a
# reason — never nan.
LONGSEQ_BIAS_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["longseq_bias"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "tokens_per_s": _METRIC_VALUE,      # bucketed fwd+bwd throughput
        "tokens_per_s_materialized": _METRIC_VALUE,  # the r5 baseline
        "vs_materialized": _METRIC_VALUE,   # bucketed / materialized ratio
        "hbm_peak_mb": _METRIC_VALUE,           # bucketed high-water
        "hbm_peak_materialized_mb": _METRIC_VALUE,  # baseline high-water
        "bias_bytes": {"type": "integer"},          # O(buckets·h) operand
        "bias_bytes_materialized": {"type": "integer"},  # O(h·s²) operand
        "seq": {"type": "integer"},
        "batch": {"type": "integer"},
        "heads": {"type": "integer"},
        "head_dim": {"type": "integer"},
        "num_buckets": {"type": "integer"},
        "causal": {"type": "boolean"},
        "spread_pct": _METRIC_VALUE,
        "pass_times_ms": {"type": "array", "items": {"type": "number"}},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status"],
}

# TP-overlap bench record (`python bench.py --tp-overlap`): one fwd+bwd
# train-pass throughput comparison between the ring-overlapped boundary
# collectives (`tp_overlap=True` / `overlap_comm=True`) and the blocking
# oracle, at tp >= 2. Same status semantics as `decode`/`longseq_bias`:
# "OK" (real multichip TPU) engages the honesty rule; off-TPU (or a
# single-chip host) the record is an explicit SKIP with a reason — the
# smoke-scale measurements may ride along as finite fields, but a SKIP
# record claims no speedup. Never nan in an OK line.
TP_OVERLAP_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["tp_overlap"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "tokens_per_s": _METRIC_VALUE,           # overlapped fwd+bwd
        "tokens_per_s_blocking": _METRIC_VALUE,  # the blocking oracle
        "vs_blocking": _METRIC_VALUE,            # overlapped / blocking
        "tp": {"type": "integer"},
        "batch": {"type": "integer"},
        "seq": {"type": "integer"},
        "sequence_parallel": {"type": "boolean"},
        # spread over each run separately: vs_blocking is a ratio, so the
        # blocking denominator's noise bar matters as much as the
        # overlapped numerator's
        "spread_pct": _METRIC_VALUE,
        "spread_pct_blocking": _METRIC_VALUE,
        "pass_times_ms": {"type": "array", "items": {"type": "number"}},
        "pass_times_blocking_ms": {"type": "array",
                                   "items": {"type": "number"}},
        "config": {"type": "object"},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status"],
}

# pipeline-schedule bench record (`python bench.py --pipeline`): the
# zero-bubble schedule family vs the autodiff 1f1b baseline at pp >= 2 —
# fwd+bwd tokens/s for both schedules plus bubble %. Two bubble flavors,
# honestly labeled: *_geometry fields are the trace-time unit-cost model
# (monitor.pipeline_cost_model — closed form, any backend); bubble_pct /
# bubble_pct_1f1b are MEASURED device idle from prof.trace_reader
# .step_anatomy and exist only on a real TPU trace. Same status semantics
# as decode/tp_overlap: "OK" (real multichip TPU) engages the honesty
# rule; off-TPU the record is an explicit SKIP(reason) with the smoke
# numbers and geometry riding along. Never nan in an OK line.
PIPELINE_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["pipeline"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "schedule": {"type": "string"},          # the measured schedule
        "pipeline_size": {"type": "integer"},
        "virtual_chunks": {"type": "integer"},
        "num_microbatches": {"type": "integer"},
        "overlap_p2p": {"type": "boolean"},
        "tokens_per_s": _METRIC_VALUE,           # the zb schedule
        "tokens_per_s_1f1b": _METRIC_VALUE,      # the autodiff baseline
        "vs_1f1b": _METRIC_VALUE,                # zb / 1f1b
        "bubble_pct": _METRIC_VALUE,             # measured (step_anatomy)
        "bubble_pct_1f1b": _METRIC_VALUE,
        "bubble_pct_geometry": _METRIC_VALUE,    # unit-cost model
        "bubble_pct_1f1b_geometry": _METRIC_VALUE,
        "p2p_bytes_per_step": {"type": "integer"},
        "jit_cache_ok": {"type": "boolean"},     # geometry reuse, no retrace
        "spread_pct": _METRIC_VALUE,
        "spread_pct_1f1b": _METRIC_VALUE,
        "pass_times_ms": {"type": "array", "items": {"type": "number"}},
        "pass_times_1f1b_ms": {"type": "array",
                               "items": {"type": "number"}},
        "config": {"type": "object"},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status"],
}

# continuous-batching serving bench record (`python bench.py --serve`):
# one record per offered-load run through apex_tpu.serving.ServingEngine —
# per-token latency and TTFT percentiles, decode tokens/s under churn,
# slot occupancy, paged-pool high-water, and the greedy-parity /
# jit-cache-pinned witnesses against the single-request DecodeEngine.
# Same status semantics as decode/longseq_bias: "OK" (real TPU) engages
# the honesty rule; off-TPU the record is an explicit SKIP with the
# smoke-scale measurements riding along as finite fields — never nan in
# an OK line.
SERVE_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["serve"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "tokens_per_s": _METRIC_VALUE,       # decode tokens/s under churn
        "latency_p50_ms": _METRIC_VALUE,     # per-token (inter-token) p50
        "latency_p99_ms": _METRIC_VALUE,     # per-token p99
        "ttft_p50_ms": _METRIC_VALUE,        # time to first token p50
        "ttft_p99_ms": _METRIC_VALUE,        # time to first token p99
        "occupancy_pct": _METRIC_VALUE,      # mean decoding-slots / slots
        "vs_single_request": _METRIC_VALUE,  # no-churn throughput parity
        "single_request_tokens_per_s": _METRIC_VALUE,
        "offered_rps": _METRIC_VALUE,        # Poisson arrival rate driven
        "greedy_parity": {"type": "boolean"},  # tokens == DecodeEngine's
        "jit_cache_ok": {"type": "boolean"},   # both steps pinned at 1
        "requests": {"type": "integer"},
        "slots": {"type": "integer"},
        "block_size": {"type": "integer"},
        "num_blocks": {"type": "integer"},
        "blocks_high_water": {"type": "integer"},
        "prefill_chunk": {"type": "integer"},
        "decode_steps": {"type": "integer"},
        "prefill_chunks": {"type": "integer"},
        "max_seq_len": {"type": "integer"},
        # ISSUE 10 telemetry fields: the anomaly section, admission
        # pressure counts, and the measured per-request trace overhead
        "serve_anomaly": None,  # filled below (shared with serve_window)
        "admission_blocked_slots": {"type": "integer"},
        "admission_blocked_blocks": {"type": "integer"},
        "queue_peak": {"type": "integer"},
        "serve_windows": {"type": "integer"},
        "telemetry_overhead_pct": _METRIC_VALUE,
        # serving tier 2 (ISSUE 13): prefix-cache effectiveness — the
        # hit-vs-miss TTFT split is the cache's headline claim
        # (hit p50 strictly below miss p50 on a warm cache) — plus
        # preemption pressure (evict-and-recompute counts) and the
        # replayable-trace seed
        "prefix_hit_rate": _METRIC_VALUE,     # shared blocks / queried
        "prefix_hit_ttft_p50_ms": _METRIC_VALUE,
        "prefix_hit_ttft_p99_ms": _METRIC_VALUE,
        "prefix_miss_ttft_p50_ms": _METRIC_VALUE,
        "prefix_miss_ttft_p99_ms": _METRIC_VALUE,
        "prefix_hit_requests": {"type": "integer"},
        "prefix_miss_requests": {"type": "integer"},
        "preemptions": {"type": "integer"},   # evict lifecycle events
        "recompute_tokens": {"type": "integer"},  # re-prefilled rows
        "swaps": {"type": "integer"},         # weight hot-swaps applied
        "replans": {"type": "integer"},       # ServePlan ladder switches
        "blocks_resident": {"type": "integer"},   # warm cache footprint
        # speculative serving (ISSUE 15): per SLOT-round acceptance
        # rolled up from the `spec` lifecycle events (present when spec
        # rounds ran; slot×dispatch granularity — ServeStats.spec_rounds
        # counts dispatches)
        "spec_slot_rounds": {"type": "integer"},
        "spec_drafted": {"type": "integer"},
        "spec_accepted": {"type": "integer"},
        "spec_acceptance_rate": _METRIC_VALUE,
        "draft_k": {"type": "integer"},
        # the pool's quantization knob, stamped by the engine at serve
        # start (absent on float pools)
        "kv_dtype": {"type": "string"},
        # greedy parity over the WHOLE churn sweep including
        # evicted-and-recomputed and prefix-hit requests
        "churn_parity": {"type": "boolean"},
        "churn_parity_checked": {"type": "integer"},
        "trace_seed": {"type": "integer"},    # Poisson replay seed
        "config": {"type": "object"},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status"],
}

# the serve_anomaly section shared by `serve` and `serve_window`
# records: the anomaly layer's counters and flags (straggler decode
# steps vs the rolling median, sustained-TTFT SLO burn, queue buildup,
# free-list leak/fragmentation accounting from BlockAllocator)
SERVE_ANOMALY_SCHEMA = {
    "type": "object",
    "properties": {
        "straggler_steps": {"type": "integer"},
        "straggler_last_ratio": _METRIC_VALUE,
        "queue_buildup": {"type": "boolean"},
        "slo_burn": {"type": "boolean"},
        "ttft_over_slo": {"type": "integer"},
        "leaked_blocks": {"type": "integer"},
        "free_list_frag_pct": _METRIC_VALUE,
    },
    "required": ["straggler_steps", "queue_buildup", "slo_burn",
                 "leaked_blocks"],
    "additionalProperties": False,
}

SERVE_SCHEMA["properties"]["serve_anomaly"] = SERVE_ANOMALY_SCHEMA

# request-lifecycle record (apex_tpu.serving.telemetry.ServeTelemetry):
# one rank-tagged record per request transition — submit → admit →
# prefill_chunk*k → first_token → decode → finish (evict reserved for
# preemption; rid -1 marks engine-level events like straggler steps).
# `at_s` is the serve clock; `step` the engine dispatch counter — the
# join key onto the serve_prefill/serve_decode device-trace scopes
# (PR-6 scope-prefix correlation). Emitted OUTSIDE the jitted steps:
# telemetry never touches the zero-recompile avals.
SERVE_EVENT_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["serve_event"]},
        "rid": {"type": "integer"},
        "phase": {"enum": ["submit", "admit", "prefill_chunk",
                           "first_token", "decode", "finish", "evict",
                           "swap", "spec", "handoff", "replan"]},
        "at_s": {"type": "number"},        # serve-clock transition time
        "slot": {"type": "integer"},
        "step": {"type": "integer"},       # engine dispatch counter
        "queue_wait_ms": {"type": "number"},   # admit
        "chunk": {"type": "integer"},          # prefill_chunk index
        "chunks": {"type": "integer"},         # first_token / finish
        "dur_ms": {"type": "number"},          # phase duration
        "prefill_ms": {"type": "number"},      # first_token: chunk sum
        "ttft_ms": {"type": "number"},         # first_token
        "decode_ms": {"type": "number"},       # finish: decode phase
        "total_ms": {"type": "number"},        # finish: arrival→finish
        "blocks_held": {"type": "integer"},
        "tokens": {"type": "integer"},         # finish: generated count
        "prompt_len": {"type": "integer"},     # submit
        "max_new_tokens": {"type": "integer"},  # submit
        "straggler": {"type": "boolean"},      # engine-level anomaly
        "ratio_to_median": {"type": "number"},
        "slots": {"type": "integer"},
        # serving tier 2 payloads: evict (preemption) + prefix sharing
        "evict_reason": {"type": "string"},    # evict: why preempted
        "blocks_released": {"type": "integer"},  # evict
        "requeue_pos": {"type": "integer"},    # evict: waiting position
        "generated": {"type": "integer"},      # evict: tokens so far
        "prefix_hit_blocks": {"type": "integer"},  # admit: shared blocks
        "resumed": {"type": "boolean"},        # re-admit / resumed decode
        # weight hot-swap (ISSUE 14): engine-level, rid -1 — a new
        # checkpoint's params replaced the serving weights between
        # dispatch steps (contents-only; both jit caches stay at 1)
        "swap_source": {"type": "string"},     # swap: where weights came from
        # ServePlan re-plan (ISSUE 20): engine-level, rid -1 — the
        # ReplanPolicy switched the active priced plan at a window edge.
        # Only aval-stable knobs applied live (both jit caches stay at
        # 1); aval-changing knobs ride deferred_knobs, reported not
        # applied.
        "plan_from": {"type": "string"},       # replan: old plan digest
        "plan_to": {"type": "string"},         # replan: new plan digest
        "replan_trigger": {"type": "string"},  # queue_buildup|slo_burn|calm
        "live_knobs": {"type": "array", "items": {"type": "string"}},
        "deferred_knobs": {"type": "array", "items": {"type": "string"}},
        # speculative round (ISSUE 15): one record per slot per round —
        # accepted_len of draft_k drafted tokens survived verification
        "accepted_len": {"type": "integer"},
        "draft_k": {"type": "integer"},
        # TREE speculative round (ISSUE 19): the round scored a
        # draft_k-deep, tree_branching-wide tree (tree_nodes verify
        # rows) and accepted_len is the winning root path's depth;
        # absent on chain rounds
        "tree_nodes": {"type": "integer"},
        "tree_branching": {"type": "integer"},
        # disaggregated KV handoff (ISSUE 17): one record per request
        # per role — the SAME trace_id rides the export (prefill
        # engine) and ingest (decode engine) legs
        "handoff_role": {"enum": ["export", "ingest"]},
        "blocks": {"type": "integer"},         # handoff: blocks streamed
        "transfer_bytes": {"type": "integer"},  # handoff: payload bytes
    },
    "required": ["schema", "kind", "rid", "phase", "at_s"],
}

# periodic live-SLO window record (ServeTelemetry.maybe_window): the
# sliding-window view bench.py --serve and any instrumented serve loop
# emit every window_s — tokens/s, TTFT/per-token quantiles from the
# PER-WINDOW streaming histograms, queue depth, occupancy, pool state,
# admission-blocked-by {slots|blocks} counts, and the serve_anomaly
# section. Same status semantics as the final `serve` record: "OK"
# (real TPU) engages the honesty rule — an unmeasurable quantile (no
# samples landed in the window) rides as an explicit skip object,
# never nan; off-TPU the records are SKIP with a reason.
SERVE_WINDOW_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["serve_window"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "at_s": {"type": "number"},    # serve clock (window END) — the
                                       # time base request rows use
        "window_s": {"type": "number"},
        "steps": {"type": "integer"},
        "prefill_chunks": {"type": "integer"},
        "tokens": {"type": "integer"},
        "tokens_per_s": _METRIC_VALUE,
        "latency_p50_ms": _METRIC_VALUE,
        "latency_p99_ms": _METRIC_VALUE,
        "ttft_p50_ms": _METRIC_VALUE,
        "ttft_p99_ms": _METRIC_VALUE,
        "queue_depth": {"type": "integer"},
        "active_slots": {"type": "integer"},
        "slots": {"type": "integer"},
        "occupancy_pct": _METRIC_VALUE,
        "blocks_live": {"type": "integer"},
        "blocks_high_water": {"type": "integer"},
        "blocks_resident": {"type": "integer"},  # warm prefix blocks
        "admission_blocked_slots": {"type": "integer"},
        "admission_blocked_blocks": {"type": "integer"},
        # serving tier 2: live prefix-cache + preemption view
        "prefix_hit_rate": _METRIC_VALUE,
        "preemptions": {"type": "integer"},
        "recompute_tokens": {"type": "integer"},
        "serve_anomaly": SERVE_ANOMALY_SCHEMA,
    },
    "required": ["schema", "kind", "status", "window_s", "serve_anomaly"],
}

# span record (monitor.spans.span): one host enter/exit window per
# instrumented region. ``name`` is the /-joined path of nested spans —
# the named-scope prefix device-trace ops carry, i.e. the host↔device
# join key. ``traced: true`` marks spans recorded while JAX traced (host
# times then measure tracing, not execution; consumers use the path and
# the collective attrs ``coll``/``axis``/``bytes`` only).
SPAN_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["span"]},
        "name": {"type": "string"},
        "t0_ns": {"type": "integer"},
        "dur_ns": {"type": "integer"},
        "traced": {"type": "boolean"},
        "coll": {"type": "string"},    # collective kind (psum, ppermute, …)
        "axis": {"type": "string"},    # mesh axis the collective rides
        "bytes": {"type": "integer"},  # static payload size per execution
        "step": {"type": "integer"},
    },
    "required": ["schema", "kind", "name", "t0_ns", "dur_ns"],
}

# step-anatomy profile record (`python bench.py --profile`): spans +
# jax.profiler trace fused into the per-step breakdown and a calibrated
# CostDB artifact. Same status semantics as decode/longseq_bias: "OK"
# (real TPU trace with per-HLO device events) engages the honesty rule;
# off-TPU the chrome trace is host-only, so the record is an explicit
# SKIP with the smoke wall-times riding along — never nan in an OK line.
PROFILE_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["profile"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "steps": {"type": "integer"},          # timed step spans captured
        "compute_pct": _METRIC_VALUE,          # of step wall, mean
        "collective_exposed_pct": _METRIC_VALUE,
        "bubble_pct": _METRIC_VALUE,           # device idle inside the step
        "host_gap_pct": _METRIC_VALUE,         # wall not covered by device
        "step_wall_ms": _METRIC_VALUE,         # mean host step-span wall
        "tokens_per_s": _METRIC_VALUE,
        "costdb_collective_rows": {"type": "integer"},
        "costdb_gemm_classes": {"type": "integer"},
        "costdb_path": {"type": "string"},
        "timeline_path": {"type": "string"},
        "trace_dir": {"type": "string"},
        "span_records": {"type": "integer"},
        "config": {"type": "object"},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status"],
}

# the CostDB artifact (prof.calibrate.build_costdb): measured spans +
# counted-bytes hooks distilled into achieved bytes/s per collective
# (kind × axis × power-of-two size bucket) and achieved FLOP/s per GEMM
# shape-class — what the auto-parallelism planner (ROADMAP item 2)
# consumes. A standalone JSON artifact, not an emitter record, but it
# dispatches through the same kind-keyed validator so
# `tools/validate_metrics.py --costdb` gates it like bench/gate records.
_COSTDB_STAT = {
    "type": "object",
    "properties": {
        "n": {"type": "integer"},         # samples folded into the row
        "mean": {"type": "number"},
        "min": {"type": "number"},
        "max": {"type": "number"},
        "spread_pct": {"type": "number"},  # (max-min)/min over samples
    },
    "required": ["n", "mean", "min", "max", "spread_pct"],
}

COSTDB_SCHEMA = {
    "type": "object",
    "properties": {
        "schema": {"enum": [SCHEMA_VERSION]},
        "kind": {"enum": ["costdb"]},
        "device_kind": {"type": "string"},
        "backend": {"type": "string"},
        "source": {"type": "string"},  # spans | counters (which join built it)
        "collectives": {
            "type": "object",
            # key "<kind>[<axis>]" -> list of size-bucket rows
            "additionalProperties": {
                "type": "array",
                "items": {
                    "type": "object",
                    "properties": {
                        "bucket_bytes": {"type": "integer"},  # 2^k floor
                        "bytes": _COSTDB_STAT,        # payload per execution
                        "bytes_per_s": _COSTDB_STAT,  # achieved bandwidth
                    },
                    "required": ["bucket_bytes", "bytes_per_s"],
                },
            },
        },
        "gemms": {
            "type": "object",
            # key: shape-class label (power-of-two FLOPs decade)
            "additionalProperties": {
                "type": "object",
                "properties": {
                    "flops_per_s": _COSTDB_STAT,  # achieved
                    "predicted_flops_per_s": {
                        "anyOf": [{"type": "number"}, {"type": "null"}]},
                },
                "required": ["flops_per_s"],
            },
        },
        "predicted_flops_per_s": {
            # whole-program XLA cost-model rate (flops / optimal_seconds)
            "anyOf": [{"type": "number"}, {"type": "null"}]},
    },
    "required": ["schema", "kind", "collectives", "gemms"],
}

# the StaticCostReport artifact (lint.jaxpr_check.static_cost): the
# jaxpr walker's per-collective calls/bytes by "<kind>[<axis>]" (the
# count_collective tag space) and per-GEMM calls/FLOPs by power-of-two
# class (the CostDB's GEMM class space), every count multiplied by
# enclosing scan lengths — the planner's PREDICTED side, diffed against
# the measured CostDB by prof.calibrate.diff_static_cost. Emitted by
# `python -m apex_tpu.lint --jaxpr --static-cost FILE`, gated by
# `tools/validate_metrics.py --static-cost`.
STATIC_COST_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["static_cost"]},
        "entrypoint": {"type": "string"},  # lint.entrypoints name
        "collectives": {
            "type": "object",
            # key "<kind>[<axis>]" — identical to count_collective tags
            "additionalProperties": {
                "type": "object",
                "properties": {
                    "calls": {"type": "integer"},  # executions per call
                    "bytes": {"type": "integer"},  # payload ·calls
                },
                "required": ["calls", "bytes"],
                "additionalProperties": False,
            },
        },
        "gemms": {
            "type": "object",
            # key "flops_<2^k>" — identical to calibrate's GEMM classes
            "additionalProperties": {
                "type": "object",
                "properties": {
                    "calls": {"type": "integer"},
                    "flops": {"type": "number"},
                },
                "required": ["calls", "flops"],
                "additionalProperties": False,
            },
        },
        "total_collective_bytes": {"type": "integer"},
        "total_gemm_flops": {"type": "number"},
        "eqns": {"type": "integer"},          # walked equations
        "unbounded_sites": {"type": "integer"},  # collective/GEMM rows fed
        # from under while bodies (unknown trip count: priced once,
        # flagged here — never silently multiplied)
    },
    "required": ["schema", "kind", "entrypoint", "collectives", "gemms"],
}

# the apexmem liveness artifact (lint.liveness.analyze → .record()):
# the donation-aware static peak-HBM bound of one traced entrypoint
# with its at-peak family breakdown — params / optimizer /
# activations-and-stashes / kv_pool / temps — plus the donation-aliased
# bytes (buffers counted ONCE because a donated operand is rebound in
# place), the scan-stash bytes (length × per-tick residual, the zb M·v
# dW stash priced explicitly), and the count of while bodies whose
# stash growth is unbounded (flagged, never silently multiplied).
# Emitted by `python -m apex_tpu.lint --jaxpr --memory --static-memory
# FILE`, gated by `tools/validate_metrics.py --static-memory`. CLOSED:
# a junk key in a memory record must fail validation, not ride along;
# the byte fields are integer-typed, so a nan can never masquerade as
# a peak (this artifact is statusless like static_cost — it is a pure
# static claim, no measured half to SKIP).
STATIC_MEMORY_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["static_memory"]},
        "entrypoint": {"type": "string"},  # lint.entrypoints name
        "peak_bytes": {"type": "integer"},
        "peak_mb": {"type": "number"},
        "families": {
            "type": "object",
            # bytes live AT the peak moment, by family — sums to
            # peak_bytes
            "properties": {
                "params": {"type": "integer"},
                "optimizer": {"type": "integer"},
                "activations": {"type": "integer"},
                "kv_pool": {"type": "integer"},
                "temps": {"type": "integer"},
            },
            "required": ["params", "optimizer", "activations",
                         "kv_pool", "temps"],
            "additionalProperties": False,
        },
        "donation_aliased_bytes": {"type": "integer"},
        "stash_bytes": {"type": "integer"},
        "unbounded_stash_sites": {"type": "integer"},
        "eqns": {"type": "integer"},
        "source": {"enum": ["liveness"]},
        "budget_bytes": {"type": "integer"},   # when gated vs a budget
        "verdict": {"enum": ["CLEAN", "VIOLATION"]},
    },
    "required": ["schema", "kind", "entrypoint", "peak_bytes",
                 "families", "donation_aliased_bytes", "stash_bytes",
                 "unbounded_stash_sites", "source"],
    "additionalProperties": False,
}

# the auto-parallelism planner record (`python bench.py --plan`,
# apex_tpu.plan.search.plan_record_fields): the searched ranking, the
# chosen ParallelPlan, its predicted step time + confidence
# (uncalibrated CostDB blind-spot keys listed, never silently priced),
# and — when a measured run followed — the measured step time and the
# predicted-vs-measured error that tools/bench_history.py gates for
# drift. Same status semantics as decode/pipeline: "OK" (real TPU
# measurement) engages the honesty rule; off-TPU the record is an
# explicit SKIP(reason) with the measured half as explicit skip
# objects — never nan in an OK line. Plan objects and ranking rows are
# closed (additionalProperties: false): a junk key in a serialized
# plan or ranking entry must fail validation, not ride along.
PLAN_OBJ_SCHEMA = {
    "type": "object",
    "properties": {
        "dp": {"type": "integer"},
        "tp": {"type": "integer"},
        "pp": {"type": "integer"},
        "cp": {"type": "integer"},
        "ep": {"type": "integer"},
        "sequence_parallel": {"type": "boolean"},
        "tp_overlap": {"type": "boolean"},
        "pp_schedule": {"enum": ["1f1b", "zb"]},
        "overlap_p2p": {"type": "boolean"},
        "virtual_chunks": {"type": "integer"},
        "zero": {"type": "boolean"},
    },
    "required": ["dp", "tp", "pp", "cp", "ep", "sequence_parallel",
                 "tp_overlap", "pp_schedule", "overlap_p2p",
                 "virtual_chunks", "zero"],
    "additionalProperties": False,
}

_PLAN_RANKING_ITEM = {
    "type": "object",
    "properties": {
        "plan": PLAN_OBJ_SCHEMA,
        "predicted_step_ms": {"type": "number"},
        "confidence": {"enum": ["calibrated", "partial"]},
        "uncalibrated": {"type": "array", "items": {"type": "string"}},
        "gemm_ms": {"type": "number"},
        "collective_ms": {"type": "number"},
        "schedule_factor": {"type": "number"},
        "bubble_pct": {"type": "number"},
        "predicted_memory_mb": {"type": "number"},
        # apexmem: which model priced predicted_memory_mb, and — when the
        # liveness bound and the closed form disagree >10% — the honesty
        # flag's magnitude (the disagreement also lands in `uncalibrated`
        # as "memory_model[...]", same never-silently-priced discipline)
        "memory_source": {"enum": ["closed_form", "liveness"]},
        "memory_disagreement_pct": {"type": "number"},
    },
    "required": ["plan", "predicted_step_ms", "confidence"],
    "additionalProperties": False,
}

PLAN_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["plan"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "chips": {"type": "integer"},
        "searched": {"type": "integer"},   # lattice size (incl. rejected)
        "feasible": {"type": "integer"},
        "chosen": PLAN_OBJ_SCHEMA,
        "chosen_describe": {"type": "string"},
        "predicted_step_ms": _METRIC_VALUE,
        "confidence": {"enum": ["calibrated", "partial"]},
        "uncalibrated": {"type": "array", "items": {"type": "string"}},
        "predicted_memory_mb": {"type": "number"},
        "memory_source": {"enum": ["closed_form", "liveness"]},
        # apexmem: the liveness bound for the CHOSEN plan's traced step,
        # and — on TPU — the measured memory_stats() high-water and the
        # prediction error bench_history gates (explicit SKIP objects
        # off-TPU, never nan in an OK line)
        "predicted_peak_hbm_mb": {"type": "number"},
        "measured_peak_hbm_mb": _METRIC_VALUE,
        "predicted_vs_measured_hbm_err_pct": _METRIC_VALUE,
        "ranking": {"type": "array", "items": _PLAN_RANKING_ITEM},
        "rejected": {"type": "array", "items": {
            "type": "object",
            "properties": {"plan": {"type": "string"},
                           "reason": {"type": "string"}},
            "required": ["plan", "reason"],
            "additionalProperties": False,
        }},
        "costdb_source": {"type": "string"},
        "measured_step_ms": _METRIC_VALUE,
        "predicted_vs_measured_err_pct": _METRIC_VALUE,
        "smoke_step_ms": _METRIC_VALUE,  # off-TPU plumbing witness
        "lint_ok": {"type": "boolean"},  # planned_gpt_step JXP check
        "config": {"type": "object"},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status", "chosen", "ranking"],
}

# the serving-plan search record (`python bench.py --serve --plan-serve`,
# apex_tpu.plan.serve.serve_plan_record_fields): the trace-replay-priced
# serving-knob search (ISSUE 20) — the candidate grid, the chosen
# ServePlan + its predicted tokens/s / TTFT quantiles / KV-pool
# footprint + confidence (CostDB blind-spot keys listed in
# `uncalibrated`, never silently priced), the hand-config comparison
# (`searched_beats_hand`), and the live re-plan witnesses (`replans`,
# `replan_parity`, `jit_cache_ok`). Same status semantics as `plan`:
# "OK" (real TPU measurement) engages the honesty rule; off-TPU the
# record is an explicit SKIP(reason) with the measured half as explicit
# skip objects — never nan in an OK line. Plan objects and ranking rows
# are CLOSED (additionalProperties: false): a junk key in a serialized
# ServePlan or ranking entry must fail validation, not ride along.
SERVE_PLAN_OBJ_SCHEMA = {
    "type": "object",
    "properties": {
        "num_blocks": {"type": "integer"},
        "block_size": {"type": "integer"},
        "num_slots": {"type": "integer"},
        "prefill_chunk": {"type": "integer"},
        "max_prefill_share": {"type": "integer"},
        "drafter": {"enum": ["none", "ngram", "ngram_tree"]},
        "spec_depth": {"type": "integer"},
        "spec_branching": {"type": "integer"},
        "spec_adaptive": {"type": "boolean"},
        "kv_dtype": {"enum": [None, "int8", "fp8_e4m3"]},
        "slo_ttft_ms": {"anyOf": [{"type": "number"}, {"type": "null"}]},
        "slo_burn_count": {"type": "integer"},
        "admission": {"enum": ["fcfs", "short_first"]},
    },
    "required": ["num_blocks", "block_size", "num_slots", "prefill_chunk",
                 "max_prefill_share", "drafter", "spec_depth",
                 "spec_branching", "spec_adaptive", "kv_dtype",
                 "slo_ttft_ms", "slo_burn_count", "admission"],
    "additionalProperties": False,
}

_SERVE_PLAN_RANKING_ITEM = {
    "type": "object",
    "properties": {
        "plan": SERVE_PLAN_OBJ_SCHEMA,
        "digest": {"type": "string"},
        "predicted_tokens_per_s": {"type": "number"},
        "predicted_ttft_p50_ms": {"type": "number"},
        "predicted_ttft_p99_ms": {"type": "number"},
        "predicted_kv_pool_mb": {"type": "number"},
        "confidence": {"enum": ["calibrated", "partial"]},
        "uncalibrated": {"type": "array", "items": {"type": "string"}},
        "decode_steps": {"type": "integer"},
        "prefill_chunks": {"type": "integer"},
        "sim_span_ms": {"type": "number"},
    },
    "required": ["plan", "predicted_tokens_per_s", "confidence"],
    "additionalProperties": False,
}

SERVE_PLAN_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["serve_plan"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "searched": {"type": "integer"},   # grid size (incl. rejected)
        "feasible": {"type": "integer"},
        "requests": {"type": "integer"},   # replayed trace size
        "trace_seed": {"type": "integer"},
        "chosen": SERVE_PLAN_OBJ_SCHEMA,
        "chosen_describe": {"type": "string"},
        "chosen_digest": {"type": "string"},
        "predicted_tokens_per_s": {"type": "number"},
        "predicted_ttft_p50_ms": {"type": "number"},
        "predicted_ttft_p99_ms": {"type": "number"},
        "predicted_kv_pool_mb": {"type": "number"},
        "confidence": {"enum": ["calibrated", "partial"]},
        "uncalibrated": {"type": "array", "items": {"type": "string"}},
        "ranking": {"type": "array", "items": _SERVE_PLAN_RANKING_ITEM},
        "rejected": {"type": "array", "items": {
            "type": "object",
            "properties": {"plan": {"type": "string"},
                           "reason": {"type": "string"}},
            "required": ["plan", "reason"],
            "additionalProperties": False,
        }},
        "costdb_source": {"type": "string"},
        # measured half — real TPU only; explicit skip objects off-TPU
        "measured_tokens_per_s": _METRIC_VALUE,
        "measured_ttft_p50_ms": _METRIC_VALUE,
        "predicted_vs_measured_err_pct": _METRIC_VALUE,
        # hand-config comparison: the fixed baseline the searched plan
        # must beat on the SAME recorded trace (tokens/s AND TTFT p50)
        "hand_tokens_per_s": _METRIC_VALUE,
        "hand_ttft_p50_ms": _METRIC_VALUE,
        "searched_beats_hand": {"type": "boolean"},
        # live re-plan witnesses: ladder switches completed mid-serve
        # with greedy output token-identical across the switch and both
        # jit caches pinned at 1
        "replans": {"type": "integer"},
        "replan_parity": {"type": "boolean"},
        "jit_cache_ok": {"type": "boolean"},
        "smoke_tokens_per_s": _METRIC_VALUE,  # off-TPU plumbing witness
        "config": {"type": "object"},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status", "chosen", "ranking"],
}

# sharded-checkpoint bench record (`python bench.py --ckpt`): the
# measured cost of elastic ZeRO checkpointing (apex_tpu.ckpt) — the
# between-steps snapshot time (the only part on the step path), the
# background write+commit time, and the headline save_overhead_pct
# (extra wall time a saving run pays per step vs the clean baseline;
# tools/bench_history.py gates it lower-is-better in absolute points).
# The `manifest` section mirrors Manifest.summary() and is CLOSED —
# a junk key in it fails validation (tools/validate_metrics.py --ckpt).
# Same status semantics as every bench record: "OK" only on real TPU
# (honesty rule engaged), off-TPU an explicit SKIP(reason) with the
# smoke measurements riding along — never nan in an OK line.
CKPT_MANIFEST_SCHEMA = {
    "type": "object",
    "properties": {
        "format": {"type": "string"},
        "version": {"type": "integer"},
        "step": {"type": "integer"},
        "count": {"type": "integer"},
        "dp": {"type": "integer"},
        "chunk_size": {"type": "integer"},
        "n_chunks": {"type": "integer"},
        "pad_rows": {"type": "integer"},
        "rows_per_rank": {"type": "integer"},
        "buffers": {"type": "array", "items": {"type": "string"}},
        "digest_algo": {"type": "string"},
    },
    "required": ["format", "dp", "chunk_size", "n_chunks",
                 "rows_per_rank", "buffers"],
    "additionalProperties": False,
}

CKPT_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["ckpt"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "save_overhead_pct": _METRIC_VALUE,  # the gated headline
        "step_ms": _METRIC_VALUE,            # clean steady-state step
        "step_ms_saving": _METRIC_VALUE,     # mean step while saving
        "snapshot_ms": _METRIC_VALUE,        # device→host, on-path part
        "write_ms": _METRIC_VALUE,           # background write+commit
        "restore_ms": _METRIC_VALUE,
        "bytes_written": {"type": "integer"},
        "steps": {"type": "integer"},
        "saves": {"type": "integer"},
        "save_every": {"type": "integer"},
        "dp": {"type": "integer"},
        "async_save": {"type": "boolean"},
        # acceptance witnesses, measured in-process by the leg
        "bitwise_resume_ok": {"type": "boolean"},   # same-dp roundtrip
        "elastic_resume_ok": {"type": "boolean"},   # dp-resize rows match
        "manifest": CKPT_MANIFEST_SCHEMA,
        "spread_pct": _METRIC_VALUE,
        "config": {"type": "object"},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status"],
}

# speculative-decoding bench record (`python bench.py --spec`): the
# two-factor decode-speed attack of ROADMAP item 3 measured as one
# artifact — tokens/s/request with a drafter vs the non-speculative
# baseline at batch 1 AND under scheduler churn, the acceptance rate
# that explains the ratio, and the int8-KV quantization leg (pool
# bytes halved, decode logit error vs the float parity oracle bounded
# in the record). Same status semantics as decode/serve: "OK" (real
# TPU) engages the honesty rule; off-TPU the record is an explicit
# SKIP(reason) with the smoke measurements riding along — never nan in
# an OK line. CLOSED schema: a junk key fails validation, not rides
# along (the drift tests pin exactly that).
SPEC_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["spec"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "tokens_per_s_request": _METRIC_VALUE,   # spec decode, batch 1
        "baseline_tokens_per_s_request": _METRIC_VALUE,
        "speedup": _METRIC_VALUE,                # spec / baseline
        "tokens_per_s_churn": _METRIC_VALUE,     # spec serve sweep
        "baseline_tokens_per_s_churn": _METRIC_VALUE,
        "speedup_churn": _METRIC_VALUE,
        "acceptance_rate": _METRIC_VALUE,        # accepted / drafted
        "accepted_per_round": _METRIC_VALUE,     # mean accepted_len
        "rounds": {"type": "integer"},
        "draft_k": {"type": "integer"},
        "drafter": {"type": "string"},           # ngram | model
        "kv_dtype": {"type": "string"},          # quantized leg's knob
        "kv_quant_logit_err": _METRIC_VALUE,     # max |Δlogit| vs oracle
        "kv_quant_pool_mb": _METRIC_VALUE,       # int8 pool footprint
        "kv_oracle_pool_mb": _METRIC_VALUE,      # float oracle footprint
        "greedy_parity": {"type": "boolean"},    # spec == baseline, b=1
        "churn_parity": {"type": "boolean"},     # spec == baseline, serve
        "jit_cache_ok": {"type": "boolean"},     # every body pinned at 1
        "prompt_len": {"type": "integer"},
        "new_tokens": {"type": "integer"},
        "requests": {"type": "integer"},         # churn sweep size
        # tree speculative decoding (`--spec --tree`, ISSUE 19): the
        # fused tree-verify leg — same closed-schema discipline, the
        # tree fields simply EXTEND the record (a pre-tree consumer
        # rejects nothing; a junk key still fails)
        "tree_spec_tokens_per_s_request": _METRIC_VALUE,  # tree, batch 1
        "tree_spec_tokens_per_s_churn": _METRIC_VALUE,    # tree serve
        "tree_spec_acceptance_rate": _METRIC_VALUE,  # path rows / depth
        "tree_speedup": _METRIC_VALUE,           # tree / baseline, b=1
        "tree_depth": {"type": "integer"},       # static tree shape
        "tree_branching": {"type": "integer"},
        "tree_nodes": {"type": "integer"},       # branching x depth
        "tree_rounds": {"type": "integer"},
        "tree_greedy_parity": {"type": "boolean"},   # tree == plain, b=1
        "tree_churn_parity": {"type": "boolean"},    # tree == plain, serve
        "drafter_pool_blocks": {"type": "integer"},  # peak drafter blocks
        #                                            # in the SHARED pool
        "adaptive_efficiency": _METRIC_VALUE,    # tokens per verify row
        "fixed_k_efficiency": {"type": "array",  # same, per fixed choice
                               "items": {"type": "number"}},
        "adaptive_beats_fixed": {"type": "boolean"},
        "spread_pct": _METRIC_VALUE,
        "pass_times_ms": {"type": "array", "items": {"type": "number"}},
        "config": {"type": "object"},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status"],
    "additionalProperties": False,
}

# tensor-parallel serving bench record (`python bench.py --serve
# --plan-tp N`, ISSUE 17): one artifact for the serve-a-model-bigger-
# than-one-chip story — churn throughput with the paged pool sharded
# over kv_heads and the projections riding the ring-overlap collective
# matmuls, the tp=1 baseline on the same request schedule (greedy
# parity token-identical by construction, asserted in the record), the
# per-decode-step collective traffic from the ring counters, and the
# disaggregated prefill→decode leg: the prefill role's TTFT, the
# streamed KV payload (blocks/bytes/export+ingest wall), digest
# verification, and handoff parity vs the monolithic engine. Same
# status semantics as decode/serve/spec: "OK" (real multichip TPU)
# engages the honesty rule; off-TPU (or a single chip) the record is an
# explicit SKIP(reason) with the virtual-mesh smoke measurements riding
# along — never nan in an OK line. CLOSED schema: a junk key fails
# validation (the drift tests pin exactly that).
TP_SERVE_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["tp_serve"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "tp": {"type": "integer"},               # shard count
        "tokens_per_s": _METRIC_VALUE,           # tp serve under churn
        "baseline_tokens_per_s": _METRIC_VALUE,  # tp=1, same schedule
        "ttft_ms_prefill_role": _METRIC_VALUE,   # disagg prefill mean
        "ttft_ms_monolithic": _METRIC_VALUE,     # same reqs, one engine
        "handoff_blocks": {"type": "integer"},   # KV blocks streamed
        "handoff_transfer_bytes": {"type": "integer"},
        "handoff_transfer_ms": _METRIC_VALUE,    # export+ingest wall
        "digests_verified": {"type": "integer"},
        "collective_ppermute_calls": {"type": "integer"},  # ring hops
        "collective_ppermute_bytes": {"type": "integer"},
        "decode_steps": {"type": "integer"},
        "collective_bytes_per_step": _METRIC_VALUE,
        "greedy_parity": {"type": "boolean"},    # tp == tp=1 tokens
        "handoff_parity": {"type": "boolean"},   # disagg == monolithic
        "jit_cache_ok": {"type": "boolean"},     # every body pinned at 1
        "kv_dtype": {"type": "string"},
        "requests": {"type": "integer"},
        "num_blocks": {"type": "integer"},       # GLOBAL pool blocks
        "pool_mb_per_shard": _METRIC_VALUE,      # the bigger-than-one-
        "pool_mb_total": _METRIC_VALUE,          # chip arithmetic
        "spread_pct": _METRIC_VALUE,
        "pass_times_ms": {"type": "array", "items": {"type": "number"}},
        "config": {"type": "object"},
        "backend": {"type": "string"},
    },
    "required": ["schema", "kind", "status"],
    "additionalProperties": False,
}

# per-process clock-sync record (ISSUE 16): the monotonic↔wall offset
# emitted once at monitor.enable() — `mono_ns` (time.perf_counter_ns)
# and `wall_s` (time.time) read back to back, so any consumer can map
# the unified `t_ns` base of this process's records onto wall time (and
# onto another process's stream through ITS clock_sync record). CLOSED:
# a junk key fails validation.
CLOCK_SYNC_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["clock_sync"]},
        "mono_ns": {"type": "integer"},   # time.perf_counter_ns()
        "wall_s": {"type": "number"},     # time.time(), same instant
        "clock": {"type": "string"},      # the monotonic source's name
        "pid": {"type": "integer"},
    },
    "required": ["schema", "kind", "mono_ns", "wall_s"],
    "additionalProperties": False,
}

# TTFT/latency attribution record (`monitor report --attribution`,
# `bench.py --serve`, monitor.trace.serve_attribution): each request's
# end-to-end latency decomposed into queue / prefill / decode / spec /
# spec-rewind / preempt-wait / recompute / swap-pause components. The
# components PARTITION [submit, finish] by construction (decode is the
# interval remainder after the spec/swap carve-outs), so per request
# they sum to the measured e2e latency up to rounding — the exact
# priced-phase input ServePlan pricing consumes (ROADMAP item 2). Both
# the record and its per-request rows are CLOSED schemas; status "OK"
# engages the no-nan honesty rule like every status record.
_ATTR_COMPONENTS = ("queue_ms", "prefill_ms", "decode_ms", "spec_ms",
                    "spec_rewind_ms", "preempt_wait_ms", "recompute_ms",
                    "swap_pause_ms")

SERVE_ATTRIBUTION_ROW_SCHEMA = {
    "type": "object",
    "properties": {
        "rid": {"type": "integer"},
        "trace_id": {"type": "string"},
        "e2e_ms": {"type": "number"},          # finish - submit, measured
        "components_ms": {"type": "number"},   # sum of the 8 components
        "residual_pct": {"type": "number"},    # |sum - e2e| / e2e * 100
        "evictions": {"type": "integer"},
        "spec_rounds": {"type": "integer"},
        **{c: {"type": "number"} for c in _ATTR_COMPONENTS},
    },
    "required": ["rid", "e2e_ms", "components_ms", *_ATTR_COMPONENTS],
    "additionalProperties": False,
}

SERVE_ATTRIBUTION_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["serve_attribution"]},
        "status": {"enum": ["OK", "SKIP"]},
        "reason": {"type": "string"},  # required when status == "SKIP"
        "requests": {"type": "integer"},       # finished requests rowed
        "unattributed": {"type": "integer"},   # rids lacking submit/finish
        "components": {
            "type": "object",
            "properties": {c: {"type": "number"}
                           for c in _ATTR_COMPONENTS},
            "required": list(_ATTR_COMPONENTS),
            "additionalProperties": False,
        },
        "e2e_ms_total": {"type": "number"},
        "components_ms_total": {"type": "number"},
        "max_residual_pct": _METRIC_VALUE,     # worst per-request gap
        "per_request": {"type": "array",
                        "items": SERVE_ATTRIBUTION_ROW_SCHEMA},
    },
    "required": ["schema", "kind", "status", "requests", "components"],
    "additionalProperties": False,
}

# anomaly flight-recorder dump (monitor.trace.FlightRecorder): the
# bounded in-memory ring of recent raw records, written to a timestamped
# file when the serve_anomaly layer fires (SLO burn, straggler, leak),
# on SIGTERM, or on demand — post-hoc debuggability even when no JSONL
# sink was attached. `events` are the raw ring records verbatim (they
# were already emitted under the honesty rule; the dump itself claims no
# success, so a SKIP record inside cannot fail it). CLOSED envelope.
FLIGHT_RECORDER_SCHEMA = {
    "type": "object",
    "properties": {
        **_COMMON,
        "kind": {"enum": ["flight_recorder_dump"]},
        "reason": {"type": "string"},      # what fired the dump
        "capacity": {"type": "integer"},   # ring size N
        "num_events": {"type": "integer"},  # len(events) <= capacity
        "mono_ns": {"type": "integer"},    # dump instant, unified clock
        "wall_s": {"type": "number"},      # dump instant, wall clock
        "pid": {"type": "integer"},
        "events": {"type": "array", "items": {"type": "object"}},
    },
    "required": ["schema", "kind", "reason", "capacity", "num_events",
                 "events"],
    "additionalProperties": False,
}

SCHEMAS_BY_KIND = {
    "step": STEP_SCHEMA,
    "meta": META_SCHEMA,
    "event": EVENT_SCHEMA,
    "gate": GATE_SCHEMA,
    "decode": DECODE_SCHEMA,
    "longseq_bias": LONGSEQ_BIAS_SCHEMA,
    "tp_overlap": TP_OVERLAP_SCHEMA,
    "pipeline": PIPELINE_SCHEMA,
    "serve": SERVE_SCHEMA,
    "serve_event": SERVE_EVENT_SCHEMA,
    "serve_window": SERVE_WINDOW_SCHEMA,
    "span": SPAN_SCHEMA,
    "profile": PROFILE_SCHEMA,
    "costdb": COSTDB_SCHEMA,
    "static_cost": STATIC_COST_SCHEMA,
    "static_memory": STATIC_MEMORY_SCHEMA,
    "plan": PLAN_SCHEMA,
    "serve_plan": SERVE_PLAN_SCHEMA,
    "ckpt": CKPT_SCHEMA,
    "spec": SPEC_SCHEMA,
    "tp_serve": TP_SERVE_SCHEMA,
    "clock_sync": CLOCK_SYNC_SCHEMA,
    "serve_attribution": SERVE_ATTRIBUTION_SCHEMA,
    "flight_recorder_dump": FLIGHT_RECORDER_SCHEMA,
}

# --- minimal JSON-Schema subset validator ------------------------------------

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "integer": int,
    "boolean": bool,
    "null": type(None),
}


def _check(obj: Any, schema: Dict[str, Any], path: str, errors: List[str]) -> None:
    if "enum" in schema:
        if obj not in schema["enum"]:
            errors.append(f"{path or '<root>'}: {obj!r} not in {schema['enum']}")
        return
    if "anyOf" in schema:
        for sub in schema["anyOf"]:
            sub_errors: List[str] = []
            _check(obj, sub, path, sub_errors)
            if not sub_errors:
                return
        errors.append(f"{path or '<root>'}: {obj!r} matches no anyOf branch")
        return
    t = schema.get("type")
    if t is not None:
        if t == "number":
            ok = isinstance(obj, (int, float)) and not isinstance(obj, bool)
        elif t == "integer":
            ok = isinstance(obj, int) and not isinstance(obj, bool)
        else:
            ok = isinstance(obj, _TYPES[t])
        if not ok:
            errors.append(f"{path or '<root>'}: expected {t}, got "
                          f"{type(obj).__name__}")
            return
    if isinstance(obj, dict):
        props = schema.get("properties", {})
        for key in schema.get("required", []):
            if key not in obj:
                errors.append(f"{path or '<root>'}: missing required "
                              f"key {key!r}")
        extra = schema.get("additionalProperties", True)
        for key, val in obj.items():
            sub = props.get(key)
            kpath = f"{path}.{key}" if path else str(key)
            if sub is not None:
                _check(val, sub, kpath, errors)
            elif extra is False:
                errors.append(f"{kpath}: unexpected key")
            elif isinstance(extra, dict):
                _check(val, extra, kpath, errors)
    elif isinstance(obj, list) and "items" in schema:
        for i, val in enumerate(obj):
            _check(val, schema["items"], f"{path}[{i}]", errors)


def _honesty_errors(record: Dict[str, Any]) -> List[str]:
    claims = (record.get("ok") is True
              or (isinstance(record.get("status"), str)
                  and record["status"].upper() == "OK")
              # bench results are success artifacts by construction
              or ("metric" in record and "value" in record))
    if not claims:
        return []
    errors = [f"success record has non-finite value at {p}"
              for p in _nonfinite_paths(record)]
    errors.extend(f"success record has stringified non-finite value at {p}"
                  for p in _stringified_nonfinite_paths(record))
    return errors


def validate(record: Dict[str, Any],
             schema: Dict[str, Any] = None) -> List[str]:
    """Validate one record; returns a list of error strings (empty = valid).

    Without an explicit ``schema``, monitor records dispatch on ``kind``
    and objects with ``metric``/``value`` validate as bench results.
    """
    if schema is None:
        if "kind" in record:
            schema = SCHEMAS_BY_KIND.get(record["kind"])
            if schema is None:
                return [f"unknown record kind {record['kind']!r}"]
        elif "metric" in record:
            schema = BENCH_SCHEMA
        else:
            return ["record has neither 'kind' nor 'metric'; cannot dispatch"]
    errors: List[str] = []
    _check(record, schema, "", errors)
    errors.extend(_honesty_errors(record))
    # the conditional half of the status contract (the emitter enforces it
    # too, but externally produced streams must not pass the validator
    # with a claim-free, reason-free skip)
    if (record.get("kind") in ("decode", "longseq_bias", "tp_overlap",
                               "profile", "serve", "pipeline",
                               "serve_window", "plan", "serve_plan",
                               "ckpt", "spec", "tp_serve",
                               "serve_attribution")
            and record.get("status") == "SKIP"
            and not record.get("reason")):
        errors.append(
            f"SKIP {record.get('kind')} record must carry a reason")
    if not errors:
        try:  # cross-check with the real jsonschema when present
            import jsonschema
        except ImportError:
            pass
        else:
            try:
                jsonschema.validate(record, schema)
            except jsonschema.ValidationError as e:  # pragma: no cover
                errors.append(f"jsonschema: {e.message}")
    return errors


def validate_jsonl(lines: Iterable[str]) -> List[Tuple[int, str]]:
    """Validate a monitor JSONL stream; returns [(lineno, error), ...]."""
    problems: List[Tuple[int, str]] = []
    n = 0
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        n += 1
        try:
            record = json.loads(line)
        except json.JSONDecodeError as e:
            problems.append((lineno, f"invalid JSON: {e}"))
            continue
        for err in validate(record):
            problems.append((lineno, err))
    if n == 0:
        problems.append((0, "stream contains no records"))
    return problems


def gate_metrics(values: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize a gate's metric dict: finite numbers pass through, a
    ``(skipped, reason)`` tuple or non-finite number becomes the explicit
    skip object. Non-finite numbers are *rejected* — the caller must have
    decided to skip, not silently measured nan."""
    out: Dict[str, Any] = {}
    for name, v in values.items():
        if isinstance(v, tuple) and len(v) == 2 and v[0] == "skipped":
            out[name] = {"skipped": True, "reason": str(v[1])}
        elif isinstance(v, (int, float)):
            if isinstance(v, float) and not math.isfinite(v):
                raise ValueError(
                    f"gate metric {name!r} is {v}; mark it skipped with "
                    "('skipped', reason) instead of passing a non-finite "
                    "measurement")
            out[name] = v
        else:
            raise TypeError(f"gate metric {name!r}: expected number or "
                            f"('skipped', reason), got {type(v).__name__}")
    return out
