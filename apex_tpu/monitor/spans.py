"""Step-anatomy span instrumentation: host enter/exit timestamps + the
named-scope join key into device traces.

The profiler layer (:mod:`apex_tpu.prof`) can read a ``jax.profiler``
trace and the monitor can time whole steps, but neither can say *which
part* of a step a device kernel belongs to — the reference's pyprof
solves this with NVTX ranges joined to kernels through the nvprof
database (``apex/pyprof/parse/db.py``). On TPU the join comes free:
``jax.named_scope`` names entered while JAX **traces** ride into every
HLO's name in the device trace. A :func:`span` therefore does double
duty:

* **host side** — when monitoring is enabled, it records a monotonic-ns
  enter/exit pair and emits one ``span`` record (rank-tagged, riding the
  same JSONL stream as step records) with any caller attrs
  (``bytes=``, ``axis=``, ``coll=`` for collectives);
* **device side** — it enters ``jax.named_scope(name)``, so any op
  traced inside carries the span's **path** (nested spans join with
  ``/``) as a prefix of its trace name. ``prof.trace_reader.correlate``
  joins the two halves on exactly that prefix.

Spans in *traced* code (pipeline ticks, TP boundary collectives, the
collective-matmul rings, decode blocks) run their Python once per trace:
their host duration is tracing time, not execution time, so the record
carries ``traced: true`` and consumers use them for the scope path and
attrs only — the real durations come from the device events under the
scope. Host-phase spans (``step``, the profile bench's timed passes)
carry wall time the anatomy table can trust.

Disabled cost: one registry load + ``is None`` test, then a bare
``yield`` — no jax import, no named_scope, no clock read (the same
contract as every other monitor hook). This also means scope names only
reach the device trace when monitoring was enabled at *trace* time:
enable the monitor before compiling the step you want to attribute
(``bench.py --profile`` does).
"""

from __future__ import annotations

import contextlib
from typing import Optional

from apex_tpu.monitor import registry as _reg
# THE unified clock (trace.monotonic_ns == time.perf_counter_ns): span
# t0_ns, registry t_ns and the serve clock all share its CLOCK_MONOTONIC
# base, so `monitor trace` merges the streams without skew
from apex_tpu.monitor.trace import monotonic_ns

# the active span path, innermost last. Training loops and tracing are
# single-threaded per process; a plain list keeps the enabled fast path
# at two list ops per span.
_STACK: list = []


def span_path() -> str:
    """The current span path ("" at top level) — the prefix any op traced
    right now would carry in a device trace."""
    return "/".join(_STACK)


def _trace_state_clean() -> bool:
    from jax import core

    try:
        return bool(core.trace_state_clean())
    except AttributeError:  # future jax: assume host context
        return True


@contextlib.contextmanager
def span(name: str, **attrs):
    """Instrument a region: ``with span("fwd_bwd"): ...``.

    Emits one ``span`` record on exit — ``name`` is the full ``/``-joined
    path of nested spans, ``t0_ns``/``dur_ns`` the monotonic host window,
    ``traced: true`` when entered under a JAX trace (host times then
    measure tracing, not execution) — and wraps the body in
    ``jax.named_scope(name)`` so traced ops join back to this span by
    name prefix. ``attrs`` pass through to the record (collective spans
    carry ``coll=kind, axis=..., bytes=...`` — what the CostDB
    calibration prices). No-op while monitoring is disabled.
    """
    r = _reg.get_registry()
    if r is None:
        yield
        return
    import jax

    _STACK.append(name)
    path = "/".join(_STACK)
    traced = not _trace_state_clean()
    t0 = monotonic_ns()
    try:
        with jax.named_scope(name):
            yield
    finally:
        dur = monotonic_ns() - t0
        _STACK.pop()
        # the registry may have been torn down inside the body
        r = _reg.get_registry()
        if r is not None:
            if traced:
                attrs.setdefault("traced", True)
            r.emit("span", name=path, t0_ns=t0, dur_ns=dur, **attrs)


@contextlib.contextmanager
def collective_span(kind: str, payload, axis_name: Optional[str]):
    """A :func:`span` around one collective, carrying the calibration
    attrs (``coll``, ``axis``, ``bytes`` — payload size from static
    shapes, the same accounting as ``hooks.count_collective``). The span
    segment is ``{kind}_{axis}`` so distinct axes keep distinct scope
    paths in the device trace. No-op while disabled; identity when
    ``axis_name`` is None (tp=1 fallthrough paths)."""
    if axis_name is None or _reg.get_registry() is None:
        yield
        return
    from apex_tpu.monitor.hooks import tree_bytes

    with span(f"{kind}_{axis_name}", coll=kind, axis=axis_name,
              bytes=tree_bytes(payload)):
        yield
