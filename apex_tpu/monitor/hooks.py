"""Instrumentation hooks wiring the hot paths into the metrics registry.

Three kinds of hook, by *when* they run:

* **host-side pull hooks** (:func:`observe_scaler`, :func:`observe_grads`,
  :func:`observe_updates`) — called from the training loop on state it
  already holds. These are the only hooks that touch device values, so the
  one device→host sync they cost is explicit and opt-in; while monitoring
  is disabled they return immediately without looking at their argument.
* **trace-time static hooks** (:func:`count_collective`,
  :func:`record_pipeline_schedule`) — called from inside traced code
  (``p2p_communication``, ``schedules``) while JAX is *tracing*, where
  shapes and schedule geometry are static Python values. They cost nothing
  at run time: a jitted step re-executes the collectives, not the Python
  that counted them, so counts are **per traced program** (a retrace adds
  another program's worth). The report reads them from the step records'
  lifetime ``counters_total`` — tracing usually happens during warm-up,
  before any step window opens, so per-step deltas would miss them.
* **wall-clock timers** — ``monitor.timer("train/step")`` around the
  blocking step call; see ``docs/OBSERVABILITY.md`` for the pattern.
"""

from __future__ import annotations

from typing import Any, Optional

from apex_tpu.monitor import registry as _reg

# re-exported registry entry points, so instrumented call sites in other
# subsystems depend on this module's public surface only
enabled = _reg.enabled
emit_event = _reg.emit_event

PyTree = Any


# --- AMP scaler --------------------------------------------------------------

def observe_scaler(state) -> Optional[dict]:
    """Pull loss-scale observability numbers from a
    :class:`~apex_tpu.amp.scaler.LossScalerState`.

    Gauges: ``amp/loss_scale``, ``amp/growth_tracker``,
    ``amp/skipped_steps_total``; counter ``amp/overflow_steps`` advances by
    the delta in ``skipped_steps`` since the previous observation, so step
    records carry per-step overflow counts. The FIRST observation is the
    delta baseline (a resumed checkpoint's historical skips must not count
    as this run's overflows) — observe the scaler once before the training
    loop so an overflow in the very first step is attributed to it.
    Returns the pulled numbers (the same dict
    :func:`apex_tpu.amp.scaler_metrics` computes), or ``None`` while
    monitoring is disabled.
    """
    r = _reg.get_registry()
    if r is None:
        return None
    from apex_tpu.amp.scaler import scaler_metrics

    m = scaler_metrics(state)
    r.gauge("amp/loss_scale", m["loss_scale"])
    r.gauge("amp/growth_tracker", m["growth_tracker"])
    r.gauge("amp/skipped_steps_total", m["skipped_steps"])
    prev = getattr(r, "_amp_skipped_prev", None)
    if prev is not None and m["skipped_steps"] > prev:
        r.counter("amp/overflow_steps", m["skipped_steps"] - prev)
    r._amp_skipped_prev = m["skipped_steps"]
    return m


# --- optimizers --------------------------------------------------------------

def _tree_norm(tree: PyTree) -> float:
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree.leaves(tree)
              if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)]
    if not leaves:
        return 0.0
    total = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return float(jnp.sqrt(total))


def observe_grads(grads: PyTree) -> Optional[float]:
    """Gauge ``optim/grad_norm`` = global L2 norm of a grad pytree.

    Host-side: call it on the grads your step returned (one reduction on
    device, one scalar transfer). No-op while disabled."""
    r = _reg.get_registry()
    if r is None:
        return None
    n = _tree_norm(grads)
    r.gauge("optim/grad_norm", n)
    return n


def observe_updates(updates: PyTree) -> Optional[float]:
    """Gauge ``optim/update_norm`` = global L2 norm of the parameter
    updates an optimizer produced."""
    r = _reg.get_registry()
    if r is None:
        return None
    n = _tree_norm(updates)
    r.gauge("optim/update_norm", n)
    return n


def observe_optimizer_step(grads: PyTree = None,
                           updates: PyTree = None) -> Optional[dict]:
    """One-call optimizer observability: gauges ``optim/grad_norm`` and
    ``optim/update_norm`` from the pytrees the step already produced.
    Returns the pulled numbers, or ``None`` while disabled (in which case
    the arguments are never touched — no device work)."""
    r = _reg.get_registry()
    if r is None:
        return None
    out = {}
    if grads is not None:
        out["grad_norm"] = observe_grads(grads)
    if updates is not None:
        out["update_norm"] = observe_updates(updates)
    return out


# --- pipeline schedules ------------------------------------------------------

def pipeline_bubble_fraction(num_microbatches: int, pipeline_size: int,
                             virtual_chunks: int = 1) -> float:
    """Analytic bubble fraction of the scanned SPMD schedule: the forward
    sweep runs ``M·v + S − 1`` chunk-ticks of which ``S − 1`` are fill/drain
    (module docstring of ``pipeline_parallel.schedules`` has the timing
    model; measured by ``tests/test_pipeline.py::TestBubbleUtilization``)."""
    ticks = num_microbatches * virtual_chunks + pipeline_size - 1
    return (pipeline_size - 1) / ticks if ticks else 0.0


def pipeline_cost_model(num_microbatches: int, pipeline_size: int,
                        virtual_chunks: int = 1, schedule: str = "1f1b",
                        overlap_p2p: bool = False) -> dict:
    """Unit-cost trace-time geometry of one full fwd+bwd pipeline step.

    Cost units: F = B = W = 1 — one chunk's forward, activation-grad (dX)
    and weight-grad (dW) compute respectively (the classic 1:1:1 split of
    a GEMM-dominated block: backward ≈ 2× forward, half of it dX). Hop
    time is priced at ZERO — off-TPU geometry cannot measure ICI; on TPU
    ``prof.trace_reader.step_anatomy`` measures what the hops actually
    expose (``overlap_p2p`` therefore only *costs* in this model — its
    longer drain — while its win, hidden hop latency, shows up only in
    measured anatomy).

    * ``"1f1b"`` (autodiff backward): every one of the
      ``Mv + L(S−1) + (L−1)`` backward ticks pays B+W — garbage
      warmup/drain lanes included. Scheduled units = 3 × fwd_ticks.
    * ``"zb"``: the backward splits — dX rides the same tick count at B
      each, dW runs ``M·v`` real-item ticks at W each.
      Scheduled units = 2 × fwd_ticks + M·v: the (S−1)·W drain term is
      gone.

    Per-device useful work is ``3·M·v`` either way, so
    ``bubble_fraction = 1 − ideal/total`` is the SLOT-WASTE fraction —
    the share of scheduled compute slots holding warmup/drain garbage.
    Recompute is priced SEPARATELY and honestly in ``recompute_units``:
    with per-tick remat the 1f1b backward re-runs F on each of its
    ``fwd_ticks``; the zb implementation re-runs F in BOTH sweeps
    (``jax.vjp`` from the per-tick stashed inputs — remat-class memory),
    so zb pays ``M·v`` MORE recompute than rematted 1f1b. Net compute
    (``total_units + recompute_units``) therefore favors 1f1b by
    ``Mv − (S−1)`` units; zb's real wins are (a) the dW sweep's
    ``M·v`` ticks are COLLECTIVE-FREE (no ppermute on the critical
    path — hop latency and inter-stage sync exit for those ticks, which
    the hop-cost-0 model cannot price) and (b) zero garbage dW slots.
    The wall-clock verdict is the measured one: ``bench.py --pipeline``'s
    ``vs_1f1b`` / ``step_anatomy`` bubbles on TPU, never this model."""
    M, S, v = num_microbatches, pipeline_size, virtual_chunks
    if schedule not in ("1f1b", "zb"):
        raise ValueError(
            f"schedule={schedule!r}: pipeline_cost_model prices '1f1b' "
            "and 'zb' only — an unknown name must not be silently priced "
            "as 1f1b")
    L = 2 if overlap_p2p else 1
    fwd = M * v + L * (S - 1) + (L - 1)
    if schedule == "zb":
        dx_ticks, dw_ticks = fwd, M * v
        recompute = fwd + M * v  # F re-run in the dX sweep AND per dW tick
    else:
        dx_ticks, dw_ticks = fwd, fwd
        recompute = fwd  # per-tick remat re-runs F once per backward tick
    total = fwd + dx_ticks + dw_ticks
    ideal = 3 * M * v
    return {
        "schedule": schedule,
        "overlap_p2p": overlap_p2p,
        "fwd_ticks": fwd,
        "bwd_dx_ticks": dx_ticks,
        "bwd_dw_ticks": dw_ticks,
        "total_units": total,
        "ideal_units": ideal,
        "recompute_units": recompute,
        "collective_free_ticks": dw_ticks if schedule == "zb" else 0,
        "bubble_fraction": (1.0 - ideal / total) if total else 0.0,
    }


def record_pipeline_schedule(*, num_microbatches: int, pipeline_size: int,
                             virtual_chunks: int = 1,
                             tick_bytes: Optional[int] = None,
                             axis: str = "pp", schedule: str = "1f1b",
                             overlap_p2p: bool = False) -> None:
    """Record a pipeline schedule's static geometry (trace-time hook).

    Emits one ``pipeline_schedule`` event with the tick count, the legacy
    forward-sweep bubble fraction, and the full-step unit-cost bubble
    (:func:`pipeline_cost_model` — schedule-aware, so ``"zb"`` shows its
    smaller step bubble); sets gauges ``pipeline/bubble_fraction``
    (forward sweep, back-compat) and ``pipeline/bubble_fraction_step``;
    and — when the per-tick activation size is known — accounts the
    schedule's ppermute traffic via :func:`count_collective` (forward
    ticks × bytes per step)."""
    r = _reg.get_registry()
    if r is None:
        return
    cost = pipeline_cost_model(num_microbatches, pipeline_size,
                               virtual_chunks, schedule=schedule,
                               overlap_p2p=overlap_p2p)
    ticks = cost["fwd_ticks"]
    bubble = pipeline_bubble_fraction(num_microbatches, pipeline_size,
                                      virtual_chunks)
    r.gauge("pipeline/bubble_fraction", bubble)
    r.gauge("pipeline/bubble_fraction_step", cost["bubble_fraction"])
    r.emit_event(
        "pipeline_schedule",
        num_microbatches=num_microbatches,
        pipeline_size=pipeline_size,
        virtual_chunks=virtual_chunks,
        ticks=ticks,
        bubble_fraction=round(bubble, 6),
        schedule=schedule,
        overlap_p2p=overlap_p2p,
        bubble_fraction_step=round(cost["bubble_fraction"], 6),
        bwd_dx_ticks=cost["bwd_dx_ticks"],
        bwd_dw_ticks=cost["bwd_dw_ticks"],
    )
    if tick_bytes:
        count_collective("ppermute", bytes=tick_bytes, count=ticks,
                         axis=axis)


# --- collectives -------------------------------------------------------------

def tree_bytes(tree: PyTree) -> int:
    """Static payload size of a pytree of (possibly traced) arrays; shapes
    are known at trace time even when values are tracers."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is not None and dtype is not None:
            try:
                total += int(size) * dtype.itemsize
            except TypeError:  # polymorphic / abstract size
                pass
    return total


def count_traffic(kind: str, payload: PyTree, axis_name: str, *,
                  count: int = 1) -> None:
    """The ``enabled()``-guarded :func:`count_collective` +
    :func:`tree_bytes` one-liner every instrumented collective call site
    uses (mappings, the SP layers, the collective-matmul rings, pipeline
    ``_rotate``) — one place to change if the counting contract grows."""
    if enabled():
        count_collective(kind, bytes=tree_bytes(payload), count=count,
                         axis=axis_name)


def count_collective(kind: str, *, bytes: int = 0, count: int = 1,
                     axis: str = "") -> None:
    """Counter hook for communication primitives (trace-time).

    Counts land in ``collective/<kind>_calls`` and
    ``collective/<kind>_bytes`` (tagged per mesh axis as
    ``collective/<kind>[<axis>]_*`` when ``axis`` is given). Because traced
    code runs this Python once per trace, totals are per *traced* step —
    the natural unit for a jitted training step."""
    r = _reg.get_registry()
    if r is None:
        return
    tag = f"{kind}[{axis}]" if axis else kind
    r.counter(f"collective/{tag}_calls", count)
    if bytes:
        r.counter(f"collective/{tag}_bytes", bytes * count)
