"""Aggregate a monitor JSONL stream into a step-timeline summary.

``python -m apex_tpu.monitor report events.jsonl`` prints a human summary
(tokens/s, derived MFU, overflow rate, pipeline bubble %, collective
volume); ``--json`` prints one machine-readable JSON object instead.
``report --attribution`` decomposes each served request's e2e latency
into queue/prefill/decode/spec/preempt/swap components (the
``serve_attribution`` record); ``python -m apex_tpu.monitor trace``
exports the stream as Chrome trace-event JSON (one track per rank, one
per request — chrome://tracing / Perfetto). A requested section whose
records are absent from the stream prints an explicit ``SKIP(reason)``
line, never a silent empty section.

The MFU convention is the same spec-peak one the bench artifact uses
(``BENCH_r05.json``): analytic model FLOPs per token (from the ``meta``
record) × achieved tokens/s ÷ the chip's public peak dense bf16 FLOP/s
(:data:`PEAK_FLOPS_BY_DEVICE`, which ``bench.py`` imports — one table, one
code path). The headline tokens/s uses the **best** (minimum-duration)
step, matching the bench's min-of-passes headline; the mean is reported
alongside.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterable, List, Optional

# peak dense bf16 FLOP/s per chip by device kind (public spec sheets) —
# THE spec-peak table: bench.py and the report both read it, so "mfu" means
# the same thing in BENCH_*.json and in `monitor report` output.
PEAK_FLOPS_BY_DEVICE = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def spec_peak_flops(device_kind: Optional[str]) -> Optional[float]:
    """Peak dense bf16 FLOP/s for a device kind, or None when unknown
    (CPU hosts, future chips) — callers must then omit MFU rather than
    fabricate it."""
    if device_kind is None:
        return None
    return PEAK_FLOPS_BY_DEVICE.get(device_kind)


def read_records(lines: Iterable[str]) -> List[Dict[str, Any]]:
    records = []
    for line in lines:
        line = line.strip()
        if line:
            records.append(json.loads(line))
    return records


def aggregate(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a record stream into the step-timeline summary dict.

    A file holding several runs (appended streams; each run opens with a
    ``meta`` record) aggregates the LAST run only — a stale run's faster
    steps must not leak into this run's tokens/s headline. The summary
    carries ``runs_in_file`` when earlier runs were skipped.
    """
    meta_idx = [i for i, r in enumerate(records) if r.get("kind") == "meta"]
    runs_in_file = len(meta_idx)
    if runs_in_file > 1:
        records = records[meta_idx[-1]:]
    meta: Dict[str, Any] = {}
    steps = []
    gate_records = []
    decode_records = []
    longseq_records = []
    tp_overlap_records = []
    serve_records = []
    serve_window_records = []
    pipeline_records = []
    plan_records = []
    ckpt_records = []
    spec_records = []
    tp_serve_records = []
    schedule = None
    for rec in records:
        kind = rec.get("kind")
        if kind == "meta":
            meta.update({k: v for k, v in rec.items()
                         if k not in ("schema", "kind", "t_s", "process",
                                      "rank")})
        elif kind == "step":
            steps.append(rec)
        elif kind == "gate":
            gate_records.append(rec)
        elif kind == "decode":
            decode_records.append(rec)
        elif kind == "longseq_bias":
            longseq_records.append(rec)
        elif kind == "tp_overlap":
            tp_overlap_records.append(rec)
        elif kind == "serve":
            serve_records.append(rec)
        elif kind == "serve_window":
            serve_window_records.append(rec)
        elif kind == "pipeline":
            pipeline_records.append(rec)
        elif kind == "plan":
            plan_records.append(rec)
        elif kind == "ckpt":
            ckpt_records.append(rec)
        elif kind == "spec":
            spec_records.append(rec)
        elif kind == "tp_serve":
            tp_serve_records.append(rec)
        elif kind == "event" and rec.get("name") == "pipeline_schedule":
            schedule = rec

    summary: Dict[str, Any] = {
        "num_steps": len(steps),
        "num_records": len(records),
    }
    if runs_in_file > 1:
        summary["runs_in_file"] = runs_in_file
    if meta:
        summary["meta"] = meta

    durs = [s["dur_s"] for s in steps
            if isinstance(s.get("dur_s"), (int, float)) and s["dur_s"] > 0]
    if durs:
        summary["step_time_s"] = {
            "best": min(durs),
            "mean": sum(durs) / len(durs),
            "worst": max(durs),
        }
    token_steps = [s for s in steps
                   if isinstance(s.get("tokens"), (int, float))
                   and isinstance(s.get("dur_s"), (int, float))
                   and s["dur_s"] > 0]
    if token_steps:
        best = min(token_steps, key=lambda s: s["dur_s"] / s["tokens"])
        total_tokens = sum(s["tokens"] for s in token_steps)
        total_time = sum(s["dur_s"] for s in token_steps)
        summary["tokens_per_s"] = {
            "best": best["tokens"] / best["dur_s"],
            "mean": total_tokens / total_time,
        }
        fpt = meta.get("model_flops_per_token")
        peak = spec_peak_flops(meta.get("device_kind"))
        if isinstance(fpt, (int, float)):
            flops_per_s = fpt * summary["tokens_per_s"]["best"]
            summary["model_tflops"] = flops_per_s / 1e12
            if peak:
                summary["mfu"] = flops_per_s / peak

    # overflow rate: per-step overflow counters, falling back to the
    # lifetime gauge delta across the stream
    overflows = sum(s.get("counters", {}).get("amp/overflow_steps", 0)
                    for s in steps)
    if not overflows and steps:
        totals = [s["gauges"].get("amp/skipped_steps_total")
                  for s in steps
                  if "amp/skipped_steps_total" in s.get("gauges", {})]
        if len(totals) >= 2:
            overflows = totals[-1] - totals[0]
    if steps:
        summary["overflow_rate"] = overflows / len(steps)
    scales = [s["gauges"].get("amp/loss_scale") for s in steps
              if "amp/loss_scale" in s.get("gauges", {})]
    if scales:
        summary["loss_scale_last"] = scales[-1]

    if schedule is not None:
        summary["pipeline"] = {
            "bubble_fraction": schedule.get("bubble_fraction"),
            "num_microbatches": schedule.get("num_microbatches"),
            "pipeline_size": schedule.get("pipeline_size"),
            "virtual_chunks": schedule.get("virtual_chunks"),
            "ticks": schedule.get("ticks"),
            "schedule": schedule.get("schedule"),
            "overlap_p2p": schedule.get("overlap_p2p"),
            "bubble_fraction_step": schedule.get("bubble_fraction_step"),
        }
        # per-(microbatch, stage) wall time: a chunk-tick is exactly one
        # microbatch through one (virtual) stage, so when the caller timed
        # the schedule call (monitor.timer("pipeline/fwd_bwd") around the
        # blocking fwd/bwd), total time / calls / ticks is the per-tick
        # wall estimate (forward-sweep convention; backward ticks ride in
        # the same timed window, so this upper-bounds the forward tick)
        ticks = schedule.get("ticks")
        tot_n, tot_s = 0, 0.0
        for s in steps:
            t = s.get("timers", {}).get("pipeline/fwd_bwd")
            if t:
                tot_n += t.get("count", 0)
                tot_s += t.get("total_s", 0.0)
        if ticks and tot_n:
            summary["pipeline"]["per_tick_wall_s"] = tot_s / tot_n / ticks

    # collective volume from the LAST step's lifetime totals: trace-time
    # counting runs during warm-up compilation, usually BEFORE step 0's
    # delta baseline, so summing per-step deltas would read 0. Totals are
    # per traced program (re-traces add to them), not per executed step.
    collectives: Dict[str, Dict[str, float]] = {}
    totals = steps[-1].get("counters_total", {}) if steps else {}
    if not totals:  # pre-counters_total streams: fall back to delta sums
        for s in steps:
            for name, v in s.get("counters", {}).items():
                if name.startswith("collective/"):
                    totals[name] = totals.get(name, 0) + v
    for name, v in totals.items():
        if name.startswith("collective/"):
            base, sep, field = name[len("collective/"):].rpartition("_")
            if not sep:  # a stray unsuffixed counter must not kill the CLI
                base, field = field, "calls"
            collectives.setdefault(base, {})[field] = v
    if collectives:
        summary["collectives"] = collectives

    def status_summary(recs, fields):
        # a status-carrying bench record (decode / longseq_bias): last
        # record wins (same one-run-per-stream rule the step headline
        # follows); explicit skip objects surface as a skipped-metric
        # list, mirroring the gate summary
        d = recs[-1]
        return {
            "status": d.get("status"),
            "skipped": sorted(k for k, v in d.items()
                              if isinstance(v, dict) and v.get("skipped")),
            **{k: d[k] for k in (*fields, "reason")
               if isinstance(d.get(k), (int, float, str))},
        }

    if decode_records:
        summary["decode"] = status_summary(
            decode_records, ("tokens_per_s", "prefill_ms", "spread_pct",
                             "vs_naive", "batch", "prompt_len",
                             "new_tokens"))

    if longseq_records:
        summary["longseq_bias"] = status_summary(
            longseq_records, ("tokens_per_s", "tokens_per_s_materialized",
                              "vs_materialized", "hbm_peak_mb",
                              "hbm_peak_materialized_mb", "seq"))

    if tp_overlap_records:
        summary["tp_overlap"] = status_summary(
            tp_overlap_records, ("tokens_per_s", "tokens_per_s_blocking",
                                 "vs_blocking", "tp", "batch", "seq",
                                 "spread_pct", "spread_pct_blocking"))

    if serve_records:
        summary["serve"] = status_summary(
            serve_records, ("tokens_per_s", "latency_p50_ms",
                            "latency_p99_ms", "ttft_p50_ms", "ttft_p99_ms",
                            "prefix_hit_rate", "prefix_hit_ttft_p50_ms",
                            "prefix_miss_ttft_p50_ms", "preemptions",
                            "recompute_tokens", "blocks_resident",
                            "churn_parity",
                            "occupancy_pct", "vs_single_request",
                            "requests", "slots", "block_size",
                            "blocks_high_water",
                            "admission_blocked_slots",
                            "admission_blocked_blocks", "queue_peak",
                            "serve_windows", "telemetry_overhead_pct"))
        anomaly = serve_records[-1].get("serve_anomaly")
        if isinstance(anomaly, dict):
            summary["serve"]["serve_anomaly"] = anomaly

    if serve_window_records:
        # the live-SLO window trail: count + the LAST window's view
        # (the full trail is the --serve-timeline rendering's job)
        last = serve_window_records[-1]
        summary["serve_window"] = {
            "windows": len(serve_window_records),
            **{k: last[k] for k in
               ("status", "tokens_per_s", "latency_p50_ms",
                "latency_p99_ms", "ttft_p50_ms", "queue_depth",
                "occupancy_pct", "blocks_high_water")
               if isinstance(last.get(k), (int, float, str))},
        }
        anomaly = last.get("serve_anomaly")
        if isinstance(anomaly, dict):
            summary["serve_window"]["serve_anomaly"] = anomaly

    if pipeline_records:
        summary["pipeline_bench"] = status_summary(
            pipeline_records, ("schedule", "tokens_per_s",
                               "tokens_per_s_1f1b", "vs_1f1b",
                               "bubble_pct", "bubble_pct_1f1b",
                               "bubble_pct_geometry",
                               "bubble_pct_1f1b_geometry",
                               "pipeline_size", "virtual_chunks",
                               "num_microbatches", "p2p_bytes_per_step"))

    if plan_records:
        summary["plan"] = status_summary(
            plan_records, ("chosen_describe", "predicted_step_ms",
                           "measured_step_ms",
                           "predicted_vs_measured_err_pct",
                           "confidence", "chips", "searched", "feasible",
                           "costdb_source"))
        uncal = plan_records[-1].get("uncalibrated")
        if isinstance(uncal, list):
            summary["plan"]["uncalibrated"] = uncal

    if ckpt_records:
        summary["ckpt"] = status_summary(
            ckpt_records, ("save_overhead_pct", "step_ms",
                           "step_ms_saving", "snapshot_ms", "write_ms",
                           "restore_ms", "bytes_written", "steps",
                           "saves", "save_every", "dp", "async_save",
                           "bitwise_resume_ok", "elastic_resume_ok"))

    if spec_records:
        summary["spec"] = status_summary(
            spec_records, ("tokens_per_s_request",
                           "baseline_tokens_per_s_request", "speedup",
                           "tokens_per_s_churn", "speedup_churn",
                           "acceptance_rate", "accepted_per_round",
                           "rounds", "draft_k", "drafter", "kv_dtype",
                           "kv_quant_logit_err", "greedy_parity",
                           "churn_parity", "jit_cache_ok",
                           "spread_pct"))

    if tp_serve_records:
        summary["tp_serve"] = status_summary(
            tp_serve_records, ("tp", "tokens_per_s",
                               "baseline_tokens_per_s",
                               "ttft_ms_prefill_role",
                               "ttft_ms_monolithic", "handoff_blocks",
                               "handoff_transfer_bytes",
                               "handoff_transfer_ms",
                               "digests_verified",
                               "collective_ppermute_calls",
                               "collective_ppermute_bytes",
                               "decode_steps",
                               "collective_bytes_per_step",
                               "greedy_parity", "handoff_parity",
                               "jit_cache_ok", "kv_dtype", "requests",
                               "num_blocks", "pool_mb_per_shard",
                               "pool_mb_total", "spread_pct"))

    if gate_records:
        summary["gates"] = [
            {"name": g.get("name"), "ok": g.get("ok"),
             "skipped": sorted(k for k, v in g.get("metrics", {}).items()
                               if isinstance(v, dict) and v.get("skipped"))}
            for g in gate_records
        ]
    return summary


def _anomaly_flags(anom: Dict[str, Any]) -> List[str]:
    """Human-readable flags from a ``serve_anomaly`` section (empty
    when the run was clean)."""
    flags = []
    if anom.get("straggler_steps"):
        flags.append(f"straggler x{anom['straggler_steps']}"
                     + (f" (last {anom['straggler_last_ratio']:g}x median)"
                        if isinstance(anom.get("straggler_last_ratio"),
                                      (int, float))
                        and anom["straggler_last_ratio"] else ""))
    if anom.get("queue_buildup"):
        flags.append("queue buildup")
    if anom.get("slo_burn"):
        flags.append(f"SLO burn ({anom.get('ttft_over_slo', '?')} "
                     f"first tokens over threshold)")
    if anom.get("leaked_blocks"):
        flags.append(f"LEAK {anom['leaked_blocks']} blocks")
    return flags


# --- the request-lifecycle timeline (`report --serve-timeline`) --------------

def serve_timeline(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold ``serve_event``/``serve_window`` records into the
    per-request lifecycle view: one row per request (queue wait, chunk
    count, prefill/TTFT/decode durations, blocks held, finish) plus the
    window trail. Rows are dicts so ``--json`` can carry them.

    Appended multi-run streams fold the LAST run only (the same
    run-splitting-at-``meta`` rule :func:`aggregate` applies) — rids
    restart at 0 per run, so folding across runs would cross-wire two
    runs' lifecycles into one garbage row."""
    meta_idx = [i for i, r in enumerate(records)
                if r.get("kind") == "meta"]
    if len(meta_idx) > 1:
        records = records[meta_idx[-1]:]
    per_rid: Dict[int, Dict[str, Any]] = {}
    stragglers = []
    swaps = []
    replans = []
    for rec in records:
        if rec.get("kind") != "serve_event":
            continue
        rid = rec.get("rid")
        if rid == -1:  # engine-level events (stragglers, swaps, replans)
            if rec.get("straggler"):
                stragglers.append({k: rec.get(k) for k in
                                   ("at_s", "step", "dur_ms",
                                    "ratio_to_median")})
            elif rec.get("phase") == "swap":
                swaps.append({k: rec.get(k) for k in
                              ("at_s", "step", "swap_source")})
            elif rec.get("phase") == "replan":
                replans.append({k: rec.get(k) for k in
                                ("at_s", "step", "plan_from", "plan_to",
                                 "replan_trigger", "live_knobs",
                                 "deferred_knobs")})
            continue
        row = per_rid.setdefault(rid, {"rid": rid})
        phase = rec.get("phase")
        if phase == "submit":
            row["submit_s"] = rec.get("at_s")
            row["prompt_len"] = rec.get("prompt_len")
            row["max_new_tokens"] = rec.get("max_new_tokens")
        elif phase == "admit":
            row["admit_s"] = rec.get("at_s")
            row["slot"] = rec.get("slot")
            row["queue_wait_ms"] = rec.get("queue_wait_ms")
        elif phase == "prefill_chunk":
            row["chunks"] = rec.get("chunk", 0) + 1
            row["blocks_held"] = rec.get("blocks_held")
        elif phase == "first_token":
            row["ttft_ms"] = rec.get("ttft_ms")
            row["prefill_ms"] = rec.get("prefill_ms")
            row["chunks"] = rec.get("chunks", row.get("chunks"))
            row["blocks_held"] = rec.get("blocks_held")
        elif phase == "evict":
            # preemption, not a terminal transition: the request
            # re-queues for evict-and-recompute and (usually) finishes
            # later — fold the count and the LAST evict's payload in
            row["evictions"] = row.get("evictions", 0) + 1
            row["evict_reason"] = rec.get("evict_reason")
            row["blocks_released"] = rec.get("blocks_released")
            row["requeue_pos"] = rec.get("requeue_pos")
            row["outcome"] = "evicted"  # until a finish overwrites it
        elif phase == "handoff":
            # disaggregated KV streaming: one leg per engine role; a
            # merged two-role stream folds both legs into the row
            # (same rid + trace_id on both sides by construction)
            roles = row.setdefault("handoff_roles", [])
            if rec.get("handoff_role"):
                roles.append(rec["handoff_role"])
            row["handoff_blocks"] = rec.get("blocks")
            row["handoff_bytes"] = (
                row.get("handoff_bytes", 0)
                + (rec.get("transfer_bytes") or 0))
        elif phase == "finish":
            row["finish_s"] = rec.get("at_s")
            row["tokens"] = rec.get("tokens")
            row["decode_ms"] = rec.get("decode_ms")
            row["total_ms"] = rec.get("total_ms")
            row["outcome"] = phase
    requests = sorted(per_rid.values(),
                      key=lambda r: r.get("submit_s") or 0.0)
    windows = [
        {k: rec.get(k) for k in
         ("at_s", "t_s", "window_s", "tokens", "tokens_per_s",
          "latency_p50_ms", "latency_p99_ms", "ttft_p50_ms",
          "queue_depth", "active_slots", "occupancy_pct", "blocks_live",
          "blocks_resident", "prefix_hit_rate", "preemptions",
          "recompute_tokens", "serve_anomaly")}
        for rec in records if rec.get("kind") == "serve_window"
    ]
    return {"requests": requests, "windows": windows,
            "stragglers": stragglers, "swaps": swaps, "replans": replans}


# --- per-request latency attribution (`report --attribution`) ----------------

_ATTRIBUTION_SKIP_REASON = (
    "stream carries no serve_event records — serve with a ServeTelemetry "
    "attached and the monitor enabled")


def serve_attribution_record(records: List[Dict[str, Any]]
                             ) -> Optional[Dict[str, Any]]:
    """The schema-validated ``serve_attribution`` record for a stream:
    per-request e2e latency decomposed into the
    :data:`~apex_tpu.monitor.trace.ATTR_COMPONENTS` partition. Returns
    ``None`` when the stream carries no ``serve_event`` records (the
    caller prints the explicit SKIP line). Appended multi-run streams
    fold the LAST run only (the :func:`serve_timeline` rule — rids
    restart per run). The record's status mirrors the stream's
    ``serve`` record when one is present: a SKIP sweep prices nothing,
    and the report must not promote its numbers."""
    meta_idx = [i for i, r in enumerate(records)
                if r.get("kind") == "meta"]
    if len(meta_idx) > 1:
        records = records[meta_idx[-1]:]
    if not any(r.get("kind") == "serve_event" for r in records):
        return None
    # lazy: the plain report never pays for the trace/registry layers
    from apex_tpu.monitor import registry as registry_lib
    from apex_tpu.monitor import trace as trace_lib
    from apex_tpu.monitor.schema import validate as validate_record

    fields = trace_lib.serve_attribution(records, per_request=True)
    serves = [r for r in records if r.get("kind") == "serve"]
    status = serves[-1].get("status") if serves else None
    reason = serves[-1].get("reason") if serves else None
    if status not in ("OK", "SKIP"):
        status = "SKIP"
        reason = ("attribution computed post-hoc by `monitor report` "
                  "from the lifecycle trail; the stream carries no "
                  "serve record to inherit a measurement status from")
    if status == "SKIP":
        fields.setdefault("reason", reason or "serve record was SKIP")
    record = registry_lib.MetricsRegistry().emit_serve_attribution(
        status, **fields)
    errors = validate_record(record)
    if errors:  # a bug in this module, never a user input problem
        raise ValueError(
            f"serve_attribution record failed validation: {errors}")
    return record


def format_attribution(record: Dict[str, Any]) -> str:
    """Render :func:`serve_attribution_record` as the terminal table:
    one totals line, then one row per finished request showing its
    NONZERO components (every request's components sum to its measured
    e2e latency up to rounding — ``residual`` is the gap)."""
    lines = []
    mr = record.get("max_residual_pct")
    lines.append(
        f"serve attribution: {record.get('requests', 0)} requests"
        + (f", {record['unattributed']} unattributed"
           if record.get("unattributed") else "")
        + f"  components {record.get('components_ms_total', 0.0):.1f} ms"
          f" vs e2e {record.get('e2e_ms_total', 0.0):.1f} ms"
        + (f"  (max residual {mr:.2f}%)"
           if isinstance(mr, (int, float)) else "")
        + (f"  [SKIP({record.get('reason', '?')})]"
           if record.get("status") == "SKIP" else ""))
    comp = record.get("components", {})
    totals = [f"{k[:-3]} {v:.1f}" for k, v in comp.items()
              if isinstance(v, (int, float)) and v > 0]
    if totals:
        lines.append("  totals (ms): " + "  ".join(totals))
    for r in record.get("per_request", []):
        parts = [f"{k[:-3]} {r[k]:.1f}" for k in comp
                 if isinstance(r.get(k), (int, float)) and r[k] > 0]
        lines.append(
            f"  rid {r['rid']:>4}"
            + (f" [{r['trace_id']}]" if r.get("trace_id") else "")
            + f"  e2e {r.get('e2e_ms', 0.0):.1f}ms = "
            + (" + ".join(parts) if parts else "0")
            + (f"  (residual {r['residual_pct']:.2f}%)"
               if isinstance(r.get("residual_pct"), (int, float))
               else "")
            + (f"  [evict x{r['evictions']}]" if r.get("evictions")
               else "")
            + (f"  [{r['spec_rounds']} spec rounds]"
               if r.get("spec_rounds") else ""))
    return "\n".join(lines)


def _ms(v, nd=1) -> str:
    return f"{v:.{nd}f}ms" if isinstance(v, (int, float)) else "-"


def format_serve_timeline(timeline: Dict[str, Any]) -> str:
    """Render :func:`serve_timeline` rows as the terminal table."""
    lines = []
    reqs = timeline["requests"]
    lines.append(f"serve timeline: {len(reqs)} requests, "
                 f"{len(timeline['windows'])} windows, "
                 f"{len(timeline['stragglers'])} straggler steps")
    def _n(r, key):
        # event payload fields land as rec.get(...) and may be None
        v = r.get(key)
        return v if isinstance(v, (int, float)) else "-"

    for r in reqs:
        line = (
            f"  rid {r['rid']:>4}  "
            f"queue {_ms(r.get('queue_wait_ms'))}  "
            f"prefill {_ms(r.get('prefill_ms'))}"
            f"/{_n(r, 'chunks')}ch  "
            f"ttft {_ms(r.get('ttft_ms'))}  "
            f"decode {_ms(r.get('decode_ms'))}"
            f"/{_n(r, 'tokens')}tok  "
            f"blocks {_n(r, 'blocks_held')}  "
            f"{r.get('outcome') or 'in-flight'}")
        if r.get("evictions"):
            # the reserved preemption transition, rendered not dropped:
            # count, reason, blocks released, re-queue position
            line += (f"  [evict x{r['evictions']}: "
                     f"{r.get('evict_reason') or '?'}, "
                     f"{_n(r, 'blocks_released')} blk released, "
                     f"requeued at {_n(r, 'requeue_pos')}]")
        if r.get("handoff_roles"):
            # the disaggregated prefill→decode leg(s) this stream saw
            line += (f"  [handoff {'+'.join(r['handoff_roles'])}: "
                     f"{_n(r, 'handoff_blocks')} blk, "
                     f"{_n(r, 'handoff_bytes')} B]")
        lines.append(line)
    def _num(w, *keys, default="-"):
        # serve_timeline materializes every window key (absent -> None),
        # so dict-get defaults never fire — coalesce None explicitly
        for k in keys:
            v = w.get(k)
            if isinstance(v, (int, float)):
                return v
        return default

    for w in timeline["windows"]:
        anom = w.get("serve_anomaly") or {}
        flags = _anomaly_flags(anom) if isinstance(anom, dict) else []
        tps = w.get("tokens_per_s")
        hr = w.get("prefix_hit_rate")
        # at_s is the serve clock (same base as the request rows);
        # pre-at_s streams fall back to the registry clock
        w_at = _num(w, "at_s", "t_s", default=None)
        lines.append(
            "  window "
            + (f"+{w_at:.2f}s  " if w_at is not None else "")
            + (f"{tps:.1f} tok/s  " if isinstance(tps, (int, float))
               else "")
            + f"p50/p99 {_ms(w.get('latency_p50_ms'), 2)}/"
              f"{_ms(w.get('latency_p99_ms'), 2)}  "
            + f"queue {_num(w, 'queue_depth')}  "
            + f"occ {_num(w, 'occupancy_pct')}%"
            + (f"  hit {100.0 * hr:.0f}%"
               if isinstance(hr, (int, float)) else "")
            + (f"  evictions {w['preemptions']}"
               if isinstance(w.get("preemptions"), int)
               and w["preemptions"] else "")
            + ("  [" + ", ".join(flags) + "]" if flags else ""))
    for s in timeline["stragglers"]:
        lines.append(f"  straggler step {s.get('step')}: "
                     f"{_ms(s.get('dur_ms'), 2)} "
                     f"({s.get('ratio_to_median', '?')}x rolling median)")
    for s in timeline.get("swaps", []):
        src = s.get("swap_source")
        lines.append(f"  swap at step {s.get('step')}"
                     + (f" from {src}" if src else "")
                     + ": weights hot-swapped (contents-only; in-flight "
                       "streams kept)")
    for s in timeline.get("replans", []):
        deferred = s.get("deferred_knobs") or []
        lines.append(f"  replan at step {s.get('step')}: "
                     f"{s.get('plan_from')} -> {s.get('plan_to')} "
                     f"({s.get('replan_trigger')}; live knobs applied"
                     + (", deferred: " + ", ".join(deferred)
                        if deferred else "")
                     + ")")
    return "\n".join(lines)


def render(summary: Dict[str, Any]) -> str:
    """Human-readable step-timeline summary."""
    lines = [f"monitor report: {summary['num_records']} records, "
             f"{summary['num_steps']} steps"]
    st = summary.get("step_time_s")
    if st:
        lines.append(f"  step time   best {st['best']*1e3:.2f} ms   "
                     f"mean {st['mean']*1e3:.2f} ms   "
                     f"worst {st['worst']*1e3:.2f} ms")
    tps = summary.get("tokens_per_s")
    if tps:
        lines.append(f"  tokens/s    best {tps['best']:.1f}   "
                     f"mean {tps['mean']:.1f}")
    if "mfu" in summary:
        lines.append(f"  mfu         {summary['mfu']:.4f}  "
                     f"(model {summary['model_tflops']:.2f} TFLOP/s vs "
                     f"{summary['meta'].get('device_kind')} spec peak)")
    elif "model_tflops" in summary:
        lines.append(f"  model flops {summary['model_tflops']:.2f} TFLOP/s "
                     f"(no spec peak for this device; MFU omitted)")
    if "overflow_rate" in summary:
        lines.append(f"  overflow    {summary['overflow_rate']:.4f} "
                     f"skipped steps/step"
                     + (f", loss scale now {summary['loss_scale_last']:g}"
                        if "loss_scale_last" in summary else ""))
    pipe = summary.get("pipeline")
    if pipe and pipe.get("bubble_fraction") is not None:
        sched = pipe.get("schedule")
        step_b = pipe.get("bubble_fraction_step")
        lines.append(f"  pipeline    bubble {100*pipe['bubble_fraction']:.2f}%"
                     f"  (M={pipe.get('num_microbatches')} "
                     f"S={pipe.get('pipeline_size')} "
                     f"v={pipe.get('virtual_chunks')}"
                     + (f" sched={sched}" if sched else "")
                     + (f" step-bubble {100*step_b:.2f}%"
                        if isinstance(step_b, (int, float)) else "")
                     + ")")
        if pipe.get("per_tick_wall_s") is not None:
            lines.append(f"  pipeline    per-(microbatch,stage) tick "
                         f"{pipe['per_tick_wall_s']*1e3:.3f} ms wall")
    for name, fields in sorted(summary.get("collectives", {}).items()):
        calls = fields.get("calls", 0)
        nbytes = fields.get("bytes", 0)
        lines.append(f"  collective  {name}: {calls:g} calls"
                     + (f", {nbytes/1e6:.2f} MB" if nbytes else "")
                     + "  (per traced program)")
    dec = summary.get("decode")
    if dec:
        if dec.get("status") == "SKIP":
            lines.append(f"  decode      SKIP({dec.get('reason', '?')})")
        else:
            parts = []
            if isinstance(dec.get("tokens_per_s"), (int, float)):
                parts.append(f"{dec['tokens_per_s']:.1f} tok/s/chip")
            if isinstance(dec.get("prefill_ms"), (int, float)):
                parts.append(f"prefill {dec['prefill_ms']:.2f} ms")
            if isinstance(dec.get("vs_naive"), (int, float)):
                parts.append(f"{dec['vs_naive']:.2f}x vs naive recompute")
            if dec.get("skipped"):
                parts.append("skipped: " + ", ".join(dec["skipped"]))
            lines.append("  decode      " + "   ".join(parts))
    lsb = summary.get("longseq_bias")
    if lsb:
        if lsb.get("status") == "SKIP":
            lines.append(
                f"  longseq-bias SKIP({lsb.get('reason', '?')})")
        else:
            parts = []
            if isinstance(lsb.get("tokens_per_s"), (int, float)):
                parts.append(f"{lsb['tokens_per_s']:.1f} tok/s bucketed")
            if isinstance(lsb.get("vs_materialized"), (int, float)):
                parts.append(f"{lsb['vs_materialized']:.2f}x vs "
                             f"materialized")
            if isinstance(lsb.get("hbm_peak_mb"), (int, float)):
                parts.append(f"HBM peak {lsb['hbm_peak_mb']:.0f} MB")
            if lsb.get("skipped"):
                parts.append("skipped: " + ", ".join(lsb["skipped"]))
            lines.append("  longseq-bias " + "   ".join(parts))
    srv = summary.get("serve")
    if srv:
        if srv.get("status") == "SKIP":
            lines.append(f"  serve       SKIP({srv.get('reason', '?')})")
        else:
            parts = []
            if isinstance(srv.get("tokens_per_s"), (int, float)):
                parts.append(f"{srv['tokens_per_s']:.1f} tok/s under churn")
            if isinstance(srv.get("latency_p50_ms"), (int, float)) and \
                    isinstance(srv.get("latency_p99_ms"), (int, float)):
                parts.append(f"p50/p99 {srv['latency_p50_ms']:.2f}/"
                             f"{srv['latency_p99_ms']:.2f} ms/token")
            if isinstance(srv.get("ttft_p50_ms"), (int, float)):
                parts.append(f"ttft p50 {srv['ttft_p50_ms']:.2f} ms")
            if isinstance(srv.get("occupancy_pct"), (int, float)):
                parts.append(f"occ {srv['occupancy_pct']:.0f}%")
            if srv.get("skipped"):
                parts.append("skipped: " + ", ".join(srv["skipped"]))
            lines.append("  serve       " + "   ".join(parts))
        anom = srv.get("serve_anomaly")
        if isinstance(anom, dict):
            flags = _anomaly_flags(anom)
            lines.append("  serve       anomalies: "
                         + (", ".join(flags) if flags else "none"))
    swin = summary.get("serve_window")
    if swin:
        parts = [f"{swin['windows']} windows"]
        if isinstance(swin.get("tokens_per_s"), (int, float)):
            parts.append(f"last {swin['tokens_per_s']:.1f} tok/s")
        if isinstance(swin.get("queue_depth"), (int, float)):
            parts.append(f"queue {swin['queue_depth']:g}")
        if isinstance(swin.get("occupancy_pct"), (int, float)):
            parts.append(f"occ {swin['occupancy_pct']:.0f}%")
        anom = swin.get("serve_anomaly")
        if isinstance(anom, dict):
            flags = _anomaly_flags(anom)
            if flags:
                parts.append("anomalies: " + ", ".join(flags))
        lines.append("  serve-win   " + "   ".join(parts))
    pb = summary.get("pipeline_bench")
    if pb:
        if pb.get("status") == "SKIP":
            lines.append(f"  pipeline-bench SKIP({pb.get('reason', '?')})")
        else:
            parts = []
            if pb.get("schedule"):
                parts.append(f"{pb['schedule']}")
            if isinstance(pb.get("tokens_per_s"), (int, float)):
                parts.append(f"{pb['tokens_per_s']:.1f} tok/s")
            if isinstance(pb.get("vs_1f1b"), (int, float)):
                parts.append(f"{pb['vs_1f1b']:.2f}x vs 1f1b")
            if isinstance(pb.get("bubble_pct"), (int, float)):
                parts.append(f"bubble {pb['bubble_pct']:.1f}%")
            elif isinstance(pb.get("bubble_pct_geometry"), (int, float)):
                parts.append(
                    f"bubble {pb['bubble_pct_geometry']:.1f}% (geometry)")
            if isinstance(pb.get("p2p_bytes_per_step"), (int, float)):
                parts.append(f"p2p {pb['p2p_bytes_per_step']/1e6:.2f} MB/step")
            if pb.get("skipped"):
                parts.append("skipped: " + ", ".join(pb["skipped"]))
            lines.append("  pipeline-bench " + "   ".join(parts))
    tpo = summary.get("tp_overlap")
    if tpo:
        if tpo.get("status") == "SKIP":
            lines.append(f"  tp-overlap  SKIP({tpo.get('reason', '?')})")
        else:
            parts = []
            if isinstance(tpo.get("tokens_per_s"), (int, float)):
                parts.append(f"{tpo['tokens_per_s']:.1f} tok/s overlapped")
            if isinstance(tpo.get("vs_blocking"), (int, float)):
                parts.append(f"{tpo['vs_blocking']:.2f}x vs blocking")
            if isinstance(tpo.get("tp"), (int, float)):
                parts.append(f"tp={tpo['tp']:g}")
            if tpo.get("skipped"):
                parts.append("skipped: " + ", ".join(tpo["skipped"]))
            lines.append("  tp-overlap  " + "   ".join(parts))
    spc = summary.get("spec")
    if spc:
        if spc.get("status") == "SKIP":
            lines.append(f"  spec        SKIP({spc.get('reason', '?')})")
        else:
            parts = []
            if isinstance(spc.get("tokens_per_s_request"), (int, float)):
                parts.append(
                    f"{spc['tokens_per_s_request']:.1f} tok/s/request")
            if isinstance(spc.get("speedup"), (int, float)):
                parts.append(f"{spc['speedup']:.2f}x vs non-spec")
            if isinstance(spc.get("acceptance_rate"), (int, float)):
                parts.append(
                    f"accept {100 * spc['acceptance_rate']:.0f}%"
                    + (f" (k={spc['draft_k']:g})"
                       if isinstance(spc.get("draft_k"), (int, float))
                       else ""))
            if spc.get("drafter"):
                parts.append(f"drafter {spc['drafter']}")
            if isinstance(spc.get("kv_quant_logit_err"), (int, float)):
                parts.append(
                    f"int8-KV |Δlogit| {spc['kv_quant_logit_err']:.3g}")
            if spc.get("skipped"):
                parts.append("skipped: " + ", ".join(spc["skipped"]))
            lines.append("  spec        " + "   ".join(parts))
    tps = summary.get("tp_serve")
    if tps:
        if tps.get("status") == "SKIP":
            lines.append(f"  tp-serve    SKIP({tps.get('reason', '?')})")
        else:
            parts = []
            if isinstance(tps.get("tokens_per_s"), (int, float)):
                parts.append(f"{tps['tokens_per_s']:.1f} tok/s")
            if isinstance(tps.get("tp"), (int, float)):
                parts.append(f"tp={tps['tp']:g}")
            if isinstance(tps.get("pool_mb_per_shard"), (int, float)):
                parts.append(
                    f"pool {tps['pool_mb_per_shard']:.1f} MB/shard")
            if isinstance(tps.get("collective_bytes_per_step"),
                          (int, float)):
                parts.append(
                    f"{tps['collective_bytes_per_step'] / 1024:.1f} "
                    f"KiB coll/step")
            if isinstance(tps.get("handoff_transfer_bytes"),
                          (int, float)):
                hand = (f"handoff {tps['handoff_transfer_bytes']} B"
                        + (f"/{tps['handoff_blocks']:g} blk"
                           if isinstance(tps.get("handoff_blocks"),
                                         (int, float)) else ""))
                if isinstance(tps.get("handoff_transfer_ms"),
                              (int, float)):
                    hand += f" in {tps['handoff_transfer_ms']:.1f}ms"
                parts.append(hand)
            for flag in ("greedy_parity", "handoff_parity"):
                if tps.get(flag) is False:
                    parts.append(f"{flag.replace('_', ' ')} BROKEN")
            if tps.get("skipped"):
                parts.append("skipped: " + ", ".join(tps["skipped"]))
            lines.append("  tp-serve    " + "   ".join(parts))
    pl = summary.get("plan")
    if pl:
        parts = []
        if pl.get("chosen_describe"):
            parts.append(f"chose {pl['chosen_describe']}")
        if isinstance(pl.get("predicted_step_ms"), (int, float)):
            parts.append(f"pred {pl['predicted_step_ms']:.3f} ms")
        if isinstance(pl.get("measured_step_ms"), (int, float)):
            parts.append(f"meas {pl['measured_step_ms']:.3f} ms")
        if isinstance(pl.get("predicted_vs_measured_err_pct"),
                      (int, float)):
            parts.append(f"err {pl['predicted_vs_measured_err_pct']:.1f}%")
        if isinstance(pl.get("feasible"), int):
            parts.append(f"{pl['feasible']}/{pl.get('searched', '?')} "
                         f"feasible")
        if pl.get("confidence"):
            parts.append(pl["confidence"])
        if pl.get("uncalibrated"):
            parts.append("uncalibrated: " + ", ".join(pl["uncalibrated"]))
        if pl.get("status") == "SKIP":
            parts.append(f"SKIP({pl.get('reason', '?')})")
        lines.append("  plan        " + "   ".join(parts))
    ck = summary.get("ckpt")
    if ck:
        if ck.get("status") == "SKIP":
            lines.append(f"  ckpt        SKIP({ck.get('reason', '?')})")
        else:
            parts = []
            if isinstance(ck.get("save_overhead_pct"), (int, float)):
                parts.append(
                    f"save overhead {ck['save_overhead_pct']:.2f}%/step")
            if isinstance(ck.get("snapshot_ms"), (int, float)):
                parts.append(f"snapshot {ck['snapshot_ms']:.2f} ms")
            if isinstance(ck.get("write_ms"), (int, float)):
                parts.append(f"write {ck['write_ms']:.2f} ms (async)")
            if isinstance(ck.get("bytes_written"), (int, float)):
                parts.append(f"{ck['bytes_written']/1e6:.2f} MB")
            if ck.get("bitwise_resume_ok") is True:
                parts.append("bitwise-resume ok")
            if ck.get("elastic_resume_ok") is True:
                parts.append("elastic ok")
            if ck.get("skipped"):
                parts.append("skipped: " + ", ".join(ck["skipped"]))
            lines.append("  ckpt        " + "   ".join(parts))
    for gate in summary.get("gates", []):
        skipped = (", skipped: " + ", ".join(gate["skipped"])
                   if gate["skipped"] else "")
        lines.append(f"  gate        {gate['name']}: "
                     f"{'OK' if gate['ok'] else 'FAILED'}{skipped}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.monitor",
        description="apex_tpu telemetry tools")
    sub = parser.add_subparsers(dest="command", required=True)
    rep = sub.add_parser("report", help="summarize a monitor JSONL stream")
    rep.add_argument("path", help="events.jsonl produced with monitoring on")
    rep.add_argument("--json", action="store_true",
                     help="print the summary as one JSON object")
    rep.add_argument("--anatomy", action="store_true",
                     help="per-step anatomy (% compute / collective-exposed"
                          " / bubble / host gap per device) from the span "
                          "stream joined with a jax.profiler trace")
    rep.add_argument("--trace", metavar="LOGDIR",
                     help="profiler log dir to join spans against "
                          "(required with --anatomy)")
    rep.add_argument("--serve-timeline", action="store_true",
                     help="per-request serving lifecycle (serve_event "
                          "records) + the serve_window SLO trail")
    rep.add_argument("--attribution", action="store_true",
                     help="per-request e2e latency decomposition (queue/"
                          "prefill/decode/spec/preempt/swap components "
                          "from the serve_event trail) as a validated "
                          "serve_attribution record")
    trc = sub.add_parser(
        "trace", help="export the stream as Chrome trace-event JSON "
                      "(chrome://tracing / Perfetto): one track per "
                      "rank, one per request")
    trc.add_argument("path", help="events.jsonl produced with monitoring "
                                  "on")
    trc.add_argument("--out", default=None,
                     help="output path (default: <path>.trace.json; a "
                          ".gz suffix gzips — both viewers load it)")
    trc.add_argument("--device-trace", metavar="LOGDIR", default=None,
                     help="jax.profiler log dir whose device events ride "
                          "along on offset process ids (the span "
                          "scope-prefix join)")
    args = parser.parse_args(argv)

    with open(args.path) as fh:
        records = read_records(fh)
    if args.command == "trace":
        return _trace_export_main(args, records)
    summary = aggregate(records)

    timeline = None
    if args.serve_timeline:
        timeline = serve_timeline(records)
        if not (timeline["requests"] or timeline["windows"]):
            print("error: stream carries no serve_event/serve_window "
                  "records (serve with a ServeTelemetry attached and "
                  "the monitor enabled)", file=sys.stderr)
            return 2
        summary["serve_timeline"] = timeline

    attribution = None
    attribution_skip = None
    if args.attribution:
        attribution = serve_attribution_record(records)
        if attribution is None:
            # the requested-section-absent contract: an explicit
            # SKIP(reason) line / stanza, never a silent empty section
            attribution_skip = _ATTRIBUTION_SKIP_REASON
            summary["serve_attribution"] = {
                "status": "SKIP", "reason": attribution_skip}
        else:
            summary["serve_attribution"] = attribution

    anatomy_rows = None
    if args.anatomy:
        if not args.trace:
            parser.error("--anatomy needs --trace LOGDIR (the directory "
                         "passed to jax.profiler.start_trace)")
        # the join lives in prof (it reads chrome traces); imported lazily
        # so the plain report never pays for it
        from apex_tpu.prof import trace_reader

        spans = [r for r in records if r.get("kind") == "span"]
        try:
            events = trace_reader.read_trace(args.trace)
        except FileNotFoundError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        anatomy_rows = trace_reader.step_anatomy(spans, events)
        summary["anatomy"] = anatomy_rows

    if args.json:
        print(json.dumps(summary))
    else:
        print(render(summary))
        if timeline is not None:
            print(format_serve_timeline(timeline))
        if attribution is not None:
            print(format_attribution(attribution))
        elif attribution_skip is not None:
            print(f"serve attribution: SKIP({attribution_skip})")
        if anatomy_rows is not None:
            from apex_tpu.prof.trace_reader import format_anatomy

            print("step anatomy (% of step wall):")
            print(format_anatomy(anatomy_rows))
    return 0


def _trace_export_main(args, records: List[Dict[str, Any]]) -> int:
    """``python -m apex_tpu.monitor trace events.jsonl [--out ...]`` —
    merge the stream (plus an optional profiler device trace) into one
    Chrome trace-event JSON file."""
    from apex_tpu.monitor import trace as trace_lib

    device_events = None
    if args.device_trace:
        from apex_tpu.prof import trace_reader
        try:
            device_events = trace_reader.read_trace(args.device_trace)
        except FileNotFoundError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    doc = trace_lib.chrome_trace(records, device_events=device_events)
    slices = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    if not slices:
        # nothing written: an empty export silently "succeeding" would
        # hide that the run never emitted span/serve_event records
        print("trace export: SKIP(stream carries no span/serve_event "
              "records to export — run with the monitor enabled, e.g. "
              "a serve with ServeTelemetry attached)")
        return 2
    out = args.out or (args.path + ".trace.json")
    trace_lib.write_chrome_trace(out, records, doc=doc)

    def _tracks(prefix: str) -> int:
        return sum(
            1 for e in doc["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
            and str(e.get("args", {}).get("name", "")).startswith(prefix))

    print(f"wrote {len(slices)} slices ({_tracks('req ')} request "
          f"tracks, {_tracks('rank ')} rank tracks) to {out}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
