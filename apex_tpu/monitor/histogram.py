"""Bounded-memory streaming latency histograms.

``bench.py --serve`` (PR 7) kept EVERY per-token latency sample in host
lists and ran ``np.percentile`` once at the end — O(tokens) memory that
grows without bound on a long-running engine, and no way to read a
quantile *while* the run degrades. :class:`StreamingHistogram` replaces
the sample lists with log-spaced fixed buckets:

* **Bounded memory by construction.** The bucket array is sized at
  construction (``decades x bins_per_decade + 2`` slots, ~700 ints at
  the defaults) and never grows — a million samples cost the same bytes
  as ten.
* **Bounded relative error.** Log-spaced edges make every bucket the
  same *relative* width (``10^(1/bins_per_decade) - 1`` — ~3.7% at the
  default 64 bins/decade), so a quantile read is off by at most one
  bucket width at its own magnitude, at p50 and p99.99 alike. The
  parity contract (quantiles match the removed sample-list math within
  one bucket width on a fixed trace) is pinned by
  ``tests/test_histogram.py``.
* **Mergeable.** Two histograms with the same geometry fold together
  (per-rank telemetry folds into one report).

Exact ``min``/``max``/``count``/``sum`` ride along, so the extreme
quantiles (q=0, q=1) and the mean are exact, not bucketed. Values are
unit-agnostic positive floats (the serving telemetry feeds
milliseconds); non-positive values clamp into the underflow bucket.
All plain host Python — never traced, no numpy/jax dependency.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

__all__ = ["StreamingHistogram"]


class StreamingHistogram:
    """Log-spaced fixed-bucket histogram over positive values.

    ``lo``/``hi`` bound the resolved range (values outside clamp into
    underflow/overflow buckets, still counted — quantiles there return
    the exact tracked min/max); ``bins_per_decade`` sets the relative
    resolution. The defaults resolve 0.1 us .. ~28 h in milliseconds at
    ~3.7% relative width.
    """

    def __init__(self, lo: float = 1e-4, hi: float = 1e8,
                 bins_per_decade: int = 64):
        if not (lo > 0 and hi > lo):
            raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
        if bins_per_decade < 1:
            raise ValueError(
                f"bins_per_decade must be >= 1, got {bins_per_decade}")
        self.lo = float(lo)
        self.hi = float(hi)
        self.bins_per_decade = int(bins_per_decade)
        self._log_lo = math.log10(self.lo)
        # bucket i (0-based) covers [lo*g^i, lo*g^(i+1)) with
        # g = 10^(1/bins_per_decade); + underflow (index -1) + overflow
        self.num_buckets = int(math.ceil(
            (math.log10(self.hi) - self._log_lo) * self.bins_per_decade))
        self._counts: List[int] = [0] * (self.num_buckets + 2)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    # --- geometry ------------------------------------------------------------

    def _index(self, value: float) -> int:
        """Slot index in the counts array (0 = underflow)."""
        if value < self.lo:
            return 0
        if value >= self.hi:
            return self.num_buckets + 1
        i = int((math.log10(value) - self._log_lo) * self.bins_per_decade)
        # float round-off at exact edges: keep inside the resolved range
        return min(max(i, 0), self.num_buckets - 1) + 1

    def bucket_edges(self, value: float) -> tuple:
        """The ``[lower, upper)`` edges of the bucket holding ``value``
        (underflow → ``(0, lo)``; overflow → ``(hi, inf)``)."""
        slot = self._index(value)
        if slot == 0:
            return (0.0, self.lo)
        if slot == self.num_buckets + 1:
            return (self.hi, math.inf)
        i = slot - 1
        scale = 1.0 / self.bins_per_decade
        return (10.0 ** (self._log_lo + i * scale),
                10.0 ** (self._log_lo + (i + 1) * scale))

    def bucket_width(self, value: float) -> float:
        """Absolute width of the bucket holding ``value`` — the parity
        tolerance of a quantile read at that magnitude."""
        low, high = self.bucket_edges(value)
        if not math.isfinite(high):
            return max((self.max or self.hi) - self.hi, 0.0) or self.hi
        return high - low

    # --- ingest --------------------------------------------------------------

    def add(self, value: float, n: int = 1) -> None:
        """Fold ``n`` observations of ``value`` in (O(1), no allocation)."""
        value = float(value)
        if math.isnan(value):
            raise ValueError("cannot add nan to a histogram")
        n = int(n)
        if n < 1:
            return
        self._counts[self._index(value)] += n
        self.count += n
        self.sum += value * n
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value

    def reset(self) -> None:
        """Zero every counter, keeping the geometry — the
        sliding-window consumers reset at each window edge inside a
        hot loop (one C-level list fill; no object reconstruction)."""
        self._counts = [0] * len(self._counts)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def merge(self, other: "StreamingHistogram") -> None:
        """Fold ``other`` into self (same geometry required)."""
        if (other.lo, other.hi, other.bins_per_decade) != \
                (self.lo, self.hi, self.bins_per_decade):
            raise ValueError(
                "histogram geometries differ: "
                f"({self.lo}, {self.hi}, {self.bins_per_decade}) vs "
                f"({other.lo}, {other.hi}, {other.bins_per_decade})")
        for i, c in enumerate(other._counts):
            self._counts[i] += c
        self.count += other.count
        self.sum += other.sum
        for v in (other.min, other.max):
            if v is not None:
                if self.min is None or v < self.min:
                    self.min = v
                if self.max is None or v > self.max:
                    self.max = v

    # --- reads ---------------------------------------------------------------

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile (q in [0, 1]) — ``None`` on an empty histogram.

        Returns the geometric midpoint of the bucket holding the
        order statistic at rank ``floor(q * (count - 1))`` (the lower
        bound of ``np.percentile``'s linear interpolation), clamped to
        the exact tracked ``[min, max]`` — so q=0 / q=1 are exact and
        interior quantiles are within one bucket width of the
        sample-list answer.
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.count == 0:
            return None
        rank = int(math.floor(q * (self.count - 1)))  # 0-based
        cum = 0
        slot = 0
        for slot, c in enumerate(self._counts):
            cum += c
            if cum > rank:
                break
        if slot == 0:
            value = self.min
        elif slot == self.num_buckets + 1:
            value = self.max
        else:
            low, high = self.bucket_edges(
                10.0 ** (self._log_lo + (slot - 0.5) / self.bins_per_decade))
            value = math.sqrt(low * high)  # geometric midpoint
        return min(max(value, self.min), self.max)

    def percentile(self, p: float) -> Optional[float]:
        """``quantile(p / 100)`` — the ``np.percentile`` calling
        convention the sample-list math used."""
        return self.quantile(p / 100.0)

    def summary(self, prefix: str = "") -> Dict[str, float]:
        """The standard quantile block for a telemetry record
        (``{prefix}p50`` / ``p90`` / ``p99`` / ``mean`` / ``max`` /
        ``count``); empty dict when no samples landed yet — callers
        encode that as an explicit skip, never nan."""
        if self.count == 0:
            return {}
        return {
            f"{prefix}p50": self.quantile(0.5),
            f"{prefix}p90": self.quantile(0.9),
            f"{prefix}p99": self.quantile(0.99),
            f"{prefix}mean": self.mean,
            f"{prefix}max": self.max,
            f"{prefix}count": self.count,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"StreamingHistogram(count={self.count}, min={self.min}, "
                f"max={self.max}, buckets={self.num_buckets})")
