"""Real-trace post-processor: ingest a ``jax.profiler`` run and emit
per-op / per-family time+cost tables.

The pyprof pipeline analog (``apex/pyprof/parse/{db,nvvp,kernel}.py`` reads
the nvprof SQLite DB and correlates kernels with NVTX ranges;
``apex/pyprof/prof/__main__.py`` then prints per-kernel FLOPs/bytes). Here
the source of truth is the ``trace.json.gz`` chrome trace that
``jax.profiler.stop_trace`` writes under ``<logdir>/plugins/profile/<run>/``:

* device rows (process ``/device:TPU:N``, thread ``XLA Ops``) carry one
  complete-event per executed HLO, named with the full ``named_scope`` path
  — the correlation step the reference needs a database join for comes free;
* :func:`op_records` turns them into compact records, folding multiple
  executions of the same op;
* :func:`summarize` ranks time sinks and aggregates op families via
  :func:`apex_tpu.prof.analyzer.analyze_ops` (whose hot path is the native
  C++ aggregator ``csrc/trace_analyzer.cpp`` for large traces).

CLI: ``python -m apex_tpu.prof <logdir> [--top N]``.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple


# the arg keys the analysis pipeline consumes; both trace parsers (the
# native csrc/trace_parser.cpp and the Python fallback) restrict
# TraceEvent.args to these so behavior doesn't depend on which is built
WANTED_ARGS = frozenset((
    "model_flops", "bytes_accessed", "raw_bytes_accessed", "hlo_category",
    "source", "flops", "bytes", "bytes accessed",
))


@dataclasses.dataclass
class TraceEvent:
    name: str
    start_us: float
    dur_us: float
    device: str       # e.g. "/device:TPU:0"
    track: str        # e.g. "XLA Ops"
    args: dict        # WANTED_ARGS subset of the raw event args


def _latest_run_dir(log_dir: str) -> str:
    pattern = os.path.join(log_dir, "plugins", "profile", "*")
    runs = sorted(glob.glob(pattern))
    if not runs:
        raise FileNotFoundError(
            f"no profiler runs under {log_dir!r} (searched {pattern!r}; "
            "pass the directory given to jax.profiler.start_trace)")
    return runs[-1]


def _trace_file(run_dir: str) -> str:
    pattern = os.path.join(run_dir, "*.trace.json.gz")
    files = glob.glob(pattern)
    if not files:
        raise FileNotFoundError(f"no chrome trace (searched {pattern!r})")
    return files[0]


def read_trace(log_dir: str) -> List[TraceEvent]:
    """Parse the newest run's chrome trace into device events.

    IO goes through the native parser (``csrc/trace_parser.cpp``) when
    built — one C pass replaces gzip+json.load, the dominant cost on real
    multi-MB traces; the pure-Python path is the fallback."""
    path = _trace_file(_latest_run_dir(log_dir))

    from apex_tpu import native as _native
    if _native.available():
        try:
            return [
                TraceEvent(
                    name=e["name"], start_us=e["ts"], dur_us=e["dur"],
                    device=e["device"], track=e["track"],
                    args=e.get("args") or {},
                )
                for e in _native.parse_trace(path)
            ]
        except (ValueError, KeyError):
            pass  # malformed for the fast path; fall through to Python

    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])

    # metadata pass: pid -> process name, (pid, tid) -> thread name
    procs = {}
    threads = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = e.get("args", {}).get("name", "")

    out: List[TraceEvent] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        pid = e.get("pid")
        dev = procs.get(pid, "")
        args = e.get("args") or {}
        out.append(TraceEvent(
            name=e.get("name", ""),
            start_us=float(e.get("ts", 0.0)),
            dur_us=float(e.get("dur", 0.0)),
            device=dev,
            track=threads.get((pid, e.get("tid")), ""),
            args={k: v for k, v in args.items() if k in WANTED_ARGS},
        ))
    return out


def device_op_events(events: Sequence[TraceEvent]) -> List[TraceEvent]:
    """The per-HLO device rows — the analog of the kernels table pyprof
    correlates against (``parse/db.py``)."""
    return [
        e for e in events
        if "/device:" in e.device and e.track in ("XLA Ops", "Async XLA Ops")
    ]


def _scope_of(name: str) -> str:
    """'encoder/block/attention/dot.7' -> 'encoder/block/attention'."""
    return name.rsplit("/", 1)[0] if "/" in name else ""


def _f(args: dict, *keys) -> float:
    for k in keys:
        v = args.get(k)
        if v not in (None, ""):
            try:
                return float(v)
            except (TypeError, ValueError):
                pass
    return 0.0


def op_records(events: Sequence[TraceEvent]) -> List[dict]:
    """Fold executions into per-op records consumable by ``analyze_ops``.

    XProf device events carry XLA's own per-op cost model in args —
    ``model_flops``, ``bytes_accessed``, ``hlo_category``, and the Python
    ``source`` line the HLO was traced from (the correlation pyprof does
    with a database join, ``apex/pyprof/parse/db.py``). Plain traces
    without those keys still aggregate by name/time.
    """
    acc: Dict[str, List] = defaultdict(lambda: [0.0, 0.0, 0.0, 0.0, "", ""])
    for e in device_op_events(events):
        a = acc[e.name]
        a[0] += 1
        a[1] += e.dur_us / 1e6
        a[2] += _f(e.args, "model_flops", "flops")
        a[3] += _f(e.args, "bytes_accessed", "raw_bytes_accessed",
                   "bytes accessed", "bytes")
        a[4] = a[4] or str(e.args.get("hlo_category", "") or "")
        a[5] = a[5] or str(e.args.get("source", "") or "")
    return [
        {"name": name, "count": int(c), "time_s": t, "flops": f, "bytes": b,
         "scope": _scope_of(name), "category": cat, "source": src}
        for name, (c, t, f, b, cat, src) in acc.items()
    ]


def by_source(recs: Sequence[dict]) -> List[dict]:
    """Roll device time up to the Python source line that emitted the HLO —
    model-code attribution (the reference gets this from NVTX call-site
    JSON, ``apex/pyprof/nvtx/nvmarker.py``). Records without a source
    (renamed/fused away) aggregate under ``""`` and are dropped. Container
    rows (while/conditional bodies, async wrappers) span their children and
    are excluded — they would otherwise double-count the whole loop body
    onto the ``lax.scan`` call site."""
    from apex_tpu.prof.analyzer import CONTAINER_FAMILIES, _family_of

    acc: Dict[str, List[float]] = defaultdict(lambda: [0.0, 0.0, 0.0, 0.0])
    for r in recs:
        src = r.get("source", "")
        if not src:
            continue
        if _family_of(r["name"], r.get("category", "")) in CONTAINER_FAMILIES:
            continue
        a = acc[src]
        a[0] += r["count"]
        a[1] += r["time_s"]
        a[2] += r.get("flops", 0.0)
        a[3] += r.get("bytes", 0.0)
    out = [
        {"source": s, "count": int(c), "time_s": t, "flops": f, "bytes": b}
        for s, (c, t, f, b) in acc.items()
    ]
    out.sort(key=lambda r: -r["time_s"])
    return out


def _analyze_run(log_dir: str):
    """(all records by time desc, non-container sinks, per-family stats)
    — the shared core of summarize/format_report. Container rows
    (while/conditional bodies, which span their children on the same
    track) are excluded from the sink ranking to avoid double counting."""
    from apex_tpu.prof.analyzer import (CONTAINER_FAMILIES, _family_of,
                                        analyze_ops)

    recs = op_records(read_trace(log_dir))
    recs.sort(key=lambda r: -r["time_s"])
    fams = analyze_ops(recs)
    sinks = [r for r in recs
             if _family_of(r["name"], r.get("category", ""))
             not in CONTAINER_FAMILIES]
    return recs, sinks, fams


def summarize(log_dir: str, top: int = 5) -> Tuple[List[dict], Dict[str, "OpStats"]]:
    """(top-K time sinks, per-family stats) for the newest run."""
    _, sinks, fams = _analyze_run(log_dir)
    return sinks[:top], fams


def format_report(log_dir: str, top: int = 5) -> str:
    """pyprof.prof-style text report: top time sinks (with the Python
    source line each HLO traces to), top source-line rollup, and the
    per-family roofline table."""
    from apex_tpu.prof.analyzer import CONTAINER_FAMILIES, report

    recs, sinks, fams = _analyze_run(log_dir)
    if not recs:
        return ("no per-HLO device events in trace — the CPU backend "
                "exports host events only; capture on TPU/GPU for op-level "
                "analysis")
    sinks = sinks[:top]
    lines = [f"top {len(sinks)} device time sinks:"]
    total = sum(s.time_s for f, s in fams.items()
                if f not in CONTAINER_FAMILIES) or 1.0
    for r in sinks:
        src = r.get("source", "")
        src = f"  [{_short_source(src)}]" if src else ""
        lines.append(
            f"  {r['time_s']*1e3:9.3f} ms  {100*r['time_s']/total:5.1f}%  "
            f"x{r['count']:<5d} {r['name'][:70]}{src}"
        )
    srcs = [r for r in by_source(recs) if r["source"]][:top]
    if srcs:
        lines.append("")
        lines.append(f"top {len(srcs)} source lines by device time:")
        for r in srcs:
            lines.append(
                f"  {r['time_s']*1e3:9.3f} ms  {100*r['time_s']/total:5.1f}%  "
                f"{_short_source(r['source'])}"
            )
    lines.append("")
    lines.append(report(fams))
    return "\n".join(lines)


def _short_source(src: str) -> str:
    """/abs/path/pkg/mod.py:12 -> pkg/mod.py:12 (last two path segments)."""
    head, _, line = src.rpartition(":")
    parts = (head or src).split(os.sep)
    short = os.sep.join(parts[-2:])
    return f"{short}:{line}" if head else short


# --- host↔device correlation (step anatomy) -----------------------------------
#
# The monitor's span stream (monitor.spans) records host enter/exit
# windows whose names are named-scope paths — the same paths device-trace
# op names carry as prefixes. That prefix IS the join: no database
# correlation pass (the reference needs apex/pyprof/parse/db.py), just a
# string match. The functions below fuse the two halves into per-step
# anatomy rows (% compute / collective-exposed / bubble / host gap, per
# device) and one merged chrome-trace timeline.


def read_span_stream(source) -> List[dict]:
    """The ``span`` records of a monitor JSONL stream (a path or an
    iterable of lines), in emission order."""
    if isinstance(source, str):
        with open(source) as fh:
            lines = fh.read().splitlines()
    else:
        lines = list(source)
    spans = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        if rec.get("kind") == "span":
            spans.append(rec)
    return spans


def host_step_spans(spans: Sequence[dict]) -> List[dict]:
    """The host-phase step windows: spans whose final path segment is
    ``step`` and that were NOT recorded under a trace (traced spans'
    host durations measure tracing, not execution), by start time."""
    return sorted(
        (s for s in spans
         if s.get("name", "").rsplit("/", 1)[-1] == "step"
         and not s.get("traced")),
        key=lambda s: s.get("t0_ns", 0))


def correlate(spans: Sequence[dict],
              events: Sequence[TraceEvent]) -> Dict[str, dict]:
    """Join device op events onto span scope paths.

    A device event belongs to span path ``p`` when its name is ``p`` or
    starts with ``p + "/"`` (named-scope nesting). Returns
    ``{span_path: {"span": record, "count", "time_s", "flops", "bytes",
    "events": [...]}}`` — one entry per distinct span path (a traced span
    re-emitted per retrace still yields one entry)."""
    out: Dict[str, dict] = {}
    dev = device_op_events(events)
    for s in spans:
        path = s.get("name", "")
        if not path or path in out:
            continue
        matched = [e for e in dev
                   if e.name == path or e.name.startswith(path + "/")]
        out[path] = {
            "span": s,
            "count": len(matched),
            "time_s": sum(e.dur_us for e in matched) / 1e6,
            "flops": sum(_f(e.args, "model_flops", "flops")
                         for e in matched),
            "bytes": sum(_f(e.args, "bytes_accessed", "raw_bytes_accessed",
                            "bytes accessed", "bytes") for e in matched),
            "events": matched,
        }
    return out


def split_steps(events: Sequence[TraceEvent],
                n: int) -> List[List[TraceEvent]]:
    """Partition one device's op events into ``n`` execution windows by
    cutting at the ``n−1`` largest idle gaps. One jitted step is one
    dense burst of device work; the gaps between bursts are host time —
    the same boundary the host step spans measure — so cutting at the
    widest gaps recovers the per-step windows without any clock
    alignment between host and device."""
    evs = sorted(events, key=lambda e: e.start_us)
    if n <= 1 or len(evs) <= 1:
        return [evs] if evs else []
    gaps = []  # (idle gap before event i, i)
    frontier = evs[0].start_us + evs[0].dur_us
    for i in range(1, len(evs)):
        gaps.append((evs[i].start_us - frontier, i))
        frontier = max(frontier, evs[i].start_us + evs[i].dur_us)
    cuts = sorted(i for _, i in sorted(gaps, reverse=True)[:n - 1])
    windows = []
    prev = 0
    for c in cuts:
        windows.append(evs[prev:c])
        prev = c
    windows.append(evs[prev:])
    return windows


def _merge_intervals(intervals):
    """Sorted merge of (start, end) pairs."""
    out = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out


def _total(merged) -> float:
    return sum(e - s for s, e in merged)


def _intersect_total(a, b) -> float:
    """Total overlap length of two MERGED interval lists."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def step_anatomy(spans: Sequence[dict],
                 events: Sequence[TraceEvent]) -> List[dict]:
    """Fuse host step spans with device op events into per-(step, device)
    anatomy rows.

    Per device, the op events split into as many execution windows as
    there are host step spans (:func:`split_steps`); window *i* pairs
    with step span *i* (both are in time order — no host↔device clock
    alignment needed). Within a window, with ``K`` = the union of
    compute-op intervals (every non-container, non-collective family)
    and ``C`` = the union of collective intervals:

    * ``compute_s``             = \\|K\\|
    * ``collective_exposed_s``  = \\|C\\| − \\|C ∩ K\\| (collective time no
      compute hides — overlapped collectives cost nothing here)
    * ``bubble_s``              = window extent − \\|K ∪ C\\| (device idle
      inside the step)
    * ``host_gap_s``            = host wall − extent (step time the
      device never saw: dispatch, host work between launches)

    and the four percentages are of the host wall, so they sum to 100
    (when the host wall is shorter than the device extent — mismatched
    streams — the extent is the denominator and ``host_gap`` is 0).
    """
    steps = host_step_spans(spans)
    if not steps:
        return []
    from apex_tpu.prof.analyzer import CONTAINER_FAMILIES, _family_of

    by_device: Dict[str, List[TraceEvent]] = defaultdict(list)
    for e in device_op_events(events):
        by_device[e.device].append(e)

    rows = []
    for device in sorted(by_device):
        windows = split_steps(by_device[device], len(steps))
        for i, (span, win) in enumerate(zip(steps, windows)):
            comp, coll = [], []
            for e in win:
                fam = _family_of(e.name, e.args.get("hlo_category", ""))
                if fam in CONTAINER_FAMILIES:
                    continue
                iv = (e.start_us / 1e6, (e.start_us + e.dur_us) / 1e6)
                (coll if fam == "collective" else comp).append(iv)
            K = _merge_intervals(comp)
            C = _merge_intervals(coll)
            busy = _merge_intervals(comp + coll)
            compute_s = _total(K)
            exposed_s = _total(C) - _intersect_total(C, K)
            extent = ((max(e.start_us + e.dur_us for e in win)
                       - min(e.start_us for e in win)) / 1e6 if win else 0.0)
            bubble_s = extent - _total(busy)
            wall_s = span.get("dur_ns", 0) / 1e9
            denom = max(wall_s, extent)
            host_gap_s = max(0.0, wall_s - extent)
            pct = (lambda x: 100.0 * x / denom) if denom else (lambda x: 0.0)
            rows.append({
                "step": span.get("step", i),
                "device": device,
                "wall_s": wall_s,
                "compute_s": compute_s,
                "collective_exposed_s": exposed_s,
                "bubble_s": bubble_s,
                "host_gap_s": host_gap_s,
                "compute_pct": pct(compute_s),
                "collective_exposed_pct": pct(exposed_s),
                "bubble_pct": pct(bubble_s),
                "host_gap_pct": pct(host_gap_s),
            })
    return rows


def format_anatomy(rows: Sequence[dict]) -> str:
    """Text table of :func:`step_anatomy` rows — what ``python -m
    apex_tpu.monitor report --anatomy`` prints."""
    if not rows:
        return ("no anatomy rows: need host step spans in the stream AND "
                "per-HLO device events in the trace (CPU traces are "
                "host-only; capture on TPU/GPU)")
    lines = [f"{'step':>5} {'device':<18}{'wall ms':>9}{'compute%':>10}"
             f"{'coll-exp%':>11}{'bubble%':>9}{'host-gap%':>11}"]
    for r in rows:
        lines.append(
            f"{r['step']:>5} {r['device']:<18}{r['wall_s']*1e3:>9.3f}"
            f"{r['compute_pct']:>10.2f}{r['collective_exposed_pct']:>11.2f}"
            f"{r['bubble_pct']:>9.2f}{r['host_gap_pct']:>11.2f}")
    return "\n".join(lines)


def merged_timeline(spans: Sequence[dict],
                    events: Sequence[TraceEvent]) -> dict:
    """One chrome-trace/Perfetto JSON object holding BOTH halves: the
    monitor's host spans (one track per process, trace-time spans on
    their own track) and the device op events. Host timestamps are
    monotonic-ns and device timestamps profiler-epoch µs, so the host
    track is shifted to align the first host step span with the start of
    the first device window — alignment is presentational; the anatomy
    numbers come from :func:`step_anatomy`, which never mixes the
    clocks."""
    trace_events = []
    pids: Dict[str, int] = {}

    def pid_of(name):
        if name not in pids:
            pids[name] = len(pids) + 1
            trace_events.append({"ph": "M", "pid": pids[name],
                                 "name": "process_name",
                                 "args": {"name": name}})
        return pids[name]

    dev = device_op_events(events)
    steps = host_step_spans(spans)
    offset_us = 0.0
    if spans:
        t0_host = min(s.get("t0_ns", 0) for s in spans) / 1e3
        if steps and dev:
            t0_host = steps[0]["t0_ns"] / 1e3
            offset_us = min(e.start_us for e in dev) - t0_host
        elif dev:
            offset_us = min(e.start_us for e in dev) - t0_host

    threads_named = set()

    def name_thread(pid, tid, label):
        if (pid, tid) not in threads_named:
            threads_named.add((pid, tid))
            trace_events.append({"ph": "M", "pid": pid, "tid": tid,
                                 "name": "thread_name",
                                 "args": {"name": label}})

    for s in spans:
        pid = pid_of(f"host:spans (process {s.get('process', 0)})")
        tid = 2 if s.get("traced") else 1
        name_thread(pid, tid, "spans (trace-time)" if tid == 2 else "spans")
        args = {k: v for k, v in s.items()
                if k not in ("schema", "kind", "t_s", "name", "t0_ns",
                             "dur_ns")}
        trace_events.append({
            "ph": "X", "pid": pid, "tid": tid, "name": s["name"],
            "ts": s["t0_ns"] / 1e3 + offset_us,
            "dur": s.get("dur_ns", 0) / 1e3, "args": args})

    for e in dev:
        pid = pid_of(e.device)
        name_thread(pid, 1, e.track or "XLA Ops")
        trace_events.append({
            "ph": "X", "pid": pid, "tid": 1, "name": e.name,
            "ts": e.start_us, "dur": e.dur_us, "args": dict(e.args)})
    return {"traceEvents": trace_events}


def write_merged_timeline(path: str, spans: Sequence[dict],
                          events: Sequence[TraceEvent]) -> str:
    """Write :func:`merged_timeline` as JSON (gzipped when ``path`` ends
    in ``.gz``); returns ``path``. Load it in Perfetto / chrome://tracing
    to see host spans and device kernels on one timeline."""
    data = merged_timeline(spans, events)
    if path.endswith(".gz"):
        with gzip.open(path, "wt") as fh:
            json.dump(data, fh)
    else:
        with open(path, "w") as fh:
            json.dump(data, fh)
    return path
