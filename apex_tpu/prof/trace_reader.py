"""Real-trace post-processor: ingest a ``jax.profiler`` run and emit
per-op / per-family time+cost tables.

The pyprof pipeline analog (``apex/pyprof/parse/{db,nvvp,kernel}.py`` reads
the nvprof SQLite DB and correlates kernels with NVTX ranges;
``apex/pyprof/prof/__main__.py`` then prints per-kernel FLOPs/bytes). Here
the source of truth is the ``trace.json.gz`` chrome trace that
``jax.profiler.stop_trace`` writes under ``<logdir>/plugins/profile/<run>/``:

* device rows (process ``/device:TPU:N``, thread ``XLA Ops``) carry one
  complete-event per executed HLO, named with the full ``named_scope`` path
  — the correlation step the reference needs a database join for comes free;
* :func:`op_records` turns them into compact records, folding multiple
  executions of the same op;
* :func:`summarize` ranks time sinks and aggregates op families via
  :func:`apex_tpu.prof.analyzer.analyze_ops` (whose hot path is the native
  C++ aggregator ``csrc/trace_analyzer.cpp`` for large traces).

CLI: ``python -m apex_tpu.prof <logdir> [--top N]``.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class TraceEvent:
    name: str
    start_us: float
    dur_us: float
    device: str       # e.g. "/device:TPU:0"
    track: str        # e.g. "XLA Ops"
    args: dict


def _latest_run_dir(log_dir: str) -> str:
    runs = sorted(glob.glob(os.path.join(log_dir, "plugins", "profile", "*")))
    if not runs:
        raise FileNotFoundError(f"no profiler runs under {log_dir!r}")
    return runs[-1]


def _trace_file(run_dir: str) -> str:
    files = glob.glob(os.path.join(run_dir, "*.trace.json.gz"))
    if not files:
        raise FileNotFoundError(f"no trace.json.gz in {run_dir!r}")
    return files[0]


def read_trace(log_dir: str) -> List[TraceEvent]:
    """Parse the newest run's chrome trace into device events."""
    path = _trace_file(_latest_run_dir(log_dir))
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])

    # metadata pass: pid -> process name, (pid, tid) -> thread name
    procs = {}
    threads = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = e.get("args", {}).get("name", "")

    out: List[TraceEvent] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        pid = e.get("pid")
        dev = procs.get(pid, "")
        out.append(TraceEvent(
            name=e.get("name", ""),
            start_us=float(e.get("ts", 0.0)),
            dur_us=float(e.get("dur", 0.0)),
            device=dev,
            track=threads.get((pid, e.get("tid")), ""),
            args=e.get("args", {}) or {},
        ))
    return out


def device_op_events(events: Sequence[TraceEvent]) -> List[TraceEvent]:
    """The per-HLO device rows — the analog of the kernels table pyprof
    correlates against (``parse/db.py``)."""
    return [
        e for e in events
        if "/device:" in e.device and e.track in ("XLA Ops", "Async XLA Ops")
    ]


def _scope_of(name: str) -> str:
    """'encoder/block/attention/dot.7' -> 'encoder/block/attention'."""
    return name.rsplit("/", 1)[0] if "/" in name else ""


def op_records(events: Sequence[TraceEvent]) -> List[dict]:
    """Fold executions into per-op records consumable by ``analyze_ops``.

    Records carry flops/bytes when the trace supplies them in event args
    (XProf exports them for some platforms; 0 otherwise — the family table
    then reports time only).
    """
    acc: Dict[str, List[float]] = defaultdict(lambda: [0.0, 0.0, 0.0, 0.0])
    for e in device_op_events(events):
        a = acc[e.name]
        a[0] += 1
        a[1] += e.dur_us / 1e6
        a[2] += float(e.args.get("flops", 0) or 0)
        a[3] += float(e.args.get("bytes accessed", e.args.get("bytes", 0)) or 0)
    return [
        {"name": name, "count": int(c), "time_s": t, "flops": f, "bytes": b,
         "scope": _scope_of(name)}
        for name, (c, t, f, b) in acc.items()
    ]


def summarize(log_dir: str, top: int = 5) -> Tuple[List[dict], Dict[str, "OpStats"]]:
    """(top-K time sinks, per-family stats) for the newest run. Container
    rows (while/conditional bodies, which span their children on the same
    track) are excluded from the sink ranking to avoid double counting."""
    from apex_tpu.prof.analyzer import CONTAINER_FAMILIES, _family_of, analyze_ops

    recs = op_records(read_trace(log_dir))
    recs.sort(key=lambda r: -r["time_s"])
    fams = analyze_ops(recs)
    sinks = [r for r in recs
             if _family_of(r["name"]) not in CONTAINER_FAMILIES]
    return sinks[:top], fams


def format_report(log_dir: str, top: int = 5) -> str:
    """pyprof.prof-style text report: top time sinks + family roofline."""
    from apex_tpu.prof.analyzer import report

    sinks, fams = summarize(log_dir, top)
    lines = [f"top {len(sinks)} device time sinks:"]
    total = sum(s.time_s for s in fams.values()) or 1.0
    for r in sinks:
        lines.append(
            f"  {r['time_s']*1e3:9.3f} ms  {100*r['time_s']/total:5.1f}%  "
            f"x{r['count']:<5d} {r['name'][:90]}"
        )
    lines.append("")
    lines.append(report(fams))
    return "\n".join(lines)
