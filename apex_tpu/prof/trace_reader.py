"""Real-trace post-processor: ingest a ``jax.profiler`` run and emit
per-op / per-family time+cost tables.

The pyprof pipeline analog (``apex/pyprof/parse/{db,nvvp,kernel}.py`` reads
the nvprof SQLite DB and correlates kernels with NVTX ranges;
``apex/pyprof/prof/__main__.py`` then prints per-kernel FLOPs/bytes). Here
the source of truth is the ``trace.json.gz`` chrome trace that
``jax.profiler.stop_trace`` writes under ``<logdir>/plugins/profile/<run>/``:

* device rows (process ``/device:TPU:N``, thread ``XLA Ops``) carry one
  complete-event per executed HLO, named with the full ``named_scope`` path
  — the correlation step the reference needs a database join for comes free;
* :func:`op_records` turns them into compact records, folding multiple
  executions of the same op;
* :func:`summarize` ranks time sinks and aggregates op families via
  :func:`apex_tpu.prof.analyzer.analyze_ops` (whose hot path is the native
  C++ aggregator ``csrc/trace_analyzer.cpp`` for large traces).

CLI: ``python -m apex_tpu.prof <logdir> [--top N]``.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple


# the arg keys the analysis pipeline consumes; both trace parsers (the
# native csrc/trace_parser.cpp and the Python fallback) restrict
# TraceEvent.args to these so behavior doesn't depend on which is built
WANTED_ARGS = frozenset((
    "model_flops", "bytes_accessed", "raw_bytes_accessed", "hlo_category",
    "source", "flops", "bytes", "bytes accessed",
))


@dataclasses.dataclass
class TraceEvent:
    name: str
    start_us: float
    dur_us: float
    device: str       # e.g. "/device:TPU:0"
    track: str        # e.g. "XLA Ops"
    args: dict        # WANTED_ARGS subset of the raw event args


def _latest_run_dir(log_dir: str) -> str:
    runs = sorted(glob.glob(os.path.join(log_dir, "plugins", "profile", "*")))
    if not runs:
        raise FileNotFoundError(f"no profiler runs under {log_dir!r}")
    return runs[-1]


def _trace_file(run_dir: str) -> str:
    files = glob.glob(os.path.join(run_dir, "*.trace.json.gz"))
    if not files:
        raise FileNotFoundError(f"no trace.json.gz in {run_dir!r}")
    return files[0]


def read_trace(log_dir: str) -> List[TraceEvent]:
    """Parse the newest run's chrome trace into device events.

    IO goes through the native parser (``csrc/trace_parser.cpp``) when
    built — one C pass replaces gzip+json.load, the dominant cost on real
    multi-MB traces; the pure-Python path is the fallback."""
    path = _trace_file(_latest_run_dir(log_dir))

    from apex_tpu import native as _native
    if _native.available():
        try:
            return [
                TraceEvent(
                    name=e["name"], start_us=e["ts"], dur_us=e["dur"],
                    device=e["device"], track=e["track"],
                    args=e.get("args") or {},
                )
                for e in _native.parse_trace(path)
            ]
        except (ValueError, KeyError):
            pass  # malformed for the fast path; fall through to Python

    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])

    # metadata pass: pid -> process name, (pid, tid) -> thread name
    procs = {}
    threads = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = e.get("args", {}).get("name", "")

    out: List[TraceEvent] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        pid = e.get("pid")
        dev = procs.get(pid, "")
        args = e.get("args") or {}
        out.append(TraceEvent(
            name=e.get("name", ""),
            start_us=float(e.get("ts", 0.0)),
            dur_us=float(e.get("dur", 0.0)),
            device=dev,
            track=threads.get((pid, e.get("tid")), ""),
            args={k: v for k, v in args.items() if k in WANTED_ARGS},
        ))
    return out


def device_op_events(events: Sequence[TraceEvent]) -> List[TraceEvent]:
    """The per-HLO device rows — the analog of the kernels table pyprof
    correlates against (``parse/db.py``)."""
    return [
        e for e in events
        if "/device:" in e.device and e.track in ("XLA Ops", "Async XLA Ops")
    ]


def _scope_of(name: str) -> str:
    """'encoder/block/attention/dot.7' -> 'encoder/block/attention'."""
    return name.rsplit("/", 1)[0] if "/" in name else ""


def _f(args: dict, *keys) -> float:
    for k in keys:
        v = args.get(k)
        if v not in (None, ""):
            try:
                return float(v)
            except (TypeError, ValueError):
                pass
    return 0.0


def op_records(events: Sequence[TraceEvent]) -> List[dict]:
    """Fold executions into per-op records consumable by ``analyze_ops``.

    XProf device events carry XLA's own per-op cost model in args —
    ``model_flops``, ``bytes_accessed``, ``hlo_category``, and the Python
    ``source`` line the HLO was traced from (the correlation pyprof does
    with a database join, ``apex/pyprof/parse/db.py``). Plain traces
    without those keys still aggregate by name/time.
    """
    acc: Dict[str, List] = defaultdict(lambda: [0.0, 0.0, 0.0, 0.0, "", ""])
    for e in device_op_events(events):
        a = acc[e.name]
        a[0] += 1
        a[1] += e.dur_us / 1e6
        a[2] += _f(e.args, "model_flops", "flops")
        a[3] += _f(e.args, "bytes_accessed", "raw_bytes_accessed",
                   "bytes accessed", "bytes")
        a[4] = a[4] or str(e.args.get("hlo_category", "") or "")
        a[5] = a[5] or str(e.args.get("source", "") or "")
    return [
        {"name": name, "count": int(c), "time_s": t, "flops": f, "bytes": b,
         "scope": _scope_of(name), "category": cat, "source": src}
        for name, (c, t, f, b, cat, src) in acc.items()
    ]


def by_source(recs: Sequence[dict]) -> List[dict]:
    """Roll device time up to the Python source line that emitted the HLO —
    model-code attribution (the reference gets this from NVTX call-site
    JSON, ``apex/pyprof/nvtx/nvmarker.py``). Records without a source
    (renamed/fused away) aggregate under ``""`` and are dropped. Container
    rows (while/conditional bodies, async wrappers) span their children and
    are excluded — they would otherwise double-count the whole loop body
    onto the ``lax.scan`` call site."""
    from apex_tpu.prof.analyzer import CONTAINER_FAMILIES, _family_of

    acc: Dict[str, List[float]] = defaultdict(lambda: [0.0, 0.0, 0.0, 0.0])
    for r in recs:
        src = r.get("source", "")
        if not src:
            continue
        if _family_of(r["name"], r.get("category", "")) in CONTAINER_FAMILIES:
            continue
        a = acc[src]
        a[0] += r["count"]
        a[1] += r["time_s"]
        a[2] += r.get("flops", 0.0)
        a[3] += r.get("bytes", 0.0)
    out = [
        {"source": s, "count": int(c), "time_s": t, "flops": f, "bytes": b}
        for s, (c, t, f, b) in acc.items()
    ]
    out.sort(key=lambda r: -r["time_s"])
    return out


def _analyze_run(log_dir: str):
    """(all records by time desc, non-container sinks, per-family stats)
    — the shared core of summarize/format_report. Container rows
    (while/conditional bodies, which span their children on the same
    track) are excluded from the sink ranking to avoid double counting."""
    from apex_tpu.prof.analyzer import (CONTAINER_FAMILIES, _family_of,
                                        analyze_ops)

    recs = op_records(read_trace(log_dir))
    recs.sort(key=lambda r: -r["time_s"])
    fams = analyze_ops(recs)
    sinks = [r for r in recs
             if _family_of(r["name"], r.get("category", ""))
             not in CONTAINER_FAMILIES]
    return recs, sinks, fams


def summarize(log_dir: str, top: int = 5) -> Tuple[List[dict], Dict[str, "OpStats"]]:
    """(top-K time sinks, per-family stats) for the newest run."""
    _, sinks, fams = _analyze_run(log_dir)
    return sinks[:top], fams


def format_report(log_dir: str, top: int = 5) -> str:
    """pyprof.prof-style text report: top time sinks (with the Python
    source line each HLO traces to), top source-line rollup, and the
    per-family roofline table."""
    from apex_tpu.prof.analyzer import CONTAINER_FAMILIES, report

    recs, sinks, fams = _analyze_run(log_dir)
    if not recs:
        return ("no per-HLO device events in trace — the CPU backend "
                "exports host events only; capture on TPU/GPU for op-level "
                "analysis")
    sinks = sinks[:top]
    lines = [f"top {len(sinks)} device time sinks:"]
    total = sum(s.time_s for f, s in fams.items()
                if f not in CONTAINER_FAMILIES) or 1.0
    for r in sinks:
        src = r.get("source", "")
        src = f"  [{_short_source(src)}]" if src else ""
        lines.append(
            f"  {r['time_s']*1e3:9.3f} ms  {100*r['time_s']/total:5.1f}%  "
            f"x{r['count']:<5d} {r['name'][:70]}{src}"
        )
    srcs = [r for r in by_source(recs) if r["source"]][:top]
    if srcs:
        lines.append("")
        lines.append(f"top {len(srcs)} source lines by device time:")
        for r in srcs:
            lines.append(
                f"  {r['time_s']*1e3:9.3f} ms  {100*r['time_s']/total:5.1f}%  "
                f"{_short_source(r['source'])}"
            )
    lines.append("")
    lines.append(report(fams))
    return "\n".join(lines)


def _short_source(src: str) -> str:
    """/abs/path/pkg/mod.py:12 -> pkg/mod.py:12 (last two path segments)."""
    head, _, line = src.rpartition(":")
    parts = (head or src).split(os.sep)
    short = os.sep.join(parts[-2:])
    return f"{short}:{line}" if head else short
