"""CLI: ``python -m apex_tpu.prof <logdir> [--top N]``.

Prints the top device time sinks and per-family roofline table from a
``jax.profiler`` run — the TPU analog of ``python -m apex.pyprof.prof``
(``apex/pyprof/prof/__main__.py``).
"""

import argparse

from apex_tpu.prof.trace_reader import format_report


def main():
    p = argparse.ArgumentParser(
        description="Analyze a jax.profiler trace directory")
    p.add_argument("logdir", help="directory passed to jax.profiler.start_trace")
    p.add_argument("--top", type=int, default=5, help="time sinks to show")
    args = p.parse_args()
    print(format_report(args.logdir, args.top))


if __name__ == "__main__":
    main()
