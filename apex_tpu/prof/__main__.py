"""CLI: ``python -m apex_tpu.prof <logdir> [--top N]
[--spans events.jsonl [--anatomy] [--merged out.json]]``.

Prints the top device time sinks and per-family roofline table from a
``jax.profiler`` run — the TPU analog of ``python -m apex.pyprof.prof``
(``apex/pyprof/prof/__main__.py``). With ``--spans`` (a monitor JSONL
stream carrying span records), ``--anatomy`` additionally prints the
per-step anatomy table and ``--merged`` writes the fused host+device
chrome-trace timeline.

Exit status: 0 on success; 2 when the logdir holds no trace run (one
line on stderr naming the searched glob — a missing capture must not
read as a crash).
"""

import argparse
import sys

from apex_tpu.prof.trace_reader import (
    format_anatomy,
    format_report,
    read_span_stream,
    read_trace,
    step_anatomy,
    write_merged_timeline,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.prof",
        description="Analyze a jax.profiler trace directory")
    p.add_argument("logdir",
                   help="directory passed to jax.profiler.start_trace")
    p.add_argument("--top", type=int, default=5, help="time sinks to show")
    p.add_argument("--spans", metavar="EVENTS_JSONL",
                   help="monitor JSONL stream with span records to join "
                        "against the trace")
    p.add_argument("--anatomy", action="store_true",
                   help="print the per-step anatomy table (needs --spans)")
    p.add_argument("--merged", metavar="OUT_JSON",
                   help="write the merged host+device chrome trace "
                        "(needs --spans; .gz suffix gzips)")
    args = p.parse_args(argv)
    if (args.anatomy or args.merged) and not args.spans:
        p.error("--anatomy/--merged need --spans EVENTS_JSONL")

    try:
        print(format_report(args.logdir, args.top))
        if args.spans:
            events = read_trace(args.logdir)
            spans = read_span_stream(args.spans)
            if args.anatomy:
                print()
                print("step anatomy (% of step wall):")
                print(format_anatomy(step_anatomy(spans, events)))
            if args.merged:
                write_merged_timeline(args.merged, spans, events)
                print(f"merged timeline written to {args.merged}")
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
