"""Per-op cost analysis (pyprof.prof analog).

The reference ships 26 analyzer classes computing FLOPs/bytes per kernel
family from argument shapes (``apex/pyprof/prof/{blas,conv,pointwise,…}.py``).
On TPU, XLA's compiler already carries an exact cost model per HLO — so the
analyzer (a) extracts program-level cost from compiled executables
(:func:`cost_analysis`) and (b) aggregates per-op records into family
statistics with roofline classification (:func:`analyze_ops`), using the
native C++ aggregator (``csrc/trace_analyzer.cpp``) when built, else numpy.
"""

from __future__ import annotations

import dataclasses
import json
from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence

import jax

from apex_tpu import native as _native

# op-name prefixes → family, the analog of pyprof's per-family analyzer
# classes (blas.py, conv.py, pointwise.py, reduction.py, …). Order matters:
# first match wins ("convert" must shadow "conv", "dynamic-update-slice"
# must shadow "dynamic-slice", "while" is a container).
FAMILIES = {
    "while": "control", "conditional": "control", "call": "control",
    "convert": "cast",
    "dynamic-update-slice": "memory", "dynamic-slice": "memory",
    "dot": "gemm", "conv": "conv", "fusion": "fusion",
    "all-reduce": "collective", "all-gather": "collective",
    "reduce-scatter": "collective", "collective-permute": "collective",
    "reduce": "reduction", "scatter": "memory", "gather": "memory",
    "copy": "memory", "transpose": "memory", "broadcast": "memory",
    "custom-call": "custom",
}

# XLA's own per-op classification as exported in XProf trace event args
# (``hlo_category``) — authoritative when present; the name-prefix table
# above is the fallback for traces without it. "convolution fusion" is the
# TPU label for fusions rooted at a dot/conv, i.e. the MXU work.
CATEGORY_FAMILIES = {
    "convolution": "gemm", "convolution fusion": "gemm",
    "loop fusion": "fusion", "input fusion": "fusion",
    "output fusion": "fusion", "fusion": "fusion",
    "custom-call": "custom", "custom fusion": "custom",
    "non-fusion elementwise": "pointwise",
    "data formatting": "memory",
    "copy": "memory", "copy-start": "memory", "copy-done": "memory",
    "dynamic-update-slice": "memory", "dynamic-slice": "memory",
    "broadcast": "memory", "slice": "memory", "iota": "memory",
    "reshape": "memory", "transpose": "memory",
    "async-start": "async", "async-done": "async", "async": "async",
    "all-reduce": "collective", "all-gather": "collective",
    "reduce-scatter": "collective", "collective-permute": "collective",
    "all-to-all": "collective", "send": "collective", "recv": "collective",
    "reduce": "reduction", "sort": "sort", "convert": "cast",
    "gather": "memory", "scatter": "memory",
    "while": "control", "conditional": "control", "call": "control",
}

# container rows span their children on the same trace track; they are
# reported as their own family but excluded from top-sink rankings to avoid
# double counting (trace_reader.summarize). async-start rows likewise span
# the wrapped op, which is reported separately.
CONTAINER_FAMILIES = ("control", "async")


@dataclasses.dataclass
class OpStats:
    family: str
    count: int
    flops: float
    bytes_accessed: float
    time_s: float

    @property
    def arithmetic_intensity(self) -> float:
        return self.flops / self.bytes_accessed if self.bytes_accessed else 0.0

    @property
    def tflops_per_s(self) -> float:
        return self.flops / self.time_s / 1e12 if self.time_s else 0.0


def cost_analysis(fn, *args, **kwargs) -> Dict[str, float]:
    """Compile ``fn`` and return XLA's cost analysis (flops, bytes accessed,
    optimal seconds) — the whole-program version of pyprof's per-kernel
    derivation from shapes."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def _family_of(name: str, category: str = "") -> str:
    # XLA's own hlo_category (XProf traces) is authoritative
    n = name.lower().rsplit("/", 1)[-1]
    base = CATEGORY_FAMILIES.get(category.lower()) if category else None
    if base is None:
        # fallback: op names carry the named_scope path
        # ("gpt/attn/dot.7"); classify on the final HLO segment
        base = "other"
        for prefix, fam in FAMILIES.items():
            if n.startswith(prefix) or f".{prefix}" in n:
                base = fam
                break
    # refinements (the ROADMAP item-5 op-family slice):
    # a REAL convolution HLO also lands in XLA's "convolution" category —
    # split it from the dot-rooted MXU work by name, so ResNet profiles
    # read "conv", not "gemm"
    if (base == "gemm" and n.startswith("conv")
            and not n.startswith("convert")):
        base = "conv"
    # embedding-style lookups (table gathers, their update-scatters and
    # the fusions XLA roots at them) attribute to their own family when
    # the scope says so — MXU work (gemm/conv) is never reclassified
    if (base in ("memory", "fusion", "pointwise", "other")
            and "embed" in name.lower()):
        base = "embedding"
    return base


def analyze_ops(ops: Sequence[dict]) -> Dict[str, OpStats]:
    """Aggregate op records ({'name', 'flops', 'bytes', 'time_s'}) into
    per-family stats. Uses the C++ aggregator for large traces."""
    ops = list(ops)
    if _native.available() and len(ops) >= 1024:
        agg = _native.aggregate_trace(
            json.dumps([
                {"f": _family_of(o.get("name", ""), o.get("category", "")),
                 "flops": float(o.get("flops", 0.0)),
                 "bytes": float(o.get("bytes", 0.0)), "t": float(o.get("time_s", 0.0))}
                for o in ops
            ])
        )
        return {
            k: OpStats(family=k, count=int(v["count"]), flops=v["flops"],
                       bytes_accessed=v["bytes"], time_s=v["t"])
            for k, v in agg.items()
        }

    acc: Dict[str, List[float]] = defaultdict(lambda: [0, 0.0, 0.0, 0.0])
    for o in ops:
        fam = _family_of(o.get("name", ""), o.get("category", ""))
        a = acc[fam]
        a[0] += 1
        a[1] += float(o.get("flops", 0.0))
        a[2] += float(o.get("bytes", 0.0))
        a[3] += float(o.get("time_s", 0.0))
    return {
        fam: OpStats(family=fam, count=int(c), flops=f, bytes_accessed=b, time_s=t)
        for fam, (c, f, b, t) in acc.items()
    }


def report(stats: Dict[str, OpStats], peak_tflops: float = 197.0,
           peak_gbs: float = 819.0) -> str:
    """Roofline-style text report (pyprof.prof output analog); defaults are
    v5e bf16 peak / HBM bandwidth."""
    lines = [f"{'family':<12}{'count':>7}{'GFLOP':>10}{'GB':>9}{'ms':>9}"
             f"{'TFLOP/s':>9}{'AI':>7}  bound"]
    for fam, s in sorted(stats.items(), key=lambda kv: -kv[1].time_s):
        ridge = peak_tflops * 1e12 / (peak_gbs * 1e9)
        bound = "compute" if s.arithmetic_intensity > ridge else "memory"
        lines.append(
            f"{fam:<12}{s.count:>7}{s.flops/1e9:>10.2f}{s.bytes_accessed/1e9:>9.3f}"
            f"{s.time_s*1e3:>9.3f}{s.tflops_per_s:>9.2f}{s.arithmetic_intensity:>7.1f}  {bound}"
        )
    return "\n".join(lines)
