"""Profiling — re-design of ``apex.pyprof``.

The reference's three stages (SURVEY.md §5) map to TPU-native equivalents:

1. ``pyprof.nvtx`` monkey-patches torch to emit NVTX ranges
   (``apex/pyprof/nvtx/nvmarker.py``) → :func:`annotate` /
   :func:`init` wrap functions in ``jax.named_scope`` so ops carry names
   into the XLA trace, and :func:`trace` drives ``jax.profiler``;
2. ``pyprof.parse`` correlates kernels with markers from the nvprof DB →
   unnecessary: XLA traces already carry scope names;
3. ``pyprof.prof`` computes per-kernel FLOPs/bytes/efficiency
   (``apex/pyprof/prof/``, one analyzer per op family) →
   :func:`cost_analysis` reads XLA's own per-program cost model from the
   compiled executable, and :mod:`apex_tpu.prof.analyzer` aggregates
   per-op-family statistics and roofline classification (native C++ fast
   path in ``csrc/trace_analyzer.cpp`` for large traces).
"""

from apex_tpu.prof.marker import annotate, init, trace  # noqa: F401
from apex_tpu.prof.analyzer import OpStats, analyze_ops, cost_analysis  # noqa: F401
from apex_tpu.prof.calibrate import build_costdb, validate_costdb, write_costdb  # noqa: F401
