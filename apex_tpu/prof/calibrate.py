"""CostDB calibration: distill measured spans + counted bytes into the
achieved-rate database the auto-parallelism planner consumes.

ROADMAP item 2's planner (AMP-style, arXiv:2210.07297) needs to *price*
a candidate plan: how many bytes/s does an ``all_gather`` over ``tp``
actually move at a given payload size on this topology, and how many
FLOP/s does a GEMM of a given size class actually achieve — numbers a
spec sheet cannot give (they depend on ICI wiring, payload size, and
compiler behavior). This module builds that database from telemetry the
repo already emits:

* **collectives** — each instrumented collective rides a monitor span
  (:mod:`apex_tpu.monitor.spans`) whose record carries ``coll`` (kind),
  ``axis`` and ``bytes`` (static payload per execution); the device
  events under the span's named-scope path carry the measured durations.
  One matched device event = one sample ``bytes / dur``; samples fold
  per ``kind[axis]`` × power-of-two size bucket with spread. When a
  stream predates spans, the counted-bytes hooks
  (``collective/<kind>[<axis>]_bytes/_calls`` in step records) price the
  trace's collective HLOs instead (``source: "counters"``).
* **GEMMs** — device events in the ``gemm`` family carry XLA's own
  ``model_flops``; achieved FLOP/s folds per power-of-two FLOPs class.
  ``predicted_flops_per_s`` (from :func:`apex_tpu.prof.cost_analysis`'s
  flops / optimal-seconds, when the caller measured it) rides along so
  the planner can see achieved vs predicted in one artifact.

The artifact is ``kind: "costdb"`` and schema-validated
(:data:`apex_tpu.monitor.schema.COSTDB_SCHEMA`;
``tools/validate_metrics.py --costdb`` gates it like bench records).
``bench.py --profile`` emits one per gate workload.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.monitor.registry import SCHEMA_VERSION

# HLO collective op kind -> the counter kind the hooks use; the join key
# of the counted-bytes fallback path
_HLO_TO_COUNTER_KIND = {
    "all-reduce": "psum",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "collective-permute": "ppermute",
    "all-to-all": "all_to_all",
}


def size_bucket(nbytes: float) -> int:
    """Power-of-two floor of a payload size — the CostDB's size-bucket
    key (1 for anything below 2 bytes)."""
    b = 1
    while b * 2 <= nbytes:
        b *= 2
    return b


def _stat(samples: Sequence[float]) -> dict:
    lo, hi = min(samples), max(samples)
    return {
        "n": len(samples),
        "mean": sum(samples) / len(samples),
        "min": lo,
        "max": hi,
        "spread_pct": 100.0 * (hi - lo) / lo if lo > 0 else 0.0,
    }


def _collective_events(events):
    from apex_tpu.prof.analyzer import _family_of
    from apex_tpu.prof.trace_reader import device_op_events

    return [e for e in device_op_events(events)
            if _family_of(e.name, e.args.get("hlo_category", ""))
            == "collective" and e.dur_us > 0]


def collective_samples_from_spans(
        spans: Sequence[dict],
        events) -> List[Tuple[str, float, float]]:
    """``(key, bytes, dur_s)`` per executed collective, joined span→device
    by named-scope path prefix. ``key`` is ``"<coll>[<axis>]"``. A ring
    span contains one ppermute HLO per hop, each moving the span's chunk
    payload — every hop is its own bandwidth sample."""
    coll_spans = {}
    for s in spans:
        if s.get("kind") == "span" and s.get("coll") and s.get("bytes"):
            coll_spans.setdefault(s["name"], s)
    out = []
    for path, s in coll_spans.items():
        key = f"{s['coll']}[{s.get('axis', '')}]"
        nbytes = float(s["bytes"])
        for e in _collective_events(events):
            if e.name == path or e.name.startswith(path + "/"):
                out.append((key, nbytes, e.dur_us / 1e6))
    return out


def counted_bytes_per_call(records: Sequence[dict]) -> Dict[str, float]:
    """``{"<kind>[<axis>]": bytes per call}`` from the last step record's
    lifetime counters — the counted-bytes hooks' view of the traffic
    (per traced program, the natural unit for one jitted step)."""
    totals = {}
    for r in records:
        if r.get("kind") == "step" and r.get("counters_total"):
            totals = r["counters_total"]
    out = {}
    for name, v in totals.items():
        if name.startswith("collective/") and name.endswith("_bytes"):
            tag = name[len("collective/"):-len("_bytes")]
            calls = totals.get(f"collective/{tag}_calls", 0)
            if calls:
                out[tag] = float(v) / float(calls)
    return out


def collective_samples_from_counters(
        records: Sequence[dict],
        events) -> List[Tuple[str, float, float]]:
    """The pre-span fallback: price each collective HLO in the trace at
    the counted bytes/call of its counter kind. Only unambiguous kinds
    participate — a kind counted on two axes cannot be attributed to a
    device event without the span join, and a wrong price is worse than
    a missing row."""
    per_call = counted_bytes_per_call(records)
    by_kind: Dict[str, List[str]] = defaultdict(list)
    for tag in per_call:
        kind = tag.split("[", 1)[0]
        by_kind[kind].append(tag)
    out = []
    for e in _collective_events(events):
        seg = e.name.lower().rsplit("/", 1)[-1]
        cat = str(e.args.get("hlo_category", "")).lower()
        for hlo, kind in _HLO_TO_COUNTER_KIND.items():
            if seg.startswith(hlo) or cat == hlo:
                tags = by_kind.get(kind, [])
                if len(tags) == 1:  # unambiguous axis
                    out.append((tags[0], per_call[tags[0]], e.dur_us / 1e6))
                break
    return out


def gemm_samples(events) -> List[Tuple[str, float, float]]:
    """``(shape-class, flops, dur_s)`` per executed GEMM-family op with a
    known FLOP count. The class key is the power-of-two FLOPs floor —
    ops of one jitted program keep one class per shape, and the planner
    prices candidate GEMMs by the nearest class."""
    from apex_tpu.prof.analyzer import _family_of
    from apex_tpu.prof.trace_reader import _f, device_op_events

    out = []
    for e in device_op_events(events):
        if _family_of(e.name, e.args.get("hlo_category", "")) != "gemm":
            continue
        flops = _f(e.args, "model_flops", "flops")
        if flops > 0 and e.dur_us > 0:
            out.append((f"flops_{size_bucket(flops)}", flops,
                        e.dur_us / 1e6))
    return out


def build_costdb(records: Sequence[dict], events, *,
                 device_kind: Optional[str] = None,
                 backend: Optional[str] = None,
                 predicted_flops_per_s: Optional[float] = None) -> dict:
    """Distill a monitor record stream + a device trace into the CostDB.

    ``records`` is the full JSONL stream (span records give the primary
    span→device join; step records give the counted-bytes fallback when
    no collective spans matched). Returns the ``kind: "costdb"``
    artifact — schema-valid by construction, with every rate a finite
    number (zero-duration events never become samples)."""
    spans = [r for r in records if r.get("kind") == "span"]
    samples = collective_samples_from_spans(spans, events)
    source = "spans"
    if not samples:
        samples = collective_samples_from_counters(records, events)
        source = "counters"

    buckets: Dict[str, Dict[int, List[Tuple[float, float]]]] = \
        defaultdict(lambda: defaultdict(list))
    for key, nbytes, dur_s in samples:
        buckets[key][size_bucket(nbytes)].append((nbytes, dur_s))
    collectives = {}
    for key, per_bucket in sorted(buckets.items()):
        rows = []
        for bucket, pairs in sorted(per_bucket.items()):
            rows.append({
                "bucket_bytes": bucket,
                "bytes": _stat([b for b, _ in pairs]),
                "bytes_per_s": _stat([b / d for b, d in pairs]),
            })
        collectives[key] = rows

    per_class: Dict[str, List[float]] = defaultdict(list)
    for cls, flops, dur_s in gemm_samples(events):
        per_class[cls].append(flops / dur_s)
    gemms = {
        cls: {"flops_per_s": _stat(rates),
              "predicted_flops_per_s": predicted_flops_per_s}
        for cls, rates in sorted(per_class.items())
    }

    db = {
        "schema": SCHEMA_VERSION,
        "kind": "costdb",
        "source": source,
        "collectives": collectives,
        "gemms": gemms,
        "predicted_flops_per_s": predicted_flops_per_s,
    }
    if device_kind is not None:
        db["device_kind"] = device_kind
    if backend is not None:
        db["backend"] = backend
    return db


def nearest_bucket_row(rows: Sequence[dict],
                       per_call_bytes: float) -> Optional[dict]:
    """The CostDB size-bucket row nearest the payload (log2 distance
    over ``bucket_bytes``, positive-rate rows only); ``None`` when no
    row carries a rate. THE bucket-matching rule — shared by
    :func:`diff_static_cost` and the planner's
    :func:`apex_tpu.plan.cost.price_plan`, so the lint CLI's coverage
    table and the planner's prices cannot silently diverge."""
    import math

    rated = [r for r in rows
             if r.get("bytes_per_s", {}).get("mean", 0) > 0]
    if not rated:
        return None
    return min(rated, key=lambda r: abs(
        math.log2(max(r["bucket_bytes"], 1))
        - math.log2(max(per_call_bytes, 1))))


def nearest_bucket_rate(rows: Sequence[dict],
                        per_call_bytes: float) -> Optional[float]:
    """Mean achieved bytes/s of :func:`nearest_bucket_row`'s pick."""
    row = nearest_bucket_row(rows, per_call_bytes)
    return None if row is None else row["bytes_per_s"]["mean"]


def diff_static_cost(static: dict, costdb: dict) -> dict:
    """Line a ``kind:"static_cost"`` report (the jaxpr walker's PREDICTED
    per-collective bytes and per-GEMM FLOPs,
    :func:`apex_tpu.lint.jaxpr_check.static_cost`) up against this
    CostDB's MEASURED rates — the planner's predicted-vs-calibrated
    substrate, and the engine behind ``python -m apex_tpu.lint --jaxpr
    --costdb``.

    The join is a plain dict join: static collective keys are the
    ``count_collective`` ``"<kind>[<axis>]"`` tags the CostDB's
    collective table is keyed by (the bucket row nearest the static
    per-call payload prices it); static GEMM classes are the
    ``"flops_<2^k>"`` labels :func:`gemm_samples` buckets by. Returns::

        {"rows": [{key, unit, calls, bytes|flops, calibrated,
                   rate?, bucket?, predicted_ms?}, ...],
         "uncovered": [keys in the trace the CostDB has never priced],
         "covered": int, "total": int}

    A traced collective with no CostDB row is exactly the planner's
    blind spot — the caller surfaces ``uncovered`` loudly rather than
    pricing it at a made-up rate. The surface is STRUCTURAL, not table
    prose (ISSUE 12 satellite): ``apex_tpu.plan.cost.price_plan``
    consumes the same blind-spot semantics as its per-plan
    ``uncalibrated`` confidence flag, the lint CLI embeds every
    entrypoint's ``uncovered`` list in its JSON report's
    ``uncalibrated`` section, and ``python -m apex_tpu.lint --jaxpr
    --costdb F --strict`` turns a nonempty surface into a nonzero
    exit for CI.
    """
    rows: List[dict] = []
    db_coll = costdb.get("collectives", {}) or {}
    for key, ent in sorted((static.get("collectives") or {}).items()):
        calls = max(int(ent.get("calls", 0)), 1)
        total_bytes = int(ent.get("bytes", 0))
        per_call = total_bytes / calls
        row = {"key": key, "unit": "bytes", "calls": int(ent.get("calls", 0)),
               "bytes": total_bytes, "calibrated": False}
        best = nearest_bucket_row(db_coll.get(key) or [], per_call)
        if best is not None:
            rate = best["bytes_per_s"]["mean"]
            row.update(calibrated=True, bucket=best["bucket_bytes"],
                       rate=rate, predicted_ms=1e3 * total_bytes / rate)
        rows.append(row)

    db_gemms = costdb.get("gemms", {}) or {}
    for key, ent in sorted((static.get("gemms") or {}).items()):
        flops = float(ent.get("flops", 0.0))
        row = {"key": key, "unit": "flops",
               "calls": int(ent.get("calls", 0)), "flops": flops,
               "calibrated": False}
        stat = (db_gemms.get(key) or {}).get("flops_per_s", {})
        rate = stat.get("mean", 0)
        if rate > 0:
            row.update(calibrated=True, rate=rate,
                       predicted_ms=1e3 * flops / rate)
        rows.append(row)

    uncovered = [r["key"] for r in rows if not r["calibrated"]]
    return {"rows": rows, "uncovered": uncovered,
            "covered": sum(1 for r in rows if r["calibrated"]),
            "total": len(rows)}


def validate_costdb(db: dict) -> List[str]:
    """Schema-validate a CostDB artifact (the shared kind-keyed
    validator); returns error strings, empty when valid."""
    from apex_tpu.monitor import schema

    errors = list(schema.validate(db, schema.COSTDB_SCHEMA))
    if db.get("kind") != "costdb":
        errors.append(f"kind must be 'costdb', got {db.get('kind')!r}")
    return errors


def write_costdb(path: str, db: dict) -> str:
    """Validate then write the CostDB as one JSON object; refuses an
    invalid artifact the same way the bench refuses an invalid record."""
    errors = validate_costdb(db)
    if errors:
        raise ValueError(f"refusing to write invalid costdb: {errors}")
    with open(path, "w") as fh:
        json.dump(db, fh, indent=1)
    return path
