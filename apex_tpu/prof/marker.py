"""Scope annotation + trace capture (pyprof.nvtx analog)."""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, Iterable, Optional

import jax


def annotate(name: Optional[str] = None) -> Callable:
    """Decorator wrapping a function in ``jax.named_scope`` — the marker the
    reference pushes via NVTX around every patched call
    (``nvmarker.py:1-45``); the scope name (with arg shapes appended at
    trace time by XLA metadata) shows up in the profiler UI."""

    def deco(fn):
        scope = name or getattr(fn, "__qualname__", fn.__name__)

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with jax.named_scope(scope):
                return fn(*args, **kwargs)

        return wrapped

    return deco


def init(module, names: Optional[Iterable[str]] = None) -> None:
    """Wrap the named (or all public) functions of ``module`` with
    :func:`annotate` — the opt-in analog of pyprof's wrap-the-world
    ``nvtx.init()`` (``apex/pyprof/__init__.py:1-5``); explicit rather than
    interpreter-wide patching."""
    if names is None:
        names = [n for n in dir(module) if not n.startswith("_")
                 and callable(getattr(module, n))]
    for n in names:
        fn = getattr(module, n)
        if callable(fn):
            setattr(module, n, annotate(f"{module.__name__}.{n}")(fn))


@contextlib.contextmanager
def trace(log_dir: str, *, host_tracer_level: int = 2):
    """Capture a profiler trace to ``log_dir`` (viewable in
    TensorBoard/XProf) — replaces running under nvprof/nsys."""
    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
