"""Disaggregated serving: a prefill engine and a decode engine as two
roles, with content-addressed KV-block streaming between their pools.

Chunked prefill is already a separable phase of the serve loop, and the
:class:`~apex_tpu.serving.kv_blocks.PrefixCache` already gives every
full prompt block a CONTENT identity — the chained ``(parent, block
tokens)`` key. Disaggregation rides both: the **prefill role** serves
each request to its first token (filling its pool and indexing the
prompt's full blocks in its prefix cache), then :func:`export_handoff`
walks the cached chain and lifts each block's k/v rows (plus the int8
scale rows on a quantized pool) off the device with a sha256 digest per
block. The payload crosses the process boundary as a directory —
:func:`write_handoff` / :func:`read_handoff`, framed exactly like the
PR-14 checkpoint transfer (an atomically-replaced ``manifest.json``
naming format/version/digest algo and the per-block digest table; raw
little-endian array files alongside) — and the **decode role**'s
:func:`ingest_handoff` verifies every digest, allocates pool blocks,
writes the streamed rows in, and indexes the chain in ITS prefix cache.
The decode engine then serves the same requests through the ordinary
admission path: the prompt's blocks are prefix-cache hits, prefill
collapses to the final block (whose last-row logits seed the first
sampled token — the recompute the copy-on-write contract always
requires), and greedy output is token-identical to a monolithic engine.

Nothing here adds device programs: export/ingest are host-side
``jnp`` gathers and ``.at[].set`` writes between dispatches, the pools
keep their avals, and both engines keep their jit caches pinned at 1.
The ``handoff`` lifecycle event (:meth:`~apex_tpu.serving.telemetry.
ServeTelemetry.on_handoff`) fires on BOTH roles carrying the SAME
request trace id — the id travels inside the payload — so a merged
timeline joins the export and ingest legs of one request.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.serving.kv_blocks import ROOT_EID

HANDOFF_FORMAT = "apex_tpu.kv_handoff"
HANDOFF_VERSION = 1
MANIFEST_NAME = "manifest.json"


def block_digest(arrays: Dict[str, np.ndarray]) -> str:
    """sha256 over the block's raw bytes, arrays in sorted-name order
    (the same per-buffer digest discipline as the PR-14 checkpoint
    manifest): the ingest side recomputes this from what it actually
    received, so a corrupted or cross-wired transfer is loud."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class BlockPayload:
    """One streamed full prompt block: its content key (the block's
    ``block_size`` token ids — chain position gives the full prefix
    identity), the pool rows per array name (``k``/``v`` are
    ``(layers, kv_heads, block_size, head_dim)``; ``k_scale``/
    ``v_scale`` ``(layers, block_size)`` on int8 pools), and the sha256
    digest of those rows."""

    tokens: Tuple[int, ...]
    arrays: Dict[str, np.ndarray]
    digest: str

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.arrays.values())


@dataclasses.dataclass
class Handoff:
    """One request's prefill→decode payload: the prompt (so the decode
    role re-derives the chain keys), the streamed blocks in chain
    order, and the request's trace id (the SAME id tags the ``handoff``
    lifecycle event on both engine roles)."""

    rid: int
    prompt: np.ndarray
    blocks: List[BlockPayload]
    trace_id: Optional[str] = None

    @property
    def nbytes(self) -> int:
        return sum(b.nbytes for b in self.blocks)


@dataclasses.dataclass
class HandoffStats:
    """Host accounting of one ingest: blocks written into the pool,
    payload bytes, digests verified, and chain links skipped (pool or
    cache pressure on the decode side — skipped blocks are simply
    recomputed by prefill, never an error)."""

    blocks: int = 0
    nbytes: int = 0
    digests_verified: int = 0
    skipped: int = 0


def export_handoff(pool, scheduler, req, *, block_size: int,
                   telemetry=None, now: float = 0.0) -> Handoff:
    """The prefill role's half: walk the longest cached chain covering
    ``req.prompt``'s full blocks (side-effect-free match — exporting
    must not perturb the source cache's LRU or hit accounting) and lift
    each chain block's pool rows to host with a digest. Raises when
    nothing is cached for the prompt — an export before (or instead of)
    the prefill run is a harness bug worth naming."""
    cache = scheduler.prefix_cache
    if cache is None:
        raise ValueError(
            "export_handoff needs the prefill scheduler's prefix cache "
            "(make_scheduler(prefix_cache=True)): the cache's chained "
            "content keys ARE the handoff's block addressing")
    chain = cache.match(req.prompt, count=False)
    if not chain:
        raise ValueError(
            f"export_handoff found no cached blocks for request "
            f"{req.rid} (prompt of {len(req.prompt)} tokens, "
            f"block_size={block_size}): run the prefill role's serve() "
            f"first — only prefilled full blocks are exportable")
    t0 = time.perf_counter()
    blocks: List[BlockPayload] = []
    for e in chain:
        arrays = {name: np.asarray(pool[name][:, e.block_id])
                  for name in pool}
        blocks.append(BlockPayload(tokens=e.tokens, arrays=arrays,
                                   digest=block_digest(arrays)))
    h = Handoff(rid=req.rid, prompt=np.asarray(req.prompt, np.int32),
                blocks=blocks,
                trace_id=getattr(req, "trace_id", None))
    if telemetry is not None:
        telemetry.on_handoff(req.rid, "export", len(blocks), h.nbytes,
                             now,
                             dur_ms=(time.perf_counter() - t0) * 1e3,
                             trace_id=h.trace_id)
    return h


def write_handoff(directory: str, handoffs: List[Handoff]) -> int:
    """Serialize handoffs for the process boundary: one raw
    little-endian array file per (request, block, array) plus ONE
    atomically-replaced ``manifest.json`` naming format, version,
    digest algo, prompts, per-block token keys / digests / array
    layouts — the PR-14 framing: readers validate the manifest before
    touching a data file, and a torn write never shows a manifest.
    Returns payload bytes written (the transfer size the ``tp_serve``
    record reports)."""
    os.makedirs(directory, exist_ok=True)
    total = 0
    reqs = []
    for h in handoffs:
        blocks = []
        for bi, b in enumerate(h.blocks):
            arrays = {}
            for name, a in b.arrays.items():
                a = np.ascontiguousarray(a)
                fname = f"r{h.rid}_b{bi}_{name}.bin"
                with open(os.path.join(directory, fname), "wb") as fh:
                    fh.write(a.tobytes())
                arrays[name] = {"file": fname, "dtype": str(a.dtype),
                                "shape": list(a.shape)}
                total += int(a.nbytes)
            blocks.append({"tokens": list(b.tokens), "digest": b.digest,
                           "arrays": arrays})
        reqs.append({"rid": int(h.rid),
                     "prompt": [int(t) for t in h.prompt],
                     "trace_id": h.trace_id, "blocks": blocks})
    manifest = {"format": HANDOFF_FORMAT, "version": HANDOFF_VERSION,
                "digest_algo": "sha256", "requests": reqs,
                "payload_bytes": total}
    path = os.path.join(directory, MANIFEST_NAME)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=1)
    os.replace(tmp, path)
    return total


def read_handoff(directory: str) -> List[Handoff]:
    """Read a handoff directory back, validating the manifest first
    (format/version named eagerly, PR-14 style) and VERIFYING every
    block digest against the bytes actually read — a mismatch names
    the request and block, never serves silently corrupt KV."""
    path = os.path.join(directory, MANIFEST_NAME)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"no {MANIFEST_NAME} under {directory!r} — not a committed "
            f"KV handoff (an interrupted export never commits its "
            f"manifest)")
    with open(path) as fh:
        m = json.load(fh)
    if m.get("format") != HANDOFF_FORMAT:
        raise ValueError(
            f"handoff manifest format {m.get('format')!r} is not "
            f"{HANDOFF_FORMAT!r} — this directory does not hold a KV "
            f"handoff")
    if int(m.get("version", 0)) > HANDOFF_VERSION:
        raise ValueError(
            f"handoff manifest version {m.get('version')} is newer "
            f"than this reader's {HANDOFF_VERSION} — upgrade before "
            f"ingesting")
    out: List[Handoff] = []
    for r in m["requests"]:
        blocks = []
        for bi, b in enumerate(r["blocks"]):
            arrays = {}
            for name, spec in b["arrays"].items():
                with open(os.path.join(directory, spec["file"]),
                          "rb") as fh:
                    raw = fh.read()
                arrays[name] = np.frombuffer(
                    raw, dtype=np.dtype(spec["dtype"])).reshape(
                        spec["shape"]).copy()
            got = block_digest(arrays)
            if got != b["digest"]:
                raise ValueError(
                    f"handoff digest mismatch on request {r['rid']} "
                    f"block {bi}: manifest {b['digest'][:12]}…, read "
                    f"{got[:12]}… — the transfer corrupted this "
                    f"block's KV rows")
            blocks.append(BlockPayload(
                tokens=tuple(int(t) for t in b["tokens"]),
                arrays=arrays, digest=b["digest"]))
        out.append(Handoff(rid=int(r["rid"]),
                           prompt=np.asarray(r["prompt"], np.int32),
                           blocks=blocks, trace_id=r.get("trace_id")))
    return out


def ingest_handoff(pool, scheduler, handoffs: List[Handoff], *,
                   telemetry=None, now: float = 0.0,
                   verify: bool = True) -> Tuple[Any, HandoffStats]:
    """The decode role's half: for each handoff, re-verify the block
    digests against the received arrays (``verify=True``; the file
    reader already checked bytes-on-disk — this guards the in-memory
    leg too), allocate a pool block per chain link, write the streamed
    rows in, and index the chain in the decode scheduler's prefix
    cache under the SAME content keys. Returns ``(pool, stats)`` —
    ``pool`` is rebound (host-side ``.at[].set`` between dispatches;
    same aval, committed sharding preserved under tp).

    After ingest the blocks sit exactly as a finished request's warm
    prefix would: one refcount held by the cache, marked resident — so
    admission treats them as reclaimable capacity, and a decode-side
    pool too small to hold the stream degrades to recompute (skipped
    links counted in ``stats.skipped``), never to an error."""
    alloc = scheduler.allocator
    cache = scheduler.prefix_cache
    if cache is None:
        raise ValueError(
            "ingest_handoff needs the decode scheduler's prefix cache "
            "(make_scheduler(prefix_cache=True)): streamed blocks are "
            "delivered to admission AS prefix-cache hits")
    stats = HandoffStats()
    pool = dict(pool)
    for h in handoffs:
        t0 = time.perf_counter()
        parent = ROOT_EID
        hb = hbytes = 0
        for b in h.blocks:
            if verify:
                if block_digest(b.arrays) != b.digest:
                    raise ValueError(
                        f"handoff digest mismatch on request {h.rid}: "
                        f"a streamed block's KV rows do not match its "
                        f"content digest — refusing to serve from it")
                stats.digests_verified += 1
            # a chain broken upstream (an earlier link skipped) cannot
            # accept later links: their parent key would not exist
            if parent is None or alloc.num_free < 1:
                stats.skipped += 1
                parent = None
                continue
            bid = alloc.allocate(1)[0]
            for name, a in b.arrays.items():
                pool[name] = pool[name].at[:, bid].set(a)
            eid = cache.insert(parent, b.tokens, bid,
                               trace_id=h.trace_id)
            entry = cache._by_eid.get(eid)
            if entry is None or entry.block_id != bid:
                # the cache declined to index (capacity) or an equal
                # chain already existed: drop our pool copy — the
                # existing/recomputed path serves the prefix
                alloc.free([bid])
                stats.skipped += 1
                if entry is None:
                    parent = None
                    continue
            else:
                # hand our allocation reference over to the cache's
                # (insert retained + marked resident): refcount settles
                # at 1, exactly a finished request's warm prefix state
                alloc.free([bid])
                stats.blocks += 1
                hb += 1
                hbytes += b.nbytes
                stats.nbytes += b.nbytes
            parent = eid
        if telemetry is not None:
            telemetry.on_handoff(
                h.rid, "ingest", hb, hbytes, now,
                dur_ms=(time.perf_counter() - t0) * 1e3,
                trace_id=h.trace_id)
    return pool, stats


def prefill_requests(requests: List) -> List:
    """Clone ``requests`` for the prefill role: same rid/prompt/
    arrival, ``max_new_tokens=1`` — the prefill engine runs exactly to
    each request's first token (its TTFT) and fills the pool + prefix
    cache; decode continues elsewhere."""
    from apex_tpu.serving.scheduler import Request
    return [Request(rid=r.rid, prompt=np.asarray(r.prompt, np.int32),
                    max_new_tokens=1, arrival_s=r.arrival_s)
            for r in requests]
