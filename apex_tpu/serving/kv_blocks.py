"""Paged KV cache: fixed-size blocks in one donated pool, host free list,
per-slot block tables.

The contiguous engine allocates ``batch × max_s`` cache rows up front —
every admitted request pays for the LONGEST possible sequence whether it
uses 30 tokens or 3000. Paging breaks the cache into fixed-size blocks
(``block_size`` tokens each) living in ONE pre-allocated pool

    {"k"/"v": (layers, num_blocks, kv_heads, block_size, head_dim)}

and gives each slot a BLOCK TABLE mapping its logical kv blocks to pool
indices. Memory is then bound by live tokens (rounded up to the block),
the pool aval never changes (stable avals → the decode step compiles
once), and admit/evict is pure host bookkeeping: allocate/free block ids
and rewrite a table row — the device arrays are never reshaped.

Device-side consumers resolve the indirection two ways: the Pallas
decode kernel reads the table as a scalar-prefetch operand inside its
BlockSpec index maps (:func:`apex_tpu.ops.pallas.decode_attention.
decode_attn_paged_fwd`); the XLA fallback gathers the table into the
contiguous view. Both are driven through
``decode_attention(..., block_tables=)``.

Block 0 is the reserved **dead block**: never allocated, it absorbs the
writes of inactive slots and backs every unused table entry, so the
device step needs no masking branches for slots that do not exist —
their DMAs land somewhere harmless and their columns are length-masked
anyway. All bookkeeping here is plain host Python/numpy (never traced).
"""

from __future__ import annotations

from typing import Iterable, List

import numpy as np

# pool index every unused table entry and every inactive-slot write
# resolves to; excluded from the free list forever
DEAD_BLOCK = 0


def blocks_needed(tokens: int, block_size: int) -> int:
    """Blocks covering ``tokens`` cache rows: ceil(tokens / block_size)."""
    return -(-int(tokens) // int(block_size))


class BlockAllocator:
    """Host-side free list over pool blocks ``[1, num_blocks)``.

    LIFO reuse (a just-freed block is hottest in cache and cheapest to
    re-DMA) with double-free/foreign-id checks — an allocator bug here
    would silently cross-wire two requests' caches, so it must be loud.

    Accounting for the serving telemetry (ISSUE 10): lifetime
    ``alloc_total`` / ``free_total`` counters, the monotone
    ``high_water`` of live blocks, the :attr:`leaked` witness
    (``alloc_total - free_total - num_live`` — non-zero means the
    free/live sets were mutated behind the allocator's back), and
    :meth:`fragmentation_pct` over the free list. All host-side ints;
    the counters never change allocation behavior.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"the pool needs >= 2 blocks (block {DEAD_BLOCK} is the "
                f"reserved dead block); got num_blocks={num_blocks}")
        self.num_blocks = int(num_blocks)
        # ascending pop order on a fresh pool: low ids first
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._live: set = set()
        self.alloc_total = 0
        self.free_total = 0
        self.high_water = 0

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_live(self) -> int:
        return len(self._live)

    @property
    def leaked(self) -> int:
        """Blocks the counters cannot account for: every allocate is
        matched by a free or is still live, so this is exactly zero
        unless ``_free``/``_live`` were mutated outside the API (the
        silent-corruption case the telemetry must make loud)."""
        return self.alloc_total - self.free_total - self.num_live

    def check_accounting(self) -> None:
        """Raise ``RuntimeError`` if the pool invariants broke: a block
        lost to both lists, a block on both, or counter drift."""
        overlap = self._live.intersection(self._free)
        missing = (self.num_blocks - 1) - self.num_free - self.num_live
        if overlap or missing or self.leaked:
            raise RuntimeError(
                f"block pool accounting broken: leaked={self.leaked}, "
                f"{missing} block(s) on neither list, "
                f"{len(overlap)} on both — free/live were mutated "
                f"outside the allocator API")

    def fragmentation_pct(self) -> float:
        """Free-list fragmentation: 100 * (1 - 1/runs) where ``runs``
        counts maximal runs of consecutive block ids among the free
        blocks — 0 when the free ids form one contiguous range (the
        fresh-pool state), approaching 100 as reuse shreds it. Purely
        diagnostic: paging is indirection-oblivious, but a shredded
        free list means future requests' blocks scatter across the
        pool (worse DMA locality on the gather path)."""
        if not self._free:
            return 0.0
        ids = sorted(self._free)
        runs = 1 + sum(1 for a, b in zip(ids, ids[1:]) if b != a + 1)
        return 100.0 * (1.0 - 1.0 / runs)

    def allocate(self, n: int = 1) -> List[int]:
        """Pop ``n`` block ids; raises when the pool cannot satisfy it
        (callers gate admission on :attr:`num_free`, so hitting this is
        a scheduler bug, not backpressure)."""
        if n > len(self._free):
            raise RuntimeError(
                f"KV block pool exhausted: requested {n} blocks with "
                f"{len(self._free)} free of {self.num_blocks - 1} "
                f"allocatable — the scheduler's reservation gate should "
                f"have prevented this")
        ids = [self._free.pop() for _ in range(n)]
        self._live.update(ids)
        self.alloc_total += n
        if self.num_live > self.high_water:
            self.high_water = self.num_live
        return ids

    def free(self, ids: Iterable[int]) -> None:
        for bid in ids:
            bid = int(bid)
            if bid == DEAD_BLOCK:
                raise ValueError("cannot free the reserved dead block")
            if bid not in self._live:
                raise ValueError(
                    f"double free / foreign block id {bid} (not live)")
            self._live.remove(bid)
            self._free.append(bid)
            self.free_total += 1


class BlockTables:
    """Per-slot block tables: ``(num_slots, max_blocks)`` int32 host
    array, every unused entry pinned at :data:`DEAD_BLOCK`. The device
    step receives a copy each call (same aval every time — the contents
    churn, the shape never does)."""

    def __init__(self, num_slots: int, max_blocks: int):
        self.num_slots = int(num_slots)
        self.max_blocks = int(max_blocks)
        self._table = np.zeros((self.num_slots, self.max_blocks), np.int32)

    def assign(self, slot: int, logical_idx: int, block_id: int) -> None:
        self._table[slot, logical_idx] = block_id

    def row(self, slot: int) -> np.ndarray:
        return self._table[slot]

    def clear(self, slot: int) -> None:
        self._table[slot] = DEAD_BLOCK

    def asarray(self) -> np.ndarray:
        """The full (num_slots, max_blocks) table (a view; callers hand
        it to jnp.asarray which copies to device)."""
        return self._table
