"""Paged KV cache: fixed-size blocks in one donated pool, host free list,
per-slot block tables — now refcounted, with copy-on-write sharing and
a block-level prefix cache.

The contiguous engine allocates ``batch × max_s`` cache rows up front —
every admitted request pays for the LONGEST possible sequence whether it
uses 30 tokens or 3000. Paging breaks the cache into fixed-size blocks
(``block_size`` tokens each) living in ONE pre-allocated pool

    {"k"/"v": (layers, num_blocks, kv_heads, block_size, head_dim)}

and gives each slot a BLOCK TABLE mapping its logical kv blocks to pool
indices. Memory is then bound by live tokens (rounded up to the block),
the pool aval never changes (stable avals → the decode step compiles
once), and admit/evict is pure host bookkeeping: allocate/free block ids
and rewrite a table row — the device arrays are never reshaped.

Device-side consumers resolve the indirection two ways: the Pallas
decode kernel reads the table as a scalar-prefetch operand inside its
BlockSpec index maps (:func:`apex_tpu.ops.pallas.decode_attention.
decode_attn_paged_fwd`); the XLA fallback gathers the table into the
contiguous view. Both are driven through
``decode_attention(..., block_tables=)``.

Block 0 is the reserved **dead block**: never allocated, it absorbs the
writes of inactive slots and backs every unused table entry, so the
device step needs no masking branches for slots that do not exist —
their DMAs land somewhere harmless and their columns are length-masked
anyway. All bookkeeping here is plain host Python/numpy (never traced).

**Sharing (serving tier 2).** Blocks carry a REFCOUNT: N requests with
a common prompt prefix map their table rows onto the same physical
blocks (:meth:`BlockAllocator.retain` per extra reference;
:meth:`BlockAllocator.free` decrements and only returns a block to the
free list when the last reference drops). Sharing is copy-on-write in
the only form a paged prompt cache needs: shared blocks hold IMMUTABLE
full blocks of prompt k/v and are never write targets — a request that
must (re)compute rows inside a block it would otherwise share gets a
private block and recomputes the content into it (the "copy" IS the
prefill of that block, which runs anyway; no device copy program
exists, so the two-executable contract is untouched). The scheduler
enforces the never-write-shared invariant structurally: writes land
strictly past a slot's shared prefix.

:class:`PrefixCache` is the index that makes sharing findable: full
prompt blocks are keyed by their CONTENT CHAIN — ``(parent entry,
block's token tuple)`` — so a key equality means the entire token
prefix up to and including this block is identical. Lookups bucket by
hash but always compare the FULL key (a hash collision can never alias
two different prefixes onto one cache block). The cache holds one
refcount on every resident block (so a warm cache survives its
requests) and releases LRU leaves under pool pressure.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

# pool index every unused table entry and every inactive-slot write
# resolves to; excluded from the free list forever
DEAD_BLOCK = 0


def blocks_needed(tokens: int, block_size: int) -> int:
    """Blocks covering ``tokens`` cache rows: ceil(tokens / block_size)."""
    return -(-int(tokens) // int(block_size))


class BlockAllocator:
    """Host-side refcounted free list over pool blocks ``[1, num_blocks)``.

    LIFO reuse (a just-freed block is hottest in cache and cheapest to
    re-DMA) with double-free/foreign-id checks — an allocator bug here
    would silently cross-wire two requests' caches, so it must be loud.

    **Refcounts.** :meth:`allocate` hands out blocks at refcount 1;
    :meth:`retain` adds a reference (a second request sharing a prefix
    block, or the :class:`PrefixCache` keeping one resident);
    :meth:`free` DECREMENTS, and a block only physically returns to the
    free list when its count reaches zero. ``alloc_total`` /
    ``free_total`` count PHYSICAL pool transitions (pop off / return to
    the free list), so the :attr:`leaked` identity
    ``alloc_total - free_total - num_live == 0`` stays refcount-exact:
    retains never drift it, and over-freeing a shared block past its
    refcount is still a loud double free (the block leaves ``_live`` at
    zero, so the next free raises).

    **Residency.** :meth:`mark_resident` flags blocks whose reference
    is held by the prefix cache rather than a live request. The leak
    detectors subtract :attr:`num_resident` from ``num_live`` when the
    engine is idle — a warm prefix cache is capacity doing its job, not
    a leak.

    Accounting for the serving telemetry (ISSUE 10): lifetime
    ``alloc_total`` / ``free_total`` counters, the monotone
    ``high_water`` of live blocks, the :attr:`leaked` witness, and
    :meth:`fragmentation_pct` over the free list. All host-side ints;
    the counters never change allocation behavior.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"the pool needs >= 2 blocks (block {DEAD_BLOCK} is the "
                f"reserved dead block); got num_blocks={num_blocks}")
        self.num_blocks = int(num_blocks)
        # ascending pop order on a fresh pool: low ids first
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._live: set = set()
        self._ref: Dict[int, int] = {}   # live block id -> refcount >= 1
        self._resident: set = set()      # live blocks the cache pins
        self.alloc_total = 0
        self.free_total = 0
        self.high_water = 0

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_live(self) -> int:
        return len(self._live)

    @property
    def num_resident(self) -> int:
        """Live blocks whose reference is the prefix cache's (marked by
        :meth:`mark_resident`) — warm capacity, not demand."""
        return len(self._resident)

    @property
    def leaked(self) -> int:
        """Blocks the physical counters cannot account for: every pop
        off the free list is matched by a return or is still live, so
        this is exactly zero unless ``_free``/``_live`` were mutated
        outside the API (the silent-corruption case the telemetry must
        make loud). Refcount churn (retain/partial free) never moves
        it: only the 1→0 transition counts as a physical free."""
        return self.alloc_total - self.free_total - self.num_live

    def refcount(self, bid: int) -> int:
        """Current reference count of ``bid`` (0 when not live)."""
        return self._ref.get(int(bid), 0)

    def is_shared(self, bid: int) -> bool:
        """More than one reference — a write target must copy first
        (for immutable prompt blocks: recompute into a private block)."""
        return self._ref.get(int(bid), 0) > 1

    def check_accounting(self) -> None:
        """Raise ``RuntimeError`` if the pool invariants broke: a block
        lost to both lists, a block on both, counter drift, or a
        refcount that disagrees with liveness (every live block must
        hold a count >= 1, exactly the live set must be counted, and
        resident blocks must be live)."""
        overlap = self._live.intersection(self._free)
        missing = (self.num_blocks - 1) - self.num_free - self.num_live
        bad_ref = (set(self._ref) != self._live
                   or any(c < 1 for c in self._ref.values()))
        stray_resident = self._resident - self._live
        if overlap or missing or self.leaked or bad_ref or stray_resident:
            raise RuntimeError(
                f"block pool accounting broken: leaked={self.leaked}, "
                f"{missing} block(s) on neither list, "
                f"{len(overlap)} on both, refcounts "
                f"{'corrupt' if bad_ref else 'ok'}, "
                f"{len(stray_resident)} resident-but-not-live — "
                f"free/live/ref were mutated outside the allocator API")

    def fragmentation_pct(self) -> float:
        """Free-list fragmentation: 100 * (1 - 1/runs) where ``runs``
        counts maximal runs of consecutive block ids among the free
        blocks — 0 when the free ids form one contiguous range (the
        fresh-pool state), approaching 100 as reuse shreds it. Purely
        diagnostic: paging is indirection-oblivious, but a shredded
        free list means future requests' blocks scatter across the
        pool (worse DMA locality on the gather path)."""
        if not self._free:
            return 0.0
        ids = sorted(self._free)
        runs = 1 + sum(1 for a, b in zip(ids, ids[1:]) if b != a + 1)
        return 100.0 * (1.0 - 1.0 / runs)

    def allocate(self, n: int = 1) -> List[int]:
        """Pop ``n`` block ids at refcount 1; raises when the pool
        cannot satisfy it (callers make room first — reclaim prefix
        residents, then preempt — so hitting this is a scheduler bug,
        not backpressure)."""
        if n > len(self._free):
            raise RuntimeError(
                f"KV block pool exhausted: requested {n} blocks with "
                f"{len(self._free)} free of {self.num_blocks - 1} "
                f"allocatable — the scheduler should have reclaimed "
                f"prefix-cache residents or preempted a request first")
        ids = [self._free.pop() for _ in range(n)]
        self._live.update(ids)
        for bid in ids:
            self._ref[bid] = 1
        self.alloc_total += n
        if self.num_live > self.high_water:
            self.high_water = self.num_live
        return ids

    def retain(self, ids: Iterable[int]) -> None:
        """Add one reference to each live block in ``ids`` (a request
        mapping its table row onto a shared prefix, or the prefix cache
        pinning a resident block). Retaining a non-live block is loud —
        it would share memory the pool no longer owns."""
        for bid in ids:
            bid = int(bid)
            if bid not in self._live:
                raise ValueError(
                    f"cannot retain block id {bid}: not live")
            self._ref[bid] += 1

    def free(self, ids: Iterable[int]) -> None:
        """Drop one reference per id; a block physically returns to the
        free list (and counts in ``free_total``) only when its last
        reference drops."""
        for bid in ids:
            bid = int(bid)
            if bid == DEAD_BLOCK:
                raise ValueError("cannot free the reserved dead block")
            if bid not in self._live:
                raise ValueError(
                    f"double free / foreign block id {bid} (not live)")
            self._ref[bid] -= 1
            if self._ref[bid] > 0:
                continue  # other holders remain: no physical free
            del self._ref[bid]
            self._live.remove(bid)
            self._resident.discard(bid)
            self._free.append(bid)
            self.free_total += 1

    def mark_resident(self, bid: int) -> None:
        """Flag a live block as prefix-cache-resident (its reference is
        warm capacity, not request demand)."""
        bid = int(bid)
        if bid not in self._live:
            raise ValueError(
                f"cannot mark block id {bid} resident: not live")
        self._resident.add(bid)

    def unmark_resident(self, bid: int) -> None:
        self._resident.discard(int(bid))


class BlockTables:
    """Per-slot block tables: ``(num_slots, max_blocks)`` int32 host
    array, every unused entry pinned at :data:`DEAD_BLOCK`. The device
    step receives a copy each call (same aval every time — the contents
    churn, the shape never does)."""

    def __init__(self, num_slots: int, max_blocks: int):
        self.num_slots = int(num_slots)
        self.max_blocks = int(max_blocks)
        self._table = np.zeros((self.num_slots, self.max_blocks), np.int32)

    def assign(self, slot: int, logical_idx: int, block_id: int) -> None:
        self._table[slot, logical_idx] = block_id

    def row(self, slot: int) -> np.ndarray:
        return self._table[slot]

    def clear(self, slot: int) -> None:
        self._table[slot] = DEAD_BLOCK

    def asarray(self) -> np.ndarray:
        """The full (num_slots, max_blocks) table (a view; callers hand
        it to jnp.asarray which copies to device)."""
        return self._table


@dataclasses.dataclass
class _PrefixEntry:
    """One cached full block: ``(parent, tokens)`` is the FULL identity
    key (parent entry ids are never reused, and the parent was itself
    verified on lookup, so key equality == the whole token prefix up to
    and including this block is identical)."""

    eid: int               # unique, monotonically assigned, never reused
    parent_eid: int        # 0 = root (this is the prompt's first block)
    tokens: Tuple[int, ...]
    block_id: int
    nchildren: int = 0     # live child entries (only leaves are evictable)
    stamp: int = 0         # LRU recency (cache-wide monotone tick)
    # provenance: the trace id of the request whose prefill indexed this
    # block — a later request's prefix hit can name which request paid
    # for the warm block it rode (pure bookkeeping, not identity)
    created_by: Optional[str] = None


#: parent id of a prompt's first block (entry ids start at 1)
ROOT_EID = 0


class PrefixCache:
    """Block-level prefix index: chained full-token keys → physical
    pool blocks, LRU-evicted under pool pressure.

    N requests sharing a system prompt :meth:`match` the same chain of
    entries, retain the underlying blocks, and skip those prefill
    chunks entirely — TTFT on a hit collapses to the unshared tail.
    The cache holds ONE refcount of its own on every indexed block
    (``mark_resident``), so a warm prefix survives the requests that
    built it; :meth:`reclaim` releases least-recently-used LEAF entries
    whose block nobody else references when the pool needs room.

    **Collision safety.** Lookups bucket by :meth:`_hash` but a hit is
    only declared after comparing the FULL ``(parent_eid, tokens)``
    key — two different token blocks (or the same tokens under
    different prefixes) can never alias one physical block, no matter
    how the hash behaves (pinned by the forced-collision test).

    **Leaf-first eviction.** A child entry is only reachable through
    its parent (lookups walk the chain from the prompt's first block),
    so evicting an inner entry would strand its subtree as unreachable
    resident blocks. ``nchildren`` tracks live children; only entries
    with none are eviction candidates. An entry whose block some
    request still references (refcount > 1) is never reclaimed — and
    because a request retains its shared prefix contiguously from
    block 0, a pinned descendant implies a pinned ancestor, which
    makes :meth:`reclaimable` (the count of refcount-1 entries) exact.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int,
                 capacity_blocks: Optional[int] = None):
        self.allocator = allocator
        self.block_size = int(block_size)
        if self.block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        self.capacity_blocks = (None if capacity_blocks is None
                                else int(capacity_blocks))
        self._buckets: Dict[int, List[_PrefixEntry]] = {}
        self._by_eid: Dict[int, _PrefixEntry] = {}
        self._next_eid = ROOT_EID + 1
        self._tick = 0
        # block-level lookup accounting (the prefix_hit_rate numerator/
        # denominator the serve record carries)
        self.block_hits = 0
        self.block_queries = 0
        self.inserts = 0
        self.evictions = 0

    # --- identity ------------------------------------------------------------

    def _hash(self, parent_eid: int, tokens: Tuple[int, ...]) -> int:
        """Bucket key ONLY — a hit still compares the full key (tests
        override this with a constant to prove collisions cannot
        alias)."""
        return hash((parent_eid, tokens))

    def _find(self, parent_eid: int,
              tokens: Tuple[int, ...]) -> Optional[_PrefixEntry]:
        for e in self._buckets.get(self._hash(parent_eid, tokens), ()):
            # FULL key comparison on every hash hit: collision-safe
            if e.parent_eid == parent_eid and e.tokens == tokens:
                return e
        return None

    # --- queries -------------------------------------------------------------

    @property
    def num_entries(self) -> int:
        return len(self._by_eid)

    @property
    def num_resident_blocks(self) -> int:
        """One block per entry: the warm footprint."""
        return len(self._by_eid)

    def hit_rate(self) -> Optional[float]:
        """Block-level hit rate over every full block queried at
        admission (None before any query)."""
        if not self.block_queries:
            return None
        return self.block_hits / self.block_queries

    def reclaimable(self) -> int:
        """Blocks the cache could free right now: entries whose block
        only the cache references. Exact (not an estimate): a request
        pins its shared prefix contiguously from block 0, so a
        refcount-1 entry's whole subtree is refcount-1 and frees
        leaf-first."""
        return sum(1 for e in self._by_eid.values()
                   if self.allocator.refcount(e.block_id) == 1)

    # --- the serving-side API ------------------------------------------------

    def match(self, prompt: Sequence[int],
              count: bool = True) -> List[_PrefixEntry]:
        """The longest cached chain covering ``prompt``'s full blocks,
        walked left to right (each link verified by full-key compare).
        Stamps matched entries most-recently-used and feeds the
        block-level hit/miss accounting — unless ``count=False``: the
        admission gate's PRE-CHECK, which must be side-effect-free (a
        held-back request retried every step would otherwise both
        double-count the stats and keep its chain pinned MRU against
        ``reclaim`` without ever using it; the gate follows up with
        :meth:`commit_match` when the admission really happens). The
        caller decides how much of the chain to USE (at least the block
        holding the prompt's last token must be recomputed privately —
        its final-row logits seed the first sampled token)."""
        B = self.block_size
        full = len(prompt) // B
        if count:
            self.block_queries += full
        chain: List[_PrefixEntry] = []
        parent = ROOT_EID
        for i in range(full):
            key = tuple(int(t) for t in prompt[i * B:(i + 1) * B])
            e = self._find(parent, key)
            if e is None:
                break
            if count:
                self._tick += 1
                e.stamp = self._tick
            chain.append(e)
            parent = e.eid
        if count:
            self.block_hits += len(chain)
        return chain

    def commit_match(self, prompt: Sequence[int],
                     chain: List[_PrefixEntry]) -> None:
        """The counting/stamping half of :meth:`match`, for a chain
        obtained with ``count=False`` that an admission then really
        used (nothing can mutate the cache between the gate's pre-check
        and the admission — same call, same thread — so re-walking the
        buckets would only duplicate work)."""
        self.block_queries += len(prompt) // self.block_size
        for e in chain:
            self._tick += 1
            e.stamp = self._tick
        self.block_hits += len(chain)

    def insert(self, parent_eid: int, tokens: Sequence[int],
               block_id: int, trace_id: Optional[str] = None) -> int:
        """Index one freshly prefilled full block under its chain key;
        returns the entry id to parent the NEXT block on. If the key is
        already present (two requests raced the same prefix through
        prefill), the existing entry wins and the caller's private
        block is simply not indexed — both copies are live and correct,
        only one is findable. At capacity the LRU leaf is reclaimed
        first; if nothing is reclaimable the block is not indexed
        (bounded residency beats an unbounded warm set). ``trace_id``
        records which request's prefill paid for the block
        (``created_by`` provenance on the entry)."""
        key = tuple(int(t) for t in tokens)
        if len(key) != self.block_size:
            raise ValueError(
                f"prefix cache indexes FULL blocks only: got {len(key)} "
                f"tokens, block_size={self.block_size}")
        if parent_eid != ROOT_EID and parent_eid not in self._by_eid:
            # the parent was reclaimed out from under the caller's
            # chain (capacity pressure from other traffic): an entry
            # under it would be unreachable — skip indexing, and keep
            # returning the dangling eid so the chain stays skipped
            return parent_eid
        found = self._find(parent_eid, key)
        if found is not None:
            self._tick += 1
            found.stamp = self._tick
            return found.eid
        if (self.capacity_blocks is not None
                and self.num_entries >= self.capacity_blocks):
            if self.reclaim(1) < 1:
                # nothing evictable: skip indexing — and return a
                # DANGLING eid (never assigned to an entry), not the
                # still-valid parent: otherwise the slot's NEXT block
                # could insert under its grandparent once capacity
                # frees, mis-keying the content chain (a prompt's
                # second block findable as a first block — exactly the
                # aliasing the chain key exists to prevent)
                self._next_eid += 1
                return self._next_eid - 1
            if parent_eid != ROOT_EID and parent_eid not in self._by_eid:
                return parent_eid  # the reclaim took the parent itself
        self.allocator.retain([block_id])
        self.allocator.mark_resident(block_id)
        self._tick += 1
        e = _PrefixEntry(eid=self._next_eid, parent_eid=int(parent_eid),
                         tokens=key, block_id=int(block_id),
                         stamp=self._tick, created_by=trace_id)
        self._next_eid += 1
        self._buckets.setdefault(self._hash(e.parent_eid, key),
                                 []).append(e)
        self._by_eid[e.eid] = e
        if e.parent_eid != ROOT_EID:
            self._by_eid[e.parent_eid].nchildren += 1
        self.inserts += 1
        return e.eid

    def reclaim(self, n: int) -> int:
        """Release up to ``n`` blocks back to the pool, least-recently-
        used LEAF entries first, skipping any block a request still
        references. Returns the number actually freed. The per-block
        candidate rescan is bounded by the POOL, not by traffic: every
        entry pins a distinct live block, so ``num_entries <
        allocator.num_blocks`` always."""
        freed = 0
        while freed < n:
            candidates = [e for e in self._by_eid.values()
                          if e.nchildren == 0
                          and self.allocator.refcount(e.block_id) == 1]
            if not candidates:
                break
            victim = min(candidates, key=lambda e: e.stamp)
            self._evict(victim)
            freed += 1
        return freed

    def _evict(self, e: _PrefixEntry) -> None:
        bucket = self._buckets[self._hash(e.parent_eid, e.tokens)]
        bucket.remove(e)
        if not bucket:
            del self._buckets[self._hash(e.parent_eid, e.tokens)]
        del self._by_eid[e.eid]
        if e.parent_eid != ROOT_EID and e.parent_eid in self._by_eid:
            self._by_eid[e.parent_eid].nchildren -= 1
        self.allocator.unmark_resident(e.block_id)
        self.allocator.free([e.block_id])
        self.evictions += 1

    def clear(self) -> int:
        """Drop every unpinned entry (leaf-first); returns blocks
        freed. Pinned entries (shared with a live request) stay."""
        return self.reclaim(self.num_entries)
