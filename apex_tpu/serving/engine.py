"""ServingEngine: continuous-batching generation over a paged KV cache.

The device side of :mod:`apex_tpu.serving` — exactly TWO compiled
programs, each with one set of avals for the lifetime of the engine:

* ``prefill_chunk(params, pool, table_row, tokens, start, live, key)``
  — one fixed-size chunk of ONE slot's prompt through the stack: the
  chunk's k/v land in the slot's pool blocks (a scatter at traced block
  ids — blocks fully past the live tokens are redirected to the dead
  block so ragged final chunks never touch foreign memory), attention
  runs chunk-queries × the slot's gathered padded cache under the
  prefix-causal mask ``key_pos <= start + i``, and the LAST chunk's
  final-row logits sample the request's first token. ``start``/``live``
  are traced scalars, so every chunk of every prompt length is the same
  executable.
* ``decode_step(params, pool, tables, tokens, lengths, key)`` — one
  token for EVERY slot at once: per-slot cache writes resolve
  ``(block, row)`` through the table (dead slots' writes land in the
  dead block), attention is the paged
  :func:`apex_tpu.ops.decode_attention` (``lengths == 0`` rows are dead
  by the kernel's convention), and the fused sampling tail
  (:func:`apex_tpu.ops.fused_sample`) turns logits into tokens in one
  dispatch.

Both donate the pool: XLA updates the cache in place, so a step's HBM
traffic is the live cache read plus one token's writes — never a pool
copy. Everything dynamic about traffic stays in
:class:`~apex_tpu.serving.scheduler.Scheduler` on the host; churn
reaches the device only as operand *contents*, which is why
``decode_step._cache_size()`` stays 1 across arbitrary admit/evict
(asserted by ``tests/test_serving.py`` and by ``bench.py --serve``).

The chunk-attention gather materializes one ``(h_kv, max_s, d)`` view
per layer per chunk — prefill is compute-bound and infrequent relative
to decode, so this buys simplicity where it is cheap; fusing the
chunk path into the flash family is future work (the decode hot path,
where the HBM bound lives, is already fused end to end).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt import GPTModel
from apex_tpu.monitor import registry as monitor_registry
from apex_tpu.monitor import spans as monitor_spans
from apex_tpu.ops import fused_layer_norm, fused_sample
from apex_tpu.ops.pallas.attention import NEG_INF
from apex_tpu.serving.kv_blocks import (DEAD_BLOCK, BlockAllocator,
                                        PrefixCache)
from apex_tpu.serving.scheduler import Request, Scheduler, SLOPolicy
from apex_tpu.serving.telemetry import ServeTelemetry


@dataclass
class ServeStats:
    """Host-side accounting of one :meth:`ServingEngine.serve` call."""

    decode_steps: int = 0
    prefill_chunks: int = 0
    blocks_high_water: int = 0
    swaps: int = 0
    occupancy_samples: List[int] = field(default_factory=list)

    def occupancy_pct(self, num_slots: int) -> Optional[float]:
        if not self.occupancy_samples:
            return None
        return (100.0 * sum(self.occupancy_samples)
                / (len(self.occupancy_samples) * num_slots))


class ServingEngine:
    """Continuous-batching serving over a :class:`GPTModel`.

    ``engine = ServingEngine(model, num_slots=8, block_size=128)``;
    ``results = engine.serve(params, requests)`` — each
    :class:`~apex_tpu.serving.scheduler.Request` comes back with its
    generated tokens and latency stamps.

    Knobs (all static — they shape the two compiled programs):

    * ``num_slots`` — concurrent streams; the decode step's batch width.
    * ``block_size`` — cache page granularity; 128 on TPU (the paged
      kernel's lane-tiling constraint), smaller off-TPU if desired.
    * ``max_seq_len`` — per-slot logical cap (prompt + generated - 1
      rows); must be a ``block_size`` multiple. Defaults to the model's
      position table rounded DOWN to the block grid.
    * ``num_blocks`` — pool capacity + 1 dead block. Defaults to full
      capacity (``num_slots * max_seq_len/block_size + 1``); size it
      DOWN to what live traffic needs — that is the point of paging —
      and the scheduler turns the shortfall into prefix-cache
      reclamation, then preemption (evict-and-recompute), instead of
      failure or an admission stall.
    * ``prefill_chunk`` — prompt tokens per prefill step (a
      ``block_size`` multiple); smaller chunks interleave tighter with
      decode (less per-step jitter), larger chunks reach the first
      token sooner.
    * ``temperature`` / ``top_k`` / ``top_p`` — the fused sampling
      tail's static program (greedy when ``temperature == 0``).
    """

    def __init__(self, model: GPTModel, *, num_slots: int,
                 block_size: int = 128, num_blocks: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 cache_dtype: Any = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0):
        model.check_decode_supported()
        self.model = model
        c = self.config = model.config
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        max_s = int(max_seq_len if max_seq_len is not None
                    else c.max_seq_len - c.max_seq_len % self.block_size)
        if max_s < self.block_size or max_s % self.block_size:
            raise ValueError(
                f"max_seq_len ({max_s}) must be a positive multiple of "
                f"block_size ({self.block_size}) — round up: "
                f"max_seq_len={-(-max_s // self.block_size) * self.block_size}")
        if max_s > c.max_seq_len:
            raise ValueError(
                f"max_seq_len ({max_s}) exceeds the model's position "
                f"table ({c.max_seq_len})")
        self.max_s = max_s
        self.max_blocks_per_slot = max_s // self.block_size
        self.num_slots = int(num_slots)
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        full = self.num_slots * self.max_blocks_per_slot + 1
        self.num_blocks = int(num_blocks if num_blocks is not None else full)
        self.prefill_chunk_size = int(
            prefill_chunk if prefill_chunk is not None else self.block_size)
        if (self.prefill_chunk_size < self.block_size
                or self.prefill_chunk_size % self.block_size):
            raise ValueError(
                f"prefill_chunk ({self.prefill_chunk_size}) must be a "
                f"positive multiple of block_size ({self.block_size})")
        self.cache_dtype = cache_dtype or c.dtype
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.last_stats: Optional[ServeStats] = None
        # pending weight hot-swap: (at_step, new_params, label) —
        # applied by the serve loop BETWEEN dispatch steps (see
        # request_swap)
        self._pending_swap = None
        # one jitted executable each; both donate the pool (argnums:
        # params=0, pool=1, ... — the cache updates in place)
        self.prefill_chunk = jax.jit(self._prefill_chunk,
                                     donate_argnums=(1,))
        self.decode_step = jax.jit(self._decode_step, donate_argnums=(1,))

    # --- pool ----------------------------------------------------------------

    def init_pool(self) -> Dict[str, jax.Array]:
        """The zeroed block pool:
        ``{"k"/"v": (layers, num_blocks, kv_heads, block_size, head_dim)}``
        — block 0 is the dead block (see kv_blocks)."""
        c = self.config
        shape = (c.num_layers, self.num_blocks, c.local_kv_heads,
                 self.block_size, c.head_dim)
        return {"k": jnp.zeros(shape, self.cache_dtype),
                "v": jnp.zeros(shape, self.cache_dtype)}

    def pool_bytes(self) -> int:
        """HBM footprint of the whole pool (both k and v)."""
        c = self.config
        itemsize = jnp.dtype(self.cache_dtype).itemsize
        return (2 * c.num_layers * self.num_blocks * c.local_kv_heads
                * self.block_size * c.head_dim * itemsize)

    # --- weight hot-swap -----------------------------------------------------

    @staticmethod
    def _validate_swap_avals(old, new) -> None:
        """The hot-swap contract: the new tree must be a contents-only
        mutation — same structure, same shape/dtype per leaf — so both
        jitted programs keep their compiled executables (stable avals;
        the jit caches stay pinned at 1 through a swap). Every mismatch
        names its leaf path eagerly; a silent aval drift would instead
        surface as a RECOMPILE mid-serve, exactly the failure mode the
        zero-recompile contract exists to prevent."""
        old_paths = jax.tree_util.tree_flatten_with_path(old)
        new_paths = jax.tree_util.tree_flatten_with_path(new)
        if jax.tree.structure(old) != jax.tree.structure(new):
            ok = {jax.tree_util.keystr(p) for p, _ in old_paths[0]}
            nk = {jax.tree_util.keystr(p) for p, _ in new_paths[0]}
            extra, missing = sorted(nk - ok), sorted(ok - nk)
            raise ValueError(
                f"hot-swap params tree mismatch: new tree "
                f"{'adds ' + str(extra) if extra else ''}"
                f"{' and ' if extra and missing else ''}"
                f"{'drops ' + str(missing) if missing else ''}"
                f"{'' if extra or missing else 'has a different structure'}"
                f" — a swap is contents-only (same model, new weights)")
        for (path, a), (_, b) in zip(old_paths[0], new_paths[0]):
            if jnp.shape(a) != jnp.shape(b) or \
                    jnp.asarray(a).dtype != jnp.asarray(b).dtype:
                raise ValueError(
                    f"hot-swap aval mismatch at {jax.tree_util.keystr(path)}: "
                    f"serving {jnp.shape(a)}/{jnp.asarray(a).dtype}, new "
                    f"checkpoint {jnp.shape(b)}/{jnp.asarray(b).dtype} — "
                    f"a swap must keep every aval (restore_params(..., "
                    f"like=current_params) produces a matching tree)")

    def request_swap(self, new_params, *, at_step: Optional[int] = None,
                     source: Optional[str] = None) -> None:
        """Queue a weight hot-swap for the live serve loop: the NEXT
        loop iteration whose dispatch counter has reached ``at_step``
        (immediately when ``None``) replaces the params reference
        BETWEEN dispatch steps — in-flight requests keep their KV cache
        and finish against the new weights without dropping. Avals are
        validated against the live params at apply time (an eager,
        leaf-naming error — never a mid-serve recompile); ``source``
        labels the ``swap`` lifecycle event (e.g. the checkpoint step).

        One swap is pending at a time (a newer request replaces an
        unapplied one), and an unapplied swap does NOT outlive the
        serve call — if ``at_step`` is never reached the swap is
        dropped when ``serve`` returns (``last_stats.swaps == 0`` is
        the tell), never silently applied to a later run.

        Typical use with the sharded checkpoint subsystem::

            new = apex_tpu.ckpt.restore_params(ckpt_dir, like=params)
            engine.request_swap(new, source="step_00000042")
        """
        self._pending_swap = (at_step, new_params, source)

    def _maybe_swap(self, params, nstep: int, tel, stats, now: float):
        if self._pending_swap is None:
            return params
        at_step, new_params, source = self._pending_swap
        if at_step is not None and nstep < at_step:
            return params
        self._pending_swap = None
        self._validate_swap_avals(params, new_params)
        stats.swaps += 1
        if tel is not None:
            tel.on_swap(nstep, now, source=source)
        return new_params

    # --- sampling tail -------------------------------------------------------

    def _sample(self, logits, key):
        return fused_sample(logits, key, temperature=self.temperature,
                            top_k=self.top_k, top_p=self.top_p)

    # --- prefill chunk -------------------------------------------------------

    def _prefill_chunk(self, params, pool, table_row, tokens, start, live,
                       key):
        # trace-time step-anatomy span (PR 6): every HLO of the chunk
        # program carries the serve_prefill scope in device traces — the
        # join key request lifecycle records correlate on; no-op when
        # monitoring is off, and never touches the stable avals
        with monitor_spans.span("serve_prefill"):
            return self._prefill_chunk_body(params, pool, table_row,
                                            tokens, start, live, key)

    def _prefill_chunk_body(self, params, pool, table_row, tokens, start,
                            live, key):
        """One chunk of ONE slot's prompt: ``tokens`` (C,) are prompt
        positions [start, start+C) with the first ``live`` valid (the
        final chunk is ragged; pad rows are written but land either
        behind the live frontier — overwritten by decode later — or in
        the dead block). Returns ``(pool, first_token, last_logits)``;
        the token/logits are meaningful on the LAST chunk only (row
        ``live - 1`` is then the prompt's final token). ``start`` and
        ``live`` are traced: one executable for every chunk of every
        prompt."""
        model, c = self.model, self.config
        C, B = self.prefill_chunk_size, self.block_size
        nb, max_s = self.max_blocks_per_slot, self.max_s
        h_kv, group = c.local_kv_heads, c.local_heads // c.local_kv_heads
        d = c.head_dim
        start = jnp.asarray(start, jnp.int32)
        live = jnp.asarray(live, jnp.int32)

        x = model.embedding(params["embedding"], tokens[None])  # (1, C, H)
        pos = start + jnp.arange(C, dtype=jnp.int32)
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(pos, ptab.shape[0] - 1),
                         axis=0)[None]

        # the chunk's target blocks: C/B table entries from start/B on
        # (chunks are block-aligned: start is always a B-multiple — the
        # scheduler resumes at the shared-prefix frontier, a whole
        # number of blocks — and C is a B-multiple); blocks with no
        # live token redirect to the dead block so the ragged tail
        # cannot touch another slot's memory. Earlier table entries
        # (a shared prefix) are READ via the gather below, never
        # written: the copy-on-write discipline in one index bound
        nblk = C // B
        ids = jax.lax.dynamic_slice(table_row.astype(jnp.int32),
                                    (start // B,), (nblk,))
        blk_live = (jnp.arange(nblk, dtype=jnp.int32) * B) < live
        ids = jnp.where(blk_live, ids, DEAD_BLOCK)

        scale = 1.0 / d ** 0.5
        js = jnp.arange(max_s, dtype=jnp.int32)
        mask = js[None, None, None, :] <= pos[None, None, :, None]
        ck, cv = pool["k"], pool["v"]
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            h_in = fused_layer_norm(x, layer["ln1_w"], layer["ln1_b"])
            q, k, v = model._proj_qkv_bshd(layer, h_in)
            # chunk k/v → (C/B, h_kv, B, d) block scatter at traced ids
            kb = k[0].reshape(nblk, B, h_kv, d).transpose(0, 2, 1, 3)
            vb = v[0].reshape(nblk, B, h_kv, d).transpose(0, 2, 1, 3)
            ck = ck.at[i, ids].set(kb.astype(ck.dtype))
            cv = cv.at[i, ids].set(vb.astype(cv.dtype))
            # prefix attention: chunk queries × the slot's gathered
            # padded cache (chunk rows included — causal within the
            # chunk falls out of the same mask)
            k_all = ck[i][table_row].transpose(1, 0, 2, 3) \
                .reshape(h_kv, max_s, d)
            v_all = cv[i][table_row].transpose(1, 0, 2, 3) \
                .reshape(h_kv, max_s, d)
            qg = q[0].reshape(C, h_kv, group, d).transpose(1, 2, 0, 3)
            s = jnp.einsum("hgcd,hsd->hgcs", qg,
                           k_all.astype(qg.dtype),
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(mask, s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("hgcs,hsd->hgcd", p.astype(v_all.dtype), v_all)
            ctx = ctx.transpose(2, 0, 1, 3).reshape(1, C, c.local_heads, d)
            x = x + model._proj_attn_out(layer, ctx)
            x = x + model._mlp(layer, fused_layer_norm(
                x, layer["ln2_w"], layer["ln2_b"]))
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        last = jax.lax.dynamic_slice(
            x, (jnp.int32(0), live - 1, jnp.int32(0)),
            (1, 1, c.hidden_size))
        logits = model.unembed(params, last)[:, 0]  # (1, V)
        return {"k": ck, "v": cv}, self._sample(logits, key)[0], logits[0]

    # --- decode step ---------------------------------------------------------

    def _decode_step(self, params, pool, tables, tokens, lengths, key):
        # same trace-time scope as above: one span per TRACE (not per
        # token), prefixing the whole decode step's HLOs in device traces
        with monitor_spans.span("serve_decode"):
            return self._decode_step_body(params, pool, tables, tokens,
                                          lengths, key)

    def _decode_step_body(self, params, pool, tables, tokens, lengths, key):
        """One token for EVERY slot: ``tokens`` (S,) are each slot's
        incoming sampled tokens, ``lengths`` (S,) the live rows INCLUDING
        them (0 = dead slot: write lands in the dead block, attention
        output zeros, sampled value ignored by the host). Returns
        ``(pool, next_tokens, logits)``. Avals are churn-independent:
        compiled exactly once."""
        model, c = self.model, self.config
        B = self.block_size
        lengths = lengths.astype(jnp.int32)
        pos = jnp.maximum(lengths - 1, 0)  # the incoming token's position
        x = model.embedding(params["embedding"], tokens[:, None])
        ptab = params["pos_embedding"]
        x = x + jnp.take(ptab, jnp.minimum(pos, ptab.shape[0] - 1),
                         axis=0)[:, None]
        tables = tables.astype(jnp.int32)
        bid = jnp.take_along_axis(tables, (pos // B)[:, None], axis=1)[:, 0]
        # dead slots (lengths == 0) write to the dead block NO MATTER what
        # their table row says: a slot mid-prefill is dead for decode but
        # its table already names real blocks — an unredirected write
        # would corrupt its own freshly prefilled cache
        bid = jnp.where(lengths > 0, bid, DEAD_BLOCK)
        row = pos % B
        rel_hook = getattr(model, "decode_rel_bias", None)
        rel_bias = None if rel_hook is None else rel_hook(params)
        ck, cv = pool["k"], pool["v"]
        for i in range(c.num_layers):
            layer = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            q, k_row, v_row = model.decode_qkv(layer, x)
            # per-slot (block, row) scatter into the DONATED pool; dead
            # slots carry table rows of DEAD_BLOCK, so their writes are
            # absorbed harmlessly
            ck = ck.at[i, bid, :, row].set(k_row[:, :, 0].astype(ck.dtype))
            cv = cv.at[i, bid, :, row].set(v_row[:, :, 0].astype(cv.dtype))
            x = model.decode_block(layer, x, q, ck[i], cv[i], lengths,
                                   rel_bias=rel_bias, block_tables=tables)
        x = fused_layer_norm(x, params["lnf_w"], params["lnf_b"])
        logits = model.unembed(params, x)[:, 0]  # (S, V)
        return {"k": ck, "v": cv}, self._sample(logits, key), logits

    # --- the serving loop ----------------------------------------------------

    def make_scheduler(self, *, prefix_cache: bool = True,
                       prefix_capacity_blocks: Optional[int] = None,
                       policy: Optional[SLOPolicy] = None) -> Scheduler:
        """A fresh scheduler + allocator matching this engine's pool.

        ``prefix_cache=True`` (the default) attaches a
        :class:`~apex_tpu.serving.kv_blocks.PrefixCache` over the same
        allocator — full prompt blocks are shared copy-on-write across
        requests and survive them as reclaimable warm capacity.
        ``policy`` injects an :class:`~apex_tpu.serving.scheduler.
        SLOPolicy` (one is created by default) for SLO-aware dispatch
        when telemetry is attached."""
        alloc = BlockAllocator(self.num_blocks)
        cache = (PrefixCache(alloc, self.block_size,
                             capacity_blocks=prefix_capacity_blocks)
                 if prefix_cache else None)
        return Scheduler(
            num_slots=self.num_slots, block_size=self.block_size,
            max_blocks_per_slot=self.max_blocks_per_slot,
            allocator=alloc, prefill_chunk=self.prefill_chunk_size,
            prefix_cache=cache,
            policy=policy if policy is not None else SLOPolicy())

    def serve(self, params, requests: List[Request], *,
              key: Optional[jax.Array] = None,
              clock: Optional[Callable[[], float]] = None,
              scheduler: Optional[Scheduler] = None,
              telemetry=None) -> List[Request]:
        """Run ``requests`` to completion; returns them in completion
        order with tokens and latency stamps filled in.

        Each loop iteration runs at most ONE prefill chunk and ONE
        decode step over the whole slot array — admission and prefill
        interleave with decode instead of stalling it. ``clock`` (a
        monotonically advancing ``() -> seconds`` callable, default
        ``time.perf_counter``) drives arrival replay and the latency
        stamps; requests whose ``arrival_s`` is in the future are held
        until the clock passes it. ``scheduler`` injects a pre-built
        scheduler (tests script churn through it).

        ``telemetry`` attaches a :class:`~apex_tpu.serving.telemetry.
        ServeTelemetry` — request lifecycle events, streaming latency
        histograms, periodic ``serve_window`` records, and the anomaly
        layer, all host-side and outside the jitted steps (the
        zero-recompile contract holds with telemetry on). When the
        monitor registry is enabled and no tracker is passed, a default
        one is attached so an instrumented process gets request traces
        for free; pass ``telemetry=False`` to suppress even that (timed
        baseline runs must not pay emit costs a comparison leg does
        not); with monitoring off and no tracker, every hook site is a
        single ``is None`` test."""
        if self.temperature > 0 and key is None:
            raise ValueError("temperature > 0 serving requires a key")
        if key is None:  # greedy: the key operand is ignored but keeps
            # the step signature (and avals) fixed
            key = jax.random.PRNGKey(0)  # apexlint: disable=APX502
        wall = clock is None
        clock = time.perf_counter if clock is None else clock
        t0 = clock()
        now = lambda: clock() - t0  # noqa: E731
        sched = scheduler if scheduler is not None else self.make_scheduler()
        tel = telemetry
        if tel is False:  # explicit opt-out beats auto-attachment AND
            # any tracker a reused scheduler still carries — a timed
            # baseline must not fire scheduler-side hooks either
            tel = None
            sched.telemetry = None
        elif tel is None and sched.telemetry is not None:
            # a tracker attached at Scheduler construction is the
            # caller's choice: adopt it fully (engine-side hooks +
            # windows too) instead of shadowing it with an auto one
            tel = sched.telemetry
        elif tel is None and monitor_registry.enabled():
            # an instrumented process gets request traces for free; the
            # auto-attached tracker claims OK only on real hardware
            # (same convention as every bench record)
            backend = jax.default_backend()
            tel = (ServeTelemetry(slots=self.num_slots)
                   if backend == "tpu" else ServeTelemetry(
                       slots=self.num_slots, status="SKIP",
                       reason=f"auto-attached serve telemetry on "
                              f"{backend}: serving windows are TPU "
                              f"measurements"))
        if tel is not None:
            sched.telemetry = tel
        for r in requests:
            if tel is not None:
                r.submit_s = now()
                tel.on_submit(r, r.submit_s)
            sched.submit(r)
        pool = self.init_pool()
        stats = ServeStats()
        # per-transition lifecycle records skip the per-line sink flush
        # inside the loop (one flush at the end) — the dominant cost of
        # an emit at token rates; see ServeTelemetry's overhead budget
        reg = monitor_registry.get_registry()
        flush_scope = (reg.buffered() if reg is not None and tel is not None
                       else contextlib.nullcontext())
        if tel is not None:
            # prime the first window's clock BEFORE any work: the first
            # iteration's tokens must not be divided by a window that
            # started after they were produced
            tel.maybe_window(now(), sched)
        try:
            with flush_scope:
                self._serve_loop(params, key, sched, tel, stats, now,
                                 wall, pool)
        finally:
            # a deferred swap this run never applied does NOT survive
            # into a later serve() call — clean return OR mid-run
            # exception — silently hot-swapping a stale checkpoint into
            # an unrelated run (or raising its aval error there) would
            # be worse than dropping it; stats.swaps==0 is the tell
            self._pending_swap = None
        self.last_stats = stats
        return sched.completed

    def _serve_loop(self, params, key, sched, tel, stats, now, wall, pool):
        nstep = 0
        policy = sched.policy
        while not sched.idle():
            # weight hot-swap lands HERE, between dispatch steps: a
            # contents-only params replacement (avals validated), so
            # neither jitted program retraces and in-flight requests
            # continue on their existing cache
            params = self._maybe_swap(params, nstep, tel, stats, now())
            sched.admit(now())
            did_work = False
            # the SLO policy widens the prefill share under queue
            # buildup: up to `prefill_share` chunks this iteration —
            # the SAME compiled program run more often, never a new one
            share = policy.prefill_share if policy is not None else 1
            for _ in range(share):
                work = sched.next_prefill(now())
                if work is None:
                    break
                sched.note_step(nstep)
                t_dispatch = now()
                pool, tok, _ = self.prefill_chunk(
                    params, pool,
                    jnp.asarray(sched.tables.row(work.slot)),
                    jnp.asarray(work.tokens),
                    jnp.int32(work.start), jnp.int32(work.live),
                    jax.random.fold_in(key, nstep))
                tok = int(tok)  # blocks until the chunk really ran
                if tel is not None:
                    tel.on_prefill_chunk(
                        work.rid, work.slot, now() - t_dispatch,
                        sched.blocks_held(work.slot), nstep, now())
                nstep += 1
                stats.prefill_chunks += 1
                sched.note_prefill(work, tok, now())
                did_work = True
            batch = sched.decode_batch(now())
            if batch is not None:
                toks, lens = batch
                ndec = len(sched.decoding_slots())
                sched.note_step(nstep)
                t_dispatch = now()
                pool, sampled, _ = self.decode_step(
                    params, pool, jnp.asarray(sched.tables.asarray()),
                    jnp.asarray(toks), jnp.asarray(lens),
                    jax.random.fold_in(key, nstep))
                sampled = np.asarray(sampled)  # blocks: step really ran
                if tel is not None:
                    tel.on_decode_step(now() - t_dispatch, ndec, nstep,
                                       now())
                nstep += 1
                stats.decode_steps += 1
                stats.occupancy_samples.append(ndec)
                sched.note_decode(sampled, now())
                did_work = True
            stats.blocks_high_water = max(stats.blocks_high_water,
                                          sched.allocator.num_live)
            if tel is not None:
                if tel.maybe_window(now(), sched) is not None \
                        and policy is not None:
                    # window edge: fold the fresh SLO/anomaly signals
                    # into the dispatch knobs (SLO-aware scheduling)
                    policy.update(tel)
            if not did_work and wall:
                # nothing runnable: only future arrivals remain
                time.sleep(1e-4)
